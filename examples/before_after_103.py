"""Example 103 — the same task with and without the one-call API.

Analog of ``103 - Before and After MMLSpark``: the "before" path
hand-assembles the pipeline (index the labels, impute missing values,
index categoricals, hash text, assemble a vector, fit a learner, compute
metrics by hand); the "after" path is a single ``TrainClassifier`` +
``ComputeModelStatistics``. Both run here and must agree — the point of
the notebook is that the one-call API does the same work (reference:
notebooks/samples/103*.ipynb).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.ml.learners import LogisticRegression
from mmlspark_tpu.stages.featurize import AssembleFeatures
from mmlspark_tpu.stages.indexers import ValueIndexer
from mmlspark_tpu.stages.missing import CleanMissingData

try:
    from examples.tabular_classification_101 import make_census_like
except ImportError:  # run directly: python examples/<name>.py
    from tabular_classification_101 import make_census_like


def run(scale: str = "small") -> dict:
    n = 2000 if scale == "small" else 30000
    table = make_census_like(n)
    split = int(0.8 * n)
    train = table.take(np.arange(split))
    test = table.take(np.arange(split, n))

    # ---- BEFORE: every step by hand ----
    label_ix = ValueIndexer(input_col="income", output_col="label").fit(train)
    clean = CleanMissingData(input_cols=["age"], output_cols=["age"],
                             cleaning_mode="Mean").fit(train)
    edu_ix = ValueIndexer(input_col="education",
                          output_col="education").fit(train)
    occ_ix = ValueIndexer(input_col="occupation",
                          output_col="occupation").fit(train)
    feats = AssembleFeatures(
        columns_to_featurize=["age", "hours_per_week", "education",
                              "occupation", "capital_gain"],
        number_of_features=4096).fit(
        occ_ix.transform(edu_ix.transform(clean.transform(train))))

    def before_prep(t):
        t = label_ix.transform(t)
        t = clean.transform(t)
        t = occ_ix.transform(edu_ix.transform(t))
        return feats.transform(t)

    btrain, btest = before_prep(train), before_prep(test)
    learner = LogisticRegression().fit_arrays(
        btrain.column_matrix("features"),
        np.asarray(btrain["label"], np.int64), num_classes=2)
    pred, _ = learner.predict_arrays(btest.column_matrix("features"))
    before_acc = float((np.asarray(pred) ==
                        np.asarray(btest["label"])).mean())

    # ---- AFTER: one call ----
    model = TrainClassifier(label_col="income").fit(train)
    scored = model.transform(test)
    after = dict(ComputeModelStatistics().transform(scored).to_rows()[0])

    return {"before_accuracy": before_acc,
            "after_accuracy": float(after["accuracy"]),
            "after_auc": float(after["AUC"]),
            "hand_written_stages": 6, "one_call_stages": 1,
            "n_test": len(test)}


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in out.items()})

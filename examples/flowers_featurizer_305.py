"""Example 305 — multi-class ImageFeaturizer pipeline.

Analog of ``305 - Flowers ImageFeaturizer``: featurize a multi-class
image dataset with a pretrained backbone's cut layers, train a logistic
regression on the embeddings, and compare against training the same
classifier on raw pixels — transfer learning must win (reference:
notebooks/samples/305*.ipynb). No egress: five synthetic "flower"
classes with class-dependent color/texture statistics.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.schema import make_image, mark_image_column
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.models.image_featurizer import ImageFeaturizer
from mmlspark_tpu.stages.image import UnrollImage

try:
    from examples.cifar_eval_301 import ensure_repo
except ImportError:  # run directly: python examples/<name>.py
    from cifar_eval_301 import ensure_repo

N_CLASSES = 5


def make_flowers(n: int, seed: int = 13) -> DataTable:
    """Class = petal-stripe *frequency*, with random phase, orientation
    flip, hue, and brightness per image — so a linear model on raw pixels
    has no fixed positional signal to latch onto, while convolutional
    features see the texture (the transfer-learning point of notebook
    305)."""
    r = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float64)
    rows, labels = [], []
    for i in range(n):
        k = i % N_CLASSES
        freq = (k + 1) * 2 * np.pi / 32.0            # class frequency
        phase = r.uniform(0, 2 * np.pi)              # nuisance phase
        axis = yy if r.random() < 0.5 else xx        # nuisance orientation
        stripes = np.sin(freq * axis + phase)        # [-1, 1]
        hue = r.uniform(0.4, 1.0, size=3)            # nuisance color
        base = (110 + 70 * stripes)[..., None] * hue[None, None, :]
        base += r.normal(scale=12, size=(32, 32, 3)) + r.uniform(-20, 20)
        rows.append(make_image(f"flower{i}", np.clip(base, 0, 255)))
        labels.append(k)
    t = DataTable({"image": rows, "label": np.asarray(labels)})
    return mark_image_column(t, "image")


def make_featurizer() -> ImageFeaturizer:
    """The backbone featurization stage (single construction point; run()
    attaches the downloaded pretrained bundle, the smoke test a
    zoo-initialized one of the same architecture)."""
    return ImageFeaturizer(output_col="features", cut_output_layers=1,
                           minibatch_size=64)


def build_pipeline():
    """Stage graph + input schema for the static-analysis smoke test."""
    from mmlspark_tpu.analysis import TableSchema
    from mmlspark_tpu.core.pipeline import Pipeline
    from mmlspark_tpu.models.zoo import get_model
    featurizer = make_featurizer()
    featurizer.set(model=get_model("ResNet_Small"))
    return (Pipeline([featurizer,
                      TrainClassifier(label_col="label",
                                      feature_columns=["features"])]),
            TableSchema.from_table(make_flowers(8)))


def run(scale: str = "small", repo_dir: str | None = None) -> dict:
    n = 300 if scale == "small" else 6000
    repo = ensure_repo(repo_dir)
    table = make_flowers(n)
    split = int(0.75 * n)
    train = table.take(np.arange(split))
    test = table.take(np.arange(split, n))

    # transfer learning: pretrained backbone embeddings
    featurizer = make_featurizer().set_model_from_repo("ResNet_Small",
                                                       repo=repo)
    deep_model = TrainClassifier(
        label_col="label", feature_columns=["features"]).fit(
        featurizer.transform(train))
    deep = dict(ComputeModelStatistics().transform(
        deep_model.transform(featurizer.transform(test))).to_rows()[0])

    # baseline: the same classifier on raw unrolled pixels
    unroll = UnrollImage(input_col="image", output_col="pixels",
                         scale=1 / 255.0)
    raw_model = TrainClassifier(
        label_col="label", feature_columns=["pixels"]).fit(
        unroll.transform(train))
    raw = dict(ComputeModelStatistics().transform(
        raw_model.transform(unroll.transform(test))).to_rows()[0])

    return {"deep_accuracy": float(deep["accuracy"]),
            "raw_pixel_accuracy": float(raw["accuracy"]),
            "n_classes": N_CLASSES, "n_test": len(test)}


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in out.items()})

"""Example 303 — transfer learning by DNN featurization.

Analog of ``303 - Transfer Learning by DNN Featurization - Airplane or
Automobile``: download a pretrained CNN from the zoo, cut its classifier
head with ``ImageFeaturizer`` (intermediate activations as features), and
train a cheap classifier on two classes (reference:
notebooks/samples/303*.ipynb; ImageFeaturizer.scala:116-140). No egress:
the zoo is the deterministic local repository; the two "classes" are
synthetic image distributions.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.schema import make_image, mark_image_column
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.models.image_featurizer import ImageFeaturizer

try:
    from examples.cifar_eval_301 import ensure_repo
except ImportError:  # run directly: python examples/<name>.py
    from cifar_eval_301 import ensure_repo


def make_two_class_images(n: int, seed: int = 5) -> DataTable:
    r = np.random.default_rng(seed)
    rows, labels = [], []
    for i in range(n):
        label = i % 2
        base = r.integers(0, 90, (32, 32, 3))
        if label:  # "automobile": bright horizontal band
            base[12:20, :, :] = r.integers(160, 255, (8, 32, 3))
        else:      # "airplane": bright vertical band
            base[:, 12:20, :] = r.integers(160, 255, (32, 8, 3))
        rows.append(make_image(f"img{i}", base))
        labels.append(label)
    t = DataTable({"image": rows, "label": np.asarray(labels)})
    return mark_image_column(t, "image")


def run(scale: str = "small", repo_dir: str | None = None) -> dict:
    n = 160 if scale == "small" else 4096
    repo = ensure_repo(repo_dir)
    table = make_two_class_images(n)
    split = int(0.75 * len(table))
    train = table.take(np.arange(split))
    test = table.take(np.arange(split, len(table)))

    featurizer = (ImageFeaturizer(output_col="features", cut_output_layers=1,
                                  minibatch_size=64)
                  .set_model_from_repo("ResNet_Small", repo=repo))
    model = TrainClassifier(
        label_col="label", feature_columns=["features"]).fit(
        featurizer.transform(train))

    scored = model.transform(featurizer.transform(test))
    metrics = dict(ComputeModelStatistics().transform(scored).to_rows()[0])
    metrics["n_test"] = len(test)
    return metrics


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in out.items()})

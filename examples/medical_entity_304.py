"""Example 304 — medical entity extraction with a pretrained BiLSTM tagger.

Analog of ``304 - Medical Entity Extraction``: download the pretrained
bidirectional-LSTM token tagger from the zoo, bucket variable-length
sentences into a few fixed shapes (the reference pads everything host-side
to 613 tokens and feeds minibatch_size=1 — here bucketing keeps XLA to a
handful of compiled shapes while padding waste stays low), score token
tags, and report token-level accuracy (reference:
notebooks/samples/304*.ipynb). No egress: the tagger comes from the
deterministic local zoo (trained on the token→tag bucket rule) and the
"sentences" are drawn from its vocabulary.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.downloader import ModelDownloader, load_bundle_file
from mmlspark_tpu.models.sequence import bucket_batches

try:
    from examples.cifar_eval_301 import ensure_repo
except ImportError:  # run directly: python examples/<name>.py
    from cifar_eval_301 import ensure_repo

VOCAB, TAGS = 512, 8  # matches the published BiLSTM_MedTag bundle


def make_sentences(n: int, seed: int = 9) -> list[np.ndarray]:
    r = np.random.default_rng(seed)
    return [r.integers(1, VOCAB, size=int(r.integers(5, 60))
                       ).astype(np.int32) for _ in range(n)]


def run(scale: str = "small", repo_dir: str | None = None) -> dict:
    import jax

    repo = ensure_repo(repo_dir)
    n = 256 if scale == "small" else 4096
    sentences = make_sentences(n)

    path = ModelDownloader(repo).download_by_name("BiLSTM_MedTag")
    bundle = load_bundle_file(path)

    # jit once: each bucket width compiles exactly one program (the point
    # of bucketing — the reference pads everything to 613 instead)
    apply = jax.jit(lambda toks: bundle.module.apply(
        {"params": bundle.params}, toks))

    correct = total = 0
    shapes = set()
    for toks, mask, idx in bucket_batches(sentences, batch_size=64,
                                          bucket_sizes=(16, 32, 64)):
        shapes.add(toks.shape[1])
        pred = np.asarray(jax.device_get(apply(toks))).argmax(-1)
        want = toks % TAGS  # the published tagger's entity rule
        ok = (pred == want) & mask
        correct += int(ok.sum())
        total += int(mask.sum())

    return {"token_accuracy": correct / total, "n_sentences": n,
            "n_tokens": total, "bucket_shapes": sorted(shapes)}


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in out.items()})

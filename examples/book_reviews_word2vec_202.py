"""Example 202 — Word2Vec features + model selection.

Analog of ``202 - Amazon Book Reviews - Word2Vec``: tokenize review text,
learn skip-gram embeddings with ``Word2Vec``, average them into row
features, train several classifiers, pick the winner with
``FindBestModel`` by AUC, and report validation metrics (reference:
notebooks/samples/202*.ipynb). No egress: reviews are synthesized with
sentiment-bearing vocabulary (same generator as example 201).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import (
    ComputeModelStatistics, FindBestModel, TrainClassifier,
)
from mmlspark_tpu.ml.learners import LogisticRegression, MLPClassifier
from mmlspark_tpu.stages.text import Tokenizer
from mmlspark_tpu.stages.word2vec import Word2Vec

try:
    from examples.book_reviews_text_201 import make_reviews
except ImportError:  # run directly: python examples/<name>.py
    from book_reviews_text_201 import make_reviews


def run(scale: str = "small") -> dict:
    n = 1500 if scale == "small" else 20000
    table = make_reviews(n)
    s1, s2 = int(0.6 * n), int(0.8 * n)
    train = table.take(np.arange(s1))
    test = table.take(np.arange(s1, s2))
    validation = table.take(np.arange(s2, n))

    # text → tokens → averaged skip-gram embeddings
    tok = Tokenizer(input_col="text", output_col="words")
    w2v = Word2Vec(input_col="words", output_col="features",
                   vector_size=32, epochs=6, min_count=2, seed=42).fit(
        tok.transform(train))

    def featurize(t: DataTable) -> DataTable:
        return w2v.transform(tok.transform(t))

    ftrain, ftest, fval = map(featurize, (train, test, validation))

    candidates = [
        TrainClassifier(model=LogisticRegression(reg_param=reg),
                        label_col="rating",
                        feature_columns=["features"]).fit(ftrain)
        for reg in (0.0, 1e-3)
    ] + [
        TrainClassifier(model=MLPClassifier(layers=[32]),
                        label_col="rating",
                        feature_columns=["features"]).fit(ftrain)
    ]

    best = FindBestModel(models=candidates,
                         evaluation_metric="AUC").fit(ftest)
    metrics = dict(ComputeModelStatistics().transform(
        best.transform(fval)).to_rows()[0])
    metrics["n_validation"] = len(validation)
    metrics["best_metric_on_test"] = best.best_metric
    metrics["synonym_probe"] = [w for w, _ in
                                w2v.find_synonyms("wonderful", 3)]
    return metrics


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in out.items()})

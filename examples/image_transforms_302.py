"""Example 302 — pipeline image transformations.

Analog of ``302 - Pipeline Image Transformations``: read images from disk,
chain geometric/color ops with ``ImageTransformer`` (resize → crop → flip),
unroll to feature vectors, and profile the result (reference:
notebooks/samples/302*.ipynb; ImageTransformer.scala:329-360,
UnrollImage.scala:18-42). No egress: images are synthesized to disk first,
then ingested through the real reader path.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from mmlspark_tpu.core.pipeline import Pipeline
from mmlspark_tpu.data.readers import read_images
from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage


def ensure_images(n: int, root: str | None = None) -> str:
    import cv2
    # per-scale directory + per-file seeds: content is reproducible and a
    # small run never ingests a larger run's leftovers
    root = root or os.path.join(tempfile.gettempdir(),
                                f"mmlspark_tpu_302_images_{n}")
    os.makedirs(root, exist_ok=True)
    for i in range(n):
        f = os.path.join(root, f"img{i:04d}.png")
        if not os.path.exists(f):
            r = np.random.default_rng(i)
            cv2.imwrite(f, r.integers(0, 255, (64 + i % 32, 96, 3)
                                      ).astype(np.uint8))
    return root


def build_pipeline():
    """The stage graph this example runs, plus its abstract input schema —
    the static-analysis smoke test (tests/test_examples.py) validates this
    without executing the example, so drift is caught pre-flight."""
    from mmlspark_tpu.analysis import TableSchema
    pipeline = Pipeline(stages=[
        ImageTransformer().resize(height=60, width=60)
                          .crop(x=0, y=0, height=48, width=48)
                          .flip(flip_code=1),
        UnrollImage(input_col="image", output_col="features",
                    scale=1 / 255.0),
    ])
    # source images are ragged in height (64..95) but fixed-width BGR
    schema = TableSchema.from_spec(
        {"image": {"kind": "image", "shape": [None, 96, 3]}})
    return pipeline, schema


def run(scale: str = "small") -> dict:
    n = 48 if scale == "small" else 2048
    root = ensure_images(n)
    table = read_images(root)

    pipeline, _ = build_pipeline()
    out = pipeline.fit(table).transform(table)

    feats = np.stack(list(out["features"]))
    first = out["image"][0]
    return {
        "n_images": len(out),
        "transformed_hw": [first["height"], first["width"]],
        "feature_dim": int(feats.shape[1]),
        "feature_mean": float(feats.mean()),
        "feature_std": float(feats.std()),
    }


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in out.items()})

"""Example 102 — regression with TrainRegressor.

Analog of ``102 - Regression Example with Flight Delay Dataset``: a
mixed-type table (carrier/origin/dest categoricals + schedule numerics),
``TrainRegressor`` with auto-featurization, metrics via
``ComputeModelStatistics`` and per-row residuals via
``ComputePerInstanceStatistics`` (reference: notebooks/samples/102*.ipynb;
TrainRegressor.scala:52-130). No egress: the flight table is generated
deterministically with the original's shape.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import (
    ComputeModelStatistics, ComputePerInstanceStatistics, TrainRegressor,
)


def make_flights_like(n: int, seed: int = 3) -> DataTable:
    r = np.random.default_rng(seed)
    carrier = r.choice(["AA", "DL", "UA", "WN", "B6"], n)
    origin = r.choice(["JFK", "SEA", "ORD", "ATL", "SFO", "DEN"], n)
    dest = r.choice(["LAX", "BOS", "MIA", "PHX", "IAD", "MSP"], n)
    dep_hour = r.integers(5, 23, n).astype(np.float64)
    distance = r.integers(200, 2800, n).astype(np.float64)
    day_of_week = r.integers(1, 8, n).astype(np.float64)
    carrier_delay = {"AA": 8, "DL": 4, "UA": 9, "WN": 6, "B6": 11}
    delay = (np.array([carrier_delay[c] for c in carrier])
             + 0.8 * np.maximum(dep_hour - 15, 0) ** 1.5
             + 0.002 * distance
             + 3.0 * (day_of_week >= 6)
             + r.gamma(2.0, 4.0, n) - 8.0)
    return DataTable({
        "carrier": list(carrier), "origin": list(origin), "dest": list(dest),
        "dep_hour": dep_hour, "distance": distance,
        "day_of_week": day_of_week, "delay_minutes": delay,
    })


def build_pipeline():
    """Stage graph + input schema for the static-analysis smoke test."""
    from mmlspark_tpu.analysis import TableSchema
    from mmlspark_tpu.core.pipeline import Pipeline
    return (Pipeline([TrainRegressor(label_col="delay_minutes")]),
            TableSchema.from_table(make_flights_like(64)))


def run(scale: str = "small") -> dict:
    n = 2000 if scale == "small" else 50000
    table = make_flights_like(n)
    split = int(0.8 * len(table))
    train = table.take(np.arange(split))
    test = table.take(np.arange(split, len(table)))

    model = TrainRegressor(label_col="delay_minutes").fit(train)
    scored = model.transform(test)
    metrics = dict(ComputeModelStatistics().transform(scored).to_rows()[0])
    per_row = ComputePerInstanceStatistics().transform(scored)
    metrics["n_test"] = len(test)
    metrics["median_L1"] = float(np.median(per_row["L1_loss"]))
    return metrics


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in out.items()})

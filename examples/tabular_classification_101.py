"""Example 101 — tabular classification with TrainClassifier.

Analog of the reference's notebook ``101 - Adult Census Income Training``:
load a mixed-type table (numeric + categorical strings), fit
``TrainClassifier`` (auto-featurization + learner), and evaluate with
``ComputeModelStatistics`` (reference:
notebooks/samples/101*.ipynb; TrainClassifier.scala:97-184).

The environment has no egress, so the census table is generated
deterministically with the same shape as the original ( mixed dtypes, a
label correlated with several columns, missing values).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import ComputeModelStatistics, TrainClassifier


def make_census_like(n: int, seed: int = 7) -> DataTable:
    r = np.random.default_rng(seed)
    age = r.integers(17, 80, n).astype(np.float64)
    hours = r.integers(10, 80, n).astype(np.float64)
    education = r.choice(
        ["HS-grad", "Bachelors", "Masters", "Doctorate", "Some-college"], n)
    occupation = r.choice(
        ["Tech", "Sales", "Exec", "Craft", "Service", "Farming"], n)
    capital_gain = np.where(r.random(n) < 0.8, 0.0,
                            r.lognormal(8, 1, n)).astype(np.float64)
    edu_rank = np.array([["HS-grad", "Some-college", "Bachelors", "Masters",
                          "Doctorate"].index(e) for e in education])
    score = (0.03 * (age - 40) + 0.04 * (hours - 40) + 0.8 * edu_rank
             + (capital_gain > 0) * 2.0
             + (occupation == "Exec") * 1.5 + r.normal(0, 1.2, n))
    label = np.where(score > 2.0, ">50K", "<=50K")
    # sprinkle missing values like the real table
    age[r.random(n) < 0.02] = np.nan
    return DataTable({
        "age": age, "hours_per_week": hours, "education": list(education),
        "occupation": list(occupation), "capital_gain": capital_gain,
        "income": list(label),
    })


def build_pipeline():
    """Stage graph + input schema for the static-analysis smoke test."""
    from mmlspark_tpu.analysis import TableSchema
    from mmlspark_tpu.core.pipeline import Pipeline
    return (Pipeline([TrainClassifier(label_col="income")]),
            TableSchema.from_table(make_census_like(64)))


def run(scale: str = "small") -> dict:
    n = 2000 if scale == "small" else 30000
    table = make_census_like(n)
    split = int(0.8 * len(table))
    train, test = table.head(split), table.take(np.arange(split, len(table)))

    model = TrainClassifier(label_col="income").fit(train)
    scored = model.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    metrics = {k: float(stats[k][0]) for k in stats.columns
               if np.issubdtype(np.asarray(stats[k]).dtype, np.number)}
    return {"accuracy": metrics["accuracy"], "auc": metrics.get("AUC"),
            "n_train": split, "n_test": len(test)}


if __name__ == "__main__":
    print(run())

"""Example 306 — one-call distributed training across mesh axes.

The reference's distributed story is a single flag: ``parallelTrain=true``
and the launcher does the rest (reference:
cntk-train/src/main/scala/CommandBuilders.scala:79-93,
CNTKLearner.scala:140-151 — a single-node MPI data-parallel ring). The
TPU-native generalization is a **device mesh**: every parallelism
strategy is an axis of ``TrainConfig.mesh_spec``, the model's
``mesh_hooks`` activate the right collectives, and XLA lays the
all-reduces/all-to-alls/ppermutes onto ICI. Same params, same losses —
parallelism is an execution detail.

This example fine-tunes on the digits data three ways and shows the loss
trajectories agree:

* ``{'dp': N}``     — pure data parallelism (the reference-parity mode),
* ``{'dp': …, 'pp': 2}`` — ViT encoder blocks pipelined across stages
  (GPipe collective pipelining),
* ``{'dp': …, 'ep': 2}`` — a mixture-of-experts transformer with
  expert-parallel all-to-all token dispatch.

Run on a TPU pod via ``mmlspark-tpu-launch``; on a dev box the test
harness provides 8 virtual CPU devices.
"""

from __future__ import annotations

import numpy as np


def digits_images(n: int = 128):
    """Real data without egress: the shared digits-rgb32 loader (same
    deterministic split the model-repo publisher and example 301 use)."""
    from mmlspark_tpu.tools.build_model_repo import digits_rgb32

    xtr, ytr, _, _ = digits_rgb32()
    return xtr[:n], ytr[:n]


def fit(module, mesh_spec, x, y):
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    cfg = TrainConfig(batch_size=32, epochs=3, optimizer="adam",
                      learning_rate=1e-3, log_every=1, seed=0,
                      mesh_spec=mesh_spec)
    t = Trainer(module, cfg)
    t.fit_arrays(x, y)
    return np.asarray(t.history)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.sequence import TransformerTagger
    from mmlspark_tpu.models.vit import ViT

    n_dev = jax.device_count()
    dp = max(1, n_dev // 2)
    print(f"devices: {n_dev} ({jax.devices()[0].platform})")

    x, y = digits_images()

    def vit():
        # depth 4 so it splits across 2 pipeline stages; patch 16 on the
        # 32x32 digits keeps the token count CI-small
        return ViT(num_classes=10, patch=16, dim=32, depth=4, heads=4,
                   mlp_dim=64, dtype=jnp.float32, pipeline_microbatches=2)

    print("\n-- ViT fine-tune: dp-only vs dp x pp (pipelined blocks) --")
    ref = fit(vit(), {"dp": dp}, x, y)
    pp = fit(vit(), {"dp": dp, "pp": 2}, x, y)
    drift = float(np.max(np.abs(ref - pp)))
    print(f"dp losses   : {np.round(ref[:4], 4)} ... {ref[-1]:.4f}")
    print(f"dp x pp     : {np.round(pp[:4], 4)} ... {pp[-1]:.4f}")
    print(f"max |Δloss| = {drift:.2e} (pipelining is exact)")
    assert drift < 1e-3

    print("\n-- MoE tagger: dp-only (dense routing) vs dp x ep "
          "(all-to-all dispatch) --")
    r = np.random.default_rng(0)
    toks = r.integers(1, 64, size=(128, 16)).astype(np.int32)
    tags = (toks % 4).astype(np.int64)  # learnable rule

    def tagger():
        return TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                                 num_layers=1, mlp_dim=32, num_tags=4,
                                 max_len=16, moe_experts=4,
                                 moe_capacity_factor=8.0, pad_token_id=0,
                                 dtype=jnp.float32)

    ref = fit(tagger(), {"dp": dp}, toks, tags)
    ep = fit(tagger(), {"dp": dp, "ep": 2}, toks, tags)
    drift = float(np.max(np.abs(ref - ep)))
    print(f"dp losses   : {np.round(ref[:4], 4)} ... {ref[-1]:.4f}")
    print(f"dp x ep     : {np.round(ep[:4], 4)} ... {ep[-1]:.4f}")
    print(f"max |Δloss| = {drift:.2e} (ample capacity ⇒ matches dense)")
    assert drift < 1e-3
    assert ref[-1] < ref[0], "training did not descend"
    print("\ndistributed_finetune_306: OK")


if __name__ == "__main__":
    main()

"""Example 301 — pretrained CNN evaluation (the reference's flagship demo).

Analog of ``301 - CIFAR10 CNTK CNN Evaluation``: download a *pretrained*
model from the zoo repository, score an image table in device minibatches
with ``JaxModel``, and compute the confusion matrix / accuracy (reference:
notebooks/samples/301*.ipynb; CNTKModel.scala:215-262).

Without egress the "zoo" is a local repository built by
``tools/build_model_repo.py`` (deterministically trained weights; the
download path — manifest, sha256 cache — is identical).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from mmlspark_tpu.data.downloader import ModelDownloader
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml.metrics import confusion_matrix
from mmlspark_tpu.models.jax_model import JaxModel



def ensure_repo(repo_dir: str | None = None) -> str:
    """Build (once) and return the local model repository."""
    from mmlspark_tpu.tools import build_model_repo
    repo_dir = repo_dir or os.path.join(tempfile.gettempdir(),
                                        "mmlspark_tpu_model_repo")
    if not os.path.exists(os.path.join(repo_dir, "MANIFEST.json")):
        build_model_repo.build(repo_dir, scale="small")
    return repo_dir


def make_model() -> JaxModel:
    """The scoring stage (single construction point; run() attaches the
    downloaded bundle, the smoke test a zoo-initialized one)."""
    return JaxModel(input_col="image", output_col="scores",
                    minibatch_size=256)


def build_pipeline():
    """Stage graph + input schema for the static-analysis smoke test: the
    same architecture the repo publishes, over the flat uint8 row layout
    run() feeds (32*32*3 = 3072 values per row)."""
    from mmlspark_tpu.analysis import TableSchema
    from mmlspark_tpu.models.zoo import get_model
    model = make_model()
    model.set(model=get_model("ConvNet_CIFAR10"))
    return [model], TableSchema.from_spec(
        {"image": {"kind": "vector", "shape": [3072], "dtype": "uint8"}})


def run(scale: str = "small", repo_dir: str | None = None) -> dict:
    # `scale` kept for CLI symmetry with the other examples; the eval set
    # is the fixed digits-rgb32 held-out split either way (real data, and
    # the split the manifest's recorded accuracy refers to)
    del scale
    from mmlspark_tpu.tools import build_model_repo
    repo = ensure_repo(repo_dir)

    path = ModelDownloader(repo).download_by_name("ConvNet_CIFAR10")
    model = make_model().set_model_location(path)

    # evaluate on REAL data: the held-out split of the dataset the zoo
    # model was trained on (the manifest records the publisher's own
    # held-out accuracy for this exact split — the notebook's "download a
    # pretrained model and reproduce its accuracy" flow)
    _, _, x, y = build_model_repo.digits_rgb32()
    n = len(x)
    table = DataTable({"image": list(x.reshape(n, -1).astype(np.uint8))})
    scored = model.transform(table)
    pred = np.stack(list(scored["scores"])).argmax(-1)
    cm = confusion_matrix(y, pred, 10)
    acc = float((pred == y).mean())
    manifest_acc = next(e.eval_value
                        for e in ModelDownloader(repo).list_models()
                        if e.name == "ConvNet_CIFAR10")
    return {"accuracy": acc, "n": n, "manifest_accuracy": manifest_acc,
            "confusion_diag": [int(v) for v in np.diag(cm)]}


if __name__ == "__main__":
    print(run())

"""Example 301 — pretrained CNN evaluation (the reference's flagship demo).

Analog of ``301 - CIFAR10 CNTK CNN Evaluation``: download a *pretrained*
model from the zoo repository, score an image table in device minibatches
with ``JaxModel``, and compute the confusion matrix / accuracy (reference:
notebooks/samples/301*.ipynb; CNTKModel.scala:215-262).

Without egress the "zoo" is a local repository built by
``tools/build_model_repo.py`` (deterministically trained weights; the
download path — manifest, sha256 cache — is identical).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from mmlspark_tpu.data.downloader import ModelDownloader
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml.metrics import confusion_matrix
from mmlspark_tpu.models.jax_model import JaxModel



def ensure_repo(repo_dir: str | None = None) -> str:
    """Build (once) and return the local model repository."""
    from mmlspark_tpu.tools import build_model_repo
    repo_dir = repo_dir or os.path.join(tempfile.gettempdir(),
                                        "mmlspark_tpu_model_repo")
    if not os.path.exists(os.path.join(repo_dir, "MANIFEST.json")):
        build_model_repo.build(repo_dir, scale="small")
    return repo_dir


def run(scale: str = "small", repo_dir: str | None = None) -> dict:
    from mmlspark_tpu.tools import build_model_repo
    repo = ensure_repo(repo_dir)
    n = 512 if scale == "small" else 8192

    path = ModelDownloader(repo).download_by_name("ConvNet_CIFAR10")
    model = (JaxModel(input_col="image", output_col="scores",
                      minibatch_size=256)
             .set_model_location(path))

    x, y = build_model_repo._class_blobs(n, (32, 32, 3), 10, seed=1)
    table = DataTable({"image": list(x.reshape(n, -1).astype(np.uint8))})
    scored = model.transform(table)
    pred = np.stack(list(scored["scores"])).argmax(-1)
    cm = confusion_matrix(y, pred, 10)
    acc = float((pred == y).mean())
    return {"accuracy": acc, "n": n,
            "confusion_diag": [int(v) for v in np.diag(cm)]}


if __name__ == "__main__":
    print(run())

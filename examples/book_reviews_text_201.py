"""Example 201 — text classification with TextFeaturizer.

Analog of ``201 - Amazon Book Reviews - TextFeaturizer``: raw review text
→ ``TextFeaturizer`` (tokenize → stop words → n-grams → hashing TF →
IDF) → classifier on the hashed features → accuracy (reference:
notebooks/samples/201*.ipynb; TextFeaturizer.scala:18-171). No egress:
reviews are synthesized with sentiment-bearing vocabulary.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import ComputeModelStatistics, TrainClassifier
from mmlspark_tpu.stages.text import TextFeaturizer

POSITIVE = ["wonderful", "gripping", "masterpiece", "loved", "brilliant",
            "delightful", "compelling", "excellent"]
NEGATIVE = ["boring", "tedious", "awful", "disappointing", "dull",
            "predictable", "terrible", "waste"]
NEUTRAL = ["book", "story", "author", "chapter", "characters", "plot",
           "pages", "read", "the", "a", "was", "it", "this"]


def make_reviews(n: int, seed: int = 11) -> DataTable:
    r = np.random.default_rng(seed)
    texts, ratings = [], []
    for _ in range(n):
        good = bool(r.random() < 0.5)
        lexicon = POSITIVE if good else NEGATIVE
        words = list(r.choice(NEUTRAL, size=r.integers(6, 14)))
        for _ in range(int(r.integers(1, 4))):
            words.insert(int(r.integers(0, len(words))),
                         str(r.choice(lexicon)))
        texts.append(" ".join(words))
        ratings.append(1 if good else 0)
    return DataTable({"text": texts, "rating": np.asarray(ratings)})


def make_stages():
    """The featurize→train stage pair (single construction point shared by
    run() and the static-analysis smoke test)."""
    return (TextFeaturizer(input_col="text", output_col="features",
                           use_stop_words_remover=True, use_ngram=False,
                           use_idf=True, num_features=1 << 12),
            TrainClassifier(label_col="rating",
                            feature_columns=["features"]))


def build_pipeline():
    from mmlspark_tpu.analysis import TableSchema
    from mmlspark_tpu.core.pipeline import Pipeline
    return (Pipeline(list(make_stages())),
            TableSchema.from_table(make_reviews(32)))


def run(scale: str = "small") -> dict:
    n = 1500 if scale == "small" else 20000
    table = make_reviews(n)
    split = int(0.8 * len(table))
    train = table.take(np.arange(split))
    test = table.take(np.arange(split, len(table)))

    text_featurizer, trainer = make_stages()
    featurizer = text_featurizer.fit(train)
    model = trainer.fit(featurizer.transform(train))

    scored = model.transform(featurizer.transform(test))
    metrics = dict(ComputeModelStatistics().transform(scored).to_rows()[0])
    metrics["n_test"] = len(test)
    return metrics


if __name__ == "__main__":
    out = run()
    print({k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in out.items()})

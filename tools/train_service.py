"""train_service — supervise an elastic fault-tolerant training job.

The actuator over the PR 9 anomaly plane (docs/training_service.md): a
supervisor launches the worker command at the first rung of a topology
ladder, watches exit codes, heartbeat beacons, and straggler verdicts,
and recovers by POLICY — restart from the latest checkpoint, evict a
persistent straggler, or elastically re-scale onto the surviving
topology (the new generation restores the checkpoint with restore
targets built on the new mesh, so optimizer/model state re-shards on
read).

Usage::

    # supervise a worker command: 4 workers, shrink to 3 then 2 on
    # permanent loss, restart transient crashes twice
    python tools/train_service.py --service-dir ./svc \\
        --checkpoint-dir ./ckpt --topology 4 --topology 3 --topology 2 \\
        --max-restarts 2 -- python my_train_job.py

    # the hardware-free dryrun rig: world 1 with 8 virtual CPU devices,
    # re-scaling to 4 (the device-level survivors analog), built-in
    # self-test worker
    python tools/train_service.py --service-dir ./svc \\
        --checkpoint-dir ./ckpt --topology 1x8 --topology 1x4 --selftest

    # run AS the built-in self-test worker (what --selftest launches)
    python tools/train_service.py worker

Topology rungs are ``WORLD`` or ``WORLDxDEVICES`` (virtual CPU devices
per worker — the dryrun rig). Every supervisor decision lands in
``<service-dir>/decisions.jsonl``; with ``MMLSPARK_TPU_OBS=1`` the same
decisions are ``service/*`` events + ``train.service.*`` gauges.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_topology(raw: str):
    from mmlspark_tpu.train.service import Topology
    if "x" in raw:
        world, devices = raw.split("x", 1)
        return Topology(world=int(world), devices=int(devices))
    return Topology(world=int(raw))


def main(argv: Sequence[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "worker":
        from mmlspark_tpu.train.service import run_selftest_worker
        return run_selftest_worker()

    ap = argparse.ArgumentParser(
        prog="train_service",
        description="Supervise an elastic fault-tolerant training job "
                    "(see module docstring)")
    ap.add_argument("--service-dir", required=True,
                    help="run directory: beacons, decisions.jsonl, "
                         "recovery snapshots, worker flight dumps")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="the job's TrainCheckpointer directory (restart "
                         "and re-scale resume from its latest step)")
    ap.add_argument("--topology", action="append", default=[],
                    help="ladder rung, WORLD or WORLDxDEVICES; repeat "
                         "from full topology down to the elastic floor")
    ap.add_argument("--max-restarts", type=int, default=2,
                    help="same-topology restarts before re-scaling")
    ap.add_argument("--hang-timeout", type=float, default=None,
                    help="seconds a busy worker may stall (no beacon "
                         "progress) before it is treated as lost")
    ap.add_argument("--evict-straggler-after", type=int, default=None,
                    help="consecutive straggler verdicts before the "
                         "named worker is evicted (re-scale without it)")
    ap.add_argument("--preempt-exit-code", type=int, action="append",
                    default=None,
                    help="exit code(s) meaning permanent capacity loss "
                         "(immediate re-scale); default: the service's "
                         "PREEMPT_EXIT_CODE (75)")
    ap.add_argument("--grace-seconds", type=float, default=10.0)
    ap.add_argument("--no-snapshot", action="store_true",
                    help="skip archiving the checkpoint dir at each "
                         "re-scale recovery point")
    ap.add_argument("--selftest", action="store_true",
                    help="use the built-in self-test worker as cmd")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (prefix with --)")
    args = ap.parse_args(argv)

    from mmlspark_tpu.train.service import (
        PREEMPT_EXIT_CODE, RecoveryPolicy, ServiceConfig, Topology,
        TrainSupervisor,
    )

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if args.selftest:
        if cmd:
            ap.error("--selftest and an explicit worker command are "
                     "mutually exclusive")
        cmd = [sys.executable, os.path.abspath(__file__), "worker"]
    if not cmd:
        ap.error("no worker command (append: -- python job.py, or use "
                 "--selftest)")
    topologies = tuple(_parse_topology(t) for t in args.topology) \
        or (Topology(),)
    # backoff schedule and preempt code come from the policy's own
    # defaults — the CLI must not fork a stale copy of either
    policy = RecoveryPolicy(
        max_restarts=args.max_restarts,
        preempt_exit_codes=tuple(args.preempt_exit_code
                                 or (PREEMPT_EXIT_CODE,)),
        hang_timeout_s=args.hang_timeout,
        evict_straggler_after=args.evict_straggler_after)
    sup = TrainSupervisor(ServiceConfig(
        cmd=cmd, service_dir=args.service_dir,
        checkpoint_dir=args.checkpoint_dir, topologies=topologies,
        policy=policy, grace_seconds=args.grace_seconds,
        snapshot_recovery=not args.no_snapshot))
    report = sup.run()
    print(json.dumps({
        "ok": report.ok, "reason": report.reason,
        "generations": len(report.generations),
        "restarts": report.restarts, "rescales": report.rescales,
        "evictions": report.evictions,
        "final_topology": (
            {"world": report.final_topology.world,
             "devices": report.final_topology.devices}
            if report.final_topology else None),
    }))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())

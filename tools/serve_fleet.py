"""serve_fleet — run the serve fleet tier: N supervised backends behind
one router.

Launches the :class:`ServeSupervisor` (backend serve processes with
restart-with-backoff recovery and SLO-burn autoscaling) and the
:class:`FleetRouter` HTTP fan-in over the shared backend pool
(docs/serving.md §fleet tier). Clients talk to the router exactly like
a single serve process — ``POST /v1/models/<name>:predict`` and
chunked ``:generate`` streams — and never observe a backend death or a
scale event: failovers re-route, drains are zero-drop.

Usage::

    # two self-test backends behind a router on :8100, warming from a
    # shared compile cache, autoscaling 1..4 on SLO burn
    python tools/serve_fleet.py --dir ./fleet --port 8100 \\
        --backends 2 --compile-cache ./cc --min-backends 1 \\
        --max-backends 4

    # run AS one backend worker (what the supervisor launches)
    python tools/serve_fleet.py worker

    # point-in-time fleet status from the run dir's beacons: per-
    # backend state, port, served {model: version} map, deploy seq —
    # the rollout-convergence view the lifecycle deployer reads
    python tools/serve_fleet.py status --dir ./fleet

Every supervisor decision (spawn/restart/scale_up/scale_down/...)
lands in ``<dir>/decisions.jsonl``; with ``MMLSPARK_TPU_OBS=1`` the
same decisions are obs ``fleet/*`` events + ``serve.fleet.*``
counters, and ``--fleet-dir`` exports router + supervisor telemetry
into the obs fleet plane (``python tools/fleet.py status`` merges it
with the backends' own exports).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def status_main(argv: Sequence[str]) -> int:
    """``serve_fleet status --dir D``: print one JSON fleet view from
    the run directory's beacon files (works with no live connection to
    the supervisor — beacons are the same sensor channel it reads).
    Includes the per-backend served ``{model: version}`` map and the
    condensed per-model rollout convergence."""
    ap = argparse.ArgumentParser(prog="serve_fleet status")
    ap.add_argument("--dir", required=True, dest="service_dir")
    args = ap.parse_args(list(argv))

    import re
    beacon_re = re.compile(r"^beacon_(\d+)\.json$")
    rows = []
    try:
        names = sorted(os.listdir(args.service_dir))
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 2
    for fname in names:
        m = beacon_re.match(fname)
        if not m:
            continue
        try:
            with open(os.path.join(args.service_dir, fname),
                      encoding="utf-8") as f:
                b = json.load(f)
        except (OSError, ValueError):
            continue
        row = {"bid": int(m.group(1))}
        for key in ("status", "generation", "host", "port",
                    "burn_short", "versions", "deploy_seq",
                    "deploy_error"):
            if key in b:
                row[key] = b[key]
        rows.append(row)
    by_model: dict = {}
    for row in rows:
        if row.get("status") != "running":
            continue
        for model, version in (row.get("versions") or {}).items():
            by_model.setdefault(model, set()).add(version)
    print(json.dumps({
        "backends": rows,
        "rollout": {model: {"converged": len(vs) == 1,
                            "versions": sorted(vs)}
                    for model, vs in sorted(by_model.items())},
    }, indent=2))
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "worker":
        from mmlspark_tpu.serve.fleet.worker import run_backend_worker
        return run_backend_worker()
    if argv and argv[0] == "status":
        return status_main(argv[1:])

    ap = argparse.ArgumentParser(
        prog="serve_fleet",
        description="Run N supervised serve backends behind one router "
                    "(see module docstring)")
    ap.add_argument("--dir", required=True, dest="service_dir",
                    help="fleet run directory: beacons, decisions.jsonl")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8100,
                    help="router port (0 = ephemeral)")
    ap.add_argument("--backends", type=int, default=2,
                    help="initial backend count")
    ap.add_argument("--compile-cache", default=None,
                    help="shared AOT compile cache dir — restarts and "
                         "scale-ups warm from it (zero fresh compiles)")
    ap.add_argument("--repo", default=None, metavar="DIR",
                    help="versioned model repo root (models/repo.py): "
                         "backends serve every model's CURRENT version "
                         "and accept the lifecycle deployer's versioned "
                         "hot-swap commands (docs/lifecycle.md)")
    ap.add_argument("--max-restarts", type=int, default=2,
                    help="per-backend restart budget")
    ap.add_argument("--min-backends", type=int, default=1)
    ap.add_argument("--max-backends", type=int, default=4)
    ap.add_argument("--fast-burn", type=float, default=14.0,
                    help="SLO fast-burn multiple that triggers scale-up")
    ap.add_argument("--burn-sustain", type=float, default=1.0,
                    help="seconds the burn must persist before scaling")
    ap.add_argument("--idle-sustain", type=float, default=30.0,
                    help="seconds of idle occupancy before scale-down")
    ap.add_argument("--cooldown", type=float, default=5.0,
                    help="seconds between scale actions")
    ap.add_argument("--slo", default=None,
                    help="JSON SLOSpec field overrides for the backends")
    ap.add_argument("--fleet-dir", default=None,
                    help="obs fleet plane dir: export router+supervisor "
                         "telemetry there and propagate to backends")
    ap.add_argument("--cmd", nargs=argparse.REMAINDER, default=[],
                    help="backend worker command (default: the built-in "
                         "self-test serve worker; prefix with --)")
    args = ap.parse_args(argv)

    from mmlspark_tpu.obs import fleet as obs_fleet
    from mmlspark_tpu.serve.fleet import (
        BackendPool, FleetConfig, FleetRouter, ScalePolicy,
        ServeSupervisor,
    )
    from mmlspark_tpu.train.service import RecoveryPolicy

    if args.fleet_dir:
        obs_fleet.enable(args.fleet_dir)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    pool = BackendPool()
    sup = ServeSupervisor(FleetConfig(
        service_dir=args.service_dir, cmd=cmd or None,
        initial_backends=args.backends,
        policy=RecoveryPolicy(max_restarts=args.max_restarts,
                              rescale_on_exhausted=False,
                              preempt_exit_codes=()),
        scale=ScalePolicy(fast_burn=args.fast_burn,
                          burn_sustain_s=args.burn_sustain,
                          idle_sustain_s=args.idle_sustain,
                          min_backends=args.min_backends,
                          max_backends=args.max_backends,
                          cooldown_s=args.cooldown),
        compile_cache=args.compile_cache,
        repo=args.repo,
        slo=json.loads(args.slo) if args.slo else None), pool=pool)
    router = FleetRouter(pool, host=args.host, port=args.port)
    sup.start()
    router.start()
    host, port = router.address
    print(json.dumps({"router": f"http://{host}:{port}",
                      "backends": args.backends,
                      "service_dir": args.service_dir}), flush=True)
    # SIGTERM must take the same clean path as ^C: without this the
    # supervisor dies silently and ORPHANS its backend processes
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        router.close()
        sup.close()
        if args.fleet_dir:
            obs_fleet.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Shim: the zoo-publishing tool now lives in the installable package
(``mmlspark_tpu.tools.build_model_repo``; console script
``mmlspark-tpu-build-repo``)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.tools.build_model_repo import build, main  # noqa: F401,E402

if __name__ == "__main__":
    main()

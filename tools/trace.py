"""trace — capture/render an obs timeline + metrics snapshot from the CLI.

Subcommands::

    python tools/trace.py demo [--out-dir DIR] [--rows N]
        Run the canonical fused image pipeline (resize → unroll → score,
        the tools/perf_smoke.py scenario) with the obs tracer enabled;
        write trace.json (Chrome-trace / Perfetto ``trace_event`` JSON)
        and metrics.json (registry snapshot), and print a text summary.

    python tools/trace.py pipeline <saved-stage-dir>
        [--schema schema.json] [--rows N] [--out-dir DIR]
        Load a saved PipelineModel / fitted transformer, synthesize
        ``--rows`` input rows from the schema (``--schema`` takes the
        tools/analyze.py JSON column spec; without it the schema is
        derived from a leading JaxModel's input_spec), run one traced
        transform, and write the same artifacts.

    python tools/trace.py render <trace.json> [--top N]
        Aggregate a previously written trace file into a per-span-name
        table (calls, total/mean ms), longest first, plus a one-line
        flow summary (request traces, if the capture carried any).
        A missing, unreadable, or malformed trace file is a typed
        :class:`TraceInputError` — one diagnostic line on stderr and
        exit code 2, never a traceback.

Open trace.json in https://ui.perfetto.dev (or chrome://tracing). For a
device-interleaved view capture ``utils/profiling.trace`` simultaneously
— spans recorded under ``--device-annotations`` also enter
``jax.profiler`` annotations, so both timelines carry the same names.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class TraceInputError(Exception):
    """A trace input file is missing, unreadable, or not Chrome-trace
    JSON. The CLI maps it to one stderr line + exit 2 (the typed-error
    contract of the serving CLIs, applied to the offline renderer)."""


def _load_trace(path: str) -> dict:
    """Read + validate a Chrome-trace JSON file; raises
    :class:`TraceInputError` with a message naming exactly what is
    wrong (no-such-file, bad JSON, or a JSON document that is not a
    ``{"traceEvents": [...]}`` object)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except OSError as e:
        raise TraceInputError(f"cannot read trace file {path!r}: "
                              f"{e.strerror or e}") from e
    except ValueError as e:
        raise TraceInputError(
            f"{path!r} is not valid JSON ({e}) — expected the "
            "Chrome-trace file written by tools/trace.py or the /trace "
            "endpoint") from e
    if not isinstance(payload, dict) \
            or not isinstance(payload.get("traceEvents"), list):
        raise TraceInputError(
            f"{path!r} is JSON but not a Chrome trace: expected an "
            'object with a "traceEvents" list (got '
            f"{type(payload).__name__})")
    return payload


def _write_artifacts(out_dir: str) -> dict:
    from mmlspark_tpu import obs
    os.makedirs(out_dir, exist_ok=True)
    trace_path = obs.write_chrome_trace(os.path.join(out_dir, "trace.json"))
    metrics_path = obs.write_snapshot(os.path.join(out_dir, "metrics.json"))
    return {"trace": trace_path, "metrics": metrics_path,
            "spans": len(obs.captured())}


def _print_summary(rows: list[dict]) -> None:
    if not rows:
        print("(no spans captured)")
        return
    width = max(len(r["name"]) for r in rows)
    print(f"{'span':<{width}}  {'calls':>6}  {'total ms':>10}  "
          f"{'mean ms':>9}")
    for r in rows:
        print(f"{r['name']:<{width}}  {r['calls']:>6}  "
              f"{r['total_ms']:>10.3f}  {r['mean_ms']:>9.3f}")


def cmd_demo(args: argparse.Namespace) -> int:
    from mmlspark_tpu import obs
    from mmlspark_tpu.core.pipeline import PipelineModel
    from mmlspark_tpu.core.schema import make_image
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.obs.export import summarize_spans
    from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage

    obs.enable(device_annotations=args.device_annotations)
    rng = np.random.default_rng(0)
    table = DataTable({"image": [
        make_image(f"i{k}", rng.integers(0, 255, (40, 40, 3)))
        for k in range(args.rows)]})
    pm = PipelineModel([
        ImageTransformer().resize(32, 32),
        UnrollImage(input_col="image", output_col="image_vec"),
        JaxModel(model=get_model("ConvNet_CIFAR10"), input_col="image_vec",
                 output_col="scores", minibatch_size=16),
    ])
    out = pm.transform(table)
    assert "scores" in out and len(out) == args.rows
    artifacts = _write_artifacts(args.out_dir)
    artifacts["compiled_programs"] = obs.compiled_programs(pm)
    print(json.dumps({"demo": "ok", "rows": args.rows, **artifacts}))
    _print_summary(summarize_spans(top=args.top))
    return 0


def cmd_pipeline(args: argparse.Namespace) -> int:
    from mmlspark_tpu import obs
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.obs.export import summarize_spans
    from mmlspark_tpu.serve.server import _as_stages, _derived_schema, \
        _example_rows

    stage = PipelineStage.load(args.model)
    stages, cache_host, _model = _as_stages(stage)
    schema = None
    if args.schema:
        from mmlspark_tpu.analysis import TableSchema
        try:
            with open(args.schema, "r", encoding="utf-8") as fh:
                schema = TableSchema.from_spec(json.load(fh))
        except OSError as e:
            raise TraceInputError(
                f"cannot read schema file {args.schema!r}: "
                f"{e.strerror or e}") from e
        except ValueError as e:
            raise TraceInputError(
                f"{args.schema!r} is not a valid JSON column spec "
                f"({e})") from e
    if schema is None:
        schema = _derived_schema(stages)
    if schema is None:
        print(f"{args.model}: no input schema derivable — pass --schema "
              "(tools/analyze.py JSON column spec)", file=sys.stderr)
        return 2
    table = _example_rows(schema, args.rows)
    if table is None:
        print("schema is not concrete enough to synthesize rows "
              "(unknown shapes) — pass a fully concrete --schema",
              file=sys.stderr)
        return 2
    obs.enable(device_annotations=args.device_annotations)
    stage.transform(table)
    artifacts = _write_artifacts(args.out_dir)
    artifacts["compiled_programs"] = obs.compiled_programs(cache_host)
    print(json.dumps({"pipeline": args.model, "rows": args.rows,
                      **artifacts}))
    _print_summary(summarize_spans(top=args.top))
    return 0


def cmd_render(args: argparse.Namespace) -> int:
    payload = _load_trace(args.trace)
    events = payload["traceEvents"]
    agg: dict[str, dict] = {}
    flow_ids: set = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise TraceInputError(
                f"{args.trace!r}: traceEvents[{i}] is "
                f"not an object (got {type(ev).__name__})")
        if ev.get("ph") in ("s", "t", "f"):
            flow_ids.add(ev.get("id"))
        if ev.get("ph") != "X":
            continue
        try:
            name = ev["name"]
            dur = float(ev.get("dur", 0.0))
        except (KeyError, TypeError, ValueError) as e:
            raise TraceInputError(
                f"{args.trace!r}: malformed complete event "
                f"({e.__class__.__name__}: {e}) — was this file "
                "written by tools/trace.py?") from e
        row = agg.setdefault(name, {"name": name,
                                    "calls": 0, "total_ms": 0.0})
        row["calls"] += 1
        row["total_ms"] += dur / 1e3
    rows = sorted(agg.values(), key=lambda d: -d["total_ms"])[:args.top]
    for row in rows:
        row["total_ms"] = round(row["total_ms"], 3)
        row["mean_ms"] = round(row["total_ms"] / row["calls"], 3)
    _print_summary(rows)
    if flow_ids:
        print(f"({len(flow_ids)} request flow(s) in the capture — open "
              "in ui.perfetto.dev to see the arrows)")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    demo = sub.add_parser("demo", help="trace the canonical fused pipeline")
    demo.add_argument("--rows", type=int, default=48)
    pipe = sub.add_parser("pipeline", help="trace a saved model")
    pipe.add_argument("model", help="saved stage dir (stage.save output)")
    pipe.add_argument("--schema", default=None,
                      help="JSON column spec (tools/analyze.py format)")
    pipe.add_argument("--rows", type=int, default=32)
    for p in (demo, pipe):
        p.add_argument("--out-dir", default="./trace_out")
        p.add_argument("--top", type=int, default=20)
        p.add_argument("--device-annotations", action="store_true",
                       help="also enter jax.profiler annotations (for a "
                            "simultaneous XProf capture)")
    rend = sub.add_parser("render", help="summarize a trace.json")
    rend.add_argument("trace")
    rend.add_argument("--top", type=int, default=20)

    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    try:
        if args.cmd == "demo":
            return cmd_demo(args)
        if args.cmd == "pipeline":
            return cmd_pipeline(args)
        return cmd_render(args)
    except TraceInputError as e:
        print(f"trace: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""trace — capture/render an obs timeline + metrics snapshot from the CLI.

Subcommands::

    python tools/trace.py demo [--out-dir DIR] [--rows N]
        Run the canonical fused image pipeline (resize → unroll → score,
        the tools/perf_smoke.py scenario) with the obs tracer enabled;
        write trace.json (Chrome-trace / Perfetto ``trace_event`` JSON)
        and metrics.json (registry snapshot), and print a text summary.

    python tools/trace.py pipeline <saved-stage-dir>
        [--schema schema.json] [--rows N] [--out-dir DIR]
        Load a saved PipelineModel / fitted transformer, synthesize
        ``--rows`` input rows from the schema (``--schema`` takes the
        tools/analyze.py JSON column spec; without it the schema is
        derived from a leading JaxModel's input_spec), run one traced
        transform, and write the same artifacts.

    python tools/trace.py render <trace.json> [--top N]
        Aggregate a previously written trace file into a per-span-name
        table (calls, total/mean ms), longest first, plus a one-line
        flow summary (request traces, if the capture carried any).
        FLEET-merged traces (obs/fleet.py, ``tools/fleet.py trace``)
        render too: multi-pid traceEvents with process-group metadata
        are admitted, and the summary adds per-host lane counts plus
        the stitched cross-process flow count. A missing, unreadable,
        or malformed trace file is a typed :class:`TraceInputError` —
        one diagnostic line on stderr and exit code 2, never a
        traceback; a MIXED-CLOCK fleet trace (a process without the
        paired ``(time.time, perf_counter)`` stamp) gets the same
        typed exit-2 diagnostic.

    python tools/trace.py postmortem <dump.json> [--top N] [--frames N]
        Render a flight-recorder dump (obs/flight.py,
        ``MMLSPARK_TPU_FLIGHT=<dir>``): the crash/hang/signal header,
        the tail of the span/event ring as a timeline, every thread's
        stack (innermost ``--frames`` frames), the top registry deltas
        of the final watchdog poll, and the heartbeat table naming the
        stalled lane. Input errors follow the same
        :class:`TraceInputError` / exit-2 discipline as ``render``.

Open trace.json in https://ui.perfetto.dev (or chrome://tracing). For a
device-interleaved view capture ``utils/profiling.trace`` simultaneously
— spans recorded under ``--device-annotations`` also enter
``jax.profiler`` annotations, so both timelines carry the same names.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class TraceInputError(Exception):
    """A trace input file is missing, unreadable, or not Chrome-trace
    JSON. The CLI maps it to one stderr line + exit 2 (the typed-error
    contract of the serving CLIs, applied to the offline renderer)."""


def _load_trace(path: str) -> dict:
    """Read + validate a Chrome-trace JSON file; raises
    :class:`TraceInputError` with a message naming exactly what is
    wrong (no-such-file, bad JSON, or a JSON document that is not a
    ``{"traceEvents": [...]}`` object)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except OSError as e:
        raise TraceInputError(f"cannot read trace file {path!r}: "
                              f"{e.strerror or e}") from e
    except ValueError as e:
        raise TraceInputError(
            f"{path!r} is not valid JSON ({e}) — expected the "
            "Chrome-trace file written by tools/trace.py or the /trace "
            "endpoint") from e
    if not isinstance(payload, dict) \
            or not isinstance(payload.get("traceEvents"), list):
        raise TraceInputError(
            f"{path!r} is JSON but not a Chrome trace: expected an "
            'object with a "traceEvents" list (got '
            f"{type(payload).__name__})")
    return payload


def _load_postmortem(path: str) -> dict:
    """Read + validate a flight-recorder dump; raises
    :class:`TraceInputError` naming what is wrong (no-such-file, bad
    JSON, or JSON that is not an ``obs/flight.py`` dump)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except OSError as e:
        raise TraceInputError(f"cannot read dump file {path!r}: "
                              f"{e.strerror or e}") from e
    except ValueError as e:
        raise TraceInputError(
            f"{path!r} is not valid JSON ({e}) — expected a "
            "flight-recorder dump (obs/flight.py)") from e
    if not isinstance(payload, dict) or "flight" not in payload \
            or not isinstance(payload.get("ring"), list) \
            or not isinstance(payload.get("threads"), dict):
        raise TraceInputError(
            f"{path!r} is JSON but not a flight-recorder dump: expected "
            'an object with "flight", "ring", and "threads" (got '
            f"{type(payload).__name__})")
    return payload


def cmd_postmortem(args: argparse.Namespace) -> int:
    dump = _load_postmortem(args.dump)
    import datetime

    reason = dump.get("reason", "?")
    when = dump.get("time_unix")
    stamp = (datetime.datetime.fromtimestamp(when).isoformat(sep=" ",
                                                            timespec="seconds")
             if isinstance(when, (int, float)) else "?")
    print(f"flight-recorder dump: reason={reason} pid={dump.get('pid')} "
          f"at {stamp}")
    exc = dump.get("exception")
    if isinstance(exc, dict):
        print(f"  exception: {exc.get('type')}: {exc.get('message')}")
        tb = exc.get("traceback") or []
        for line in tb[-3:]:
            print("    " + str(line).rstrip())
    extra = dump.get("extra")
    if isinstance(extra, dict):
        for k, v in extra.items():
            print(f"  {k}: {v}")

    # -- ring tail: the last N records as a relative-time timeline --
    ring = [r for r in dump["ring"] if isinstance(r, dict)]
    print(f"\nring: {len(ring)} record(s) captured")
    tail = ring[-args.top:]
    if tail:
        def _num(v):  # a hand-edited/truncated dump must not traceback
            return v if isinstance(v, (int, float)) else 0

        def _start(r):  # spans carry start_ns, instant events ts_ns
            return _num(r.get("start_ns", r.get("ts_ns", 0)))

        t_end = max(_start(r) + _num(r.get("dur_ns", 0)) for r in tail)
        for r in tail:
            rel_ms = (_start(r) - t_end) / 1e6
            dur = r.get("dur_ns")
            kind = (f"{_num(dur) / 1e6:9.3f}ms" if dur is not None
                    else "    event")
            print(f"  {rel_ms:10.3f}ms  {kind}  "
                  f"[{r.get('thread_name', '?')}] {r.get('name', '?')}")

    # -- thread stacks, innermost frames --
    threads = dump["threads"]
    print(f"\nthreads: {len(threads)}")
    for tid, info in threads.items():
        name = info.get("name", tid) if isinstance(info, dict) else tid
        stack = info.get("stack", []) if isinstance(info, dict) else []
        print(f"  [{name}]")
        for frame in stack[-args.frames:]:
            for line in str(frame).splitlines():
                print("    " + line.rstrip())

    # -- what moved (and stopped moving) in the final poll --
    deltas = dump.get("metric_deltas")
    deltas = deltas if isinstance(deltas, dict) else {}

    def _mag(v):  # rank non-numeric deltas last, don't traceback
        try:
            return abs(float(v))
        except (TypeError, ValueError):
            return -1.0

    if deltas:
        print(f"\ntop metric deltas (last {len(deltas)} moving):")
        ranked = sorted(deltas.items(),
                        key=lambda kv: -_mag(kv[1]))[:args.top]
        for name, d in ranked:
            d_txt = f"{d:+12g}" if isinstance(d, (int, float)) \
                else f"{str(d):>12}"
            print(f"  {d_txt}  {name}")
    else:
        print("\ntop metric deltas: (none moved in the final poll)")

    # -- heartbeat table: who stalled --
    beats = dump.get("heartbeats")
    beats = beats if isinstance(beats, dict) else {}
    if beats:
        print("\nheartbeats:")
        width = max(len(str(n)) for n in beats)
        for name, hb in sorted(beats.items()):
            hb = hb if isinstance(hb, dict) else {}
            state = "BUSY" if hb.get("busy") else "idle"
            print(f"  {name:<{width}}  {state}  beats={hb.get('beats')}"
                  f"  age={hb.get('age_s')}s"
                  f"  threshold={hb.get('threshold_s')}s")
    return 0


def _write_artifacts(out_dir: str) -> dict:
    from mmlspark_tpu import obs
    os.makedirs(out_dir, exist_ok=True)
    trace_path = obs.write_chrome_trace(os.path.join(out_dir, "trace.json"))
    metrics_path = obs.write_snapshot(os.path.join(out_dir, "metrics.json"))
    return {"trace": trace_path, "metrics": metrics_path,
            "spans": len(obs.captured())}


def _print_summary(rows: list[dict]) -> None:
    if not rows:
        print("(no spans captured)")
        return
    width = max(len(r["name"]) for r in rows)
    print(f"{'span':<{width}}  {'calls':>6}  {'total ms':>10}  "
          f"{'mean ms':>9}")
    for r in rows:
        print(f"{r['name']:<{width}}  {r['calls']:>6}  "
              f"{r['total_ms']:>10.3f}  {r['mean_ms']:>9.3f}")


def cmd_demo(args: argparse.Namespace) -> int:
    from mmlspark_tpu import obs
    from mmlspark_tpu.core.pipeline import PipelineModel
    from mmlspark_tpu.core.schema import make_image
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.obs.export import summarize_spans
    from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage

    obs.enable(device_annotations=args.device_annotations)
    rng = np.random.default_rng(0)
    table = DataTable({"image": [
        make_image(f"i{k}", rng.integers(0, 255, (40, 40, 3)))
        for k in range(args.rows)]})
    pm = PipelineModel([
        ImageTransformer().resize(32, 32),
        UnrollImage(input_col="image", output_col="image_vec"),
        JaxModel(model=get_model("ConvNet_CIFAR10"), input_col="image_vec",
                 output_col="scores", minibatch_size=16),
    ])
    out = pm.transform(table)
    assert "scores" in out and len(out) == args.rows
    artifacts = _write_artifacts(args.out_dir)
    artifacts["compiled_programs"] = obs.compiled_programs(pm)
    print(json.dumps({"demo": "ok", "rows": args.rows, **artifacts}))
    _print_summary(summarize_spans(top=args.top))
    return 0


def cmd_pipeline(args: argparse.Namespace) -> int:
    from mmlspark_tpu import obs
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.obs.export import summarize_spans
    from mmlspark_tpu.serve.server import _as_stages, _derived_schema, \
        _example_rows

    stage = PipelineStage.load(args.model)
    stages, cache_host, _model = _as_stages(stage)
    schema = None
    if args.schema:
        from mmlspark_tpu.analysis import TableSchema
        try:
            with open(args.schema, "r", encoding="utf-8") as fh:
                schema = TableSchema.from_spec(json.load(fh))
        except OSError as e:
            raise TraceInputError(
                f"cannot read schema file {args.schema!r}: "
                f"{e.strerror or e}") from e
        except ValueError as e:
            raise TraceInputError(
                f"{args.schema!r} is not a valid JSON column spec "
                f"({e})") from e
    if schema is None:
        schema = _derived_schema(stages)
    if schema is None:
        print(f"{args.model}: no input schema derivable — pass --schema "
              "(tools/analyze.py JSON column spec)", file=sys.stderr)
        return 2
    table = _example_rows(schema, args.rows)
    if table is None:
        print("schema is not concrete enough to synthesize rows "
              "(unknown shapes) — pass a fully concrete --schema",
              file=sys.stderr)
        return 2
    obs.enable(device_annotations=args.device_annotations)
    stage.transform(table)
    artifacts = _write_artifacts(args.out_dir)
    artifacts["compiled_programs"] = obs.compiled_programs(cache_host)
    print(json.dumps({"pipeline": args.model, "rows": args.rows,
                      **artifacts}))
    _print_summary(summarize_spans(top=args.top))
    return 0


def _check_fleet_clocks(path: str, meta: Any) -> dict | None:
    """Validate a fleet-merged trace's ``fleetMeta`` (obs/fleet.py adds
    it). A process that exported no ``(time.time, perf_counter)`` stamp
    pair has records on a bare perf clock that CANNOT be placed on the
    fleet wall-clock timeline — rendering them as comparable would
    silently misorder the fleet; that is the typed mixed-clock error."""
    if not isinstance(meta, dict):
        return None
    unaligned = meta.get("unaligned") or []
    if unaligned:
        raise TraceInputError(
            f"{path!r} is a mixed-clock fleet trace: process(es) "
            f"{', '.join(str(p) for p in unaligned)} exported no "
            "(time.time, perf_counter) stamp pair, so their records "
            "cannot be placed on the fleet wall clock — re-export with "
            "obs.fleet.TelemetryExporter (its snapshots always carry "
            "the stamp) or remove the hand-built snapshot directories")
    return meta


def cmd_render(args: argparse.Namespace) -> int:
    payload = _load_trace(args.trace)
    events = payload["traceEvents"]
    fleet_meta = _check_fleet_clocks(args.trace, payload.get("fleetMeta"))
    agg: dict[str, dict] = {}
    flow_ids: set = set()
    flow_pids: dict = {}       # flow id -> pids it touches
    lanes_by_pid: dict = {}    # pid -> distinct tids of complete events
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise TraceInputError(
                f"{args.trace!r}: traceEvents[{i}] is "
                f"not an object (got {type(ev).__name__})")
        if ev.get("ph") in ("s", "t", "f"):
            # fleet fence-stitch arrows (cat fleet.fence) are barrier
            # structure, not request journeys — counting them as
            # "request flows" would report phantom traces in a capture
            # that carries none
            if ev.get("cat") != "fleet.fence":
                flow_ids.add(ev.get("id"))
            flow_pids.setdefault(ev.get("id"), set()).add(ev.get("pid"))
        if ev.get("ph") != "X":
            continue
        lanes_by_pid.setdefault(ev.get("pid"), set()).add(ev.get("tid"))
        try:
            name = ev["name"]
            dur = float(ev.get("dur", 0.0))
        except (KeyError, TypeError, ValueError) as e:
            raise TraceInputError(
                f"{args.trace!r}: malformed complete event "
                f"({e.__class__.__name__}: {e}) — was this file "
                "written by tools/trace.py?") from e
        row = agg.setdefault(name, {"name": name,
                                    "calls": 0, "total_ms": 0.0})
        row["calls"] += 1
        row["total_ms"] += dur / 1e3
    rows = sorted(agg.values(), key=lambda d: -d["total_ms"])[:args.top]
    for row in rows:
        row["total_ms"] = round(row["total_ms"], 3)
        row["mean_ms"] = round(row["total_ms"] / row["calls"], 3)
    _print_summary(rows)
    if fleet_meta is not None:
        hosts = fleet_meta.get("hosts") or {}
        stitched = sum(1 for pids in flow_pids.values()
                       if len(pids) >= 2)
        per_host = {
            str(h): sum(len(lanes_by_pid.get(pid, ()))
                        for pid in pids or ())
            for h, pids in hosts.items()}
        lane_txt = ", ".join(f"{h}: {n} lane(s)"
                             for h, n in sorted(per_host.items()))
        print(f"fleet trace: {len(hosts)} host(s), "
              f"{len(fleet_meta.get('processes') or [])} process(es) — "
              f"{lane_txt}")
        print(f"({stitched} stitched cross-process flow(s) at the "
              "fence seams)")
    if flow_ids:
        print(f"({len(flow_ids)} request flow(s) in the capture — open "
              "in ui.perfetto.dev to see the arrows)")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    demo = sub.add_parser("demo", help="trace the canonical fused pipeline")
    demo.add_argument("--rows", type=int, default=48)
    pipe = sub.add_parser("pipeline", help="trace a saved model")
    pipe.add_argument("model", help="saved stage dir (stage.save output)")
    pipe.add_argument("--schema", default=None,
                      help="JSON column spec (tools/analyze.py format)")
    pipe.add_argument("--rows", type=int, default=32)
    for p in (demo, pipe):
        p.add_argument("--out-dir", default="./trace_out")
        p.add_argument("--top", type=int, default=20)
        p.add_argument("--device-annotations", action="store_true",
                       help="also enter jax.profiler annotations (for a "
                            "simultaneous XProf capture)")
    rend = sub.add_parser("render", help="summarize a trace.json")
    rend.add_argument("trace")
    rend.add_argument("--top", type=int, default=20)
    post = sub.add_parser("postmortem",
                          help="render a flight-recorder dump")
    post.add_argument("dump", help="flight_*.json written by obs/flight.py")
    post.add_argument("--top", type=int, default=15,
                      help="ring-tail rows and metric-delta rows shown")
    post.add_argument("--frames", type=int, default=4,
                      help="innermost stack frames per thread")

    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    try:
        if args.cmd == "demo":
            return cmd_demo(args)
        if args.cmd == "pipeline":
            return cmd_pipeline(args)
        if args.cmd == "postmortem":
            return cmd_postmortem(args)
        return cmd_render(args)
    except TraceInputError as e:
        print(f"trace: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

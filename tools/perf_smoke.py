"""perf_smoke — fast, CPU-safe check that the perf subsystems actually
engage.

Three gates, all counted at instrumented seams (no timing, so they cannot
flake on a loaded CI box):

* **pipeline fusion** — the planner executes the canonical image pipeline
  (resize → unroll → score) as ONE device segment costing exactly one H2D
  upload and one async D2H fetch round per minibatch, counted through the
  planner's ``_upload``/``_issue_fetch`` seams
  (:func:`mmlspark_tpu.core.plan.count_crossings`).
* **train input prefetch** — on the canonical CIFAR train config the
  ``DeviceLoader`` (train/input.py) actually commits batches ahead of
  consumption: ``committed_ahead_max >= prefetch_depth``, every batch
  flows through exactly once, and the input-wait/step-time decomposition
  is reported.
* **train device preprocessing** — the thin-wire on-device preprocessing
  layer (``train/preprocess.py``) at FULL augmentation
  (pad-crop/flip/brightness/contrast fused into the jitted step) must
  ship ≥ 4× fewer H2D image-payload bytes than the host-preprocess
  baseline — measured at the obs registry byte counters behind the same
  ``core/plan`` seam ``count_crossings`` patches, so the numbers are
  deterministic counts, not wall clock — with loss histories equal to
  ≤ 1e-5 across the two wire forms (the stochastic draws fold from the
  global step, so both runs augment identically), exactly ONE compiled
  step program per input shape, a bit-reproducible resume from a
  mid-epoch checkpoint (the PRNG-fold correctness observable), and the
  Pallas fused-geometry kernel pinned ≤ 1 ULP equal to its pure-XLA
  reference in CPU interpret mode.
* **train elastic recovery** — a supervised worker hard-killed mid-run
  (preemption exit code) must be detected by the training service
  supervisor, re-scaled onto the surviving topology (8 → 4 virtual
  devices, a real dp×fsdp re-shard), and complete with a loss-history
  tail + final params BIT-identical to an uninterrupted continuation at
  the surviving topology from the recovery snapshot — plus shutdown
  hygiene (dead workers' flight heartbeat rows forgotten, no stray
  threads).
* **serve dynamic batching** — a burst of concurrent single-row requests
  through the model server compiles at most ``len(buckets)`` programs
  (bucket quantization holds: no per-shape recompile, counted at the
  jitted composite's own compile cache AND at the dispatch-shape seam)
  and coalesces to a mean batch occupancy > 1 (the batcher actually
  batches under load).
* **serve compile cache (persistent AOT warm start)** — a cold load
  against an empty ``compile_cache`` dir compiles and atomically
  publishes one serialized program per distinct entry shape (bounded by
  the bucket ladder); a second COLD-START
  PROCESS against the same dir loads with ZERO fresh XLA compiles
  (asserted at the cache's own stats, the jit-cache-size hook, and the
  obs ``plan.compile_cache.hits`` counter), serves outputs bit-identical
  to the compiling process, and its warm wall beats the cold wall
  (core/compile_cache.py, docs/serving.md §compile cache).
* **serve sharded (dp-replica fan-out)** — on the 8-device dryrun mesh a
  dp=4 replicated model sustains ≥ 2.5× the dp=1 throughput on a
  latency-bound model (device time simulated by an in-program callback
  hold — virtual CPU devices share the host's cores, so only latency
  overlap measures the fan-out honestly), outputs bit-identical across
  replica counts, all four replicas used, and compiled programs still ≤
  ``len(buckets)`` per model — never replicas × buckets.
* **serve token generation (continuous batching)** — a streaming
  generate burst with seeded join/leave churn must deliver every token
  stream bit-identical to the one-shot whole-sequence decode through
  the same compiled programs (cancelled streams exact prefixes),
  compile ≤ ``len(prefill_buckets) + 1`` programs (ONE fixed-shape
  decode program forever), publish TTFT/ITL gauges through ``/slo``
  into the timeseries MetricHistory, leak no engine threads, and
  sustain ≥ 2× the tokens/s of request-serial decoding on a
  latency-bound decode program (serve/generate.py, docs/serving.md).
* **serve low-precision (int8w+bf16)** — a model served through the
  plan-level precision pass (``core/precision.py``: per-channel int8
  weights dequantized in-program, bf16 activations) must stay within
  its pinned per-model tolerance of the f32 OFFLINE transform across
  packings, compile ≤ ``len(buckets)`` programs per (model, precision),
  ship ≤ 0.35× the f32 param bytes, record a real load-time calibration
  parity, and have its QUANTIZED segment verify clean (zero manual
  collectives) under ``audit_plan_spmd``.
* **serve lifecycle (zero-downtime + self-healing)** — under a SEEDED
  fault plan (``serve/faults.py``: count-deterministic triggers, so the
  chaos replays): a lane worker killed mid-burst by an injected
  non-request exception self-heals (undispatched batches requeued,
  in-flight failed typed-retryable, lane restarted under backoff) with
  zero dropped or duplicated responses; a hot-swap mid-burst flips the
  model version with every answer bit-identical to some version's
  offline transform and the new version provably taking traffic; an
  induced canary fast-burn auto-rolls back via the pure
  ``PromotionPolicy`` with the decision journaled to
  ``decisions.jsonl``; compiled programs stay ≤ ``len(buckets)`` per
  (model, version).
* **obs disabled-path overhead** — the observability seams threaded
  through the fused pipeline (docs/observability.md) must cost < 2% of
  the microbench when the tracer is off. Gated on a measured analytic
  bound (per-call disabled-seam cost × the number of seams one transform
  actually hits, against the transform's own wall time) rather than an
  A/B wall-clock diff, so a loaded CI box cannot flake it.
* **obs request tracing** — a ≥200-request serve burst across dp=4
  replica lanes must yield exactly ONE trace per completed request with
  the admission → pack → dispatch → drain → complete links intact
  (``obs/context.py``): every request's trace id appears on its own
  admit/complete spans and in the links of the bucket-batch spans it
  was coalesced into, every flow exports as Perfetto flow events, and
  all four replica lanes participate (the latency-bound model makes the
  fan-out deterministic, as in the sharded gate).
* **fleet observability** — a dp=4 serve burst plus a 2-worker
  supervised training run exporting telemetry snapshots under ONE
  ``MMLSPARK_TPU_FLEET`` directory (obs/fleet.py) must merge into
  fleet counters BIT-EQUAL to the summed per-process registries, a
  clock-aligned fleet Perfetto trace (``tools/trace.py render`` exit 0,
  cross-process flows stitched at the fenced-collective seams),
  supervisor-published ``train.fleet.*`` aggregates from the worker
  beacons, and a non-empty timeseries history (>= 3 samples) for every
  ``serve.slo_burn_*`` gauge — with no exporter/sampler threads
  surviving teardown (``check_obs_overhead`` keeps gating the
  disabled path: exporter off = one attribute check).
* **flight recorder** — an induced mid-run crash (a NaN'd batch dying
  on the typed ``NonFiniteLossError``) and an induced hang (a serve-lane
  dispatch held inside its compiled program past the recorder's
  threshold) must each leave a well-formed post-mortem dump — intact
  span/event ring, per-thread stacks, registry snapshot, heartbeat
  table — that ``tools/trace.py postmortem`` renders, with the hang
  dump naming the stalled serve lane.
* **spmd clean** — the symbolic SPMD verifier
  (mmlspark_tpu/analysis/spmd.py, docs/spmd_analysis.md) over every
  declared parallel entry point (sharding contracts, partial-sum
  escapes, capacity/divisibility, conditional collectives), the
  drain-fence discipline of the multi-host sources, the multi-chip plan
  audit of the canonical fused pipeline (zero manual collectives), and
  the JAX lint including JX201–JX204 — all at zero unallowlisted
  findings.

The same checks run in tier-1 as tests/test_perf_smoke.py; this entry
point is the ``BENCH_FAST=1``-style standalone for CI wiring:

    JAX_PLATFORMS=cpu python tools/perf_smoke.py

Prints one JSON line and exits non-zero on any regression.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def canonical_pipeline(n: int = 48, minibatch: int = 16):
    """(PipelineModel, table, n, minibatch) — the canonical fused image
    pipeline (resize → unroll → score) every gate here runs against."""
    from mmlspark_tpu.core.pipeline import PipelineModel
    from mmlspark_tpu.core.schema import make_image
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage

    rng = np.random.default_rng(0)
    table = DataTable({"image": [
        make_image(f"i{k}", rng.integers(0, 255, (40, 40, 3)))
        for k in range(n)]})
    stages = [
        ImageTransformer().resize(32, 32),
        UnrollImage(input_col="image", output_col="image_vec"),
        JaxModel(model=get_model("ConvNet_CIFAR10"), input_col="image_vec",
                 output_col="scores", minibatch_size=minibatch),
    ]
    return PipelineModel(stages), table, n, minibatch


def check_fused_crossings() -> dict:
    """Run the canonical pipeline; raise AssertionError on regression."""
    from mmlspark_tpu.core import plan

    pm, table, n, minibatch = canonical_pipeline()
    stages = pm.stages

    segments = plan.describe_plan(stages, table)
    kinds = [(kind, len(ss)) for kind, ss in segments]
    assert kinds == [("device", 3)], (
        f"canonical image pipeline did not plan as one 3-stage device "
        f"segment: {kinds}")

    with plan.count_crossings() as c:
        out = pm.transform(table)
    n_minibatches = -(-n // minibatch)
    assert c.uploads == n_minibatches, (
        f"{c.uploads} H2D uploads for {n_minibatches} minibatches — "
        "fusion must cost exactly one upload per minibatch")
    assert c.fetches == n_minibatches, (
        f"{c.fetches} D2H fetch rounds for {n_minibatches} minibatches — "
        "fusion must cost exactly one async fetch round per minibatch")
    assert len(out) == n and "scores" in out

    return {
        "segments": kinds,
        "minibatches": n_minibatches,
        "h2d_uploads": c.uploads,
        "d2h_fetch_rounds": c.fetches,
        "rows": n,
    }


def check_train_prefetch() -> dict:
    """Canonical CIFAR train config through the prefetching input
    pipeline; raise AssertionError unless the loader ran ahead."""
    from mmlspark_tpu.models.zoo import ConvNetCifar
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    n, bs, depth = 256, 32, 2
    rng = np.random.default_rng(0)
    # uint8 source: ships thin, casts/normalizes inside the jitted step
    x = rng.integers(0, 255, (n, 32, 32, 3)).astype(np.uint8)
    y = rng.integers(0, 10, n).astype(np.int64)
    cfg = TrainConfig(batch_size=bs, epochs=1, optimizer="momentum",
                      learning_rate=0.01, log_every=2,
                      prefetch_depth=depth)
    tr = Trainer(ConvNetCifar(num_classes=10, widths=(8, 16),
                              dense_width=32), cfg)
    tr.fit_arrays(x, y)

    stats = tr.input_stats
    steps = n // bs
    assert stats is not None and stats["batches"] == steps, (
        f"expected {steps} batches through the loader, got {stats}")
    assert stats["committed_ahead_max"] >= depth, (
        f"loader never ran {depth} batches ahead of consumption "
        f"(committed_ahead_max={stats['committed_ahead_max']}) — the "
        "prefetch pipeline is not overlapping input with compute")
    assert 0.0 <= stats["input_bound_fraction"] <= 1.0
    assert tr.history and all(np.isfinite(v) for v in tr.history), (
        f"non-finite training history {tr.history}")
    return {
        "steps": steps,
        "prefetch_depth": depth,
        "batches": stats["batches"],
        "committed_ahead_max": stats["committed_ahead_max"],
        "input_bound_fraction": stats["input_bound_fraction"],
        "input_wait_s": stats["input_wait_s"],
        "step_s": stats["step_s"],
    }


def check_train_device_preprocess(min_reduction: float = 4.0) -> dict:
    """Full-augment thin-wire training vs the host-preprocess baseline;
    raise AssertionError unless the device path ships ≥ ``min_reduction``×
    fewer H2D image bytes with loss parity, one program per input shape,
    and a bit-reproducible mid-epoch resume.

    Both runs carry the SAME DevicePreprocess spec: the device run ships
    raw uint8 and does geometry+normalize+augment in-step; the host run
    feeds ``host_preprocess`` f32 (the float-input convention skips the
    in-step geometry/normalize) so the stochastic stages still execute
    identically on device — the A/B differs ONLY in the wire form, which
    is exactly what the byte gate prices. Bytes are read from the obs
    registry counter at the ``core/plan.train_commit`` seam; the known
    label/weight payload (identical across the A/B) is subtracted so the
    ratio prices the image payload the preprocessing layer owns."""
    import glob
    import tempfile

    from mmlspark_tpu import obs
    from mmlspark_tpu.models.zoo import ConvNetCifar
    from mmlspark_tpu.obs import runtime as obs_rt
    from mmlspark_tpu.ops.pallas.resize import fused_resize_norm
    from mmlspark_tpu.train.loop import TrainConfig, Trainer
    from mmlspark_tpu.train.preprocess import (
        DevicePreprocess, host_preprocess,
    )

    n, bs, side = 640, 32, 32
    steps = n // bs
    rng = np.random.default_rng(0)
    x_u8 = rng.integers(0, 256, (n, side, side, 3)).astype(np.uint8)
    y = rng.integers(0, 10, n).astype(np.int64)
    spec = DevicePreprocess(crop_pad=4, flip_lr=True, brightness=0.1,
                            contrast=(0.9, 1.1))

    def module():
        return ConvNetCifar(num_classes=10, widths=(4, 8), dense_width=16)

    def cfg(**kw):
        return TrainConfig(batch_size=bs, epochs=1, optimizer="momentum",
                           learning_rate=0.01, log_every=1,
                           prefetch_depth=2, preprocess=spec, seed=0, **kw)

    # the label/weight payload both wire forms ship identically per step:
    # y int64 + the 0/1 f32 mask vector
    aux_bytes = steps * bs * (y.dtype.itemsize + 4)

    obs.disable()
    obs.clear()
    obs.registry().reset()
    obs.enable()
    runs: dict = {}
    try:
        for label, data in (("device_thin", x_u8),
                            ("host_f32",
                             host_preprocess(spec, x_u8, 1.0 / 255.0))):
            obs.registry().reset()
            tr = Trainer(module(), cfg())
            tr.fit_arrays(data, y)
            total = int(obs.registry().value("plan.h2d_bytes") or 0)
            runs[label] = {
                "h2d_bytes": total,
                "x_bytes": total - aux_bytes,
                "x_bytes_expected": steps * bs * int(
                    np.prod(data.shape[1:])) * data.dtype.itemsize,
                "programs": obs_rt.jit_cache_size(tr.step_masked),
                "input_bound_fraction":
                    tr.input_stats["input_bound_fraction"],
                "wire_mb": tr.input_stats["wire_mb"],
                "history": tr.history,
                "params": tr.params,
            }
        for label, run in runs.items():
            assert run["x_bytes"] == run["x_bytes_expected"], (
                f"{label}: observed {run['x_bytes']} image-payload bytes "
                f"at the train_commit seam, expected "
                f"{run['x_bytes_expected']} — the registry byte counter "
                "and the commit path disagree")
            assert run["programs"] is None or run["programs"] == 1, (
                f"{label}: {run['programs']} step programs compiled for "
                "ONE input shape — the fused preprocess is recompiling")
        reduction = runs["host_f32"]["x_bytes"] / runs[
            "device_thin"]["x_bytes"]
        assert reduction >= min_reduction, (
            f"thin-wire H2D image bytes only {reduction:.2f}x below the "
            f"host-preprocess baseline ({runs['device_thin']['x_bytes']} "
            f"vs {runs['host_f32']['x_bytes']}) — the uint8 wire "
            "convention regressed")
        hist_dev = np.asarray(runs["device_thin"]["history"])
        hist_host = np.asarray(runs["host_f32"]["history"])
        max_diff = float(np.abs(hist_dev - hist_host).max())
        assert hist_dev.shape == hist_host.shape and max_diff <= 1e-5, (
            f"device-thin vs host-preprocessed loss histories diverge by "
            f"{max_diff} (> 1e-5) — the two wire forms are not replaying "
            "the same preprocessing")

        # ---- bit-reproducible resume: crash past a mid-epoch
        #      checkpoint, resume fresh, and the remaining steps replay
        #      the EXACT augmentation stream (keys fold from the
        #      checkpointed global step) ----
        ck_dir = tempfile.mkdtemp(prefix="pp_resume_")
        cfg_ck = cfg(checkpoint_dir=ck_dir, checkpoint_every=7)
        tr1 = Trainer(module(), cfg_ck)
        real_step, calls = tr1.step_masked, {"n": 0}

        def preempted(state, bx, by, bw):
            calls["n"] += 1
            if calls["n"] > 10:
                raise RuntimeError("induced preemption")
            return real_step(state, bx, by, bw)

        tr1.step_masked = preempted
        try:
            tr1.fit_arrays(x_u8, y)
            raise AssertionError("induced preemption never fired")
        except RuntimeError:
            pass
        assert glob.glob(os.path.join(ck_dir, "*")), (
            "no checkpoint written before the induced preemption")
        tr2 = Trainer(module(), cfg_ck)
        tr2.fit_arrays(x_u8, y)
        # died at step 11 → latest checkpoint step 7 → resume replays
        # batches 1-7 as no-ops and trains 8..20; history and final
        # params must be BIT-identical to the uninterrupted run
        resumed_tail = runs["device_thin"]["history"][7:]
        assert tr2.history == resumed_tail, (
            "resumed loss history differs from the uninterrupted run — "
            f"the per-step PRNG fold is not replaying: {tr2.history[:3]} "
            f"vs {resumed_tail[:3]}")
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(tr2.params),
                        jax.tree_util.tree_leaves(
                            runs["device_thin"]["params"])):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                "resumed params are not bit-identical to the "
                "uninterrupted run")

        # ---- Pallas fused-geometry kernel ≤ 1 ULP from the pure-XLA
        #      reference, in CPU interpret mode, inside jit (the context
        #      the step uses) ----
        import jax as _jax
        src = rng.integers(0, 256, (6, 24, 20, 3)).astype(np.uint8)
        oy = rng.integers(0, 5, 6).astype(np.int32)
        ox = rng.integers(0, 5, 6).astype(np.int32)

        def run_impl(impl):
            fn = _jax.jit(lambda a, b, c: fused_resize_norm(
                a, b, c, (20, 16), (8, 8), 1.0 / 255.0, impl=impl))
            return np.asarray(fn(src, oy, ox))

        np.testing.assert_array_max_ulp(run_impl("xla"),
                                        run_impl("pallas"), maxulp=1)
    finally:
        obs.disable()
        obs.clear()
        obs.registry().reset()

    return {
        "steps": steps,
        "batch_size": bs,
        "min_reduction": min_reduction,
        "h2d_x_bytes_thin": runs["device_thin"]["x_bytes"],
        "h2d_x_bytes_host": runs["host_f32"]["x_bytes"],
        "h2d_reduction": round(reduction, 3),
        "wire_mb_thin": runs["device_thin"]["wire_mb"],
        "wire_mb_host": runs["host_f32"]["wire_mb"],
        "programs_thin": runs["device_thin"]["programs"],
        "loss_history_max_diff": max_diff,
        "input_bound_fraction":
            runs["device_thin"]["input_bound_fraction"],
        "resume_history_len": len(tr2.history),
        "kernel_max_ulp": 1,
    }


def check_train_elastic() -> dict:
    """Kill a worker mid-run; raise AssertionError unless the training
    service supervisor detects the loss, elastically re-scales onto the
    surviving topology, re-shards state from checkpoint, and the
    completed run's loss-history tail + final params are BIT-identical
    to an uninterrupted continuation at the surviving topology from the
    supervisor's recovery snapshot (the PR 10 preemption-replay
    discipline extended to topology change).

    Shape of the run (the hardware-free analog of losing half a pod):
    generation 0 trains the self-test workload in a worker process
    owning 8 virtual devices (mesh dp=4×fsdp=2) and hard-exits with the
    preemption code mid-epoch; policy re-scales to the 4-device rung
    (dp=2×fsdp=2 — a REAL topology change: fsdp-sharded params re-shard
    on restore) and generation 1 completes the schedule. Ingest is the
    deterministic elastic walk (``train/service.elastic_stream``), so
    the global batch composition is identical at every rung and the
    resumed prefix replays exactly the consumed examples — no example
    dropped or double-consumed across the boundary. Shutdown hygiene is
    part of the contract: the supervisor must ``FlightRecorder.forget``
    dead workers' heartbeat rows and leave no stray loader/beacon/pump
    threads (the satellite fix this gate pins)."""
    import tempfile
    import threading

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.data.readers import DECODE_THREAD_PREFIX
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.obs import flight
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    from mmlspark_tpu.train.input import THREAD_PREFIX
    from mmlspark_tpu.train.loop import Trainer
    from mmlspark_tpu.train.service import (
        BEACON_THREAD, PREEMPT_EXIT_CODE, RecoveryPolicy,
        SELFTEST_EPOCH_PASSES, ServiceConfig, Topology, TrainSupervisor,
        WATCH_THREAD, elastic_stream, selftest_config, selftest_data,
    )

    if len(jax.devices()) < 4:
        raise AssertionError(
            "check_train_elastic needs >= 4 devices for the surviving-"
            f"topology control run; got {len(jax.devices())}")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    service_dir = tempfile.mkdtemp(prefix="train_elastic_svc_")
    ckpt_dir = tempfile.mkdtemp(prefix="train_elastic_ckpt_")
    flight_dir = tempfile.mkdtemp(prefix="train_elastic_flight_")
    try:
        # the supervisor itself under the flight recorder: dead workers'
        # service/ heartbeat rows must be forgotten by shutdown
        flight.enable(flight_dir, poll_s=0.1)
        sup = TrainSupervisor(ServiceConfig(
            cmd=(sys.executable,
                 os.path.join(repo, "tools", "train_service.py"),
                 "worker"),
            service_dir=service_dir, checkpoint_dir=ckpt_dir,
            topologies=(Topology(world=1, devices=8),
                        Topology(world=1, devices=4)),
            policy=RecoveryPolicy(max_restarts=0),
            extra_env={"MMLSPARK_TPU_SERVICE_DIE_AT_STEP": "12",
                       "MMLSPARK_TPU_SERVICE_DIE_GEN": "0"}))
        report = sup.run()

        assert report.ok, f"supervised run failed: {report.reason}"
        assert len(report.generations) == 2, (
            f"{len(report.generations)} generations for one preemption "
            "— expected exactly kill + re-scaled completion")
        g0, g1 = report.generations
        assert g0.signal is not None and \
            g0.signal.code == PREEMPT_EXIT_CODE, (
                f"generation 0 signal {g0.signal!r} — the induced "
                f"preemption (exit {PREEMPT_EXIT_CODE}) was not the "
                "detected loss")
        assert report.rescales == 1 and report.evictions == 1
        assert (g1.topology.world, g1.topology.devices) == (1, 4), (
            f"re-scaled topology {g1.topology} — expected the 4-device "
            "survivors rung")
        assert report.snapshots, "no recovery snapshot archived"
        snapshot = report.snapshots[0]

        # supervisor decisions are on disk (observable recovery)
        with open(os.path.join(service_dir, "decisions.jsonl")) as f:
            kinds = [json.loads(ln)["kind"] for ln in f]
        for kind in ("launch", "worker_exit", "evict", "rescale", "done"):
            assert kind in kinds, (
                f"decision log is missing {kind!r}: {kinds}")

        # the re-scaled worker really re-formed the mesh on survivors
        with open(os.path.join(service_dir,
                               "result_gen1_rank0.json")) as f:
            result = json.load(f)
        assert result["devices"] == 4 and result["mesh"]["dp"] == 2 \
            and result["mesh"]["fsdp"] == 2, (
                f"generation 1 mesh {result}")
        assert result["resumed"] >= 1, "generation 1 did not resume "\
            "from the checkpoint — it retrained from scratch"

        # ---- the bit-compat pin: an UNINTERRUPTED continuation at the
        #      surviving topology from the recovery snapshot must match
        #      the elastic run's tail and final params EXACTLY ----
        cfg = selftest_config(snapshot)
        x, y = selftest_data()
        mesh4 = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh4)
        tr.fit_stream(
            elastic_stream(x, y, batch_size=cfg.batch_size,
                           seed=cfg.seed, epochs=SELFTEST_EPOCH_PASSES),
            input_spec=(x.shape[1],))
        assert len(tr.history) == len(result["history"]), (
            f"tail lengths differ: control {len(tr.history)} vs elastic "
            f"{len(result['history'])}")
        tail_max_diff = max(
            (abs(a - b) for a, b in zip(tr.history, result["history"])),
            default=0.0)
        assert tail_max_diff == 0.0, (
            "elastic run's loss tail is not bit-identical to the "
            "uninterrupted continuation at the surviving topology "
            f"(max diff {tail_max_diff}): {result['history'][:3]} vs "
            f"{tr.history[:3]}")
        worker_params = np.load(result["params_npz"])
        flat = jax.tree_util.tree_flatten_with_path(tr.params)[0]
        assert len(flat) == len(worker_params.files)
        diverged = []
        for path, leaf in flat:
            key = "/".join(str(getattr(k, "key", k)) for k in path)
            if not np.array_equal(np.asarray(leaf), worker_params[key]):
                diverged.append(key)
        params_bit_identical = not diverged
        assert params_bit_identical, (
            f"final params differ at {diverged} — the elastic re-shard "
            "drifted from the plain continuation")

        # ---- shutdown hygiene (the PR 11 satellite fix): no dead
        #      heartbeat rows, no stray threads ----
        rec = flight.recorder()
        stray_hb = [n for n in rec.heartbeats()
                    if n.startswith("service/")]
        assert not stray_hb, (
            f"supervisor left dead workers' heartbeat rows {stray_hb} — "
            "FlightRecorder.forget regressed")
        stray = [t.name for t in threading.enumerate()
                 if t.name.startswith((WATCH_THREAD, BEACON_THREAD,
                                       THREAD_PREFIX,
                                       DECODE_THREAD_PREFIX))]
        assert not stray, (
            f"stray service/loader threads after the supervised run: "
            f"{stray}")
    finally:
        flight.disable()
        obs.disable()
        obs.clear()
        obs.registry().reset()

    return {
        "generations": len(report.generations),
        "preempt_exit_code": g0.signal.code,
        "rescales": report.rescales,
        "evictions": report.evictions,
        "topology_full": {"world": 1, "devices": 8},
        "topology_survivors": {"world": g1.topology.world,
                               "devices": g1.topology.devices},
        "mesh_full": {"dp": 4, "fsdp": 2},
        "mesh_survivors": {k: v for k, v in result["mesh"].items()
                           if v > 1},
        "resumed_step": result["resumed"],
        "total_steps": result["steps"],
        "tail_len": len(result["history"]),
        "tail_max_diff": tail_max_diff,
        "params_bit_identical": params_bit_identical,
        "decision_kinds": kinds,
    }


def check_serve_batching() -> dict:
    """Burst the model server with concurrent single-row requests; raise
    AssertionError unless bucket quantization bounded the compiles and
    requests actually coalesced."""
    from mmlspark_tpu.core import plan
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    buckets, n_req = (1, 8, 32), 64
    bundle = get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)
    jm = JaxModel(model=bundle, input_col="image", output_col="scores")
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 255, (n_req, 32 * 32 * 3)).astype(np.uint8)

    server = ModelServer(ServeConfig(buckets=buckets, max_queue=n_req,
                                     deadline_ms=None))
    try:
        # example rows warm the full ladder at load: every bucket's
        # program exists before the first request
        server.add_model("cnn", jm,
                         example=DataTable({"image": [rows[0]]}))
        warmed = server.compiled_programs("cnn")
        # count the burst's H2D uploads at the planner's own seam: the
        # distinct upload shapes are the ground-truth recompile surface,
        # independent of anything the serve layer reports about itself
        with plan.count_crossings() as crossings:
            handles = [server.submit("cnn",
                                     DataTable({"image": [rows[i]]}))
                       for i in range(n_req)]
            outs = [h.result(timeout=300) for h in handles]
        snap = server.stats("cnn").snapshot()
        programs = server.compiled_programs("cnn")
    finally:
        server.close()

    assert all(len(o) == 1 and "scores" in o for o in outs)
    if programs is not None:  # the compile-counter hook (jit cache size)
        assert programs <= len(buckets), (
            f"{programs} XLA programs compiled for a {len(buckets)}-bucket "
            "ladder — requests are recompiling per shape instead of "
            "quantizing to the ladder")
    assert snap["distinct_batch_shapes"] <= len(buckets), (
        f"{snap['distinct_batch_shapes']} distinct batch shapes dispatched "
        f"for a {len(buckets)}-bucket ladder")
    assert len(crossings.upload_shapes) <= len(buckets), (
        f"{len(crossings.upload_shapes)} distinct upload shapes at the "
        f"planner seam ({sorted(crossings.upload_shapes)}) for a "
        f"{len(buckets)}-bucket ladder — per-shape recompiles")
    occ = snap["batch_occupancy_mean"]
    assert occ is not None and occ > 1.0, (
        f"mean batch occupancy {occ} under a {n_req}-request burst — the "
        "dynamic batcher is not coalescing")
    assert snap["completed"] == n_req
    return {
        "buckets": list(buckets),
        "requests": n_req,
        "programs_warmed": warmed,
        "programs_compiled": programs,
        "distinct_batch_shapes": snap["distinct_batch_shapes"],
        "distinct_upload_shapes": len(crossings.upload_shapes),
        "batches": snap["batches"],
        "batch_occupancy_mean": occ,
    }


# the warm cold-start half of check_compile_cache: a FRESH python
# process (nothing shares jax's in-memory caches with the parent) loads
# the same bundle against the same cache dir and reports what it paid.
# NOTE: must use a plain flax model — a bundle with a pure_callback
# (e.g. the latency model) compiles to an unserializable executable and
# the cache deliberately degrades to in-memory compiles for it.
_COMPILE_CACHE_CHILD = r"""
import hashlib, json, sys
repo, bundle_path, cache_dir, buckets_csv = sys.argv[1:5]
sys.path.insert(0, repo)
import numpy as np
from mmlspark_tpu import obs
from mmlspark_tpu.core import compile_cache as cc
from mmlspark_tpu.data.downloader import load_bundle_file
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.obs.metrics import registry
from mmlspark_tpu.serve import ModelServer, ServeConfig

obs.enable()
buckets = tuple(int(b) for b in buckets_csv.split(","))
bundle = load_bundle_file(bundle_path)
jm = JaxModel(model=bundle, input_col="image", output_col="scores")
rng = np.random.default_rng(7)
rows = rng.integers(0, 255, (8, 32 * 32 * 3)).astype(np.uint8)
server = ModelServer(ServeConfig(buckets=buckets, deadline_ms=None,
                                 compile_cache=cache_dir))
try:
    server.add_model("cnn", jm, example=DataTable({"image": [rows[0]]}))
    out = server.submit(
        "cnn", DataTable({"image": list(rows)})).result(timeout=300)
    snap = server.stats("cnn").snapshot()
    programs = server.compiled_programs("cnn")
finally:
    server.close()
digest = hashlib.sha256(np.ascontiguousarray(
    np.stack(list(out["scores"]))).tobytes()).hexdigest()
print(json.dumps({
    "stats": dict(cc.active().stats),
    "programs": programs,
    "obs_hits": registry().value("plan.compile_cache.hits"),
    "digest": digest,
    "warm_wall_s": snap["warm_wall_s"],
}))
"""


def check_compile_cache() -> dict:
    """Persistent AOT compile cache: a cold load compiles and publishes
    every bucket program; a second COLD-START PROCESS against the same
    cache dir comes up with zero fresh XLA compiles (every published
    program deserialized — counted at the cache's own stats, the
    jit-cache-size hook, and the obs ``plan.compile_cache.hits``
    counter), serves bit-identical outputs, and its warm wall beats the
    cold one."""
    import hashlib
    import subprocess
    import tempfile

    from mmlspark_tpu.core import compile_cache as _cc
    from mmlspark_tpu.data.downloader import save_bundle_file
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    buckets = (1, 8)
    bundle = get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 255, (8, 32 * 32 * 3)).astype(np.uint8)

    with tempfile.TemporaryDirectory(prefix="mmlspark-cc-") as tmp:
        bundle_path = os.path.join(tmp, "cnn.bundle")
        save_bundle_file(bundle, bundle_path)
        cache_dir = os.path.join(tmp, "cache")

        _cc.reset()
        server = ModelServer(ServeConfig(buckets=buckets, deadline_ms=None,
                                         compile_cache=cache_dir))
        try:
            jm = JaxModel(model=bundle, input_col="image",
                          output_col="scores")
            server.add_model("cnn", jm,
                             example=DataTable({"image": [rows[0]]}))
            out = server.submit(
                "cnn", DataTable({"image": list(rows)})).result(timeout=300)
            cold_snap = server.stats("cnn").snapshot()
            cold_programs = server.compiled_programs("cnn")
            cold = dict(_cc.active().stats)
        finally:
            server.close()
            _cc.reset()  # don't leave the cache active for other gates
        cold_digest = hashlib.sha256(np.ascontiguousarray(
            np.stack(list(out["scores"]))).tobytes()).hexdigest()

        # the planner may fold several rungs onto one padded entry shape
        # (e.g. the 8-virtual-device mesh pads a 1-row batch to the same
        # shape as the 8-bucket), so gate on what the cold load actually
        # compiled, never on ladder cardinality — but quantization still
        # bounds it by the ladder
        assert cold["hits"] == 0 and cold["compiles"] >= 1 \
            and cold["puts"] == cold["compiles"] \
            and cold["misses"] == cold["puts"], (
            f"cold load against an empty cache should miss+compile+publish "
            f"every program exactly once: {cold}")
        assert cold["puts"] <= len(buckets), (
            f"{cold['puts']} programs published for a {len(buckets)}-bucket "
            f"ladder — per-shape recompiles leaked into the cache: {cold}")
        assert cold["bytes"] > 0, f"nothing published on disk: {cold}"

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _COMPILE_CACHE_CHILD, repo, bundle_path,
             cache_dir, ",".join(str(b) for b in buckets)],
            capture_output=True, text=True, env=env, timeout=600)
        assert proc.returncode == 0, (
            f"warm cold-start process failed:\n{proc.stderr[-2000:]}")
        warm = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        ws = warm["stats"]

        assert ws["compiles"] == 0, (
            f"warm cold-start paid fresh XLA compiles: {ws}")
        assert ws["hits"] == cold["puts"] and ws["puts"] == 0, (
            f"warm cold-start should deserialize every published program "
            f"({cold['puts']} hits, 0 puts): {ws}")
        if warm["programs"] is not None and cold_programs is not None:
            assert warm["programs"] == cold_programs, (
                f"{warm['programs']} programs materialized warm vs "
                f"{cold_programs} cold — the processes disagree on the "
                "program set")
        assert warm["obs_hits"] and warm["obs_hits"] >= cold["puts"], (
            f"obs plan.compile_cache.hits={warm['obs_hits']} — the cache "
            "counters are not mirrored into the metrics registry")
        assert warm["digest"] == cold_digest, (
            "warm-start outputs differ from the compiling process — the "
            "deserialized program is not the program that was published")
        assert warm["warm_wall_s"] < cold_snap["warm_wall_s"], (
            f"warm load wall {warm['warm_wall_s']:.3f}s did not beat the "
            f"cold {cold_snap['warm_wall_s']:.3f}s — deserialization is "
            "not cheaper than compiling")
        return {
            "buckets": list(buckets),
            "cold": {k: cold[k] for k in
                     ("misses", "puts", "compiles", "bytes")},
            "warm": {k: ws[k] for k in ("hits", "compiles", "load_ms")},
            "cold_wall_s": cold_snap["warm_wall_s"],
            "warm_wall_s": warm["warm_wall_s"],
            "bit_identical": True,
        }


class _HoldProbe:
    """Concurrency accounting for the latency model's device holds: how
    many replicas were inside the hold simultaneously — the
    DETERMINISTIC fan-out observable (wall clock on a shared-core box
    jitters; hold concurrency does not)."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.active = 0
        self.peak = 0

    def reset(self):
        with self._lock:
            self.active = self.peak = 0

    def enter(self):
        with self._lock:
            self.active += 1
            self.peak = max(self.peak, self.active)

    def exit(self):
        with self._lock:
            self.active -= 1


def _latency_bundle(sleep_s: float, d_in: int = 24, n_out: int = 8):
    """A served model whose DEVICE time is a fixed latency, not host CPU:
    a dense head plus a ``jax.pure_callback`` hold inside the program.

    On the virtual-CPU dryrun mesh all "devices" share the host's cores,
    so a compute-bound model cannot show replica scaling no matter how
    correct the fan-out is — aggregate FLOP/s is fixed. A real TPU
    replica's device time is exactly a latency the host does not pay, and
    the callback hold models that: N replicas hold concurrently, one
    replica holds serially. The gate therefore measures what it should —
    the scheduler's ability to keep N replicas busy. Returns
    ``(bundle, probe)``; the probe counts concurrent holds."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.bundle import ModelBundle

    probe = _HoldProbe()

    class LatencyMLP(nn.Module):
        sleep_s: float = 0.01
        OUTPUT_NAMES = ("logits",)

        @nn.compact
        def __call__(self, x, output: str = "logits",
                     train: bool = False):
            import time as _time
            y = nn.Dense(n_out, name="head")(x.astype(jnp.float32))

            def hold(v):
                probe.enter()
                _time.sleep(self.sleep_s)
                probe.exit()
                return v

            return jax.pure_callback(
                hold, jax.ShapeDtypeStruct(y.shape, y.dtype), y)

    module = LatencyMLP(sleep_s=sleep_s)
    params = module.init(jax.random.PRNGKey(0),
                         np.zeros((1, d_in), np.float32))["params"]
    return ModelBundle(module=module,
                       params=jax.tree_util.tree_map(np.asarray, params),
                       input_spec=(d_in,),
                       output_names=("logits",)), probe


def check_serve_sharded(min_speedup: float = 2.5) -> dict:
    """DP-replica fan-out on the 8-device dryrun mesh: dp=4 serving must
    sustain ≥ ``min_speedup``× the dp=1 throughput on a latency-bound
    model (see :func:`_latency_bundle`), reach 4 CONCURRENT device holds
    (the deterministic fan-out observable), keep outputs BIT-IDENTICAL
    across replica counts, and compile ≤ ``len(buckets)`` programs per
    model — the per-replica caches each hold one copy of the same
    logical ladder, never replicas × buckets.

    Measurement discipline: holds overlap on lane threads whose GIL
    hand-offs are the noise floor on a shared-core CI box, so the timed
    bursts run under a 1 ms GIL switch interval (restored after) and
    each config reports its best of two trials — the capability, not the
    scheduler jitter of a loaded box. The concurrency assertion stays
    trial-independent."""
    import sys as _sys
    import time

    import jax

    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    if len(jax.devices()) < 8:
        raise AssertionError(
            "check_serve_sharded needs the 8-device dryrun mesh; got "
            f"{len(jax.devices())} device(s)")
    # the hold must dominate the GIL-serialized per-dispatch host work
    # (~2-5 ms/batch of planning+packing) or the ratio loses margin: at
    # 24 ms, dp1 ≈ 32×28 ms and dp4 ≈ max(32×5, 8×28) ms → ~3.5×, so a
    # 2× drift in host overhead still clears the 2.5× gate
    sleep_s, bucket, n_req, trials = 0.024, 8, 32, 2
    bundle, probe = _latency_bundle(sleep_s)
    rng = np.random.default_rng(0)
    reqs = [DataTable({"x": list(
        rng.normal(size=(bucket, 24)).astype(np.float32))})
        for _ in range(n_req)]

    def burst(server):
        probe.reset()
        t0 = time.perf_counter()
        handles = [server.submit("m", r) for r in reqs]
        outs = [h.result(timeout=120) for h in handles]
        return outs, time.perf_counter() - t0, probe.peak

    results: dict[int, dict] = {}
    outputs: dict[int, list] = {}
    switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.001)
    try:
        for dp in (1, 4):
            jm = JaxModel(model=bundle, input_col="x",
                          output_col="scores")
            server = ModelServer(ServeConfig(
                buckets=(bucket,), max_queue=n_req + 8, deadline_ms=None,
                mesh=f"dp={dp}"))
            try:
                server.add_model("m", jm,
                                 example=reqs[0].take(np.arange(1)))
                wall, peak, outs = None, 0, None
                for _ in range(trials):
                    outs, w, p = burst(server)
                    wall = w if wall is None else min(wall, w)
                    peak = max(peak, p)
                snap = server.stats("m").snapshot()
                programs = server.compiled_programs("m")
            finally:
                server.close()
            outputs[dp] = [np.stack([np.asarray(v) for v in o["scores"]])
                           for o in outs]
            results[dp] = {
                "rows_per_s": round(n_req * bucket / wall, 1),
                "wall_s": round(wall, 4),
                "peak_concurrent_holds": peak,
                "batches": snap["batches"],
                "programs_compiled": programs,
                "replicas_used": sorted(snap["replicas"]),
                "replica_batches": {k: v.get("batches")
                                    for k, v in snap["replicas"].items()},
            }
            if programs is not None:
                assert programs <= 1, (
                    f"dp={dp}: {programs} programs for a 1-bucket ladder "
                    "— per-model compiles must stay <= len(buckets), "
                    "not replicas x buckets")
            assert snap["distinct_batch_shapes"] <= 1
    finally:
        _sys.setswitchinterval(switch)

    for a, b in zip(outputs[1], outputs[4]):
        assert np.array_equal(a, b), (
            "dp=4 outputs are not bit-identical to dp=1 single-chip "
            "serving")
    assert len(results[4]["replicas_used"]) == 4, (
        f"dp=4 used replicas {results[4]['replicas_used']} — the "
        "least-loaded scheduler is not fanning out")
    assert results[4]["peak_concurrent_holds"] >= 4, (
        f"dp=4 reached only {results[4]['peak_concurrent_holds']} "
        "concurrent device holds — replica dispatch is serializing")
    assert results[1]["peak_concurrent_holds"] <= 1
    speedup = (results[4]["rows_per_s"] / results[1]["rows_per_s"]
               if results[1]["rows_per_s"] else 0.0)
    assert speedup >= min_speedup, (
        f"dp=4 serve throughput is only {speedup:.2f}x dp=1 "
        f"({results[4]['rows_per_s']} vs {results[1]['rows_per_s']} "
        f"rows/s) on the latency-bound dryrun model — replica fan-out "
        "is not overlapping device time")
    return {
        "min_speedup": min_speedup,
        "speedup": round(speedup, 2),
        "device_hold_ms": sleep_s * 1e3,
        "requests": n_req,
        "bucket": bucket,
        "trials": trials,
        "dp1": results[1],
        "dp4": results[4],
    }


def check_serve_lifecycle() -> dict:
    """Zero-downtime model lifecycle under a seeded fault plan: a lane
    kill mid-burst self-heals (requeue + restart, nothing dropped), a
    hot-swap mid-burst flips versions with every answer bit-identical
    to SOME version's offline transform, an induced canary fast-burn
    auto-rolls back with the decision journaled, and compiled programs
    stay ≤ len(buckets) per (model, version). All triggers are
    count-deterministic (serve/faults.py) — the chaos replays."""
    import tempfile
    import threading
    import time

    import jax

    from mmlspark_tpu.core.retry import RetryPolicy
    from mmlspark_tpu.core.stage import LambdaTransformer
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.bundle import ModelBundle
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.repo import ModelRepo
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.serve import (
        Client, FaultPlan, FaultSpec, ModelServer, ServeConfig,
        THREAD_PREFIX, faults,
    )

    buckets, d_in, n_rows = (1, 4, 8), 6, 24

    def bundle(seed):
        module = MLP(features=(8,), num_outputs=4)
        params = module.init(jax.random.PRNGKey(seed),
                             np.zeros((1, d_in), np.float32))["params"]
        return ModelBundle(
            module=module,
            params=jax.tree_util.tree_map(np.asarray, params),
            input_spec=(d_in,), output_names=("features", "logits"),
            name="m")

    def tbl(sl):
        return DataTable({"x": list(sl)})

    def sc(out):
        return np.stack([np.asarray(v) for v in out["s"]])

    rows = np.random.default_rng(0).normal(
        size=(n_rows, d_in)).astype(np.float32)
    workdir = tempfile.mkdtemp(prefix="serve_lifecycle_")

    # the versioned repo is the artifact source: digests verify on load
    repo = ModelRepo(os.path.join(workdir, "repo"))
    v1 = repo.publish("m", bundle(seed=0))
    v2 = repo.publish("m", bundle(seed=1))
    jm1 = JaxModel(model=repo.load("m", v1)[0], input_col="x",
                   output_col="s")
    jm2 = JaxModel(model=repo.load("m", v2)[0], input_col="x",
                   output_col="s")
    off1 = sc(jm1.transform(tbl(rows)))
    off2 = sc(jm2.transform(tbl(rows)))
    assert not np.array_equal(off1, off2)

    def burning_canary():
        def fn(table):
            if len(table) == 0:
                return table.with_column("s", np.asarray([], object))
            raise RuntimeError("induced canary failure")
        return LambdaTransformer(fn=fn)

    server = ModelServer(ServeConfig(
        buckets=buckets, max_queue=512, lifecycle_dir=workdir,
        slo={"objective": 0.99, "min_requests": 4, "window_s": 30.0,
             "long_window_s": 60.0},
        lane_restart=RetryPolicy(max_attempts=4, base_delay_s=0.02,
                                 max_delay_s=0.1, jitter=0.0)))
    result: dict = {"buckets": list(buckets)}
    try:
        server.add_model("m", jm1, example=tbl(rows[:1]), version=v1)

        def burst(pace_s=0.0):
            """4 client threads × 8 two-row requests; returns
            [(offset, scores)] — every response, exactly one per
            request (the zero-dropped/zero-duplicated observable)."""
            client = Client(server, retry=True)  # LaneFailed retries
            results, errors = [], []
            lock = threading.Lock()

            def worker(k):
                try:
                    for i in range(8):
                        off = ((k * 8 + i) * 2) % (n_rows - 2)
                        out = client.predict(
                            "m", tbl(rows[off:off + 2]), timeout=60)
                        with lock:
                            results.append((off, sc(out)))
                        if pace_s:
                            time.sleep(pace_s)
                except BaseException as e:  # noqa: BLE001 — reported
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(4)]
            for t in threads:
                t.start()
            return threads, results, errors

        # -- phase 1: seeded lane kill mid-burst ----------------------
        plan = FaultPlan([FaultSpec("lane_death", model="m", after=2)],
                         seed=42)
        with faults.inject(plan):
            threads, results, errors = burst()
            for t in threads:
                t.join()
        assert errors == [], f"lane-kill burst dropped requests: {errors}"
        assert len(results) == 32
        for off, got in results:
            assert np.array_equal(got, off1[off:off + 2]), (
                "a response during lane self-healing was not "
                "bit-identical to the stable version's offline transform")
        snap1 = server.snapshot()["m"]
        assert snap1["lane_deaths"] == 1, snap1["lane_deaths"]
        assert snap1["lane_restarts"] == 1
        assert snap1["lane_health"]["alive"] == 1
        programs_v1 = server.compiled_programs("m")
        if programs_v1 is not None:
            assert programs_v1 <= len(buckets)
        result["lane_kill"] = {
            "responses": len(results),
            "lane_deaths": snap1["lane_deaths"],
            "lane_restarts": snap1["lane_restarts"],
            "requeued_batches": snap1["requeued_batches"],
            "faults_fired": plan.counts(),
            "programs_v1": programs_v1,
        }

        # -- phase 2: hot-swap mid-burst ------------------------------
        # traffic provably SPANS the flip: workers keep submitting
        # until the swap completes, then a few more — so both versions
        # answer requests in one burst, deterministically
        flipped = threading.Event()
        results, errors = [], []
        lock = threading.Lock()
        client = Client(server, retry=True)

        def swap_worker(k):
            try:
                done_after = i = 0
                while done_after < 3 and i < 500:
                    off = ((k * 8 + i) * 2) % (n_rows - 2)
                    out = client.predict("m", tbl(rows[off:off + 2]),
                                         timeout=60)
                    with lock:
                        results.append((off, sc(out)))
                    if flipped.is_set():
                        done_after += 1
                    i += 1
            except BaseException as e:  # noqa: BLE001 — reported
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=swap_worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.02)
        server.add_model("m", jm2, example=tbl(rows[:1]), version=v2)
        flipped.set()
        for t in threads:
            t.join()
        assert errors == [], f"swap burst dropped requests: {errors}"
        v1_served = v2_served = 0
        for off, got in results:
            if np.array_equal(got, off1[off:off + 2]):
                v1_served += 1
            elif np.array_equal(got, off2[off:off + 2]):
                v2_served += 1
            else:
                raise AssertionError(
                    "a response through the hot-swap matches NEITHER "
                    "version's offline transform bit-for-bit")
        assert v2_served >= 4, (
            f"only {v2_served} answers from v2 after the flip — the "
            "swap is not taking traffic")
        post = sc(server.predict("m", tbl(rows[:2])))
        assert np.array_equal(post, off2[:2]), "post-swap not on v2"
        swaps = server.lifecycle_decisions("swap")
        assert len(swaps) == 1 and swaps[0]["to_version"] == v2
        programs_v2 = server.compiled_programs("m")
        if programs_v2 is not None:
            assert programs_v2 <= len(buckets)
        result["hot_swap"] = {
            "responses": len(results),
            "served_v1": v1_served, "served_v2": v2_served,
            "programs_v2": programs_v2,
        }

        # -- phase 3: induced canary fast-burn → auto-rollback --------
        server.deploy_canary("m", burning_canary(), mode="shadow",
                             fraction=1.0, version=v2 + 1)
        first = server.lifecycle_tick("m")
        assert first["action"] == "hold"
        for i in range(8):
            out = sc(server.predict("m", tbl(rows[i:i + 1]), timeout=30))
            assert np.array_equal(out, off2[i:i + 1]), (
                "a stable answer changed while the canary burned")
        time.sleep(0.1)  # past the burn ring's coalescing resolution
        deadline = time.monotonic() + 10
        decision = None
        while time.monotonic() < deadline:
            decision = server.lifecycle_tick("m")
            if decision is None or decision["action"] == "rollback":
                break
            time.sleep(0.05)
        assert decision is not None and decision["action"] == "rollback", (
            f"canary fast-burn did not auto-roll back: {decision}")
        assert decision["burn_short"] >= 14.0
        assert server.canary_status("m") is None
        post = sc(server.predict("m", tbl(rows[:2])))
        assert np.array_equal(post, off2[:2]), "stable lost after rollback"
        with open(os.path.join(workdir, "decisions.jsonl")) as f:
            journaled = [json.loads(ln) for ln in f if ln.strip()]
        kinds = [e["kind"] for e in journaled]
        for expected in ("lane_death", "lane_restart", "swap",
                         "canary_deploy", "rollback"):
            assert expected in kinds, f"{expected!r} not journaled"
        result["canary"] = {
            "burn_short": decision["burn_short"],
            "ticks": decision["ticks"],
            "decision_kinds": sorted(set(kinds)),
        }
    finally:
        server.close()
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(THREAD_PREFIX)]
    assert leaked == [], f"serve threads leaked: {leaked}"
    return result


def check_serve_generate(min_speedup: float = 2.0) -> dict:
    """Autoregressive token serving (serve/generate.py): a streaming
    burst with join/leave churn must deliver every request's token
    stream BIT-IDENTICAL to the one-shot whole-sequence decode through
    the same compiled programs (a seeded ``generate_cancel`` churn plan
    truncates some streams — those must be exact PREFIXES), compile at
    most ``len(prefill_buckets) + 1`` XLA programs (the one-fixed-shape
    decode discipline, counted at the engine's own plan cache), publish
    the per-token SLO gauges (TTFT p50/p99, ITL p99) through ``/slo``
    into the timeseries MetricHistory, leak no engine threads, and —
    on a latency-bound decode (callback hold inside the decode
    program, the :func:`_latency_bundle` argument) — sustain
    ≥ ``min_speedup``× the tokens/s of request-serial decoding with
    ≥ 2× fewer decode-step dispatches per token (continuous batching
    actually batches)."""
    import sys as _sys
    import threading
    import time

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.models.sequence import TransformerTagger
    from mmlspark_tpu.obs import timeseries as obs_ts
    from mmlspark_tpu.serve import (
        Client, FaultPlan, FaultSpec, GenerateBatcher, GenerateConfig,
        ModelServer, ServeConfig, THREAD_PREFIX, faults,
    )

    vocab, t_max = 48, 64
    model = TransformerTagger(vocab_size=vocab, embed_dim=16, num_heads=2,
                              num_layers=2, mlp_dim=32, num_tags=vocab,
                              max_len=t_max, causal=True)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    cfg = GenerateConfig(slots=4, t_max=t_max, prefill_buckets=(4, 8),
                         prefill_rows=2, max_new_tokens=8, max_queue=64)
    rng = np.random.default_rng(0)
    n_req = 12
    prompts = [[int(t) for t in rng.integers(1, vocab,
                                             int(rng.integers(2, 9)))]
               for _ in range(n_req)]
    budgets = [int(rng.integers(4, 13)) for _ in range(n_req)]

    obs.disable()
    obs.clear()
    obs.registry().reset()
    obs.enable()
    result: dict = {"requests": n_req,
                    "prefill_buckets": list(cfg.prefill_buckets)}
    server = ModelServer(ServeConfig(slo={
        "objective": 0.99, "min_requests": 1,
        "window_s": 2.0, "long_window_s": 4.0}))
    sampler = obs_ts.enable(
        interval_s=3600.0,  # on-demand: one history sample per /slo poll
        registries=lambda: [obs.registry()] + server.metric_registries())
    try:
        server.add_generator("lm", model, params, config=cfg)
        # the one-shot references FIRST: same engine, same compiled
        # programs, fresh buffers — what every stream must reproduce
        refs = [server.generate_oneshot("lm", p, n)
                for p, n in zip(prompts, budgets)]
        assert all(len(r) >= 1 for r in refs)

        # -- the streaming burst, under a seeded churn plan (clients
        #    abandoning streams mid-decode → slot leave/rejoin) --
        churn = FaultPlan([FaultSpec("generate_cancel", model="lm",
                                     after=6, times=2)], seed=11)
        client = Client(server)
        with faults.inject(churn):
            streams = [client.generate("lm", p, max_new_tokens=n,
                                       stream=True)
                       for p, n in zip(prompts, budgets)]
            got = [st.result(timeout=300) for st in streams]
        cancelled = sum(1 for st in streams if st.cancelled)
        assert churn.counts().get("generate_cancel", 0) >= 1 \
            and cancelled >= 1, (
            f"the seeded churn plan never cancelled a stream "
            f"(fired={churn.counts()}, cancelled={cancelled}) — the "
            "join/leave path went unexercised")
        for i, (st, toks) in enumerate(zip(streams, got)):
            if st.cancelled:
                assert toks == refs[i][:len(toks)], (
                    f"request {i}: cancelled stream is not a prefix of "
                    f"the one-shot decode: {toks} vs {refs[i]}")
            else:
                assert toks == refs[i], (
                    f"request {i}: continuously-batched stream diverged "
                    f"from the one-shot whole-sequence decode: {toks} "
                    f"vs {refs[i]} — slot state is leaking across "
                    "requests")

        snap = server.snapshot()["lm"]
        assert snap.get("generator") is True
        programs = snap["programs_compiled"]
        budget = len(cfg.prefill_buckets) + 1
        if programs is not None:
            assert programs <= budget, (
                f"{programs} XLA programs for a "
                f"{len(cfg.prefill_buckets)}-bucket prefill ladder + ONE "
                f"decode program (budget {budget}) — join/leave churn is "
                "recompiling the decode step")
        assert snap["decode_steps"] > 0
        occ = snap["slot_occupancy_mean"]
        assert occ is not None and occ > 1.0 / cfg.slots, (
            f"mean slot occupancy {occ} under a {n_req}-request burst "
            f"on {cfg.slots} slots — the engine is decoding one request "
            "at a time")

        # -- per-token SLO gauges through /slo into MetricHistory --
        slo = None
        for _ in range(3):
            slo = server.slo_snapshot()
            sampler.sample()
            time.sleep(0.01)
        g = slo["lm"]
        assert g.get("generator") is True
        assert g["ttft_ms"] and g["ttft_ms"]["p50"] > 0 \
            and g["ttft_ms"]["p99"] >= g["ttft_ms"]["p50"], g["ttft_ms"]
        assert g["itl_ms"] and g["itl_ms"]["p99"] > 0, g["itl_ms"]
        history = {}
        for gname in ("serve.ttft_p50_ms", "serve.ttft_p99_ms",
                      "serve.itl_p99_ms"):
            series = obs_ts.range_(gname)
            assert series, f"no MetricHistory for {gname} — the "\
                "serve.ttft_/serve.itl_ sampler prefixes regressed"
            for key, samples in series.items():
                assert len(samples) >= 3, (
                    f"timeseries {key} holds {len(samples)} sample(s); "
                    "the per-token SLO history needs >= 3")
            history[gname] = {k: len(v) for k, v in series.items()}
        result["burst"] = {
            "cancelled": cancelled,
            "faults_fired": churn.counts(),
            "programs_compiled": programs,
            "program_budget": budget,
            "decode_steps": snap["decode_steps"],
            "tokens_out": snap["tokens_out"],
            "slot_occupancy_mean": occ,
            "ttft_ms": g["ttft_ms"],
            "itl_ms": g["itl_ms"],
            "slo_gauge_history": history,
        }
    finally:
        server.close()
        obs_ts.disable()
        obs.disable()
        obs.clear()
        obs.registry().reset()
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(THREAD_PREFIX)]
    assert leaked == [], f"generate engine threads leaked: {leaked}"

    # -- continuous batching vs request-serial decode on a
    #    latency-bound model: the decode program holds inside a
    #    callback (a real device's per-step latency the host does not
    #    pay), so packed slots amortize it and serial decode cannot --
    from mmlspark_tpu.ops.pallas.attention import decode_attention

    hold_s = 0.006  # ×2 layers = 12 ms per decode dispatch

    def holding_attention(q, k, v, keep):
        out = decode_attention(q, k, v, keep)

        def hold(x):
            time.sleep(hold_s)
            return x

        return jax.pure_callback(
            hold, jax.ShapeDtypeStruct(out.shape, out.dtype), out)

    cfg2 = GenerateConfig(slots=4, t_max=32, prefill_buckets=(8,),
                          prefill_rows=4, max_new_tokens=8, max_queue=64)
    n2, max_new2 = 8, 8
    prompts2 = [[int(t) for t in rng.integers(1, vocab, 6)]
                for _ in range(n2)]
    runs: dict[str, dict] = {}
    tokens_by_mode: dict[str, list] = {}
    switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.001)
    try:
        for mode in ("serial", "batched"):
            engine = GenerateBatcher(f"lm_{mode}", model, params,
                                     config=cfg2,
                                     decode_attention_fn=holding_attention)
            try:
                # warm both programs outside the timed burst
                engine.submit(prompts2[0], max_new_tokens=2).result(
                    timeout=300)
                steps0 = engine.stats.decode_steps
                t0 = time.perf_counter()
                if mode == "serial":
                    toks = [engine.submit(p, max_new_tokens=max_new2)
                            .result(timeout=300) for p in prompts2]
                else:
                    pending = [engine.submit(p, max_new_tokens=max_new2)
                               for p in prompts2]
                    toks = [st.result(timeout=300) for st in pending]
                wall = time.perf_counter() - t0
                steps = engine.stats.decode_steps - steps0
            finally:
                engine.close()
            n_tokens = sum(len(t) for t in toks)
            tokens_by_mode[mode] = toks
            runs[mode] = {
                "tokens": n_tokens,
                "wall_s": round(wall, 4),
                "tokens_per_s": round(n_tokens / wall, 1),
                "decode_steps": steps,
            }
    finally:
        _sys.setswitchinterval(switch)
    assert tokens_by_mode["batched"] == tokens_by_mode["serial"], (
        "batched decode produced different tokens than request-serial "
        "decode — continuous batching is not row-independent")
    step_ratio = (runs["serial"]["decode_steps"]
                  / max(1, runs["batched"]["decode_steps"]))
    assert step_ratio >= 2.0, (
        f"continuous batching dispatched only {step_ratio:.2f}x fewer "
        f"decode steps than request-serial decode "
        f"({runs['serial']['decode_steps']} vs "
        f"{runs['batched']['decode_steps']}) for {cfg2.slots} slots — "
        "requests are not sharing decode dispatches")
    speedup = (runs["batched"]["tokens_per_s"]
               / runs["serial"]["tokens_per_s"]
               if runs["serial"]["tokens_per_s"] else 0.0)
    assert speedup >= min_speedup, (
        f"continuous batching sustained only {speedup:.2f}x the "
        f"request-serial tokens/s ({runs['batched']['tokens_per_s']} vs "
        f"{runs['serial']['tokens_per_s']}) on the latency-bound decode "
        "— slot packing is not amortizing the per-step device latency")
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(THREAD_PREFIX)]
    assert leaked == [], f"generate engine threads leaked: {leaked}"
    result["throughput"] = {
        "min_speedup": min_speedup,
        "speedup": round(speedup, 2),
        "step_ratio": round(step_ratio, 2),
        "device_hold_ms": hold_s * 2 * 1e3,
        "slots": cfg2.slots,
        "serial": runs["serial"],
        "batched": runs["batched"],
    }
    return result


def check_serve_lowprec(tolerance: float = 6e-2) -> dict:
    """Serve a model int8w+bf16 (weight-only int8, bf16 activations —
    core/precision.py); raise AssertionError unless its outputs stay
    within the pinned per-model ``tolerance`` of the f32 OFFLINE
    transform across packings (single-row, partial-bucket, and
    full-bucket requests), compiled programs stay ≤ ``len(buckets)``
    for the (model, precision), the load-time calibration measured a
    real (non-zero, in-tolerance) parity, the quantized params ship
    ≤ 0.35× the f32 bytes, and ``audit_plan_spmd`` verifies the
    QUANTIZED segment clean (zero manual collectives) — the serving
    half of ROADMAP item 5, gated the PR 9 way on counted seams, not
    wall clock."""
    import jax

    from mmlspark_tpu.analysis.spmd import audit_plan_spmd
    from mmlspark_tpu.core import plan
    from mmlspark_tpu.core.precision import (
        PrecisionPolicy, quantized_bytes,
    )
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.bundle import ModelBundle
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    buckets, d_in, n_req = (1, 8), 24, 24
    rng = np.random.default_rng(0)
    module = MLP(features=(32,), num_outputs=8)
    params = module.init(jax.random.PRNGKey(0),
                         np.zeros((1, d_in), np.float32))["params"]
    bundle = ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(d_in,), output_names=("features", "logits"))

    def jm():
        return JaxModel(model=bundle, input_col="x", output_col="scores",
                        mesh_spec={"dp": 1})

    rows = (rng.normal(size=(n_req, d_in)) * 2).astype(np.float32)
    table = DataTable({"x": list(rows)})
    ref = np.stack(list(jm().transform(table)["scores"]))  # f32 offline

    policy = PrecisionPolicy(mode="int8w", tolerance=tolerance)
    served = jm()
    server = ModelServer(ServeConfig(buckets=buckets, max_queue=n_req + 8,
                                     deadline_ms=None))
    try:
        server.add_model("m", served, precision=policy,
                         example=table.take(np.arange(8)))
        snap_load = server.snapshot()["m"]
        # packings: 8 single-row, 2× 4-row (partial bucket), 1× 8-row
        handles = [(i, 1, server.submit("m", table.take(np.arange(i, i + 1))))
                   for i in range(8)]
        handles += [(i, 4, server.submit(
            "m", table.take(np.arange(i, i + 4)))) for i in (8, 12)]
        handles += [(16, 8, server.submit(
            "m", table.take(np.arange(16, 24))))]
        worst = 0.0
        for start, n, h in handles:
            got = np.stack(list(h.result(timeout=120)["scores"]))
            worst = max(worst, float(
                np.abs(got - ref[start:start + n]).max()))
        programs = server.compiled_programs("m")
        snap = server.stats("m").snapshot()
    finally:
        server.close()

    assert worst > 0.0, (
        "int8w serving returned the f32 outputs bit-for-bit — the "
        "precision pass is not engaging (cache key or policy threading "
        "regressed)")
    assert worst <= tolerance, (
        f"int8w+bf16 serving diverges from the f32 offline transform by "
        f"max-abs {worst:.4g} across packings (pinned per-model "
        f"tolerance {tolerance:g})")
    calibrated = snap_load.get("precision_parity")
    assert calibrated is not None and 0 < calibrated <= tolerance, (
        f"load-time calibration parity {calibrated!r} is missing or "
        f"out of tolerance — ModelServer.add_model's calibration flow "
        "regressed")
    assert snap_load.get("precision", "").startswith("int8w")
    if programs is not None:
        assert programs <= len(buckets), (
            f"{programs} XLA programs for a {len(buckets)}-bucket ladder "
            "under ONE precision — per-(model, precision) compiles must "
            "stay on the ladder")
    assert snap["distinct_batch_shapes"] <= len(buckets)

    # the quantized storage really ships thin (the HBM/wire win)
    seg = plan.collect_segment(
        [served], 0, lambda c: plan._entry_meta(table, c),
        min_stages=1, precision=policy)
    _fn, stored = plan.segment_composite(seg, plan._segment_mesh(seg))
    nbytes, f32_bytes = quantized_bytes(stored)
    assert nbytes <= 0.35 * f32_bytes, (
        f"quantized params are {nbytes} B vs {f32_bytes} B f32 — int8 "
        "weight storage regressed")

    # the QUANTIZED segment verifies clean against the serve contracts
    audit = audit_plan_spmd([served],
                            lambda c: plan._entry_meta(table, c),
                            n_rows=n_req, precision=policy)
    assert audit.ok and len(audit.segments) == 1, audit.format()
    assert audit.segments[0].schedule.ops == [], (
        "the precision pass introduced manual collectives into the "
        "served segment")

    return {
        "buckets": list(buckets),
        "requests": len(handles),
        "precision": policy.describe(),
        "pinned_tolerance": tolerance,
        "calibration_parity": calibrated,
        "serve_parity_max_abs": worst,
        "programs_compiled": programs,
        "distinct_batch_shapes": snap["distinct_batch_shapes"],
        "quantized_bytes": nbytes,
        "f32_bytes": f32_bytes,
        "weight_bytes_ratio": round(nbytes / f32_bytes, 4),
        "audit_findings": len(audit.findings),
        "audit_collectives": len(audit.segments[0].schedule.ops),
    }


def check_obs_request_tracing(n_req: int = 200, dp: int = 4) -> dict:
    """A serve burst across dp replica lanes; raise AssertionError
    unless every completed request resolves to exactly one request
    trace with intact fan-in/fan-out links.

    The request-scoped tracing contract (docs/observability.md): a
    trace id is minted at admission, the admit/complete spans carry it,
    and the pack/dispatch/drain bucket-batch spans link every coalesced
    member — so the registry of captured spans reconstructs each
    request's whole journey across the scheduler and replica-lane
    threads, and the Chrome-trace export draws it as one flow. Uses the
    latency-bound callback-hold model of :func:`check_serve_sharded` so
    all ``dp`` lanes deterministically participate."""
    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.obs import context as obs_context
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    if len(jax.devices()) < dp:
        raise AssertionError(
            f"check_obs_request_tracing needs >= {dp} devices for the "
            f"dp={dp} fan-out; got {len(jax.devices())}")
    buckets = (1, 8, 32)
    bundle, probe = _latency_bundle(0.004)
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(n_req, 24)).astype(np.float32)

    obs.disable()
    obs.clear()
    obs.registry().reset()
    obs.enable()
    try:
        jm = JaxModel(model=bundle, input_col="x", output_col="scores")
        server = ModelServer(ServeConfig(
            buckets=buckets, max_queue=n_req + 8, deadline_ms=None,
            mesh=f"dp={dp}"))
        try:
            server.add_model("m", jm,
                             example=DataTable({"x": [rows[0]]}))
            obs.clear()  # warmup spans out: count the burst only
            handles = [server.submit("m", DataTable({"x": [rows[i]]}))
                       for i in range(n_req)]
            outs = [h.result(timeout=300) for h in handles]
            snap = server.stats("m").snapshot()
        finally:
            server.close()
        assert all(len(o) == 1 and "scores" in o for o in outs)
        assert snap["completed"] == n_req

        trace_ids = [h.trace_id for h in handles]
        assert all(t is not None for t in trace_ids), (
            "tracer enabled but requests carry no trace id — minting "
            "at admission regressed")
        assert len(set(trace_ids)) == n_req, (
            f"{len(set(trace_ids))} distinct trace ids for {n_req} "
            "requests — trace ids must be unique per request")
        traces = obs_context.request_traces()
        broken = []
        for h in handles:
            spans = traces.get(h.trace_id)
            if spans is None:
                broken.append((h.trace_id, "no spans captured"))
                continue
            why = obs_context.check_journey(spans)
            if why is not None:
                broken.append((h.trace_id, why))
        assert not broken, (
            f"{len(broken)}/{n_req} completed requests lack an intact "
            f"admission → pack → dispatch → drain → complete trace; "
            f"first failures: {broken[:5]}")

        # the fan-in is real: at least one bucket-batch span links >1
        # request (the burst coalesces), and the fan-out reached every
        # replica lane
        pack_links = [len(s.links or ()) for s in obs.captured()
                      if getattr(s, "name", "") == "serve/pack"]
        assert pack_links and max(pack_links) > 1, (
            f"no pack span linked more than one request "
            f"({pack_links}) — fan-in links regressed")
        assert sorted(snap["replicas"]) == list(range(dp)), (
            f"burst used replicas {sorted(snap['replicas'])} of "
            f"{list(range(dp))}")

        # every trace renders as one flow in the export
        trace = obs.chrome_trace()
        flow_ids = {e["id"] for e in trace["traceEvents"]
                    if e.get("ph") in ("s", "t", "f")}
        missing_flows = set(trace_ids) - flow_ids
        assert not missing_flows, (
            f"{len(missing_flows)} request traces have no Perfetto "
            "flow events in the export")
    finally:
        obs.disable()
        obs.clear()
        obs.registry().reset()

    return {
        "requests": n_req,
        "dp": dp,
        "buckets": list(buckets),
        "traces": len(set(trace_ids)),
        "intact": n_req - len(broken),
        "batches": snap["batches"],
        "batch_occupancy_mean": snap["batch_occupancy_mean"],
        "max_pack_fan_in": max(pack_links),
        "replicas_used": sorted(snap["replicas"]),
        "flow_ids_exported": len(flow_ids & set(trace_ids)),
    }


def _well_formed_dump(path: str) -> dict:
    """Load one flight-recorder dump and assert the post-mortem contract:
    intact ring, per-thread stacks, registry snapshot, heartbeat table,
    mesh/config fingerprint — and that ``tools/trace.py postmortem``
    renders it (exit 0)."""
    with open(path, "r", encoding="utf-8") as fh:
        dump = json.load(fh)
    for key in ("flight", "reason", "ring", "threads", "registry",
                "heartbeats", "fingerprint"):
        assert key in dump, f"dump {path} is missing {key!r}"
    assert isinstance(dump["ring"], list) and dump["ring"], (
        f"dump {path} captured an empty span/event ring")
    assert all(isinstance(r, dict) and "name" in r
               for r in dump["ring"]), "malformed ring records"
    assert dump["threads"], f"dump {path} captured no thread stacks"
    assert all(isinstance(t, dict) and t.get("stack")
               for t in dump["threads"].values()), (
        "a dumped thread has an empty stack")
    assert "counters" in dump["registry"], "registry snapshot malformed"
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mmlspark_tools_trace",  # plain `import trace` would shadow the
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "trace.py"))  # stdlib module of the same name
    trace_cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_cli)
    code = trace_cli.main(["postmortem", path])
    assert code == 0, (
        f"tools/trace.py postmortem exited {code} on {path}")
    return dump


def check_flight_recorder() -> dict:
    """Induce a mid-run crash AND a hang on the dryrun mesh; raise
    AssertionError unless each produces a well-formed flight-recorder
    dump (recent ring + per-thread stacks + registry snapshot) that
    ``tools/trace.py postmortem`` renders.

    The crash is a NaN'd training batch dying on the typed
    :class:`NonFiniteLossError` (the anomaly plane's sentinel riding the
    lagged loss fetch) — the flight recorder dumps at the failure point,
    inside ``Trainer.fit_arrays``. The hang is a serve-lane dispatch
    stalled inside its compiled program (the callback-hold model of
    :func:`check_serve_sharded`, held past the recorder's hang
    threshold) — the lane heartbeat goes stale while busy and the
    watchdog dumps, naming the lane, before the dispatch completes."""
    import glob
    import tempfile
    import time

    from mmlspark_tpu import obs
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import ConvNetCifar
    from mmlspark_tpu.obs import flight
    from mmlspark_tpu.obs.anomaly import NonFiniteLossError
    from mmlspark_tpu.serve import ModelServer, ServeConfig
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    out: dict = {}
    try:
        # ---- induced crash: NaN batch → typed raise → dump ----
        crash_dir = tempfile.mkdtemp(prefix="flight_crash_")
        flight.enable(crash_dir, poll_s=0.05)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 32, 32, 3)).astype(np.float32)
        x[5] = np.nan  # lands in step 1's batch
        y = rng.integers(0, 10, 64).astype(np.int64)
        tr = Trainer(ConvNetCifar(num_classes=10, widths=(4,),
                                  dense_width=8),
                     TrainConfig(batch_size=16, epochs=1, optimizer="sgd",
                                 learning_rate=0.1, log_every=1,
                                 prefetch_depth=0, input_scale=1.0))
        crashed = None
        try:
            tr.fit_arrays(x, y)
        except NonFiniteLossError as e:
            crashed = e
        assert crashed is not None, (
            "the NaN'd batch did not raise NonFiniteLossError — the "
            "non-finite sentinel regressed")
        crash_dumps = sorted(glob.glob(
            os.path.join(crash_dir, "flight_crash_*.json")))
        assert crash_dumps, (
            "NonFiniteLossError raised but no flight_crash_*.json dump "
            "appeared — Trainer.fit_arrays is not calling "
            "obs.flight.on_crash at the failure point")
        crash = _well_formed_dump(crash_dumps[-1])
        assert crash["exception"]["type"] == "NonFiniteLossError", (
            f"crash dump recorded {crash['exception']['type']}, expected "
            "the sentinel's NonFiniteLossError")
        assert any(r.get("name") == "train/step" for r in crash["ring"]), (
            "crash dump ring holds no train/step spans — the recorder "
            "is not dumping the live tracer ring")
        flight.disable()

        # ---- induced hang: dispatch stalled past the threshold ----
        hang_dir = tempfile.mkdtemp(prefix="flight_hang_")
        hold_s, threshold_s = 1.2, 0.3
        bundle, _probe = _latency_bundle(hold_s)
        jm = JaxModel(model=bundle, input_col="x", output_col="scores")
        server = ModelServer(ServeConfig(buckets=(1,), max_queue=8,
                                         deadline_ms=None))
        try:
            server.add_model("m", jm, example=DataTable(
                {"x": [np.zeros(24, np.float32)]}))
            # enable AFTER the load+warm: only the stalled dispatch is
            # under watch
            flight.enable(hang_dir, hang_threshold_s=threshold_s,
                          poll_s=0.05)
            h = server.submit("m", DataTable(
                {"x": [np.zeros(24, np.float32)]}))
            deadline = time.monotonic() + 30.0
            hang_dumps: list = []
            while time.monotonic() < deadline and not hang_dumps:
                hang_dumps = glob.glob(
                    os.path.join(hang_dir, "flight_hang_*.json"))
                time.sleep(0.05)
            result = h.result(timeout=60)  # the stall completes after
            assert len(result) == 1 and "scores" in result
        finally:
            server.close()
        assert hang_dumps, (
            f"no hang dump after a {hold_s}s dispatch stall against a "
            f"{threshold_s}s threshold — the lane heartbeat or watchdog "
            "regressed")
        hang = _well_formed_dump(hang_dumps[0])
        stalled = hang["extra"]["heartbeat"]
        assert stalled.startswith("serve/"), (
            f"hang dump blames heartbeat {stalled!r}, expected the "
            "serve lane that was holding")
        assert hang["extra"]["stalled_for_s"] >= threshold_s
        lane_threads = [t["name"] for t in hang["threads"].values()]
        assert any("ServeLane" in n or "lane" in n.lower()
                   or "DynamicBatcher" in n for n in lane_threads) \
            or len(lane_threads) >= 2, (
            f"hang dump captured threads {lane_threads} — the stalled "
            "lane's stack is missing")
        out = {
            "crash_dump": crash_dumps[-1],
            "crash_exception": crash["exception"]["type"],
            "crash_ring_records": len(crash["ring"]),
            "crash_threads": len(crash["threads"]),
            "hang_dump": hang_dumps[0],
            "hang_heartbeat": stalled,
            "hang_stalled_for_s": hang["extra"]["stalled_for_s"],
            "hang_ring_records": len(hang["ring"]),
            "hang_threads": len(hang["threads"]),
        }
    finally:
        flight.disable()
        obs.disable()
        obs.clear()
        obs.registry().reset()
    return out


# the gate's jax-free supervised worker: records train spans + counters
# through the obs substrate (tracer on via the supervisor's
# MMLSPARK_TPU_OBS, fleet exporter on via the propagated
# MMLSPARK_TPU_FLEET), writes its registry-counter TRUTH file for the
# bit-equality assertion, then flushes its final fleet snapshot
_FLEET_WORKER_SRC = """
import json, os, time
from mmlspark_tpu import obs
from mmlspark_tpu.obs import fleet
from mmlspark_tpu.obs.metrics import Counter, format_series
from mmlspark_tpu.train.service import service_context

with service_context(beacon_interval_s=0.05) as info:
    assert info is not None
    assert obs.enabled()        # MMLSPARK_TPU_OBS=1 from the supervisor
    assert fleet.enabled()      # MMLSPARK_TPU_FLEET propagated
    reg = obs.registry()
    for k in range(24):
        with obs.span("train/step", "train"):
            time.sleep(0.0005)
        reg.counter("train.steps").add()
        reg.counter("train.commits", loader="w%d" % info.rank).add(2)
        if k % 8 == 0:
            # the fenced-collective seam the fleet trace stitches at
            with obs.span("train/liveness_sync", "train"):
                time.sleep(0.002)
    reg.gauge("train.host_step_ms", host=str(info.rank)).set(
        1.0 + info.rank)
    time.sleep(0.2)  # >= one beacon interval with the final counters
    truth = {format_series(m.name, m.labels): m.value
             for m in reg.iter_metrics() if isinstance(m, Counter)}
    with open(os.path.join(info.service_dir,
                           "truth_%d.json" % info.rank), "w") as f:
        json.dump(truth, f)
    fleet.disable()  # final exit snapshot AFTER the truth capture
"""


def check_fleet_obs() -> dict:
    """The fleet telemetry plane (obs/fleet.py + obs/timeseries.py): a
    dp=4 serve burst plus a 2-worker supervised run exporting under ONE
    ``MMLSPARK_TPU_FLEET`` directory must merge into a fleet view whose
    summed ``serve.*``/``train.*`` counters are BIT-EQUAL to the sum of
    the per-process registries (this process's + both workers' truth
    files), render a clock-aligned fleet Perfetto trace that
    ``tools/trace.py render`` accepts exit-0 (with >= 1 stitched
    cross-process flow at the workers' fence seams), and leave a
    non-empty timeseries history (>= 3 samples) for every
    ``serve.slo_burn_*`` gauge — the metric HISTORY the adaptive-ladder
    and autoscaling actuators consume. Teardown is pinned: no
    FleetExporter/TimeSeriesSampler threads survive, and the tracer is
    left disabled so ``check_obs_overhead`` stays honest."""
    import json as _json
    import shutil
    import sys as _sys
    import tempfile
    import threading
    import time

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.obs import fleet as obs_fleet
    from mmlspark_tpu.obs import timeseries as obs_ts
    from mmlspark_tpu.obs.metrics import Counter, format_series, registry
    from mmlspark_tpu.serve import ModelServer, ServeConfig
    from mmlspark_tpu.train.service import (
        RecoveryPolicy, ServiceConfig, Topology, TrainSupervisor,
    )

    if len(jax.devices()) < 4:
        raise AssertionError(
            "check_fleet_obs needs >= 4 dryrun devices for the dp=4 "
            f"serve mesh; got {len(jax.devices())}")
    fleet_dir = tempfile.mkdtemp(prefix="fleet_obs_")
    svc_dir = os.path.join(fleet_dir, "service")
    obs.enable()
    obs.clear()
    registry().reset()
    obs_fleet.enable(fleet_dir, interval_s=0.2)
    server = None
    try:
        # -- 1. the dp=4 serve burst (latency-bound model, as in the
        #       sharded/tracing gates) + 3 SLO polls, each followed by
        #       one timeseries sample --
        bundle, _probe = _latency_bundle(0.004)
        jm = JaxModel(model=bundle, input_col="x", output_col="scores")
        server = ModelServer(ServeConfig(
            buckets=(8,), max_queue=64, deadline_ms=None, mesh="dp=4",
            slo={"window_s": 2.0, "long_window_s": 4.0,
                 "min_requests": 1}))
        rng = np.random.default_rng(0)
        reqs = [DataTable({"x": list(
            rng.normal(size=(8, 24)).astype(np.float32))})
            for _ in range(24)]
        server.add_model("m", jm, example=reqs[0].take(np.arange(1)))
        handles = [server.submit("m", r) for r in reqs]
        outs = [h.result(timeout=120) for h in handles]
        assert len(outs) == len(reqs)
        sampler = obs_ts.sampler()
        assert sampler is not None, (
            "obs.fleet.enable must start the timeseries sampler")
        for _ in range(3):
            server.slo_snapshot()   # publishes the serve.slo_burn_* /
            sampler.sample()        # queue-depth gauges; one history
            time.sleep(0.01)        # sample per poll
        burn_history = {}
        for gname in ("serve.slo_burn_short", "serve.slo_burn_long"):
            got = obs_ts.range_(gname)
            assert got, f"no timeseries history for {gname}"
            for key, samples in got.items():
                assert len(samples) >= 3, (
                    f"timeseries {key} holds {len(samples)} sample(s); "
                    "the SLO-gauge history needs >= 3")
            burn_history[gname] = {k: len(v) for k, v in got.items()}
        assert obs_ts.range_("serve.queue_depth"), (
            "no serve.queue_depth history")

        # -- 2. the 2-worker supervised run (jax-free workers; the
        #       supervisor propagates MMLSPARK_TPU_FLEET and publishes
        #       train.fleet.* aggregates from the beacon excerpts) --
        report = TrainSupervisor(ServiceConfig(
            cmd=(_sys.executable, "-c", _FLEET_WORKER_SRC),
            service_dir=svc_dir, topologies=(Topology(world=2),),
            policy=RecoveryPolicy(), poll_s=0.05, grace_seconds=15.0,
            worker_obs=True, worker_flight=False)).run()
        assert report.ok, f"fleet worker generation failed: {report.reason}"
        truths = []
        for rank in (0, 1):
            with open(os.path.join(svc_dir, f"truth_{rank}.json"),
                      encoding="utf-8") as fh:
                truths.append(_json.load(fh))
        fleet_steps = registry().value("train.fleet.steps", rank=0)
        assert fleet_steps == 24, (
            "supervisor did not aggregate worker beacon deltas into "
            f"train.fleet.steps{{rank=0}} (got {fleet_steps})")
        assert (registry().value("train.fleet.steps", rank=0) or 0) \
            + (registry().value("train.fleet.steps", rank=1) or 0) == 48

        # -- 3. expected fleet sum: THIS process's counters (default +
        #       per-model serve registries) + both workers' truths —
        #       captured immediately before the final snapshot --
        expected: dict[str, float] = {}

        def _acc(items):
            for key, value in items:
                expected[key] = expected.get(key, 0.0) + float(value)

        for reg in [registry()] + server.metric_registries():
            _acc((format_series(m.name, m.labels), m.value)
                 for m in reg.iter_metrics() if isinstance(m, Counter))
        for truth in truths:
            _acc(truth.items())
        obs_fleet.disable()   # writes the final exit snapshot
        server.close()

        # -- 4. merge + bit-equality --
        view = obs_fleet.FleetCollector(fleet_dir).collect()
        merged = {format_series(m.name, m.labels): m.value
                  for m in view.registry.iter_metrics()
                  if isinstance(m, Counter)}
        missing = {k: v for k, v in expected.items()
                   if merged.get(k) != v}
        extra = sorted(set(merged) - set(expected))
        assert not missing and not extra, (
            "fleet-merged counters are not bit-equal to the summed "
            f"per-process registries: mismatched={missing} "
            f"extra={extra}")
        n_serve = sum(1 for k in merged if k.startswith("serve."))
        n_train = sum(1 for k in merged if k.startswith("train."))
        assert n_serve > 0 and n_train > 0

        # -- 5. the fleet timeline renders exit-0 through the CLI --
        trace_path = os.path.join(fleet_dir, "fleet_trace.json")
        fleet_payload = view.chrome_trace()
        with open(trace_path, "w", encoding="utf-8") as fh:
            _json.dump(fleet_payload, fh)
        meta = fleet_payload["fleetMeta"]
        assert meta["unaligned"] == []
        assert meta["stitched_flows"] >= 1, (
            "no cross-process flow stitched at the workers' "
            "train/liveness_sync fence seams")
        import importlib.util as _ilu
        spec = _ilu.spec_from_file_location(
            "mmlspark_tools_trace",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "trace.py"))
        trace_cli = _ilu.module_from_spec(spec)
        spec.loader.exec_module(trace_cli)
        rc = trace_cli.main(["render", trace_path, "--top", "5"])
        assert rc == 0, f"tools/trace.py render exited {rc} on the " \
                        "fleet trace"
        return {
            "processes": len(view.processes),
            "counters_merged": len(merged),
            "serve_counters": n_serve,
            "train_counters": n_train,
            "stitched_flows": meta["stitched_flows"],
            "trace_render_rc": rc,
            "burn_gauge_history": burn_history,
            "fleet_steps_rank0": int(fleet_steps),
            "supervisor_ok": report.ok,
        }
    finally:
        obs_fleet.disable()
        if server is not None:
            server.close()
        obs.disable()
        obs.clear()
        registry().reset()
        leaked = [t.name for t in threading.enumerate()
                  if t.name in ("FleetExporter", "TimeSeriesSampler")]
        assert not leaked, f"fleet threads leaked: {leaked}"
        shutil.rmtree(fleet_dir, ignore_errors=True)


def check_obs_overhead(max_fraction: float = 0.02) -> dict:
    """The obs seams' disabled-path cost on the fused-pipeline microbench
    must stay under ``max_fraction`` (2%) of the transform itself.

    Methodology (all measured, no A/B wall-clock diff to flake):

    1. time one warm fused transform with the tracer OFF (median of 5);
    2. run it once with the tracer ON and count what the seams actually
       did — spans recorded and counter increments — giving the number
       of disabled-path flag checks one transform performs;
    3. measure the per-call cost of the disabled seam itself (a
       ``span()`` call: one module-flag check + shared null context —
       strictly an upper bound on a bare flag check) over 200k calls;
    4. gate ``unit_cost × seam_calls / transform_time < max_fraction``.
    """
    import statistics
    import time

    from mmlspark_tpu import obs
    from mmlspark_tpu.obs.metrics import registry
    from mmlspark_tpu.obs.spans import span as obs_span

    assert not obs.enabled(), (
        "check_obs_overhead must start with the tracer disabled")
    pm, table, _n, _mb = canonical_pipeline()
    pm.transform(table)  # compile + warm outside the timed passes

    t_run = statistics.median(
        _timed_once(pm, table, time) for _ in range(5))

    # count the seams one transform hits: every span and every counter
    # increment is one disabled-path flag check (plus the span-call
    # overhead where a span exists — bounded below by pricing EVERY site
    # at the span() unit cost, the more expensive of the two)
    registry().reset()
    obs.enable()
    obs.clear()
    try:
        pm.transform(table)
        n_spans = len(obs.captured())
        counters = registry().snapshot()["counters"]
        n_increments = int(
            3 * counters.get("plan.h2d_uploads", 0)       # uploads+bytes+shape
            + 2 * counters.get("plan.d2h_fetches", 0)     # fetch + d2h bytes
            + counters.get("plan.segment_compiles", 0))
    finally:
        obs.disable()
        obs.clear()
        registry().reset()
    # enter/exit both touch the seam; +8 for timed()'s lazy imports etc.
    seam_calls = 2 * n_spans + n_increments + 8

    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        obs_span("overhead-probe", "bench")
    unit = (time.perf_counter() - t0) / reps

    fraction = (unit * seam_calls) / t_run if t_run > 0 else 0.0
    assert fraction < max_fraction, (
        f"disabled-path obs overhead bound {fraction:.4%} exceeds "
        f"{max_fraction:.0%} of the fused-pipeline microbench "
        f"({seam_calls} seam calls × {unit * 1e9:.0f} ns vs "
        f"{t_run * 1e3:.1f} ms transform) — an obs seam grew work on "
        "the disabled path")
    return {
        "transform_ms": round(t_run * 1e3, 3),
        "seam_calls": seam_calls,
        "spans_when_enabled": n_spans,
        "disabled_span_ns": round(unit * 1e9, 1),
        "overhead_fraction_bound": round(fraction, 6),
        "max_fraction": max_fraction,
    }


def check_spmd_clean() -> dict:
    """Repo-wide static SPMD gate; raise AssertionError on any finding.

    Needs the 8-device CPU mesh (tier-1's conftest forces it; the
    standalone entry point sets the flag itself before jax loads)."""
    import jax

    from mmlspark_tpu.analysis.spmd import audit_plan_spmd, verify_repo
    from mmlspark_tpu.core import plan

    if len(jax.devices()) < 8:
        raise AssertionError(
            "check_spmd_clean needs the 8-device CPU mesh "
            "(--xla_force_host_platform_device_count=8); got "
            f"{len(jax.devices())} device(s)")
    res = verify_repo()
    findings = [str(f) for f in res["findings"]]
    assert findings == [], (
        "SPMD verifier findings over the parallel layer:\n"
        + "\n".join(findings))

    # multi-chip plan audit of the canonical fused pipeline: a fused
    # inference segment must carry ZERO manual collectives and its
    # minibatch walk must divide the mesh's data extent
    pm, table, n, _mb = canonical_pipeline()
    audit = audit_plan_spmd(pm.stages,
                            lambda col: plan._entry_meta(table, col),
                            n_rows=n)
    assert audit.ok and len(audit.segments) == 1, (
        "plan spmd audit regressed:\n" + audit.format())

    # the sharded serve entries: the same audit over a DP replica's
    # single-chip sub-mesh (manual-collective-free) and a tp
    # model-parallel layout (collectives only over the declared
    # model-parallel axes) — what ModelServer.add_model(mesh=...)
    # enforces at load time
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    from mmlspark_tpu.serve.mesh import MODEL_PARALLEL_AXES
    replica_mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    tp_mesh = make_mesh(MeshSpec(dp=1, tp=2), jax.devices()[:2])
    serve_audits = {
        "dp_replica": audit_plan_spmd(
            pm.stages, lambda col: plan._entry_meta(table, col),
            n_rows=n, mesh=replica_mesh),
        "tp_segment": audit_plan_spmd(
            pm.stages, lambda col: plan._entry_meta(table, col),
            n_rows=n, mesh=tp_mesh,
            expect_axes=MODEL_PARALLEL_AXES),
    }
    for label, a in serve_audits.items():
        assert a.ok and len(a.segments) == 1, (
            f"sharded serve audit [{label}] regressed:\n" + a.format())

    # the AST lint (incl. JX201–JX204) over the codebase
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lint_jax
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lint = lint_jax.lint_paths([os.path.join(repo, "mmlspark_tpu")])
    assert lint == [], "\n".join(str(f) for f in lint)

    reports = res["reports"]
    return {
        "entry_points": sorted(reports),
        "collectives": {name: rep.schedule.counts()
                        for name, rep in reports.items()},
        "shard_map_sites": sum(len(rep.sites)
                               for rep in reports.values()),
        "fence_files": res["fence_files"],
        "plan_segments": len(audit.segments),
        "plan_minibatches": audit.segments[0].minibatches,
        "serve_audits": sorted(serve_audits),
        # the real count, not a constant: the asserts above guarantee 0
        # on the happy path, and a refactor that stops raising would
        # surface here instead of silently passing the tier-1 gate
        "findings": (len(res["findings"]) + len(audit.findings)
                     + sum(len(a.findings) for a in serve_audits.values())
                     + len(lint)),
    }


def check_concurrency_clean(min_confirmed: int = 5,
                            max_static_s: float = 20.0,
                            max_fraction: float = 0.02) -> dict:
    """The whole-repo concurrency gate (docs/concurrency.md), three
    clauses in one pass:

    1. **static** — ``analysis.concurrency.analyze_repo()`` over the
       package finishes inside ``max_static_s`` with ZERO unsuppressed
       findings, and every suppression carries a non-empty
       justification (the pragma/allowlist policy is load-bearing);
    2. **witness** — a dp=4 serve burst (shadow canary deployed,
       overload driven, ``snapshot()`` + ``lifecycle_tick()`` +
       ``rollback()`` exercised) runs with the lock-order witness on:
       at least ``min_confirmed`` static lock-order edges must be
       CONFIRMED by real acquisitions, with ZERO order violations
       (no edge observed in both directions);
    3. **overhead** — the witness's disabled-path cost — the delta of a
       witnessed acquire/release cycle over a raw ``threading.Lock``,
       priced at every acquisition the burst actually performed — stays
       under ``max_fraction`` (2%) of the burst wall time, the same
       analytic-bound methodology as :func:`check_obs_overhead`.
    """
    import threading
    import time

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.analysis.concurrency import analyze_repo
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.obs import lockwitness as lw
    from mmlspark_tpu.serve.config import ServeConfig
    from mmlspark_tpu.serve.errors import Overloaded
    from mmlspark_tpu.serve.server import ModelServer

    if len(jax.devices()) < 8:
        raise AssertionError(
            "check_concurrency_clean needs the 8-device CPU mesh "
            "(--xla_force_host_platform_device_count=8); got "
            f"{len(jax.devices())} device(s)")
    assert not lw.enabled(), (
        "check_concurrency_clean must start with the witness disabled")

    # -- 1. static pass under a wall budget --
    t0 = time.perf_counter()
    an = analyze_repo()
    static_s = time.perf_counter() - t0
    assert static_s < max_static_s, (
        f"whole-repo concurrency pass took {static_s:.1f}s "
        f"(budget {max_static_s:.0f}s) — the analyzer grew "
        "superlinear work")
    findings = [str(f) for f in an.findings]
    assert findings == [], (
        "concurrency verifier findings over the repo:\n"
        + "\n".join(findings))
    for f, why in an.suppressed:
        assert why.strip(), f"unjustified concurrency suppression: {f}"

    # -- 2. witnessed dp=4 serve burst --
    sleep_s, n_req, rows = 0.004, 64, 4
    bundle, _probe = _latency_bundle(sleep_s)
    bundle2, _probe2 = _latency_bundle(sleep_s)
    jm = JaxModel(model=bundle, input_col="x", output_col="scores")
    jm2 = JaxModel(model=bundle2, input_col="x", output_col="scores")
    d_in = int(np.prod(tuple(bundle.input_spec)))
    rng = np.random.default_rng(7)

    def table(n):
        return DataTable({"x": [rng.random(d_in).astype(np.float32)
                                for _ in range(n)]})

    obs.enable(max_traces=4)
    lw.enable()
    rejected = 0
    t0 = time.perf_counter()
    try:
        srv = ModelServer(ServeConfig(buckets=(8,), max_queue=40,
                                      deadline_ms=None, mesh="dp=4"))
        srv.add_model("m", jm, example=table(1))
        srv.deploy_canary("m", jm2, mode="shadow", fraction=1.0,
                          version="v2")
        handles = []
        for _ in range(n_req):
            try:
                handles.append(srv.submit("m", table(rows)))
            except Overloaded:
                rejected += 1
        for h in handles:
            h.result(timeout=60.0)
        srv.snapshot()
        srv.lifecycle_tick("m")
        srv.rollback("m")
        srv.close()
    finally:
        burst_wall = time.perf_counter() - t0
        lw.disable()
        obs.disable()
        obs.clear()
    cross = lw.crosscheck(an.static_edges())
    n_ops = sum(lw.acquire_counts().values())
    lw.reset()
    assert cross["violations"] == [], (
        "lock-order inversion observed at runtime (both directions of "
        f"an edge executed): {cross['violations']}")
    assert len(cross["confirmed"]) >= min_confirmed, (
        f"only {len(cross['confirmed'])} of {len(an.static_edges())} "
        f"static lock-order edges confirmed at runtime (need "
        f">={min_confirmed}): {cross['confirmed']} — the serve burst "
        "stopped exercising the hot lock nests, or the witness names "
        "drifted from the analyzer's identities")

    # -- 3. disabled-path witness cost, priced per real acquisition --
    reps = 200_000
    probe_w = lw.named_lock("concurrency.overhead.probe")
    probe_r = threading.Lock()
    t0 = time.perf_counter()
    for _ in range(reps):
        with probe_r:
            pass
    unit_raw = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        with probe_w:
            pass
    unit_wit = (time.perf_counter() - t0) / reps
    delta = max(0.0, unit_wit - unit_raw)
    fraction = (delta * n_ops) / burst_wall if burst_wall > 0 else 0.0
    assert fraction < max_fraction, (
        f"disabled-path witness overhead bound {fraction:.4%} exceeds "
        f"{max_fraction:.0%} of the serve burst ({n_ops} acquisitions "
        f"× {delta * 1e9:.0f} ns vs {burst_wall * 1e3:.0f} ms) — the "
        "witness grew work on its disabled path")

    return {
        "locks": len(an.locks),
        "static_edges": len(an.static_edges()),
        "static_s": round(static_s, 2),
        "findings": len(findings),
        "suppressed": len(an.suppressed),
        "confirmed": len(cross["confirmed"]),
        "plausible": len(cross["plausible"]),
        "novel": len(cross["novel"]),
        "violations": len(cross["violations"]),
        "burst_requests": n_req,
        "burst_rejected": rejected,
        "burst_wall_s": round(burst_wall, 2),
        "lock_ops": n_ops,
        "witness_delta_ns": round(delta * 1e9, 1),
        "overhead_fraction_bound": round(fraction, 6),
        "max_fraction": max_fraction,
    }


def check_serve_fleet() -> dict:
    """The fleet serving tier (serve/fleet/) end-to-end on REAL serve
    workers: two supervised backend processes behind the router, each
    warmed from the persistent compile cache the single-process
    reference published. kill -9 one backend mid-burst — every request
    in the burst still answers, bit-identical to the single-process
    reference (the router re-routes torn requests, the supervisor
    journals the exit and respawns generation 1). Then an induced
    fast-burn (tiny-deadline volley against tightened SLO windows)
    drives the autoscaler to spawn a THIRD backend, whose beacon proves
    it warmed from the cache with zero fresh XLA compiles. The fleet
    telemetry plane merges the router's counters bit-equal across the
    process set, and teardown leaks no router/supervisor/exporter
    threads."""
    import shutil
    import signal as _signal
    import tempfile
    import threading
    import time
    import urllib.error
    import urllib.request

    from mmlspark_tpu import obs
    from mmlspark_tpu.core import compile_cache as _cc
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.obs import fleet as obs_fleet
    from mmlspark_tpu.obs.metrics import Counter, format_series, registry
    from mmlspark_tpu.serve import ModelServer, ServeConfig
    from mmlspark_tpu.serve.fleet import (
        BackendPool, FleetConfig, FleetRouter, ScalePolicy,
        ServeSupervisor,
    )
    from mmlspark_tpu.serve.fleet.worker import (
        MODEL_NAME, SELFTEST_BUCKETS, selftest_bundle, selftest_rows,
    )
    from mmlspark_tpu.service.core import read_beacon
    from mmlspark_tpu.train.service import RecoveryPolicy

    tmp = tempfile.mkdtemp(prefix="mmlspark-fleet-serve-")
    service_dir = os.path.join(tmp, "fleet")
    cache_dir = os.path.join(tmp, "cache")
    obs_dir = os.path.join(tmp, "obs")
    rows = selftest_rows(8)

    # -- 1. single-process reference: the same seeded model served in
    #       process. Publishes every bucket program into the cache all
    #       three backends must warm from, and fixes the answer every
    #       router response is compared against (exact — the JSON float
    #       round trip is lossless for float32-derived doubles) --
    _cc.reset()
    ref_server = ModelServer(ServeConfig(
        buckets=SELFTEST_BUCKETS, deadline_ms=None,
        compile_cache=cache_dir))
    try:
        jm = JaxModel(model=selftest_bundle(), input_col="image",
                      output_col="scores")
        ref_server.add_model(MODEL_NAME, jm,
                             example=DataTable({"image": [rows[0]]}))
        out = ref_server.submit(
            MODEL_NAME,
            DataTable({"image": list(rows)})).result(timeout=300)
        ref_scores = [[float(v) for v in r] for r in out["scores"]]
        published = dict(_cc.active().stats)
    finally:
        ref_server.close()
        _cc.reset()
    assert published["puts"] >= 1, (
        f"reference serve published no programs to warm from: "
        f"{published}")

    obs.enable()
    obs.clear()
    registry().reset()
    obs_fleet.enable(obs_dir, interval_s=0.2)
    pool = BackendPool()
    sup = ServeSupervisor(FleetConfig(
        service_dir=service_dir, initial_backends=2,
        compile_cache=cache_dir,
        policy=RecoveryPolicy(max_restarts=2,
                              rescale_on_exhausted=False,
                              preempt_exit_codes=()),
        scale=ScalePolicy(fast_burn=5.0, burn_sustain_s=0.5,
                          min_backends=1, max_backends=3,
                          cooldown_s=2.0, idle_sustain_s=3600.0),
        # tight SLO windows so induced burn shows within a beacon or two
        slo={"window_s": 2.0, "long_window_s": 4.0, "min_requests": 1},
    ), pool=pool)
    router = FleetRouter(pool)

    def _journal_kinds():
        path = os.path.join(service_dir, "decisions.jsonl")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [json.loads(line) for line in f]

    def _wait(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while not pred():
            assert time.monotonic() < deadline, f"timed out: {what}"
            time.sleep(0.1)

    try:
        sup.start()
        router.start()
        host, port = router.address
        base = f"http://{host}:{port}"
        body = json.dumps({"rows": [{"image": r.tolist()} for r in rows],
                           "dtype": "uint8"}).encode()
        burn_body = json.dumps(
            {"rows": [{"image": rows[0].tolist()}], "dtype": "uint8",
             "deadline_ms": 1}).encode()

        def predict(payload=body, timeout=60.0):
            req = urllib.request.Request(
                f"{base}/v1/models/{MODEL_NAME}:predict", data=payload,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return (int(r.headers["X-Fleet-Backend"]),
                        json.loads(r.read()))

        _wait(lambda: pool.up_count() == 2, 180.0,
              "initial backends routable")

        # -- 2. kill -9 one backend mid-burst: zero drops, every answer
        #       bit-identical to the single-process reference --
        results, errors = [], []

        def burst_one():
            try:
                results.append(predict())
            except Exception as e:  # any error here IS the failure
                errors.append(repr(e))

        n_burst = 24
        threads = [threading.Thread(target=burst_one)
                   for _ in range(n_burst)]
        for t in threads[:n_burst // 2]:
            t.start()
        victim_bid, victim = next(iter(sup._backends.items()))
        os.kill(victim.proc.pid, _signal.SIGKILL)
        for t in threads[n_burst // 2:]:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, (
            f"{len(errors)}/{n_burst} requests dropped across the "
            f"kill: {errors[:3]}")
        assert len(results) == n_burst
        backends_seen = {bid for bid, _ in results}
        for _bid, resp in results:
            got = [r["scores"] for r in resp["rows"]]
            assert got == ref_scores, (
                "router answer diverged from single-process serving "
                f"(via backend {_bid})")

        # the supervisor noticed the kill and respawned generation 1
        _wait(lambda: any(e["kind"] == "restart"
                          for e in _journal_kinds()), 60.0,
              "restart journaled after kill -9")
        _wait(lambda: pool.up_count() == 2, 180.0,
              "killed backend respawned and routable")

        # -- 3. induced fast-burn: tiny-deadline volley → sustained
        #       burn in the beacons → autoscaler spawns backend 3 --
        deadline = time.monotonic() + 120.0
        burn_statuses = []
        while pool.up_count() < 3:
            assert time.monotonic() < deadline, (
                f"autoscaler never spawned a third backend; journal="
                f"{[e['kind'] for e in _journal_kinds()]}")
            try:
                predict(burn_body, timeout=30.0)
                burn_statuses.append(200)
            except urllib.error.HTTPError as e:
                burn_statuses.append(e.code)  # 504s are the point
            time.sleep(0.05)
        scale_ups = [e for e in _journal_kinds()
                     if e["kind"] == "scale_up"]
        assert scale_ups, "third backend up but no scale_up journaled"
        new_bid = scale_ups[0]["bid"]
        assert new_bid not in (victim_bid,)

        # the scaled-up backend warmed from the compile cache: its
        # beacon carries the worker's own cache stats — zero fresh XLA
        # compiles, every program deserialized
        beacon = read_beacon(service_dir, new_bid, 0)
        assert beacon is not None, "no beacon from the scaled backend"
        cc_stats = beacon.get("compile_cache")
        assert cc_stats is not None, (
            "scaled-up backend beacon has no compile-cache stats — "
            "MMLSPARK_TPU_COMPILE_CACHE did not reach the worker")
        assert cc_stats["compiles"] == 0 and cc_stats["hits"] >= 1, (
            f"scaled-up backend paid fresh XLA compiles: {cc_stats}")

        # and it serves the SAME answers (clean request, no deadline)
        post_bid, resp = predict()
        assert [r["scores"] for r in resp["rows"]] == ref_scores

        # -- 4. the telemetry plane: the router's counters merge into
        #       the fleet view bit-equal, alongside the worker exports --
        expected = {
            format_series(m.name, m.labels): m.value
            for m in registry().iter_metrics()
            if isinstance(m, Counter)
            and m.name.startswith("serve.fleet.router.")}
        assert expected.get("serve.fleet.router.reroutes", 0) >= 1, (
            "kill -9 mid-burst never exercised the re-route path")
        obs_fleet.disable()  # final exit snapshot before collecting
        view = obs_fleet.FleetCollector(obs_dir).collect(
            include_ring=False)
        merged = {
            format_series(m.name, m.labels): m.value
            for m in view.registry.iter_metrics()
            if isinstance(m, Counter)
            and m.name.startswith("serve.fleet.router.")}
        assert merged == expected, (
            "fleet-merged router counters are not bit-equal to the "
            f"router registry: missing/changed "
            f"{dict(set(expected.items()) - set(merged.items()))}, "
            f"extra {dict(set(merged.items()) - set(expected.items()))}")
        worker_snaps = [p for p in view.processes
                        if p.pid != os.getpid()]
        assert worker_snaps, (
            "no backend process exported to the fleet dir — "
            "MMLSPARK_TPU_FLEET did not reach the workers")

        journal = _journal_kinds()
        kinds = [e["kind"] for e in journal]
        status = sup.status()
        return {
            "burst_requests": n_burst,
            "burst_errors": 0,
            "burst_backends": sorted(backends_seen),
            "killed_bid": victim_bid,
            "bit_identical": True,
            "burn_statuses": {s: burn_statuses.count(s)
                              for s in sorted(set(burn_statuses))},
            "scale_up_reason": scale_ups[0]["reason"],
            "scaled_bid": new_bid,
            "scaled_backend_cache": {k: cc_stats[k] for k in
                                     ("hits", "compiles")},
            "journal_kinds": sorted(set(kinds)),
            "scale_ups": status["scale_ups"],
            "router_counters": {k.rsplit(".", 1)[-1]: v
                                for k, v in expected.items()},
            "fleet_processes": len(view.processes),
        }
    finally:
        router.close()
        sup.close()
        obs_fleet.disable()
        obs.disable()
        obs.clear()
        registry().reset()
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith(("ServeFleetRouter",
                                        "ServeFleetWatch"))
                  or t.name in ("FleetExporter", "TimeSeriesSampler")]
        assert not leaked, f"fleet threads leaked: {leaked}"
        shutil.rmtree(tmp, ignore_errors=True)


def check_train_to_serve() -> dict:
    """Continuous deployment, checkpoint to fleet-wide promotion
    (mmlspark_tpu/lifecycle, docs/lifecycle.md): a supervised fine-tune
    must end with its eval-gated checkpoint SERVING through the
    deployer — dark-published with provenance, ramped shadow → canary
    under live traffic, promoted with the repo ``CURRENT`` flipped, and
    every served answer bit-identical to SOME published version's
    offline transform with ZERO dropped requests. A degraded run (same
    workload, shifted data) must dark-publish but ROLL BACK in shadow on
    parity drift — repo CURRENT and the serving plane both back on the
    good version. The whole journey is journaled across train + serve +
    lifecycle decisions with cross-references both ways, replays from
    the lifecycle journal alone, lands the ``lifecycle.rollouts`` /
    ``lifecycle.rollbacks`` counters and the ``deploy.wall_s`` gauge,
    and stitches >= 1 cross-process fleet-timeline flow at the
    train→deployment publish-fence seam."""
    import shutil
    import tempfile
    import threading

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.lifecycle import (
        Deployer, EvalGate, PublishPolicy, RolloutPolicy, ServerTarget,
        bundle_from_npz, replay_decisions,
    )
    from mmlspark_tpu.models.bundle import ModelBundle
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.repo import ModelRepo
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.obs import fleet as obs_fleet
    from mmlspark_tpu.obs.metrics import registry
    from mmlspark_tpu.serve import (
        Client, ModelServer, ServeConfig, THREAD_PREFIX,
    )
    from mmlspark_tpu.train.service import (
        RecoveryPolicy, ServiceConfig, Topology, TrainSupervisor,
    )

    repo_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    workdir = tempfile.mkdtemp(prefix="train_to_serve_")
    repo_root = os.path.join(workdir, "repo")
    lifecycle_dir = os.path.join(workdir, "lifecycle")
    serve_dir = os.path.join(workdir, "serve")
    fleet_dir = os.path.join(workdir, "fleetobs")
    d_in, n_rows = 8, 24  # the selftest worker's XOR input width
    module = MLP(features=(16,), num_outputs=2)  # its architecture

    def train_run(tag: str, extra_env: dict) -> object:
        """One supervised fine-tune whose clean completion feeds the
        eval gate; a pass dark-publishes the result params as a new
        repo version with provenance."""
        sup = TrainSupervisor(ServiceConfig(
            cmd=(sys.executable,
                 os.path.join(repo_dir, "tools", "train_service.py"),
                 "worker"),
            service_dir=os.path.join(workdir, f"svc_{tag}"),
            checkpoint_dir=os.path.join(workdir, f"ckpt_{tag}"),
            topologies=(Topology(world=1, devices=4),),
            policy=RecoveryPolicy(max_restarts=0),
            extra_env=extra_env,
            publish=PublishPolicy(
                model="xor", repo_root=repo_root,
                gate=EvalGate(min_points=4, tail=4),
                bundle_from_result=lambda r: bundle_from_npz(
                    r, module, (d_in,)),
                notes=f"fine-tune {tag}",
                lifecycle_dir=lifecycle_dir)))
        report = sup.run()
        assert report.ok, f"train run {tag} failed: {report.reason}"
        return sup

    def tbl(sl):
        return DataTable({"input": list(sl)})

    def sc(out):
        return np.stack([np.asarray(v) for v in out["scores"]])

    rows = np.random.default_rng(0).normal(
        size=(n_rows, d_in)).astype(np.float32)

    # bit-identity discipline: every request is exactly the largest
    # bucket (8 rows — no padding, no coalescing with foreign rows),
    # and the offline references are computed in the SAME 8-row chunks,
    # so served and offline answers run the identical program shape —
    # on the multi-device CPU mesh XLA's partitioning is shape-
    # dependent, so a (24, d) offline batch vs a bucket-padded (4, d)
    # serve batch differ by 1 ULP and would mask real corruption checks
    req = 8
    assert n_rows % req == 0
    req_offsets = tuple(range(0, n_rows, req))

    def offline(version):
        jm = JaxModel(model=repo.load("xor", version)[0],
                      input_col="input", output_col="scores")
        return np.concatenate([sc(jm.transform(tbl(rows[o:o + req])))
                               for o in req_offsets])

    obs.enable()
    obs.clear()
    registry().reset()
    obs_fleet.enable(fleet_dir, interval_s=0.2)
    server = None
    try:
        # -- v1: the pre-trained baseline in production ---------------
        repo = ModelRepo(repo_root)
        params = module.init(jax.random.PRNGKey(0),
                             np.zeros((1, d_in), np.float32))["params"]
        v1 = repo.publish("xor", ModelBundle(
            module=module,
            params=jax.tree_util.tree_map(np.asarray, params),
            input_spec=(d_in,), output_names=("logits",), name="xor"))
        assert repo.current_version("xor") == v1

        server = ModelServer(ServeConfig(
            buckets=(1, 4, 8), max_queue=512, deadline_ms=None,
            lifecycle_dir=serve_dir,
            slo={"objective": 0.99, "min_requests": 4,
                 "window_s": 30.0, "long_window_s": 60.0}))
        server.add_model_from_repo(repo, "xor", example=tbl(rows[:1]))
        off = {v1: offline(v1)}

        # -- run 1: healthy fine-tune → dark v2 with provenance -------
        sup1 = train_run("good", {})
        v2 = v1 + 1
        assert repo.versions("xor") == [v1, v2], (
            f"healthy run did not dark-publish: {repo.versions('xor')}")
        assert repo.current_version("xor") == v1, (
            "dark publish moved CURRENT — promotion is the deployer's "
            "decision")
        _, info2 = repo.load("xor", v2)
        assert info2.provenance is not None
        assert info2.provenance["checkpoint_step"] == 16
        assert info2.provenance["run_id"].startswith("train-")
        assert len(info2.provenance["eval"]["series_tail"]) > 0
        off[v2] = offline(v2)
        assert not np.array_equal(off[v1], off[v2])

        # -- live traffic across both rollouts ------------------------
        stop_traffic = threading.Event()
        answers, errors = [], []
        lock = threading.Lock()

        def pump(k):
            client = Client(server, retry=True)
            try:
                i = 0
                while not stop_traffic.is_set():
                    o = req_offsets[(k + i) % len(req_offsets)]
                    got = client.predict("xor", tbl(rows[o:o + req]),
                                         timeout=60)
                    with lock:
                        answers.append((o, sc(got)))
                    i += 1
            except BaseException as e:  # noqa: BLE001 — reported
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=pump, args=(k,))
                   for k in range(2)]
        for t in threads:
            t.start()

        # -- rollout 1: v2 shadow → canary → promoted -----------------
        dep1 = Deployer(
            lifecycle_dir, repo,
            ServerTarget(server, "xor", example=tbl(rows[:1])),
            policy=RolloutPolicy(advance_after=2),
            refs={"train_journal": os.path.join(workdir, "svc_good",
                                                "decisions.jsonl"),
                  "serve_journal": os.path.join(serve_dir,
                                                "decisions.jsonl")})
        r1 = dep1.start_rollout("xor", version=v2)
        outcome1 = dep1.run(r1, tick_s=0.05, timeout_s=90.0)
        assert outcome1 == "promoted", (
            f"healthy rollout ended {outcome1!r} "
            f"(stage {r1.ledger.stage})")
        assert repo.current_version("xor") == v2, (
            "promotion did not flip the repo CURRENT pointer")
        snap = server.snapshot()["xor"]
        assert snap["version"] == v2, f"serving {snap.get('version')}"

        # -- run 2: degraded fine-tune (shifted data) → dark v3 -------
        sup2 = train_run(
            "degraded",
            {"MMLSPARK_TPU_SERVICE_SELFTEST_DATA_SEED": "3"})
        v3 = v2 + 1
        assert repo.versions("xor") == [v1, v2, v3]
        assert repo.current_version("xor") == v2
        off[v3] = offline(v3)

        # -- rollout 2: v3 drifts in shadow → rolled back -------------
        dep2 = Deployer(
            lifecycle_dir, repo,
            ServerTarget(server, "xor", example=tbl(rows[:1])),
            policy=RolloutPolicy(advance_after=2,
                                 parity_tolerance=1e-6),
            refs={"train_journal": os.path.join(workdir, "svc_degraded",
                                                "decisions.jsonl"),
                  "serve_journal": os.path.join(serve_dir,
                                                "decisions.jsonl")})
        r2 = dep2.start_rollout("xor", version=v3)
        outcome2 = dep2.run(r2, tick_s=0.05, timeout_s=90.0)
        assert outcome2 == "rolled_back", (
            f"degraded rollout ended {outcome2!r} — parity drift in "
            "shadow must roll back")
        assert repo.current_version("xor") == v2, (
            "rollback did not pin the repo CURRENT back to the good "
            "version")
        assert server.canary_status("xor") is None

        stop_traffic.set()
        for t in threads:
            t.join()

        # -- zero drops; every answer is SOME version's exact output --
        assert errors == [], f"requests dropped across the rollouts: " \
                             f"{errors}"
        assert len(answers) > 0
        unmatched = 0
        for o, got in answers:
            if not any(np.array_equal(got, off[v][o:o + req])
                       for v in off):
                unmatched += 1
        assert unmatched == 0, (
            f"{unmatched}/{len(answers)} answers match NO published "
            "version's offline transform bit-for-bit")
        post = sc(server.predict("xor", tbl(rows[:req])))
        assert np.array_equal(post, off[v2][:req]), (
            "post-rollback serving is not on the good version")

        # -- one journey, one trace -----------------------------------
        lc_path = os.path.join(lifecycle_dir, "decisions.jsonl")
        with open(lc_path, encoding="utf-8") as f:
            lc_recs = [json.loads(ln) for ln in f if ln.strip()]
        lc_kinds = [r["kind"] for r in lc_recs]
        for expected in ("publish", "rollout", "stage", "promote",
                         "rollback"):
            assert expected in lc_kinds, f"{expected!r} not journaled"
        ro_recs = [r for r in lc_recs if r["kind"] == "rollout"]
        assert all("train_journal" in r and "serve_journal" in r
                   for r in ro_recs), "rollouts missing journal refs"
        for tag in ("good", "degraded"):
            tj = os.path.join(workdir, f"svc_{tag}", "decisions.jsonl")
            with open(tj, encoding="utf-8") as f:
                t_recs = [json.loads(ln) for ln in f if ln.strip()]
            pubs = [r for r in t_recs if r["kind"] == "publish"]
            assert pubs and pubs[0]["lifecycle_journal"] == lc_path, (
                f"train run {tag} does not cross-reference the "
                "lifecycle journal")
        journeys = replay_decisions(lc_path)
        assert [j["outcome"] for j in journeys] == ["promoted",
                                                    "rolled_back"]
        assert journeys[0]["version"] == v2
        assert journeys[0]["stages"] == ["shadow", "canary",
                                         "promoting"]
        assert journeys[1]["version"] == v3
        assert journeys[1]["prior_version"] == v2

        # -- obs: counters, the deploy gauge, the stitched fence ------
        assert registry().value("lifecycle.rollouts") == 2
        assert registry().value("lifecycle.rollbacks") == 1
        wall = registry().value("deploy.wall_s", model="xor")
        assert wall is not None and wall > 0
        server.close()
        server = None
        obs_fleet.disable()  # final snapshot (this process's fences)
        view = obs_fleet.FleetCollector(fleet_dir).collect()
        meta = view.chrome_trace()["fleetMeta"]
        assert meta["stitched_flows"] >= 1, (
            "no cross-process flow stitched at the "
            "lifecycle/publish_fence seam (worker result write vs "
            "supervisor gate+publish)")
        return {
            "versions": repo.versions("xor"),
            "current": repo.current_version("xor"),
            "outcomes": [outcome1, outcome2],
            "provenance_v2": {
                "checkpoint_step": info2.provenance["checkpoint_step"],
                "eval_points": info2.provenance["eval"]["points"]},
            "responses": len(answers),
            "dropped": len(errors),
            "deploy_wall_s": wall,
            "rollouts": int(registry().value("lifecycle.rollouts")),
            "rollbacks": int(registry().value("lifecycle.rollbacks")),
            "stitched_flows": meta["stitched_flows"],
            "lifecycle_kinds": sorted(set(lc_kinds)),
        }
    finally:
        if server is not None:
            server.close()
        obs_fleet.disable()
        obs.disable()
        obs.clear()
        registry().reset()
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith(THREAD_PREFIX)
                  or t.name in ("FleetExporter", "TimeSeriesSampler")]
        assert leaked == [], f"threads leaked: {leaked}"
        shutil.rmtree(workdir, ignore_errors=True)


def _timed_once(pm, table, time_mod) -> float:
    t0 = time_mod.perf_counter()
    pm.transform(table)
    return time_mod.perf_counter() - t0


def main() -> int:
    # the spmd gate verifies the parallel layer on the 8-device CPU
    # mesh; force it BEFORE jax initializes (same flag as tests/conftest)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        result = check_fused_crossings()
        train = check_train_prefetch()
        train_pp = check_train_device_preprocess()
        train_elastic = check_train_elastic()
        serve = check_serve_batching()
        serve_cc = check_compile_cache()
        serve_sharded = check_serve_sharded()
        serve_generate = check_serve_generate()
        serve_lowprec = check_serve_lowprec()
        serve_lifecycle = check_serve_lifecycle()
        obs_overhead = check_obs_overhead()
        obs_tracing = check_obs_request_tracing()
        fleet_obs = check_fleet_obs()
        serve_fleet = check_serve_fleet()
        train_to_serve = check_train_to_serve()
        flight_rec = check_flight_recorder()
        spmd = check_spmd_clean()
        concurrency = check_concurrency_clean()
    except AssertionError as e:
        print(json.dumps({"perf_smoke": "FAIL", "reason": str(e)}))
        return 1
    print(json.dumps({"perf_smoke": "OK", **result,
                      "train_prefetch": train,
                      "train_device_preprocess": train_pp,
                      "train_elastic": train_elastic,
                      "serve": serve,
                      "serve_compile_cache": serve_cc,
                      "serve_sharded": serve_sharded,
                      "serve_generate": serve_generate,
                      "serve_lowprec": serve_lowprec,
                      "serve_lifecycle": serve_lifecycle,
                      "obs_overhead": obs_overhead,
                      "obs_request_tracing": obs_tracing,
                      "fleet_obs": fleet_obs,
                      "serve_fleet": serve_fleet,
                      "train_to_serve": train_to_serve,
                      "flight_recorder": flight_rec, "spmd": spmd,
                      "concurrency": concurrency}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

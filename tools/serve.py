"""serve — online model-server CLI.

Usage::

    python tools/serve.py <model-path> [--name NAME] [--host H] [--port P]
        [--buckets 1,8,32,128] [--max-queue N] [--deadline-ms D]
        [--mesh dp=N[,tp=M][,pp=K]] [--schema schema.json] [--no-warmup]
        [--obs] [--fleet DIR] [--slo-objective 0.999]
        [--slo-latency-ms P99_MS] [--compile-cache DIR]

``<model-path>`` is any of

* a directory saved with ``stage.save()`` (``metadata.json`` inside) — a
  ``PipelineModel`` or any fitted transformer;
* a single ``ModelBundle`` file (``tools/build_model_repo.py`` output) —
  wrapped in a ``JaxModel`` reading column ``input``, writing ``scores``;
* a model *repository* directory (``MANIFEST.json`` inside) — every
  manifest entry is loaded and served under its manifest name;
* with ``--repo``: a **versioned** model repository
  (``models/repo.py`` layout — per-version dirs with sha256 manifests
  and a ``CURRENT`` pointer): every model's current version is
  digest-verified and served, tagged with its version (per-version
  stats/SLO series, swap decisions journaled under
  ``ServeConfig.lifecycle_dir``). See docs/serving.md §model lifecycle.

Every model is validated by the pre-flight analyzer at load time (the
load fails fast — exit 2 with the diagnostics — before any device work),
and the bucket ladder is warmed when a concrete input schema is known
(``--schema``, or derived from the bundle's input_spec).

``--schema`` takes the same JSON column-spec file as ``tools/analyze.py``.

Every server exposes ``/healthz`` (drain-aware readiness: 200 while
ready, 503 when draining or the SLO burn rate turns the model
unhealthy), ``/livez`` (liveness: always 200 while the process answers
HTTP — restart probes go here, never at ``/healthz``) and ``/slo``
(burn rates, error-budget remaining, latency
verdict, queue-depth/occupancy/replica-skew signals) — tune the
objective with ``--slo-objective``/``--slo-latency-ms``. ``--obs``
additionally enables the span tracer so ``/metrics`` (JSON, or
Prometheus text under ``Accept: text/plain``) and ``/trace``
(Chrome-trace JSON with per-request flows) carry a live timeline.
``--fleet DIR`` exports this process's telemetry snapshots into the
fleet plane (obs/fleet.py; equivalent to ``MMLSPARK_TPU_FLEET=DIR``)
and serves the fleet-merged cross-process view on ``/fleet``. See
docs/observability.md.

Prints one JSON line when serving starts; Ctrl-C drains in-flight
requests and exits.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_versioned_repo(path: str, name: str | None
                         ) -> list[tuple[str, object, object]]:
    """[(serve name, model, ModelVersion), ...] from a VERSIONED model
    repo (models/repo.py layout): every model's CURRENT version, digest-
    verified before deserialization — a torn or corrupt version is a
    typed refusal at startup, never a silently-wrong served model."""
    from mmlspark_tpu.models.repo import ModelRepo
    repo = ModelRepo(path)
    names = [name] if name else repo.models()
    if not names:
        raise SystemExit(f"{path}: no published models in the repo")
    out = []
    for n in names:
        model, info = repo.load(n)
        out.append((n, model, info))
    return out


def _load_models(path: str, name: str | None) -> list[tuple[str, object]]:
    """[(serve name, model object), ...] for any supported model path."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.data.downloader import (
        MANIFEST_NAME, Repository, load_bundle_file,
    )

    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "metadata.json")):
            stage = PipelineStage.load(path)
            return [(name or os.path.basename(os.path.normpath(path)),
                     stage)]
        if os.path.exists(os.path.join(path, MANIFEST_NAME)):
            repo = Repository(path)
            out = []
            for entry in repo.read_manifest():
                bundle = load_bundle_file(os.path.join(path, entry.uri))
                out.append((entry.name, bundle))
            return out
        raise SystemExit(
            f"{path}: neither a saved stage (metadata.json) nor a model "
            f"repository ({MANIFEST_NAME})")
    bundle = load_bundle_file(path)
    return [(name or bundle.name, bundle)]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model", help="saved stage dir, bundle file, or "
                                  "model-repo dir")
    ap.add_argument("--name", default=None,
                    help="serve name (default: dir/bundle name); with "
                         "--repo, serve only this model from the repo")
    ap.add_argument("--repo", action="store_true",
                    help="treat <model-path> as a VERSIONED model repo "
                         "(models/repo.py: per-version dirs with sha256 "
                         "manifests + a CURRENT pointer): serve every "
                         "model's current version, digest-verified at "
                         "load. Publish a new version + re-run (or use "
                         "the deploy_canary/add_model APIs in-process) "
                         "to roll forward; see docs/serving.md §model "
                         "lifecycle")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--buckets", default="1,8,32,128",
                    help="comma-separated batch bucket ladder")
    ap.add_argument("--max-queue", type=int, default=128,
                    help="queued requests per model before Overloaded")
    ap.add_argument("--deadline-ms", type=float, default=1000.0,
                    help="default per-request deadline (0 = none)")
    ap.add_argument("--mesh", default=None,
                    help="serving mesh: dp=N[,tp=M][,pp=K][,lockstep] — "
                         "N DP replicas of M×K chips each (sharded "
                         "serving, docs/serving.md). The load fails with "
                         "a typed ModelLoadError when the mesh does not "
                         "divide this host's device count")
    ap.add_argument("--schema", default=None,
                    help="JSON column-spec file (tools/analyze.py format) "
                         "used for validation + bucket warmup")
    ap.add_argument("--precision", default=None,
                    choices=["f32", "bf16", "int8w"],
                    help="serving precision policy (docs/quantization.md)"
                         ": bf16 activations, or int8 weight-only on top;"
                         " parity vs the f32 offline transform is "
                         "calibrated at load against the policy's pinned "
                         "tolerance (typed ModelLoadError on drift)")
    ap.add_argument("--precision-tolerance", type=float, default=None,
                    help="per-model max-abs parity pin for --precision "
                         "(default: the mode's documented tolerance)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent AOT compile cache (same as "
                         "MMLSPARK_TPU_COMPILE_CACHE): compiled bucket "
                         "programs serialize into DIR and later cold "
                         "starts deserialize them instead of paying XLA "
                         "compiles (docs/serving.md §compile cache). An "
                         "unwritable DIR degrades to one warning + "
                         "in-memory compiles — never a failed load")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip compiling the bucket ladder at load")
    ap.add_argument("--obs", action="store_true",
                    help="enable the obs tracer (docs/observability.md): "
                         "GET /metrics and /trace expose the registry "
                         "snapshot (JSON, or Prometheus text under "
                         "content negotiation) and the Chrome-trace "
                         "span timeline with per-request flows")
    ap.add_argument("--fleet", default=None, metavar="DIR",
                    help="export fleet telemetry snapshots into DIR "
                         "(obs/fleet.py; same as MMLSPARK_TPU_FLEET=DIR) "
                         "and serve the fleet-merged view on GET /fleet; "
                         "implies --obs")
    ap.add_argument("--slo-objective", type=float, default=0.999,
                    help="SLO success-ratio objective; its complement "
                         "is the error budget the /healthz burn-rate "
                         "state machine meters (default 0.999)")
    ap.add_argument("--slo-latency-ms", type=float, default=None,
                    help="optional p99 latency objective in ms; when "
                         "violated the model reports degraded on "
                         "/healthz and /slo")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    from mmlspark_tpu.serve import ModelLoadError, ModelServer, ServeConfig
    from mmlspark_tpu.serve.http import start_http_server

    if args.obs:
        from mmlspark_tpu import obs
        obs.enable()
    if args.fleet:
        from mmlspark_tpu.obs import fleet as obs_fleet
        obs_fleet.enable(args.fleet)  # enables the tracer too

    schema = None
    if args.schema:
        from mmlspark_tpu.analysis import TableSchema
        with open(args.schema, "r", encoding="utf-8") as fh:
            schema = TableSchema.from_spec(json.load(fh))

    mesh = None
    if args.mesh:
        from mmlspark_tpu.serve.mesh import ServeMeshSpec
        try:
            mesh = ServeMeshSpec.parse(args.mesh)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2

    from mmlspark_tpu.obs.slo import SLOSpec
    try:
        slo = SLOSpec(objective=args.slo_objective,
                      latency_ms=args.slo_latency_ms)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    precision = None
    if args.precision and args.precision != "f32":
        precision = {"mode": args.precision}
        if args.precision_tolerance is not None:
            precision["tolerance"] = args.precision_tolerance
    elif args.precision_tolerance is not None:
        # a tolerance without an active low-precision mode would be
        # silently ignored — refuse loudly instead
        print("--precision-tolerance needs --precision bf16|int8w "
              "(f32 serving is bit-exact; there is nothing to pin)",
              file=sys.stderr)
        return 2

    try:
        config = ServeConfig(
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            max_queue=args.max_queue,
            deadline_ms=args.deadline_ms or None,
            warmup=not args.no_warmup,
            mesh=mesh,
            slo=slo,
            precision=precision,
            compile_cache=args.compile_cache)
    except (ModelLoadError, ValueError) as e:
        # a misordered/duplicate --buckets ladder is a typed refusal
        print(str(e), file=sys.stderr)
        return 2
    server = ModelServer(config)
    versions = None
    provenance = None
    try:
        if args.repo:
            from mmlspark_tpu.models.repo import ModelRepoError
            try:
                loaded = _load_versioned_repo(args.model, args.name)
            except ModelRepoError as e:
                print(str(e), file=sys.stderr)
                return 2
            versions = {}
            provenance = {}
            for model_name, model, info in loaded:
                server.add_model(model_name, model, schema=schema,
                                 version=info.version)
                versions[model_name] = info.version
                if info.provenance is not None:
                    # the lifecycle Publisher's stamp: which checkpoint
                    # step, which eval tail, which train run published
                    # the version this process is about to serve
                    provenance[model_name] = info.provenance
                    print(f"serving {model_name} v{info.version} "
                          f"(checkpoint step "
                          f"{info.provenance.get('checkpoint_step')}, "
                          f"run {info.provenance.get('run_id')}, "
                          f"eval {info.provenance.get('eval')})",
                          file=sys.stderr)
        else:
            for model_name, model in _load_models(args.model, args.name):
                server.add_model(model_name, model, schema=schema)
    except ModelLoadError as e:
        print(str(e), file=sys.stderr)
        return 2

    httpd = start_http_server(server, args.host, args.port,
                              background=False)
    print(json.dumps({
        "serving": server.models(),
        "versions": versions,
        "provenance": provenance,
        "host": httpd.server_address[0],
        "port": httpd.server_address[1],
        "buckets": list(config.buckets),
        "precision": args.precision or "f32",
        "max_queue": config.max_queue,
        "deadline_ms": config.deadline_ms,
        "mesh": mesh.describe() if mesh is not None else None,
        "slo": slo.describe(),
        "compile_cache": args.compile_cache,
        "endpoints": ["/healthz", "/livez", "/slo", "/metrics",
                      "/trace", "/v1/models", "/v1/stats"],
    }), flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        server.close(drain=True)  # answer everything admitted
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""lint_jax — AST lint for JAX anti-patterns in mmlspark_tpu.

A pyflakes-style single-pass visitor (no imports of the linted code, no
jax initialization) catching the mistakes that cost the most on TPU:

* **JX101 host sync in jit** — ``np.asarray``/``np.array``, ``float()``/
  ``int()``/``bool()`` on non-constants, ``.item()``/``.tolist()`` inside
  a jit-compiled function. Each forces a device→host transfer + blocking
  sync in the middle of a traced computation (or a tracer error).
* **JX102 jit in loop** — ``jax.jit(...)`` constructed inside a for/while
  body: every iteration builds a fresh callable with an empty compile
  cache (the classic accidental-recompile).
* **JX103 raw shard_map** — importing/calling ``jax.shard_map`` or
  ``jax.experimental.shard_map`` directly instead of the
  ``mmlspark_tpu/parallel/mesh.py`` compat shim (the shim papers over the
  check_rep/check_vma rename across jax versions; direct use breaks one
  side or the other).
* **JX104 mutable Param default** — ``Param(default=[])`` / ``{}`` /
  ``set()``: the default is shared across every stage instance.
* **JX105 blocking scalar fetch in a step loop** — ``float()``/``int()``/
  ``.item()`` on the output of a ``*step*`` call inside the training loop
  that issued it: the coercion blocks the host on that step's device
  completion mid-pipeline, stalling the prefetch window every time it
  runs. Record the device scalar and resolve it one step later (the
  lagged-fetch sites in ``train/loop.py`` carry the pragma).
* **JX106 blocking device fetch in a serve dispatch loop** —
  ``np.asarray``/``float()``/``int()``/``.item()``/``.tolist()`` on the
  output of a ``*dispatch*``/``*_async`` call inside the loop that issued
  it: the fetch blocks the dispatch loop on that batch's device
  completion, serializing host packing with device compute and forfeiting
  the overlap the serving batcher exists for. Push the dispatched handle
  through the bounded in-flight window and drain the *oldest* entry (or
  fetch after the loop) — the discipline of
  ``mmlspark_tpu/serve/batcher.py``.
* **JX109 blocking fetch in a decode/generate loop** — ``np.asarray``/
  ``float()``/``int()``/``.item()``/``.tolist()`` on the output of a
  ``*decode*``/``*generate*`` call (the full dotted spelling counts:
  ``self._decode.jitted(...)`` qualifies) inside the loop that issued
  it: autoregressive decode is a chain of tiny dispatches, so a
  same-step host fetch serializes every token on its device round-trip
  — the worst case of the JX105/JX106 stall, paid per token. Carry the
  token on device (the decode program's own output feeds the next
  step's input) and consume the *previous* step's output instead — the
  one-step-lagged discipline of ``mmlspark_tpu/serve/generate.py``.
* **JX108 implicit f64 promotion in device code** — ``np.float64(...)``/
  ``np.double(...)`` scalar constructors or ``dtype=np.float64`` /
  ``dtype="float64"`` arguments inside a jit-traced body, a device-stage
  body (a function defined inside ``device_fn``/``device_fn_mesh``), or
  a step/serve dispatch loop. numpy float64 scalars are STRONGLY typed
  under jax promotion, so one leaking into jitted math silently widens
  a bf16/f32 activation chain (the exact degradation a bf16 serving
  policy exists to avoid — docs/quantization.md); python float literals
  are weak-typed and fine, which is why the rule targets the np scalar
  forms specifically.
* **JX107 host-side image work under a device-preprocess spec** —
  ``imgops.resize``/any ``cv2.*`` call/PIL decode (``Image.open``,
  ``decode_image``) inside a train step loop or inside a function fed to
  a ``DeviceLoader`` as its source, in a module that uses
  ``DevicePreprocess`` (the static stand-in for "a device-preprocess
  spec is active"): the spec already replays geometry inside the jitted
  step, so host image work in the input path burns producer-thread time
  AND fattens the wire (f32/resized pixels instead of thin uint8).
  Ship source-resolution uint8 and let ``train/preprocess.py`` do the
  geometry on device.

The JX2xx family is the AST face of the SPMD verifier
(``mmlspark_tpu/analysis/spmd.py`` — which checks the same hazards
semantically on the traced jaxpr; see docs/spmd_analysis.md):

* **JX201 collective under data-dependent control flow** — a
  ``psum``/``ppermute``/``all_gather``/``all_to_all``/``psum_scatter``
  inside a ``lax.cond``/``lax.switch``/``lax.while_loop`` branch or
  body: hosts whose predicate (or trip count) differs disagree on the
  collective schedule — a cross-host deadlock-in-waiting. Hoist the
  collective out (compute both sides, select after).
* **JX202 unknown mesh axis name** — a collective (or ``axis_index``)
  whose literal axis name is not one of the canonical mesh axes
  (``parallel/mesh.py`` ``AXES``): a typo'd axis traces fine inside a
  matching-named shard_map but can never bind to the production meshes.
* **JX203 unreduced axis escapes a shard_map** — an axis named in
  ``in_specs`` but absent from every ``out_specs`` entry, with no
  reducing collective (``psum``/``all_gather``/...) over it in the
  body: the out_spec claims replication over an axis the inputs vary
  over, and ``check_vma=False`` (which every body here needs) stops jax
  from checking the claim — values escape as unreduced partial sums.
* **JX204 per-shard capacity arithmetic** — a shard_map body that
  assigns capacity slots from a local ``cumsum`` and dispatches with
  ``all_to_all``/``psum_scatter`` but never exchanges the routed counts
  (``all_gather``): the slot budget is split per source shard, so
  which tokens survive depends on where the batch (and its padding)
  landed — the MoE pad-capacity bug class. Assign slot positions
  globally (gather counts, offset the local ranks).

The JX3xx family is the AST face of the whole-repo concurrency verifier
(``mmlspark_tpu/analysis/concurrency.py`` — which derives the same
hazards interprocedurally, with lock identity and call-graph context;
see docs/concurrency.md). These are the single-file checks cheap enough
to run on every save:

* **JX301 blocking call under a held lock** — ``time.sleep`` or a
  ``subprocess.*`` call lexically inside a ``with <lock>:`` block (the
  receiver *looks* like a lock: ``_lock``/``_cv``/``mutex``/...). The
  deep pass (CC102) also follows callees and thread joins.
* **JX302 manual acquire without try/finally** — a bare
  ``lock.acquire()`` statement not immediately followed by a
  ``try/finally`` that releases it: an exception between the two leaks
  the lock forever (CC103's single-file face). Use ``with``.
* **JX303 Thread() without an explicit daemon flag** — every spawn site
  must declare its lifecycle; the deep pass (CC104) audits that
  non-daemon threads have a reachable ``join()`` owner.

Intentional exceptions are suppressed two ways, both documented in
docs/static_analysis.md:

* an inline pragma on the offending line: ``# lint-jax: allow(JX101)``.
  JX3xx pragmas **require a justification** after a colon
  (``# lint-jax: allow(JX301): why this wait is the contract``) — an
  unjustified one is itself a finding (**JX300**);
* the curated :data:`DEFAULT_ALLOWLIST` below (file-suffix → rules,
  with a per-entry justification), for files whose whole purpose is the
  exception (the shard_map shim itself).

Usage::

    python tools/lint_jax.py [path ...] [--json]   # default: mmlspark_tpu/

Prints one line per finding and exits 1 if any survive the allowlist
(0 clean, 2 on a nonexistent path). ``--json`` emits the machine
report — findings and suppressions with rule id, path, line, message,
and pragma status — the same schema ``analyze.py concurrency --json``
uses. ``tests/test_lint.py`` runs this over the codebase in tier-1
(zero-findings gate) and over a seeded fixture (exact-findings gate).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys

# files whose entire purpose is the exception; suffix-matched against the
# normalized path, each rule carrying its justification so the gate
# stays reviewable in one place.
DEFAULT_ALLOWLIST: dict[str, dict] = {
    # the compat shim itself: it must touch both jax.shard_map spellings
    "mmlspark_tpu/parallel/mesh.py": {
        "JX103": "the compat shim is the one module that must spell "
                 "jax.shard_map directly (both sides of the "
                 "check_rep/check_vma rename)"},
}

RULES = {
    "JX101": "host sync inside a jit-compiled function",
    "JX102": "jax.jit constructed inside a loop body",
    "JX103": "shard_map used directly; route through parallel/mesh.py's "
             "compat shim",
    "JX104": "mutable default value in a Param declaration",
    "JX105": "blocking scalar fetch on a step output inside the step loop; "
             "record the device scalar and resolve it one step later",
    "JX106": "blocking device fetch on a dispatched batch inside a serve "
             "dispatch loop; drain through the bounded in-flight window "
             "(or after the loop)",
    "JX107": "host-side image work in a train step loop or DeviceLoader "
             "producer while a device-preprocess spec is active; ship "
             "thin uint8 and replay the geometry on device "
             "(train/preprocess.py)",
    "JX108": "np.float64/np.double scalar (or dtype=float64) inside "
             "device-stage bodies or step/serve loops; numpy f64 scalars "
             "are strongly typed and silently widen bf16/f32 activation "
             "chains — use np.float32 or a python literal",
    "JX109": "blocking fetch on the current decode step's output inside "
             "the decode/generate loop; carry the token on device and "
             "consume the previous step's output one step lagged "
             "(serve/generate.py's discipline)",
    "JX201": "collective under data-dependent control flow (lax.cond/"
             "switch/while_loop); hoist it out — hosts that disagree on "
             "the predicate deadlock",
    "JX202": "collective names a mesh axis outside the canonical AXES "
             "(parallel/mesh.py); typo'd axes can never bind to the "
             "production meshes",
    "JX203": "axis sharded by in_specs but absent from out_specs with no "
             "reducing collective over it in the body; the output escapes "
             "as an unreduced partial sum (check_vma=False hides it)",
    "JX204": "capacity slots assigned from a local cumsum with no "
             "cross-shard count exchange (all_gather) before the "
             "dispatch; assign slot positions globally",
    "JX300": "pragma suppressing a JX3xx rule has no justification; add "
             "one after a colon: # lint-jax: allow(JX30n): why",
    "JX301": "blocking call (time.sleep / subprocess.*) inside a "
             "with-lock block; move the wait outside the critical "
             "section (deep face: analysis/concurrency.py CC102)",
    "JX302": "bare lock.acquire() not followed by try/finally release; "
             "an exception in between leaks the lock — use `with` "
             "(deep face: CC103)",
    "JX303": "threading.Thread(...) without an explicit daemon= flag; "
             "declare the lifecycle at the spawn site (deep face: "
             "CC104 audits join ownership)",
}

# JX301's "looks like a lock" heuristic: the terminal name of a with-item
# context expression. The deep pass resolves real lock identities; the
# lint only needs the conventional spellings used in this codebase.
_LOCKISH_RE = re.compile(
    r"(?:^|_)(lock|locks|cv|cond|condition|mutex|sem|semaphore)$")

# JX301's needles: module-level blocking calls that never belong inside
# a critical section (thread joins / queue ops need type context — the
# deep pass covers those)
_BLOCKING_UNDER_LOCK = {("time", "sleep"), ("subprocess", "run"),
                        ("subprocess", "call"), ("subprocess", "check_call"),
                        ("subprocess", "check_output")}

_PRAGMA_RE = re.compile(
    r"lint-jax:\s*allow\(([A-Z0-9,\s]+)\)(?::\s*(.*))?")

# mirror of parallel/mesh.py AXES — the lint must not import jax code
_MESH_AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")
_COLLECTIVE_CALLS = {"psum", "pmean", "pmax", "pmin", "ppermute",
                     "pshuffle", "all_gather", "all_to_all",
                     "psum_scatter"}
# collectives that make a value invariant over their axis (JX203)
_REDUCING_CALLS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                   "all_to_all", "psum_scatter"}
_COND_CALLS = {"cond", "switch", "while_loop"}

# the callee-name hint marking a train-step call whose outputs JX105 tracks
_STEP_HINT = "step"

# JX108: the strongly-typed f64 spellings (namespace attr names) and the
# namespaces they ride on. jnp.float64 is included — with x64 disabled it
# canonicalizes, but code written against it flips behavior the moment a
# library enables x64
_F64_ATTRS = {"float64", "double"}
_F64_NAMESPACES = {"np", "numpy", "onp", "jnp"}

# PIL-style decode roots for JX107 (cv2 is matched as a whole namespace)
_PIL_ROOTS = {"Image", "PIL"}


def _is_step_call(name: str) -> bool:
    # "decode" spellings route to JX109 (the per-token face of the same
    # stall), so a `decode_step` call must not double-fire as JX105
    low = name.lower()
    return _STEP_HINT in low.rsplit(".", 1)[-1] \
        and not _is_decode_call(low)


def _is_decode_call(name: str) -> bool:
    """JX109's taint source: an autoregressive decode/generate call —
    matched over the FULL dotted spelling (``self._decode.jitted``,
    ``engine.advance_decode``, ``decode_step``), because the decode
    handle is usually the receiver, not the terminal attribute."""
    low = name.lower()
    return "decode" in low or "generate" in low


def _host_image_call(node: ast.Call) -> str | None:
    """JX107's needle: a host-side image decode/geometry call —
    ``imgops.resize``, any ``cv2.*``, PIL ``Image.open``, or the
    readers' ``decode_image`` helper. Returns the spelled call or
    None."""
    func = node.func
    if isinstance(func, ast.Attribute):
        root = func.value
        while isinstance(root, ast.Attribute):
            root = root.value
        root_name = root.id if isinstance(root, ast.Name) else None
        if root_name == "cv2":
            return f"cv2.{func.attr}"
        if func.attr == "resize" and root_name == "imgops":
            return "imgops.resize"
        if func.attr in ("open", "imdecode") and root_name in _PIL_ROOTS:
            return f"{root_name}.{func.attr}"
    if isinstance(func, ast.Name) and func.id == "decode_image":
        return "decode_image"
    return None


def _is_dispatch_call(name: str) -> bool:
    """JX106's taint source: an async batch dispatch — ``*dispatch*`` or
    the ``*_async`` naming convention (``transform_async`` & co). A
    decode-flavored dispatch (``self._decode.dispatch``) routes to
    JX109 instead — one site, one rule."""
    low = name.lower()
    leaf = low.rsplit(".", 1)[-1]
    return ("dispatch" in leaf or leaf.endswith("_async")) \
        and not _is_decode_call(low)

_JIT_NAMES = {"jit", "pjit"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_HOST_NP_CALLS = {"asarray", "array", "copy"}
_HOST_BUILTINS = {"float", "int", "bool"}
_HOST_METHODS = {"item", "tolist"}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> dict:  # same schema as analysis/concurrency.py
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


def _callee_name(node: ast.AST) -> str | None:
    """Terminal name of a call target: ``step`` / ``self.step_masked``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _call_spelling(node: ast.AST) -> str | None:
    """Full dotted spelling of a call target, lowercased:
    ``self._decode.jitted`` → ``"self._decode.jitted"``. The fetch-loop
    rules match sources over this (JX109 needs the qualifying path —
    the decode handle is the receiver, the terminal attr is just
    ``dispatch``/``jitted``); predicates that only care about the
    terminal name split the last segment off themselves."""
    parts = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif not parts:
        return None
    return ".".join(reversed(parts)).lower()


def _literal_axis_names(expr: ast.AST | None) -> set:
    """String literals in an axis argument: ``"pp"`` or ``("dp", "ep")``.
    Non-literal axis expressions yield nothing (the lint never guesses)."""
    if expr is None:
        return set()
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = set()
        for elt in expr.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
        return out
    return set()


def _spec_axis_names(expr: ast.AST | None) -> set:
    """Canonical axis names appearing literally anywhere in an
    in_specs/out_specs expression (inside ``P(...)`` calls and tuples)."""
    if expr is None:
        return set()
    return {n.value for n in ast.walk(expr)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and n.value in _MESH_AXES}


def _is_jit_func(node: ast.AST) -> bool:
    """Is this expression a reference to jax.jit / jit / pjit?"""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    return False


def _has_jit_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if _is_jit_func(dec):
            return True
        if isinstance(dec, ast.Call):
            # @partial(jax.jit, ...) / @functools.partial(jit, ...)
            fname = dec.func
            is_partial = (isinstance(fname, ast.Name)
                          and fname.id == "partial") or (
                isinstance(fname, ast.Attribute) and fname.attr == "partial")
            if is_partial and dec.args and _is_jit_func(dec.args[0]):
                return True
            if _is_jit_func(fname):  # @jax.jit(static_argnums=...)
                return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.suppressed: list[tuple[Finding, str]] = []  # (finding, why)
        self.loop_depth = 0
        self.jitted_names: set[str] = set()
        self.jitted_lambdas: list[ast.Lambda] = []
        self.func_defs: dict[str, ast.AST] = {}
        self.uses_device_preprocess = False

    # -- pass 1 collects jit targets + local defs; pass 2 walks bodies --

    def collect(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_jit_func(node.func):
                if node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        self.jitted_names.add(target.id)
                    elif isinstance(target, ast.Lambda):
                        self.jitted_lambdas.append(target)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # JX201/JX203/JX204 resolve branch/body callables by name;
                # later definitions shadow earlier ones, as at runtime
                self.func_defs[node.name] = node
            # JX107 fires only when the module actually engages the
            # device-preprocess layer — the static stand-in for "a spec
            # is active" (an import or any mention of DevicePreprocess)
            if (isinstance(node, ast.Name)
                    and node.id == "DevicePreprocess") or (
                    isinstance(node, ast.Attribute)
                    and node.attr == "DevicePreprocess") or (
                    isinstance(node, ast.ImportFrom)
                    and any(a.name == "DevicePreprocess"
                            for a in node.names)):
                self.uses_device_preprocess = True

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        text = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        finding = Finding(self.path, line, rule, message)
        m = _PRAGMA_RE.search(text)
        if m and rule in {r.strip() for r in m.group(1).split(",")}:
            why = (m.group(2) or "").strip()
            if rule.startswith("JX3") and not why:
                # concurrency-family suppressions must say why — an
                # unjustified pragma is itself a finding (mirrors CC100)
                finding = Finding(self.path, line, "JX300", RULES["JX300"])
                if finding not in self.findings:
                    self.findings.append(finding)
                return
            if finding not in (f for f, _ in self.suppressed):
                self.suppressed.append((finding, why))
            return
        # nested loops run the JX105 subtree analysis once per level —
        # report each site once
        if finding not in self.findings:
            self.findings.append(finding)

    # -- JX301 / JX302 / JX303: single-file concurrency face --

    @staticmethod
    def _lockish(expr: ast.AST) -> bool:
        name = None
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        return bool(name and _LOCKISH_RE.search(name.lower()))

    def visit_With(self, node: ast.With) -> None:
        if any(self._lockish(item.context_expr) for item in node.items):
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and (f.value.id, f.attr) in _BLOCKING_UNDER_LOCK):
                    self._emit(sub, "JX301",
                               f"{f.value.id}.{f.attr}(...) blocks inside "
                               "a with-lock block; move the wait outside "
                               "the critical section")
        self.generic_visit(node)

    def lint_acquire_blocks(self, tree: ast.AST) -> None:
        """JX302: a bare ``lock.acquire()`` statement must be chained to
        a ``try/finally`` releasing it as its immediate next sibling."""
        for node in ast.walk(tree):
            for field in ("body", "orelse", "finalbody"):
                stmts = getattr(node, field, None)
                if not isinstance(stmts, list):
                    continue
                for i, stmt in enumerate(stmts):
                    if not (isinstance(stmt, ast.Expr)
                            and isinstance(stmt.value, ast.Call)
                            and isinstance(stmt.value.func, ast.Attribute)
                            and stmt.value.func.attr == "acquire"
                            and self._lockish(stmt.value.func.value)):
                        continue
                    nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                    if isinstance(nxt, ast.Try) and any(
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "release"
                            for s in nxt.finalbody
                            for sub in ast.walk(s)):
                        continue
                    self._emit(stmt.value, "JX302", RULES["JX302"])

    # -- JX102 / JX103 / JX104 / JX105: module-wide --

    def visit_For(self, node: ast.For) -> None:
        self._loop_body(node)

    def visit_While(self, node: ast.While) -> None:
        self._loop_body(node)

    def _loop_body(self, node: ast.AST) -> None:
        # JX105: blocking scalar coercion on train-step outputs
        self._lint_fetch_loop(node, _is_step_call, "JX105",
                              "a step output", "mid-pipeline",
                              flag_np=False)
        # JX106: blocking device fetch on serve-dispatch outputs (also
        # catches np.asarray — a full-batch fetch, not just a scalar)
        self._lint_fetch_loop(node, _is_dispatch_call, "JX106",
                              "a dispatched batch",
                              "inside the serve dispatch loop",
                              flag_np=True)
        # JX109: same stall, paid PER TOKEN — a fetch on the current
        # decode step's output inside the decode/generate loop
        self._lint_fetch_loop(node, _is_decode_call, "JX109",
                              "a decode-step output",
                              "inside the decode loop", flag_np=True)
        has_step = any(
            isinstance(sub, ast.Call)
            and (name := _callee_name(sub.func)) is not None
            and _is_step_call(name)
            for sub in ast.walk(node))
        # JX107: host image work in a loop that dispatches train steps,
        # in a module where a device-preprocess spec is active
        if self.uses_device_preprocess and has_step:
            self._lint_host_image_calls(node, "the train step loop")
        # JX108: f64 scalars built in a step or serve dispatch loop —
        # they feed the loop's device calls as strong float64
        has_dispatch = any(
            isinstance(sub, ast.Call)
            and (name := _callee_name(sub.func)) is not None
            and _is_dispatch_call(name)
            for sub in ast.walk(node))
        if has_step or has_dispatch:
            self._lint_f64_sites(node)
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    # -- JX108: strongly-typed f64 leaking into device code --

    def _is_f64_expr(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Constant) and expr.value in ("float64",
                                                             "double"):
            return True
        if isinstance(expr, ast.Attribute) and expr.attr in _F64_ATTRS:
            root = expr.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) \
                and root.id in _F64_NAMESPACES
        return False

    def _lint_f64_sites(self, scope: ast.AST) -> None:
        """Flag f64-spelling sites anywhere in ``scope`` (a traced body,
        a device-stage body, or a step/serve loop). The message is
        context-free so a site reachable through two scopes (a jitted
        fn inside a step loop) reports once — ``_emit`` dedups."""
        for sub in ast.walk(scope):
            if not isinstance(sub, ast.Call):
                continue
            if self._is_f64_expr(sub.func):
                self._emit(sub, "JX108",
                           f"{ast.unparse(sub.func)}(...) builds a "
                           "strongly-typed float64 scalar in device "
                           "code — it silently widens bf16/f32 "
                           "activation chains; use np.float32 or a "
                           "python literal")
                continue
            for kw in sub.keywords:
                if kw.arg == "dtype" and self._is_f64_expr(kw.value):
                    self._emit(sub, "JX108",
                               f"dtype={ast.unparse(kw.value)} in device "
                               "code — it silently widens bf16/f32 "
                               "activation chains; use np.float32 or a "
                               "python literal")

    def _lint_host_image_calls(self, scope: ast.AST, where: str) -> None:
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call):
                spelled = _host_image_call(sub)
                if spelled is not None:
                    self._emit(sub, "JX107",
                               f"{spelled}() runs host-side image work "
                               f"in {where} while a device-preprocess "
                               "spec is active; ship thin uint8 and "
                               "replay the geometry on device "
                               "(train/preprocess.py)")

    # -- JX105 / JX106: blocking fetches on pipelined outputs in a loop --

    def _lint_fetch_loop(self, loop: ast.AST, is_source, rule: str,
                         noun: str, where: str, flag_np: bool) -> None:
        """Taint names bound from source calls (``is_source`` over the
        callee name) anywhere in this loop's subtree (``state, metrics =
        self.step_masked(...)``), propagate through plain/subscript
        aliasing (``pending = metrics["loss"]``), and flag blocking
        coercions on tainted values inside the loop. Host fetches after
        the loop drains are fine — only the in-loop sync stalls the
        pipeline."""
        tainted: set[str] = set()
        for node in ast.walk(loop):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            fname = _call_spelling(node.value.func)
            if fname and is_source(fname):
                for target in node.targets:
                    elts = (target.elts if isinstance(target, ast.Tuple)
                            else [target])
                    tainted.update(n.id for n in elts
                                   if isinstance(n, ast.Name))
        if not tainted:
            return
        changed = True
        while changed:  # alias fixpoint: pending = metrics["loss"]
            changed = False
            for node in ast.walk(loop):
                if not isinstance(node, ast.Assign):
                    continue
                src = node.value
                if isinstance(src, ast.Subscript):
                    src = src.value
                if isinstance(src, ast.Name) and src.id in tainted:
                    for target in node.targets:
                        if (isinstance(target, ast.Name)
                                and target.id not in tainted):
                            tainted.add(target.id)
                            changed = True

        def tainted_value(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Subscript):
                expr = expr.value
            return isinstance(expr, ast.Name) and expr.id in tainted

        fix = RULES[rule].split("; ")[1]
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Name) and func.id in ("float", "int")
                    and node.args and tainted_value(node.args[0])):
                self._emit(node, rule,
                           f"{func.id}() on {noun} blocks the host "
                           f"{where}; {fix}")
            elif (isinstance(func, ast.Attribute)
                    and func.attr in ("item", "tolist")
                    and tainted_value(func.value)):
                self._emit(node, rule,
                           f".{func.attr}() on {noun} blocks the "
                           f"host {where}; {fix}")
            elif (flag_np and isinstance(func, ast.Attribute)
                    and func.attr in _HOST_NP_CALLS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in _NUMPY_ALIASES
                    and node.args and tainted_value(node.args[0])):
                self._emit(node, rule,
                           f"np.{func.attr}() on {noun} blocks the "
                           f"host {where}; {fix}")

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_func(node.func) and self.loop_depth > 0:
            self._emit(node, "JX102",
                       "jax.jit called inside a loop builds a fresh "
                       "callable (and compile cache) every iteration; "
                       "hoist it out of the loop")
        func = node.func
        # jax.shard_map(...) / jax.experimental.shard_map.shard_map(...) —
        # but NOT the shim's own surface (mesh.shard_map / mesh_lib.
        # shard_map), which is exactly what the rule tells you to call
        if isinstance(func, ast.Attribute) and func.attr == "shard_map":
            root = func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "jax":
                self._emit(node, "JX103", RULES["JX103"])
        # getattr(jax, "shard_map")
        if (isinstance(func, ast.Name) and func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value == "shard_map"):
            self._emit(node, "JX103", RULES["JX103"])
        # JX303: Thread spawned without declaring its lifecycle
        if ((isinstance(func, ast.Attribute) and func.attr == "Thread"
                and isinstance(func.value, ast.Name)
                and func.value.id == "threading")
                or (isinstance(func, ast.Name) and func.id == "Thread")):
            if not any(kw.arg == "daemon" for kw in node.keywords):
                self._emit(node, "JX303", RULES["JX303"])
        # Param(default=<mutable>)
        if (isinstance(func, ast.Name) and func.id == "Param") or (
                isinstance(func, ast.Attribute) and func.attr == "Param"):
            for kw in node.keywords:
                if kw.arg == "default" and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    self._emit(node, "JX104",
                               "Param(default=<mutable literal>) is shared "
                               "across every stage instance; use None or a "
                               "tuple")
        callee = _callee_name(func)
        # JX201: collective inside a lax.cond/switch/while_loop callable
        if callee in _COND_CALLS:
            for arg in node.args:
                body = self._resolve_callable(arg)
                if body is None:
                    continue
                for sub in ast.walk(body):
                    if (isinstance(sub, ast.Call) and _callee_name(sub.func)
                            in _COLLECTIVE_CALLS):
                        self._emit(sub, "JX201", RULES["JX201"])
        # JX202: collective with a literal axis name outside the canon
        if callee in _COLLECTIVE_CALLS or callee == "axis_index":
            pos = 0 if callee == "axis_index" else 1
            axis_arg = None
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axes"):
                    axis_arg = kw.value
            if axis_arg is None and len(node.args) > pos:
                axis_arg = node.args[pos]
            for name in _literal_axis_names(axis_arg):
                if name not in _MESH_AXES:
                    self._emit(node, "JX202",
                               f"axis {name!r} is not a canonical mesh "
                               f"axis {_MESH_AXES}; see parallel/mesh.py")
        # JX203/JX204: shard_map contract checks at the shim call site
        if callee == "shard_map":
            self._lint_shard_map_site(node)
        # JX107 (producer face): host image work inside the function fed
        # to a DeviceLoader as its batch source — that function IS the
        # train input path, loop or not
        if callee == "DeviceLoader" and self.uses_device_preprocess \
                and node.args:
            src = node.args[0]
            if isinstance(src, ast.Call):  # DeviceLoader(batches(), ...)
                src = src.func
            body = self._resolve_callable(src)
            if body is not None:
                self._lint_host_image_calls(
                    body, "a DeviceLoader producer")
        self.generic_visit(node)

    # -- JX201/JX203/JX204 helpers --

    def _resolve_callable(self, expr: ast.AST) -> ast.AST | None:
        """A Lambda inline, or a Name bound to a module-local def."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            return self.func_defs.get(expr.id)
        return None

    def _lint_shard_map_site(self, node: ast.Call) -> None:
        kw = {k.arg: k.value for k in node.keywords}
        in_specs = kw.get("in_specs") if "in_specs" in kw else (
            node.args[2] if len(node.args) > 2 else None)
        out_specs = kw.get("out_specs") if "out_specs" in kw else (
            node.args[3] if len(node.args) > 3 else None)
        body = self._resolve_callable(node.args[0]) if node.args else None
        in_axes = _spec_axis_names(in_specs)
        out_axes = _spec_axis_names(out_specs)
        # JX203: in_spec axes that never reach an out_spec need a
        # reducing collective in the body (literal-resolvable sites only;
        # a variable axis arg in the body gets the benefit of the doubt)
        missing = in_axes - out_axes
        if missing and body is not None:
            covered: set[str] = set()
            for sub in ast.walk(body):
                if not (isinstance(sub, ast.Call) and _callee_name(sub.func)
                        in _REDUCING_CALLS):
                    continue
                axis_arg = None
                for k in sub.keywords:
                    if k.arg in ("axis_name", "axes"):
                        axis_arg = k.value
                if axis_arg is None and len(sub.args) > 1:
                    axis_arg = sub.args[1]
                lits = _literal_axis_names(axis_arg)
                if lits:
                    covered |= lits
                elif axis_arg is not None:
                    covered |= missing  # unresolvable axis: assume covers
            for axis in sorted(missing - covered):
                self._emit(node, "JX203",
                           f"axis {axis!r} is sharded by in_specs, absent "
                           "from out_specs, and never reduced in the body "
                           "— the output escapes as an unreduced partial "
                           "sum over it (check_vma=False hides this)")
        # JX204: local-cumsum capacity slots + dispatch, no count exchange
        if body is not None:
            calls = {_callee_name(sub.func) for sub in ast.walk(body)
                     if isinstance(sub, ast.Call)}
            if ("cumsum" in calls
                    and calls & {"all_to_all", "psum_scatter"}
                    and "all_gather" not in calls):
                for sub in ast.walk(body):
                    if (isinstance(sub, ast.Call)
                            and _callee_name(sub.func) == "cumsum"):
                        self._emit(sub, "JX204", RULES["JX204"])
                        break

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and node.module.startswith("jax.experimental.shard_map"):
            self._emit(node, "JX103", RULES["JX103"])
        self.generic_visit(node)

    # -- JX101: walk jitted function bodies --

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._maybe_lint_jit_body(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._maybe_lint_jit_body(node)
        self.generic_visit(node)

    def _maybe_lint_jit_body(self, node: ast.AST) -> None:
        name = getattr(node, "name", None)
        if _has_jit_decorator(node) or (name and name in self.jitted_names):
            self._lint_traced_body(node)
        if name in ("device_fn", "device_fn_mesh"):
            # a device-stage body: everything built here (closure
            # constants included) flows into the planner's jitted
            # composite — JX108 guards the f64 spellings
            self._lint_f64_sites(node)

    def lint_lambdas(self) -> None:
        for lam in self.jitted_lambdas:
            self._lint_traced_body(lam)

    def _lint_traced_body(self, fn: ast.AST) -> None:
        """Flag host syncs anywhere inside a traced function (nested defs
        included — they trace too)."""
        self._lint_f64_sites(fn)  # JX108 rides every traced body
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute):
                    if func.attr in _HOST_METHODS:
                        self._emit(node, "JX101",
                                   f".{func.attr}() blocks on a device→"
                                   "host sync inside a traced function")
                    elif (func.attr in _HOST_NP_CALLS
                          and isinstance(func.value, ast.Name)
                          and func.value.id in _NUMPY_ALIASES):
                        self._emit(node, "JX101",
                                   f"np.{func.attr} materializes a traced "
                                   "value on host; use jnp inside jitted "
                                   "code")
                elif isinstance(func, ast.Name) \
                        and func.id in _HOST_BUILTINS:
                    if node.args and not isinstance(node.args[0],
                                                    ast.Constant):
                        self._emit(node, "JX101",
                                   f"{func.id}() on a traced value forces "
                                   "a host sync (or a tracer error); keep "
                                   "the computation in jax")


def lint_source_full(source: str, path: str = "<string>",
                     ) -> tuple[list[Finding], list[tuple[Finding, str]]]:
    """(active findings, pragma-suppressed (finding, justification))."""
    tree = ast.parse(source, filename=path)
    linter = _Linter(path, source)
    linter.collect(tree)
    linter.visit(tree)
    linter.lint_lambdas()
    linter.lint_acquire_blocks(tree)
    return linter.findings, linter.suppressed


def lint_source(source: str, path: str = "<string>") -> list[Finding]:
    return lint_source_full(source, path)[0]


def _allowed(path: str, rule: str, allowlist: dict) -> str | None:
    """The allowlist justification suppressing (path, rule), or None.
    Legacy frozenset entries justify as the empty string."""
    norm = path.replace(os.sep, "/")
    for suffix, rules in allowlist.items():
        if norm.endswith(suffix) and rule in rules:
            return rules[rule] if isinstance(rules, dict) else ""
    return None


def lint_paths_full(paths: list[str], allowlist: dict | None = None,
                    ) -> tuple[list[Finding], list[dict]]:
    """(active findings, suppressed entries with pragma status) over
    files/trees — the ``--json`` payload halves."""
    allowlist = DEFAULT_ALLOWLIST if allowlist is None else allowlist
    findings: list[Finding] = []
    suppressed: list[dict] = []
    for root in paths:
        files = []
        if os.path.isdir(root):
            for dirpath, _dirs, names in os.walk(root):
                files.extend(os.path.join(dirpath, n) for n in names
                             if n.endswith(".py"))
        else:
            files.append(root)
        for f in sorted(files):
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            active, pragmaed = lint_source_full(src, f)
            for x in active:
                why = _allowed(f, x.rule, allowlist)
                if why is None:
                    findings.append(x)
                else:
                    suppressed.append({**x.as_dict(), "pragma": "allowed",
                                       "justification": why})
            suppressed.extend({**x.as_dict(), "pragma": "allowed",
                               "justification": why}
                              for x, why in pragmaed)
    return findings, suppressed


def lint_paths(paths: list[str],
               allowlist: dict | None = None) -> list[Finding]:
    return lint_paths_full(paths, allowlist)[0]


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    json_out = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    bad = [p for p in argv if not os.path.exists(p)]
    if bad:
        print(f"no such path(s): {', '.join(bad)}", file=sys.stderr)
        return 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = argv or [os.path.join(repo, "mmlspark_tpu")]
    findings, suppressed = lint_paths_full(paths)
    if json_out:
        print(json.dumps(
            {"findings": [{**f.as_dict(), "pragma": "none"}
                          for f in findings],
             "suppressed": suppressed},
            indent=2, sort_keys=True))
        return 1 if findings else 0
    for f in findings:
        print(f)
    print(f"lint_jax: {len(findings)} finding(s) over {paths} "
          f"({len(suppressed)} suppressed)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

"""fleet — read one fleet telemetry directory from the CLI.

Subcommands (all read-only over ``<fleet-dir>`` — the directory every
process exports into under ``MMLSPARK_TPU_FLEET``; see
docs/observability.md §fleet telemetry plane)::

    python tools/fleet.py status <fleet-dir>
        One row per exporting process: host, pid, snapshot count,
        newest seq/reason, and the age of its last snapshot (a stale
        age on a busy process is the first sign of a wedged exporter
        or a dead worker).

    python tools/fleet.py metrics <fleet-dir> [--prom]
        The fleet-MERGED registry (counters summed across processes,
        gauges per host/pid, histogram windows merged) as the JSON
        snapshot, or as the Prometheus text exposition with --prom —
        the same bodies the serve ``/fleet`` endpoint negotiates.

    python tools/fleet.py trace <fleet-dir> --out fleet_trace.json
        Write the clock-aligned fleet Perfetto timeline (one process
        group per host, skew corrected at the fenced-collective seams,
        cross-process flows stitched there). Render the file with
        ``python tools/trace.py render`` or open it in
        https://ui.perfetto.dev.

    python tools/fleet.py watch <fleet-dir> [--interval 2]
        [--iterations N]
        Re-print status on an interval (Ctrl-C to stop; --iterations
        bounds the loop for scripting).

A missing or empty fleet directory is a typed error: one diagnostic
line on stderr and exit code 2 (the tools/trace.py discipline) —
except ``watch``, whose purpose includes waiting for the first process
to appear, so it keeps printing an empty status instead of failing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _print_status(status: dict) -> None:
    rows = status["processes"]
    print(f"fleet dir: {status['fleet_dir']} — {len(rows)} process(es)")
    if not rows:
        return
    width = max(len(str(r["process"])) for r in rows)
    print(f"{'process':<{width}}  {'snaps':>5}  {'seq':>5}  "
          f"{'age s':>8}  reason")
    for r in rows:
        age = r.get("age_s")
        print(f"{r['process']:<{width}}  {r['snapshots']:>5}  "
              f"{str(r.get('seq', '?')):>5}  "
              f"{age if age is not None else '?':>8}  "
              f"{r.get('reason', '?')}")


def cmd_status(args: argparse.Namespace) -> int:
    from mmlspark_tpu.obs.fleet import FleetCollector, FleetReadError
    status = FleetCollector(args.fleet_dir).status()
    if not status["processes"]:
        # an operator gating on `status && deploy` must not pass on a
        # directory nothing has exported into — same typed exit-2 as
        # metrics/trace on the same input
        raise FleetReadError(
            f"fleet dir {args.fleet_dir!r} holds no process snapshot "
            "directories (has any process exported yet?)")
    _print_status(status)
    return 0


def cmd_metrics(args: argparse.Namespace) -> int:
    from mmlspark_tpu.obs.fleet import FleetCollector
    # registry-only merge — the metrics bodies never read the rings
    view = FleetCollector(args.fleet_dir).collect(include_ring=False)
    if args.prom:
        from mmlspark_tpu.obs.export import prometheus_text
        sys.stdout.write(prometheus_text([view.registry]))
        return 0
    print(json.dumps(view.snapshot(), indent=2, default=str))
    return 0


def cmd_trace(args: argparse.Namespace) -> int:
    from mmlspark_tpu.obs.fleet import FleetCollector
    view = FleetCollector(args.fleet_dir).collect()
    payload = view.chrome_trace()  # built once: the file AND the
    with open(args.out, "w", encoding="utf-8") as fh:  # summary line
        json.dump(payload, fh)
    meta = payload["fleetMeta"]
    print(json.dumps({
        "trace": args.out,
        "hosts": len(meta["hosts"]),
        "processes": len(meta["processes"]),
        "stitched_flows": meta["stitched_flows"],
        "unaligned": meta["unaligned"],
    }))
    return 0


def cmd_watch(args: argparse.Namespace) -> int:
    from mmlspark_tpu.obs.fleet import FleetCollector, FleetReadError
    collector = FleetCollector(args.fleet_dir)
    k = 0
    try:
        while True:
            try:
                _print_status(collector.status())
            except FleetReadError:
                # exporters create the directory lazily on enable():
                # waiting for the first process to appear — including
                # before the dir itself exists — is watch's whole job
                print(f"fleet dir: {args.fleet_dir} — not created yet, "
                      "waiting")
            k += 1
            if args.iterations is not None and k >= args.iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:  # pragma: no cover - interactive stop
        return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, helptext in (
            ("status", "per-process snapshot ages"),
            ("metrics", "fleet-merged registry"),
            ("trace", "write the clock-aligned fleet timeline"),
            ("watch", "status on an interval")):
        p = sub.add_parser(name, help=helptext)
        p.add_argument("fleet_dir",
                       help="the MMLSPARK_TPU_FLEET directory")
        if name == "metrics":
            p.add_argument("--prom", action="store_true",
                           help="Prometheus text exposition instead of "
                                "the JSON snapshot")
        if name == "trace":
            p.add_argument("--out", default="fleet_trace.json",
                           help="output Chrome-trace path")
        if name == "watch":
            p.add_argument("--interval", type=float, default=2.0)
            p.add_argument("--iterations", type=int, default=None,
                           help="stop after N prints (default: forever)")

    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    from mmlspark_tpu.obs.fleet import FleetReadError
    try:
        if args.cmd == "status":
            return cmd_status(args)
        if args.cmd == "metrics":
            return cmd_metrics(args)
        if args.cmd == "trace":
            return cmd_trace(args)
        return cmd_watch(args)
    except FleetReadError as e:
        print(f"fleet: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Shim: doc generation lives in the installable package
(``mmlspark_tpu.tools.docgen``; console script ``mmlspark-tpu-docgen``).
Running this regenerates docs/api/ and tests/test_generated_smoke.py in the
repo root."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.tools.docgen import generate, main  # noqa: F401,E402

if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

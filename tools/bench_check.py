"""bench_check — the perf-regression sentinel over the BENCH trajectory.

The bench harness archives one JSON record per round (``BENCH_r*.json``
at the repo root: ``{"n": round, ..., "parsed": {<the bench.py JSON
line>}}``). This tool is the trend's gate: it compares the CURRENT line
key-by-key against the best prior round, per metric, with per-class
tolerance bands::

    throughput (``*per_s*``, ``*_mb_s``, ``*_tf_s``)  current >= 0.9x best prior (max)
    tail latency (``*p99*``)                          current <= 1.25x best prior (min)
    byte ratios (``*bytes_ratio*``)                   exact == last prior

The round-18 token-serving keys ride the same bands —
``serve_generate_tokens_per_s`` is throughput,
``serve_generate_ttft_p99_ms``/``serve_generate_itl_p99_ms`` are tail
latency — plus :data:`LATENCY_GATED_P50` names median-latency keys
(e.g. ``serve_generate_ttft_p50_ms``) that gate under the p99 band
too: a median is far less weather-prone than a tail, so a 1.25x drift
there is a real regression, not a loaded box.

The round-19 fleet-serving keys ride them too:
``serve_fleet_rows_per_s_1b``/``serve_fleet_rows_per_s_2b``
(router-hop throughput at 1 and 2 supervised backends) gate as
throughput, and ``serve_fleet_kill_p99_ms`` — the client-observed tail
across a kill -9 mid-burst, failover included — gates as tail latency;
a drift there means the re-route path got slower, not the model.

and exits **2 with a named-regressions report** when any gated metric
falls outside its band (``tools/trace.py``'s typed exit-2 discipline).
Metrics present only in the current line are reported as *new* (a
trajectory grows keys every round); metrics in :data:`VOLATILE` are
tracked and reported but never gated — they are host-I/O-bound probes
whose historical rounds swing more than 2x with CI-box load on
identical code (e.g. ``inference_images_per_s_per_chip`` moved
14817 → 5866 across rounds 2-4 with no inference-path change), so a
band tight enough to catch a real regression would page on weather.
The gated metrics are the seam-counted / latency-bound ones the
tier-1 perf gates also pin.

CLI::

    python tools/bench_check.py [--repo DIR] [--current FILE.json]
        [--throughput-band 0.9] [--p99-band 1.25]

Default: the newest round under ``--repo`` (the repo root) is the
current line, checked against all prior rounds; ``--current`` checks an
external line (either a bare bench.py JSON line or a full round record)
against the whole archived trajectory. ``bench.py --check`` runs the
same comparison in-process after archiving and stamps the verdict into
its JSON line (``bench_check_verdict``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_THROUGHPUT_BAND = 0.9   # current >= band * best prior
DEFAULT_P99_BAND = 1.25         # current <= band * best prior

#: tracked-but-not-gated metrics: host-I/O-bound probes whose archived
#: rounds show >2x swings on identical code (shared-core CI boxes);
#: they stay in the report so a sustained cliff is still visible
VOLATILE = frozenset({
    "inference_images_per_s_per_chip",  # e2e incl. host decode/marshal
    "tunnel_upload_mb_s",               # raw H2D bandwidth weather
})


#: median-latency keys gated under the p99 band: medians of
#: high-sample-count token streams (TTFT over a whole burst) are stable
#: enough that the tail band is a meaningful floor for them too
LATENCY_GATED_P50 = frozenset({
    "serve_generate_ttft_p50_ms",
})


def classify(key: str) -> str | None:
    """Metric key → tolerance class (None = informational, ungated)."""
    if "bytes_ratio" in key:
        return "exact"
    if "p99" in key or key in LATENCY_GATED_P50:
        return "p99"
    if "per_s" in key or key.endswith("_mb_s") or key.endswith("_tf_s"):
        return "throughput"
    return None


def load_rounds(repo_dir: str) -> list[tuple[int, dict]]:
    """All archived rounds, ``[(n, parsed line), ...]`` sorted by round
    number. Unreadable or line-less records are skipped (a torn archive
    must not crash the sentinel)."""
    rounds: list[tuple[int, dict]] = []
    for path in glob.glob(os.path.join(repo_dir, "BENCH_r*.json")):
        try:
            with open(path, encoding="utf-8") as fh:
                rec = json.load(fh)
            parsed = rec.get("parsed")
            if isinstance(parsed, dict):
                rounds.append((int(rec.get("n", 0)), parsed))
        except (OSError, ValueError, TypeError):
            continue
    rounds.sort(key=lambda r: r[0])
    return rounds


def check_line(current: dict, priors: list[tuple[int, dict]],
               throughput_band: float = DEFAULT_THROUGHPUT_BAND,
               p99_band: float = DEFAULT_P99_BAND) -> dict:
    """Compare one bench line against the prior rounds. Returns the
    report: ``verdict`` (``"ok"`` / ``"regressed"`` / ``"no-priors"``),
    the named ``regressions`` (key, class, current, best prior + its
    round, the band), everything ``checked``, ``volatile`` tracked
    values, and ``new`` keys with no prior."""
    report: dict = {"verdict": "ok", "regressions": [], "checked": [],
                    "volatile": [], "new": [],
                    "rounds_compared": [n for n, _p in priors]}
    # within-line A/B: absolute model-load walls are box weather (a
    # cross-round band would flake on shared-core CI), but cold and warm
    # come from the same line on the same box minutes apart — a warm
    # compile-cache load costing MORE than the cold load that populated
    # the cache is a real regression regardless of the box
    cold = current.get("serve_load_wall_cold_s")
    warm = current.get("serve_load_wall_warm_s")
    if isinstance(cold, (int, float)) and not isinstance(cold, bool) \
            and isinstance(warm, (int, float)) \
            and not isinstance(warm, bool):
        row = {"key": "serve_load_wall_warm_s", "class": "within-line",
               "current": warm, "best": cold, "best_round": None,
               "ratio": round(warm / cold, 4) if cold else None,
               "band": "<= serve_load_wall_cold_s (same line)"}
        report["checked"].append(row)
        if warm > cold:
            report["regressions"].append(row)
    # same within-line discipline for the lifecycle deployer's
    # checkpoint→serving wall: the warm rollout rides the compile cache
    # the cold rollout populated, minutes apart on the same box
    d_cold = current.get("deploy_wall_cold_s")
    d_warm = current.get("deploy_wall_warm_s")
    if isinstance(d_cold, (int, float)) and not isinstance(d_cold, bool) \
            and isinstance(d_warm, (int, float)) \
            and not isinstance(d_warm, bool):
        row = {"key": "deploy_wall_warm_s", "class": "within-line",
               "current": d_warm, "best": d_cold, "best_round": None,
               "ratio": round(d_warm / d_cold, 4) if d_cold else None,
               "band": "<= deploy_wall_cold_s (same line)"}
        report["checked"].append(row)
        if d_warm > d_cold:
            report["regressions"].append(row)
    if not priors:
        report["verdict"] = ("regressed" if report["regressions"]
                             else "no-priors")
        return report
    for key in sorted(current):
        cls = classify(key)
        v = current.get(key)
        if cls is None or not isinstance(v, (int, float)) \
                or isinstance(v, bool):
            continue
        prior_vals = [(n, p[key]) for n, p in priors
                      if isinstance(p.get(key), (int, float))
                      and not isinstance(p.get(key), bool)]
        if not prior_vals:
            report["new"].append(key)
            continue
        if cls == "throughput":
            best_n, best = max(prior_vals, key=lambda nv: nv[1])
            ok = v >= throughput_band * best
            band = f">= {throughput_band:g}x best"
        elif cls == "p99":
            best_n, best = min(prior_vals, key=lambda nv: nv[1])
            ok = v <= p99_band * best
            band = f"<= {p99_band:g}x best"
        else:  # exact
            best_n, best = prior_vals[-1]
            ok = v == best
            band = "== last"
        row = {"key": key, "class": cls, "current": v, "best": best,
               "best_round": best_n,
               "ratio": round(v / best, 4) if best else None,
               "band": band}
        if key in VOLATILE:
            report["volatile"].append({**row, "gated": False})
            continue
        report["checked"].append(row)
        if not ok:
            report["regressions"].append(row)
    if report["regressions"]:
        report["verdict"] = "regressed"
    return report


def format_report(report: dict) -> str:
    """The human lines the CLI prints under the JSON verdict."""
    lines = [f"bench_check: {report['verdict']} — "
             f"{len(report['checked'])} gated metric(s) vs rounds "
             f"{report['rounds_compared']}"]
    for r in report["regressions"]:
        lines.append(
            f"  REGRESSION {r['key']} [{r['class']}]: "
            f"{r['current']} vs best {r['best']} (r{r['best_round']}) "
            f"— {r['ratio']}x, band {r['band']}")
    for r in report["volatile"]:
        lines.append(
            f"  volatile (not gated) {r['key']}: {r['current']} vs "
            f"best {r['best']} ({r['ratio']}x)")
    if report["new"]:
        lines.append(f"  new (no prior): {', '.join(report['new'])}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="bench_check", description=__doc__,
                                 formatter_class=argparse.
                                 RawDescriptionHelpFormatter)
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json trajectory")
    ap.add_argument("--current", default=None,
                    help="JSON file to check against the WHOLE "
                         "trajectory (a bench.py line, or a round "
                         "record with a 'parsed' key); default: the "
                         "newest archived round vs its priors")
    ap.add_argument("--throughput-band", type=float,
                    default=DEFAULT_THROUGHPUT_BAND)
    ap.add_argument("--p99-band", type=float, default=DEFAULT_P99_BAND)
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    rounds = load_rounds(args.repo)
    if args.current:
        try:
            with open(args.current, encoding="utf-8") as fh:
                current = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"bench_check: cannot read --current "
                  f"{args.current!r}: {e}", file=sys.stderr)
            return 2
        if isinstance(current, dict) and isinstance(
                current.get("parsed"), dict):
            current = current["parsed"]
        if not isinstance(current, dict):
            print(f"bench_check: {args.current!r} is not a bench line",
                  file=sys.stderr)
            return 2
        priors = rounds
    else:
        if not rounds:
            print(f"bench_check: no BENCH_r*.json rounds under "
                  f"{args.repo!r}", file=sys.stderr)
            return 2
        current = rounds[-1][1]
        priors = rounds[:-1]

    report = check_line(current, priors,
                        throughput_band=args.throughput_band,
                        p99_band=args.p99_band)
    print(json.dumps({"bench_check": report["verdict"],
                      "regressions": [r["key"] for r in
                                      report["regressions"]],
                      "checked": len(report["checked"]),
                      "volatile": len(report["volatile"]),
                      "new": len(report["new"])}))
    print(format_report(report))
    return 2 if report["verdict"] == "regressed" else 0


if __name__ == "__main__":
    sys.exit(main())

"""deployer — drive a published model version through the rollout
lifecycle against a running serve fleet.

The CLI front-end of ``mmlspark_tpu/lifecycle`` (docs/lifecycle.md):
``rollout`` admits one repo version into the
``published → shadow → canary → promoted`` state machine and ticks the
:class:`Deployer` until it terminates — canary backends hot-swap first,
promotion blocks until every backend's beacon reports the new version,
and parity drift / fast burn / a stuck stage rolls back BOTH repo-side
(``CURRENT`` repointed) and serve-side. Every transition lands in
``<dir>/decisions.jsonl``; ``replay`` reconstructs the trajectories
from that journal alone.

Usage::

    # roll the newest published version of "mlp" out over the fleet
    # running in ./fleet (tools/serve_fleet.py --dir ./fleet --repo R)
    python tools/deployer.py rollout --repo ./repo --fleet-dir ./fleet \\
        --model mlp

    # pin an explicit version, widen the canary, slow the ramp
    python tools/deployer.py rollout --repo ./repo --fleet-dir ./fleet \\
        --model mlp --version 3 --canary-backends 2 --advance-after 4

    # forensic view: every rollout's journey from the journal
    python tools/deployer.py replay --journal ./fleet/lifecycle/decisions.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rollout_main(argv: Sequence[str]) -> int:
    ap = argparse.ArgumentParser(prog="deployer rollout")
    ap.add_argument("--repo", required=True,
                    help="versioned model repo root (models/repo.py)")
    ap.add_argument("--fleet-dir", required=True,
                    help="the fleet run dir (tools/serve_fleet.py --dir)"
                         ": beacons in, deploy.json commands out")
    ap.add_argument("--model", required=True)
    ap.add_argument("--version", type=int, default=None,
                    help="version to roll out (default: newest "
                         "published)")
    ap.add_argument("--dir", dest="lifecycle_dir", default=None,
                    help="lifecycle journal dir (default: "
                         "<fleet-dir>/lifecycle)")
    ap.add_argument("--canary-backends", type=int, default=1,
                    help="backends the ramp stages target before "
                         "fleet-wide promotion")
    ap.add_argument("--advance-after", type=int, default=2,
                    help="consecutive clean ticks per stage before "
                         "advancing")
    ap.add_argument("--fast-burn", type=float, default=14.0,
                    help="SLO fast-burn multiple that aborts the "
                         "rollout")
    ap.add_argument("--max-stage-ticks", type=int, default=240,
                    help="ticks a stage may hold before the rollout "
                         "aborts (a stuck deploy is a failed deploy)")
    ap.add_argument("--tick-s", type=float, default=0.25)
    ap.add_argument("--timeout-s", type=float, default=120.0)
    args = ap.parse_args(list(argv))

    from mmlspark_tpu.lifecycle import (
        Deployer, FleetTarget, RolloutPolicy,
    )
    from mmlspark_tpu.models.repo import ModelRepo, ModelRepoError

    repo = ModelRepo(args.repo)
    try:
        versions = repo.versions(args.model)
        if not versions:
            print(f"model {args.model!r}: nothing published in "
                  f"{args.repo}", file=sys.stderr)
            return 2
        version = args.version if args.version is not None \
            else versions[-1]
        deployer = Deployer(
            args.lifecycle_dir
            or os.path.join(args.fleet_dir, "lifecycle"),
            repo,
            FleetTarget(args.fleet_dir, args.repo,
                        canary_backends=args.canary_backends),
            policy=RolloutPolicy(
                advance_after=args.advance_after,
                fast_burn=args.fast_burn,
                max_stage_ticks=args.max_stage_ticks),
            refs={"serve_journal": os.path.join(args.fleet_dir,
                                                "decisions.jsonl")})
        rollout = deployer.start_rollout(args.model, version=version)
        outcome = deployer.run(rollout, tick_s=args.tick_s,
                               timeout_s=args.timeout_s)
    except ModelRepoError as e:
        print(str(e), file=sys.stderr)
        return 2
    print(json.dumps({
        "model": args.model, "version": rollout.version,
        "prior_version": rollout.prior_version, "outcome": outcome,
        "ticks": rollout.ledger.ticks,
        "journal": deployer.journal.path,
    }, indent=2))
    return 0 if outcome == "promoted" else 1


def replay_main(argv: Sequence[str]) -> int:
    ap = argparse.ArgumentParser(prog="deployer replay")
    ap.add_argument("--journal", required=True,
                    help="a lifecycle decisions.jsonl")
    args = ap.parse_args(list(argv))
    from mmlspark_tpu.lifecycle import replay_decisions
    try:
        print(json.dumps(replay_decisions(args.journal), indent=2))
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 2
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "rollout":
        return rollout_main(argv[1:])
    if argv and argv[0] == "replay":
        return replay_main(argv[1:])
    print(__doc__, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())

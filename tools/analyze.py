"""analyze — pre-flight pipeline & codebase analysis CLI.

Four subcommands::

    python tools/analyze.py pipeline <saved-stage-dir> --schema schema.json
        [--rows N] [--precision f32|bf16|int8w] [--strict]
    python tools/analyze.py code [path ...] [--json]
    python tools/analyze.py spmd [target ...] [--schema schema.json]
        [--rows N] [--cpu-devices N]
    python tools/analyze.py concurrency [path ...] [--json]

``pipeline`` loads a persisted stage (a Pipeline/PipelineModel saved with
``.save()``, or any single stage), abstractly interprets it over the
column schema declared in the JSON file, and prints typed diagnostics,
the predicted output schema, and the device-plan audit (fusion segments,
predicted H2D/D2H crossings, recompile hazards) — **without building a
table or touching a device**. Exit code 1 when error-level diagnostics
exist (``--strict`` also fails on warnings).

The schema JSON maps column name → spec (see
``TableSchema.from_spec``)::

    {"image": {"kind": "image", "shape": [32, 32, 3]},
     "age":   {"kind": "scalar", "dtype": "float64"},
     "text":  "text"}

``code`` runs the JAX anti-pattern lint (tools/lint_jax.py) and shares
its exit semantics.

``spmd`` runs the symbolic SPMD verifier (mmlspark_tpu/analysis/spmd.py;
docs/spmd_analysis.md): each target is a parallel entry point
(``moe_apply``, ``pipeline_apply``, ``ring_attention``,
``ulysses_attention``), ``parallel`` (all of them, the default), or a
saved-model directory (with ``--schema``: the device-plan audit's
multi-chip mode — fused segments must be manual-collective-free and
dp-divisible). Prints each function's sharding contract, collective
schedule, and findings; exit 1 when any finding survives. Runs on a
virtual CPU mesh (``--cpu-devices``, default 8) — no accelerator is
touched.

``concurrency`` runs the whole-repo concurrency verifier
(mmlspark_tpu/analysis/concurrency.py; docs/concurrency.md): lock
inventory, interprocedural lock-order graph, and typed findings
(CC101 lock-order cycle, CC102 blocking under lock, CC103 unguarded
acquire, CC104 joinless non-daemon thread, CC105 callback under lock).
Default target is the mmlspark_tpu package itself. ``--json`` emits
the machine report (rule id, path, line, message, pragma status — the
same schema as ``lint_jax --json``). Exit 0 clean, 1 when any
unsuppressed finding survives, 2 on usage errors. Pure AST: nothing is
imported or executed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def cmd_pipeline(args: argparse.Namespace) -> int:
    # keep analysis off accelerators: eval_shape needs no device, and a
    # pre-flight check must not grab a TPU just to reject a pipeline
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from mmlspark_tpu.analysis import TableSchema, analyze
    from mmlspark_tpu.core.stage import PipelineStage

    with open(args.schema, "r", encoding="utf-8") as fh:
        spec = json.load(fh)
    schema = TableSchema.from_spec(spec)
    stage = PipelineStage.load(args.model)
    report = analyze(stage, schema, n_rows=args.rows,
                     precision=args.precision)
    print(report.format())
    if report.errors or (args.strict and report.warnings):
        return 1
    return 0


def cmd_code(args: argparse.Namespace) -> int:
    import lint_jax
    return lint_jax.main(args.paths + (["--json"] if args.json else []))


def cmd_concurrency(args: argparse.Namespace) -> int:
    from mmlspark_tpu.analysis.concurrency import analyze_paths, analyze_repo
    if args.paths:
        bad = [p for p in args.paths if not os.path.exists(p)]
        if bad:
            print(f"no such path(s): {', '.join(bad)}", file=sys.stderr)
            return 2
        an = analyze_paths(args.paths)
    else:
        an = analyze_repo()
    if args.json:
        print(json.dumps(an.report(), indent=2, sort_keys=True))
        return 1 if an.findings else 0
    print(f"concurrency: {len(an.locks)} lock(s), {len(an.threads)} "
          f"thread spawn(s), {len(an.edges)} lock-order edge(s)")
    for e in sorted(an.edges, key=lambda e: (e.a, e.b)):
        via = f"  (via {e.chain})" if e.chain else ""
        print(f"  edge {e.a} -> {e.b}  [{e.path}:{e.line}]{via}")
    for f, why in an.suppressed:
        print(f"{f}  [suppressed: {why}]")
    for f in sorted(an.findings, key=lambda f: (f.path, f.line, f.rule)):
        print(f)
    n = len(an.findings)
    print(f"concurrency: {n} finding(s), {len(an.suppressed)} suppressed")
    return 1 if n else 0


def cmd_spmd(args: argparse.Namespace) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{args.cpu_devices}").strip()
    from mmlspark_tpu.analysis.spmd import (
        ENTRY_POINTS, audit_plan_spmd, verify_entry_point,
    )

    targets = args.targets or ["parallel"]
    by_name = {ep.name: ep for ep in ENTRY_POINTS}
    n_findings = 0
    for target in targets:
        if os.path.isdir(target):
            if not args.schema:
                print(f"{target}: saved-model targets need --schema")
                return 2
            from mmlspark_tpu.analysis import TableSchema
            from mmlspark_tpu.core.stage import PipelineStage

            with open(args.schema, "r", encoding="utf-8") as fh:
                schema = TableSchema.from_spec(json.load(fh))
            stage = PipelineStage.load(target)
            stages = getattr(stage, "stages", [stage])
            audit = audit_plan_spmd(stages, schema.entry_meta,
                                    n_rows=args.rows)
            print(f"== plan spmd audit: {target}")
            print(audit.format())
            n_findings += len(audit.findings)
            continue
        eps = (list(ENTRY_POINTS) if target == "parallel"
               else [by_name[t] for t in [target] if t in by_name])
        if not eps:
            print(f"unknown target {target!r}; choose from "
                  f"{sorted(by_name)} | parallel | <saved-model-dir>")
            return 2
        for ep in eps:
            report = verify_entry_point(ep)
            print(f"== {report.format()}")
            n_findings += len(report.findings)
    print(f"spmd: {n_findings} finding(s)")
    return 1 if n_findings else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="analyze", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("pipeline",
                       help="statically validate a saved pipeline")
    p.add_argument("model", help="directory of a stage saved with .save()")
    p.add_argument("--schema", required=True,
                   help="JSON file declaring the input column schema")
    p.add_argument("--rows", type=int, default=None,
                   help="row count for concrete crossing prediction")
    p.add_argument("--precision", default=None,
                   choices=["f32", "bf16", "int8w"],
                   help="resolve each device segment's serving precision "
                        "policy in the plan report (mode + expected "
                        "parity tolerance; docs/quantization.md)")
    p.add_argument("--strict", action="store_true",
                   help="exit non-zero on warnings too")
    p.set_defaults(func=cmd_pipeline)

    c = sub.add_parser("code", help="run the JAX anti-pattern lint")
    c.add_argument("paths", nargs="*", help="files/dirs (default: "
                   "mmlspark_tpu/)")
    c.add_argument("--json", action="store_true",
                   help="machine-readable findings (rule, path, line, "
                        "message, pragma status)")
    c.set_defaults(func=cmd_code)

    k = sub.add_parser("concurrency",
                       help="run the whole-repo concurrency verifier")
    k.add_argument("paths", nargs="*",
                   help="files/dirs (default: the mmlspark_tpu package)")
    k.add_argument("--json", action="store_true",
                   help="machine-readable report (locks, edges, findings "
                        "with pragma status)")
    k.set_defaults(func=cmd_concurrency)

    s = sub.add_parser("spmd", help="run the symbolic SPMD verifier")
    s.add_argument("targets", nargs="*",
                   help="parallel entry point(s), 'parallel' (default), "
                        "or a saved-model directory")
    s.add_argument("--schema", default=None,
                   help="schema JSON (saved-model targets)")
    s.add_argument("--rows", type=int, default=None,
                   help="row count for minibatch-round prediction")
    s.add_argument("--cpu-devices", type=int, default=8,
                   help="virtual CPU mesh size (default 8)")
    s.set_defaults(func=cmd_spmd)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())

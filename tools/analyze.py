"""analyze — pre-flight pipeline & codebase analysis CLI.

Two subcommands::

    python tools/analyze.py pipeline <saved-stage-dir> --schema schema.json
        [--rows N] [--strict]
    python tools/analyze.py code [path ...]

``pipeline`` loads a persisted stage (a Pipeline/PipelineModel saved with
``.save()``, or any single stage), abstractly interprets it over the
column schema declared in the JSON file, and prints typed diagnostics,
the predicted output schema, and the device-plan audit (fusion segments,
predicted H2D/D2H crossings, recompile hazards) — **without building a
table or touching a device**. Exit code 1 when error-level diagnostics
exist (``--strict`` also fails on warnings).

The schema JSON maps column name → spec (see
``TableSchema.from_spec``)::

    {"image": {"kind": "image", "shape": [32, 32, 3]},
     "age":   {"kind": "scalar", "dtype": "float64"},
     "text":  "text"}

``code`` runs the JAX anti-pattern lint (tools/lint_jax.py) and shares
its exit semantics.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def cmd_pipeline(args: argparse.Namespace) -> int:
    # keep analysis off accelerators: eval_shape needs no device, and a
    # pre-flight check must not grab a TPU just to reject a pipeline
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from mmlspark_tpu.analysis import TableSchema, analyze
    from mmlspark_tpu.core.stage import PipelineStage

    with open(args.schema, "r", encoding="utf-8") as fh:
        spec = json.load(fh)
    schema = TableSchema.from_spec(spec)
    stage = PipelineStage.load(args.model)
    report = analyze(stage, schema, n_rows=args.rows)
    print(report.format())
    if report.errors or (args.strict and report.warnings):
        return 1
    return 0


def cmd_code(args: argparse.Namespace) -> int:
    import lint_jax
    return lint_jax.main(args.paths)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="analyze", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("pipeline",
                       help="statically validate a saved pipeline")
    p.add_argument("model", help="directory of a stage saved with .save()")
    p.add_argument("--schema", required=True,
                   help="JSON file declaring the input column schema")
    p.add_argument("--rows", type=int, default=None,
                   help="row count for concrete crossing prediction")
    p.add_argument("--strict", action="store_true",
                   help="exit non-zero on warnings too")
    p.set_defaults(func=cmd_pipeline)

    c = sub.add_parser("code", help="run the JAX anti-pattern lint")
    c.add_argument("paths", nargs="*", help="files/dirs (default: "
                   "mmlspark_tpu/)")
    c.set_defaults(func=cmd_code)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())

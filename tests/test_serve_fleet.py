"""Serve fleet tier (serve/fleet/): pool selection + holds + drains,
router failover (predict resend, generate prefix-skip replay, the
seeded backend_* fault points), pure ScalePolicy decisions, supervisor
restart/drain/scale mechanics on fake beacon workers, the Retry-After
sleep floor in core/retry, and the fleet-telemetry merge across the
router hop. The full JAX end-to-end (kill -9 + scale-up under induced
burn, bit-identical answers, compile-cache-warm spawn) is the
``check_serve_fleet`` tier-1 gate in tools/perf_smoke.py."""

import http.client
import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mmlspark_tpu.core.retry import RetryPolicy, call_with_retry
from mmlspark_tpu.serve import faults as serve_faults
from mmlspark_tpu.serve.errors import Overloaded
from mmlspark_tpu.serve.faults import FaultPlan, FaultSpec
from mmlspark_tpu.serve.fleet import (
    BackendPool, FleetConfig, FleetLedger, FleetRouter, Hold,
    NoBackendAvailable, ScaleDown, ScalePolicy, ScaleSignal, ScaleUp,
    ServeSupervisor, signal_from_history, sustained_s,
)
from mmlspark_tpu.obs.timeseries import MetricHistory
from mmlspark_tpu.serve.fleet.scale import BURN_SERIES, OCCUPANCY_SERIES
from mmlspark_tpu.train.service import RecoveryPolicy


# ---------------------------------------------------------------------------
# stub backends: the serve HTTP wire protocol without a ModelServer
# ---------------------------------------------------------------------------


class _StubHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):  # noqa: N802 - http.server contract
        stub = self.server.stub
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        stub.requests.append((self.path, body,
                              self.headers.get("X-Fleet-Request-Id")))
        if self.path.endswith(":predict"):
            self._predict(stub)
        elif self.path.endswith(":generate"):
            self._generate(stub)
        else:
            self._json(404, {"error": "NotFound"})

    def _json(self, status, payload, headers=None):
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _die(self):
        # vanish without a status line: the client sees a torn
        # connection, exactly like a kill -9 with bytes in flight.
        # shutdown(), not close(): rfile/wfile hold io-refs on the
        # socket, so close() alone never sends the FIN.
        self.close_connection = True
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _predict(self, stub):
        if stub.mode == "die":
            self._die()
            return
        if stub.mode == "reject":
            self._json(429, {"error": "Overloaded"},
                       headers={"Retry-After": str(stub.retry_after)})
            return
        stub.served += 1
        self._json(200, {"model": "m", "port": stub.port,
                         "rows": [{"scores": [1.0, 2.0]}]})

    def _chunk(self, obj):
        data = json.dumps(obj).encode() + b"\n"
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _generate(self, stub):
        if stub.mode == "reject":
            self._json(429, {"error": "Overloaded"},
                       headers={"Retry-After": str(stub.retry_after)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        stub.streaming += 1
        try:
            for i in range(stub.tokens):
                if stub.tear_after is not None and i >= stub.tear_after:
                    self._die()  # mid-stream kill
                    return
                self._chunk({"token": f"tok{i}", "index": i})
                if stub.token_delay:
                    time.sleep(stub.token_delay)
            self._chunk({"done": True, "model": "m",
                         "tokens": stub.tokens, "cancelled": False})
            self.wfile.write(b"0\r\n\r\n")
            stub.streams_finished += 1
        finally:
            stub.streaming -= 1


class _Stub:
    """One fake backend process (in-process HTTP server). Deterministic
    token stream — every stub emits the same sequence, the stand-in for
    deterministic decode that makes prefix-skip replay exact."""

    def __init__(self, mode="ok", tokens=6, token_delay=0.0,
                 tear_after=None, retry_after=0.2):
        self.mode = mode
        self.tokens = tokens
        self.token_delay = token_delay
        self.tear_after = tear_after
        self.retry_after = retry_after
        self.requests = []
        self.served = 0
        self.streaming = 0
        self.streams_finished = 0
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
        self._httpd.daemon_threads = True
        self._httpd.stub = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"Stub[{self.port}]",
            daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


@pytest.fixture
def fleet_pair():
    """Two healthy stubs registered in a pool behind a started router."""
    from mmlspark_tpu.obs.metrics import registry
    registry().reset()  # router counters live in the global registry
    stubs = [_Stub(), _Stub()]
    pool = BackendPool()
    for bid, s in enumerate(stubs):
        pool.add(bid, "127.0.0.1", s.port)
    router = FleetRouter(pool, wait_budget_s=2.0,
                         default_retry_after_s=0.2).start()
    yield stubs, pool, router
    router.close()
    for s in stubs:
        s.close()
    serve_faults.clear()


def _predict(router, body=b'{"rows": [{"x": 1}]}', timeout=10):
    host, port = router.address
    req = urllib.request.Request(
        f"http://{host}:{port}/v1/models/m:predict", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def _generate(router, timeout=10):
    """Stream :generate through the router; returns (headers, lines)."""
    host, port = router.address
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/models/m:generate",
                     body=b'{"prompt": "p"}',
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        lines = []
        while True:
            line = resp.readline()
            if not line:
                break
            lines.append(json.loads(line))
            if "done" in lines[-1] or "error" in lines[-1]:
                break
        return dict(resp.getheaders()), lines
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# BackendPool
# ---------------------------------------------------------------------------


class TestBackendPool:
    def test_pick_least_loaded_ties_to_lowest_bid(self):
        pool = BackendPool()
        for bid in (0, 1, 2):
            pool.add(bid, "h", 9000 + bid)
        assert pool.pick() == 0
        with pool.lease(0):
            assert pool.pick() == 1
            with pool.stream_lease(1):
                assert pool.pick() == 2
        assert pool.pick() == 0

    def test_pick_skips_down_draining_excluded(self):
        pool = BackendPool()
        for bid in (0, 1, 2):
            pool.add(bid, "h", 9000 + bid)
        assert pool.mark_down(0) is True
        assert pool.mark_down(0) is False  # reported once
        pool.drain(1)
        assert pool.pick() == 2
        with pytest.raises(NoBackendAvailable):
            pool.pick(exclude=(2,))

    def test_all_held_raises_with_earliest_expiry(self):
        pool = BackendPool()
        pool.add(0, "h", 9000)
        pool.add(1, "h", 9001)
        pool.hold(0, 5.0)
        pool.hold(1, 0.2)
        with pytest.raises(NoBackendAvailable) as exc:
            pool.pick()
        assert exc.value.retry_after_s == pytest.approx(0.2, abs=0.1)
        time.sleep(0.25)
        assert pool.pick() == 1  # the short hold expired

    def test_readd_after_restart_revives_but_never_unrains(self):
        pool = BackendPool()
        pool.add(0, "h", 9000, generation=0)
        pool.mark_down(0)
        pool.add(0, "h", 9100, generation=1)  # restarted: new port/gen
        assert pool.pick() == 0
        assert pool.address(0) == ("h", 9100)
        pool.drain(0)
        pool.add(0, "h", 9100, generation=1)  # a beacon mid-drain
        with pytest.raises(NoBackendAvailable):
            pool.pick()  # still draining: never resurrected

    def test_idle_is_the_zero_drop_stop_point(self):
        pool = BackendPool()
        pool.add(0, "h", 9000)
        lease = pool.stream_lease(0)
        with lease:
            pool.drain(0)
            assert not pool.idle(0)  # active stream holds it
        assert pool.idle(0)
        assert not pool.idle(99)  # unregistered is not "safe to stop"


# ---------------------------------------------------------------------------
# ScalePolicy (pure) + signal condensation
# ---------------------------------------------------------------------------


class TestScalePolicy:
    POLICY = ScalePolicy(fast_burn=14.0, burn_sustain_s=1.0,
                         idle_occupancy=0.02, idle_sustain_s=30.0,
                         min_backends=1, max_backends=4, cooldown_s=5.0)

    def test_sustained_s_measures_the_trailing_run(self):
        pred = lambda v: v >= 14.0  # noqa: E731
        samples = [(0.0, 20.0), (1.0, 1.0), (2.0, 15.0), (3.0, 18.0)]
        assert sustained_s(samples, 4.0, pred) == pytest.approx(2.0)
        assert sustained_s([(0.0, 1.0)], 4.0, pred) == 0.0
        assert sustained_s([], 4.0, pred) == 0.0

    def test_sustained_burn_scales_up_until_max(self):
        act = self.POLICY.decide(
            ScaleSignal(backends=2, burn=20.0, burn_high_s=1.5),
            FleetLedger())
        assert isinstance(act, ScaleUp)
        act = self.POLICY.decide(
            ScaleSignal(backends=4, burn=20.0, burn_high_s=1.5),
            FleetLedger())
        assert isinstance(act, Hold) and "max_backends" in act.reason

    def test_momentary_burn_holds(self):
        act = self.POLICY.decide(
            ScaleSignal(backends=2, burn=20.0, burn_high_s=0.3),
            FleetLedger())
        assert isinstance(act, Hold)

    def test_sustained_idle_scales_down_until_min(self):
        act = self.POLICY.decide(
            ScaleSignal(backends=2, occupancy=0.0, idle_s=31.0),
            FleetLedger())
        assert isinstance(act, ScaleDown)
        act = self.POLICY.decide(
            ScaleSignal(backends=1, occupancy=0.0, idle_s=31.0),
            FleetLedger())
        assert isinstance(act, Hold) and "min_backends" in act.reason

    def test_cooldown_gates_everything(self):
        act = self.POLICY.decide(
            ScaleSignal(backends=2, burn=99.0, burn_high_s=9.0),
            FleetLedger(since_scale_s=1.0))
        assert isinstance(act, Hold) and "cooldown" in act.reason

    def test_validation(self):
        with pytest.raises(ValueError):
            ScalePolicy(min_backends=0)
        with pytest.raises(ValueError):
            ScalePolicy(min_backends=3, max_backends=2)

    def test_signal_from_history_condenses_both_series(self):
        h = MetricHistory()
        for t in range(5):
            h.append(100.0 + t, BURN_SERIES,
                     20.0 if t >= 2 else 1.0)
            h.append(100.0 + t, OCCUPANCY_SERIES, 0.01)
        sig = signal_from_history(h, now=105.0, backends=2,
                                  policy=self.POLICY, window_s=60.0)
        assert sig.burn == 20.0
        assert sig.burn_high_s == pytest.approx(3.0)
        assert sig.occupancy == 0.01
        assert sig.idle_s == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# satellite: client retry backoff honors Retry-After as a sleep FLOOR
# ---------------------------------------------------------------------------


class TestRetryAfterFloor:
    def _run(self, policy, exc):
        sleeps = []
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] == 1:
                raise exc
            return "ok"

        out = call_with_retry(fn, policy, sleep=sleeps.append)
        assert out == "ok"
        return sleeps

    def test_hint_longer_than_backoff_floors_the_sleep(self):
        sleeps = self._run(
            RetryPolicy(max_attempts=3, base_delay_s=0.01, jitter=0.0,
                        retry_on=(Overloaded,)),
            Overloaded("m", queued=1, max_queue=1, retry_after_s=5.0))
        assert sleeps == [5.0]

    def test_hint_shorter_than_backoff_keeps_the_backoff(self):
        # the hint is a FLOOR, never a cap: a server begging "come back
        # in 1ms" must not collapse the client's own pacing
        sleeps = self._run(
            RetryPolicy(max_attempts=3, base_delay_s=1.0, jitter=0.0,
                        retry_on=(Overloaded,)),
            Overloaded("m", queued=1, max_queue=1,
                       retry_after_s=0.001))
        assert sleeps == [1.0]

    def test_unstamped_error_keeps_pure_backoff(self):
        sleeps = self._run(
            RetryPolicy(max_attempts=3, base_delay_s=0.25, jitter=0.0,
                        retry_on=(Overloaded,)),
            Overloaded("m", queued=1, max_queue=1))
        assert sleeps == [0.25]


# ---------------------------------------------------------------------------
# FleetRouter: predict failover
# ---------------------------------------------------------------------------


class TestRouterPredict:
    def test_relay_carries_backend_identity(self, fleet_pair):
        stubs, _pool, router = fleet_pair
        status, headers, body = _predict(router)
        assert status == 200
        bid = int(headers["X-Fleet-Backend"])
        assert body["port"] == stubs[bid].port
        # the proxied request carried the span-link id to the backend
        assert stubs[bid].requests[-1][2] is not None

    def test_dead_backend_reroutes_never_drops(self, fleet_pair):
        stubs, pool, router = fleet_pair
        stubs[0].mode = "die"
        for _ in range(4):
            status, _h, body = _predict(router)
            assert status == 200
            assert body["port"] == stubs[1].port
        snap = {s["bid"]: s["state"] for s in pool.snapshot()}
        assert snap[0] == "down"
        assert router.counters()["serve.fleet.router.reroutes"] >= 1

    def test_backpressure_hold_moves_traffic_over(self, fleet_pair):
        stubs, pool, router = fleet_pair
        stubs[0].mode = "reject"
        stubs[1].mode = "reject"
        # both reject with Retry-After=0.2: the router holds each, then
        # waits out the earliest hold (within its budget) and retries —
        # flip the stubs healthy meanwhile so the wait pays off
        def _recover():
            for s in stubs:
                s.mode = "ok"
        t = threading.Timer(0.15, _recover)
        t.start()
        try:
            status, _headers, _body = _predict(router)
        finally:
            t.join()
        assert status == 200
        assert router.counters()["serve.fleet.router.held"] >= 2

    def test_no_live_backends_is_typed_503_with_retry_after(self):
        pool = BackendPool()
        # wait_budget_s bounds how long the router stalls hoping for a
        # revival beacon before conceding the typed 503
        router = FleetRouter(pool, wait_budget_s=0.2,
                             default_retry_after_s=3.0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _predict(router)
            assert exc.value.code == 503
            assert exc.value.headers["Retry-After"] == "3"
            assert json.loads(exc.value.read())["error"] == \
                "NoBackendAvailable"
        finally:
            router.close()

    def test_seeded_fault_points_drive_failover(self, fleet_pair):
        stubs, _pool, router = fleet_pair
        # backend_down raises before the slow seam is reached, so the
        # slow spec's first hit is already on the rerouted attempt
        plan = FaultPlan([
            FaultSpec(point="backend_down", times=1),
            FaultSpec(point="backend_slow", delay_s=0.2, times=1),
        ], seed=7)
        serve_faults.install(plan)
        t0 = time.monotonic()
        status, _h, _b = _predict(router)
        elapsed = time.monotonic() - t0
        assert status == 200
        assert plan.counts() == {"backend_down": 1, "backend_slow": 1}
        assert elapsed >= 0.2  # the slow seam actually slept
        assert router.counters()["serve.fleet.router.reroutes"] == 1

    def test_torn_response_fault_resends_elsewhere(self, fleet_pair):
        stubs, pool, router = fleet_pair
        serve_faults.install(FaultPlan([
            FaultSpec(point="backend_torn_response", times=1)]))
        status, _h, _b = _predict(router)
        assert status == 200
        assert sum(s.served for s in stubs) == 2  # one wasted + resend
        assert router.counters()["serve.fleet.router.reroutes"] == 1


# ---------------------------------------------------------------------------
# FleetRouter: generate streams (affinity, prefix-skip replay)
# ---------------------------------------------------------------------------


class TestRouterGenerate:
    def test_stream_relays_tokens_and_done(self, fleet_pair):
        stubs, _pool, router = fleet_pair
        headers, lines = _generate(router)
        assert headers["Content-Type"] == "application/x-ndjson"
        assert [ln["token"] for ln in lines[:-1]] == \
            [f"tok{i}" for i in range(6)]
        assert [ln["index"] for ln in lines[:-1]] == list(range(6))
        assert lines[-1]["done"] is True

    def test_torn_stream_replays_minus_delivered_prefix(self,
                                                        fleet_pair):
        stubs, pool, router = fleet_pair
        # force the stream onto stub 0, which tears after 3 tokens;
        # the replay leg on stub 1 must skip the delivered prefix:
        # the client sees tok0..tok5 exactly once, indexes contiguous
        stubs[0].tear_after = 3
        stubs[1].tokens = 6
        _headers, lines = _generate(router)
        assert [ln.get("token") for ln in lines[:-1]] == \
            [f"tok{i}" for i in range(6)]
        assert [ln["index"] for ln in lines[:-1]] == list(range(6))
        assert lines[-1]["done"] is True
        assert router.counters()["serve.fleet.router.stream_replays"] \
            == 1
        assert {s["bid"]: s["state"] for s in pool.snapshot()}[0] == \
            "down"

    def test_drain_keeps_active_streams_routes_new_elsewhere(
            self, fleet_pair):
        """Satellite: backend affinity across scale-down. The draining
        backend finishes its in-flight :generate stream (strict-prefix
        — in fact complete); new streams route to the survivor; the
        drained backend reaches the zero-drop idle point only after
        its last stream ends."""
        stubs, pool, router = fleet_pair
        for s in stubs:
            s.token_delay = 0.08
        first = {}

        def run_first():
            first["result"] = _generate(router, timeout=30)

        t = threading.Thread(target=run_first)
        t.start()
        # wait until the stream is in flight on some backend
        deadline = time.monotonic() + 5
        while not any(s.streaming for s in stubs):
            assert time.monotonic() < deadline, "stream never started"
            time.sleep(0.005)
        active = 0 if stubs[0].streaming else 1
        pool.drain(active)
        assert not pool.idle(active)  # the stream lease pins it
        # a NEW stream must route to the survivor
        headers2, lines2 = _generate(router, timeout=30)
        assert int(headers2["X-Fleet-Backend"]) == 1 - active
        t.join(timeout=30)
        headers1, lines1 = first["result"]
        assert int(headers1["X-Fleet-Backend"]) == active
        assert [ln["index"] for ln in lines1[:-1]] == list(range(6))
        assert lines1[-1]["done"] is True
        assert stubs[active].streams_finished == 1
        assert pool.idle(active)  # now safe to stop the process


# ---------------------------------------------------------------------------
# ServeSupervisor on fake beacon workers (no JAX, fast)
# ---------------------------------------------------------------------------


_FAKE_WORKER = r"""
import json, os, signal, threading, time
stop = threading.Event()
signal.signal(signal.SIGTERM, lambda *a: stop.set())
d = os.environ["MMLSPARK_TPU_SERVICE_DIR"]
rank = int(os.environ["MMLSPARK_TPU_SERVICE_RANK"])
gen = int(os.environ["MMLSPARK_TPU_SERVICE_GENERATION"])
path = os.path.join(d, "beacon_%d.json" % rank)
time.sleep(float(os.environ.get("FAKE_START_DELAY", "0")))
def write(status):
    tmp = path + ".tmp%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump({"rank": rank, "generation": gen, "ts": time.time(),
                   "status": status, "host": "127.0.0.1",
                   "port": 40000 + 100 * gen + rank,
                   "burn_short": float(os.environ.get("FAKE_BURN", "0")),
                   "occupancy": float(os.environ.get("FAKE_OCC", "0.5"))},
                  f)
    os.replace(tmp, path)
while not stop.wait(0.05):
    write("running")
write("draining")
write("exited")
"""


def _fake_cfg(tmp_path, **kw):
    kw.setdefault("cmd", (sys.executable, "-c", _FAKE_WORKER))
    kw.setdefault("initial_backends", 2)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("grace_s", 5.0)
    kw.setdefault("policy", RecoveryPolicy(
        max_restarts=2,
        restart_backoff=RetryPolicy(base_delay_s=0.05, max_delay_s=0.1,
                                    jitter=0.0),
        rescale_on_exhausted=False, preempt_exit_codes=()))
    kw.setdefault("scale", ScalePolicy(idle_sustain_s=3600.0,
                                       burn_sustain_s=3600.0))
    kw.setdefault("worker_obs", False)
    kw.setdefault("worker_fleet", False)
    return FleetConfig(service_dir=str(tmp_path), **kw)


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timed out: {msg}"
        time.sleep(0.02)


def _kinds(tmp_path):
    with open(os.path.join(str(tmp_path), "decisions.jsonl")) as f:
        return [json.loads(line)["kind"] for line in f]


class TestServeSupervisor:
    def test_beacons_register_backends_and_kill_restarts(self,
                                                         tmp_path):
        sup = ServeSupervisor(_fake_cfg(tmp_path))
        try:
            sup.start()
            _wait(lambda: sup.pool.up_count() == 2, msg="fleet up")
            ports = {s["bid"]: s["port"] for s in sup.pool.snapshot()}
            assert ports == {0: 40000, 1: 40001}  # beacon-carried
            victim = sup._backends[0]
            os.kill(victim.proc.pid, signal.SIGKILL)
            # the pool loses it, the policy respawns generation 1
            _wait(lambda: any(s["bid"] == 0 and s["generation"] == 1
                              and s["state"] == "up"
                              for s in sup.pool.snapshot()),
                  msg="restarted backend routable")
            assert sup.pool.address(0) == ("127.0.0.1", 40100)
            kinds = _kinds(tmp_path)
            assert "backend_exit" in kinds and "restart" in kinds
        finally:
            sup.close()
        assert "stop" in _kinds(tmp_path)
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("ServeFleetWatch")]
        assert not leaked, leaked

    def test_restart_budget_exhaustion_fails_the_backend(self,
                                                         tmp_path):
        sup = ServeSupervisor(_fake_cfg(
            tmp_path, initial_backends=1,
            policy=RecoveryPolicy(
                max_restarts=0, rescale_on_exhausted=False,
                preempt_exit_codes=())))
        try:
            sup.start()
            _wait(lambda: sup.pool.up_count() == 1, msg="fleet up")
            os.kill(sup._backends[0].proc.pid, signal.SIGKILL)
            _wait(lambda: "fail" in _kinds(tmp_path), msg="fail entry")
            _wait(lambda: sup.pool.up_count() == 0
                  and not sup.pool.ids(), msg="pool forgot it")
        finally:
            sup.close()

    def test_slow_boot_gets_start_grace_not_hang(self, tmp_path):
        # cold backends pay jax import + compile before the FIRST
        # beacon: the stall deadline must not shoot a booting worker
        sup = ServeSupervisor(_fake_cfg(
            tmp_path, extra_env={"FAKE_START_DELAY": "0.5"},
            beacon_timeout_s=0.2, start_grace_s=10.0))
        try:
            sup.start()
            _wait(lambda: sup.pool.up_count() == 2, msg="slow boot up")
            assert "hang" not in _kinds(tmp_path)
        finally:
            sup.close()

    def test_start_grace_expiry_hangs_all_without_crash(self, tmp_path):
        # both backends miss the first-beacon deadline in the SAME read
        # pass: each hang verdict mutates _backends mid-scan (regression
        # for the dict-changed-size crash), and the stall deadline takes
        # over normally once a restarted worker has beaconed
        sup = ServeSupervisor(_fake_cfg(
            tmp_path, extra_env={"FAKE_START_DELAY": "60"},
            beacon_timeout_s=5.0, start_grace_s=0.2,
            policy=RecoveryPolicy(
                max_restarts=0, rescale_on_exhausted=False,
                preempt_exit_codes=())))
        try:
            sup.start()
            _wait(lambda: _kinds(tmp_path).count("hang") >= 2,
                  msg="both boots declared hung")
            _wait(lambda: _kinds(tmp_path).count("fail") >= 2,
                  msg="budget-exhausted fails")
            # the watch loop survived the double verdict
            assert any(t.name.startswith("ServeFleetWatch")
                       for t in threading.enumerate())
        finally:
            sup.close()

    def test_manual_scale_down_drains_zero_drop(self, tmp_path):
        sup = ServeSupervisor(_fake_cfg(tmp_path))
        try:
            sup.start()
            _wait(lambda: sup.pool.up_count() == 2, msg="fleet up")
            sup.scale_down()
            # drain → (idle, no leases) → SIGTERM → clean exit, reaped
            _wait(lambda: "drained" in _kinds(tmp_path), msg="drained")
            assert sup.pool.up_count() == 1
            kinds = _kinds(tmp_path)
            assert "scale_down" in kinds
            # the drained worker exited 0 (SIGTERM honored, no kill)
            exits = [json.loads(line) for line in
                     open(os.path.join(str(tmp_path),
                                       "decisions.jsonl"))
                     if json.loads(line)["kind"] == "backend_exit"]
            assert exits and exits[-1]["code"] == 0
            assert exits[-1]["draining"] is True
        finally:
            sup.close()

    def test_sustained_burn_autoscales_up(self, tmp_path):
        sup = ServeSupervisor(_fake_cfg(
            tmp_path, initial_backends=1,
            extra_env={"FAKE_BURN": "100.0"},
            scale=ScalePolicy(fast_burn=14.0, burn_sustain_s=0.3,
                              idle_sustain_s=3600.0, min_backends=1,
                              max_backends=2, cooldown_s=60.0)))
        try:
            sup.start()
            _wait(lambda: "scale_up" in _kinds(tmp_path),
                  msg="burn-driven scale_up")
            _wait(lambda: sup.pool.up_count() == 2,
                  msg="scaled backend routable")
            assert sup.status()["scale_ups"] == 1
            # cooldown_s=60 pins it at 2 — no flapping past max
            assert _kinds(tmp_path).count("scale_up") == 1
        finally:
            sup.close()

    def test_sustained_idle_autoscales_down(self, tmp_path):
        sup = ServeSupervisor(_fake_cfg(
            tmp_path, initial_backends=2,
            extra_env={"FAKE_OCC": "0.0"},
            scale=ScalePolicy(fast_burn=1e9, burn_sustain_s=3600.0,
                              idle_occupancy=0.02, idle_sustain_s=0.3,
                              min_backends=1, max_backends=2,
                              cooldown_s=60.0)))
        try:
            sup.start()
            _wait(lambda: "drained" in _kinds(tmp_path),
                  msg="idle-driven drain")
            assert sup.pool.up_count() == 1  # min_backends floor
            assert "scale_down" in _kinds(tmp_path)
        finally:
            sup.close()


# ---------------------------------------------------------------------------
# satellite: fleet telemetry across the router hop
# ---------------------------------------------------------------------------


class TestFleetTelemetryMerge:
    def test_router_counters_merge_bit_equal(self, tmp_path):
        """The router registers with the fleet plane like any serve
        process: after a burst, the FleetCollector-merged
        ``serve.fleet.router.*`` counters are bit-equal to the router's
        live registry AND to the backend-observed request count — the
        pin that the merged view survives the router hop intact."""
        from mmlspark_tpu import obs
        from mmlspark_tpu.obs import fleet as obs_fleet
        from mmlspark_tpu.obs.metrics import (
            Counter, format_series, registry,
        )

        stub = _Stub()
        pool = BackendPool()
        pool.add(0, "127.0.0.1", stub.port)
        registry().reset()
        obs_fleet.enable(str(tmp_path), interval_s=0.1)
        router = FleetRouter(pool).start()
        try:
            for _ in range(5):
                status, _h, _b = _predict(router)
                assert status == 200
            expected = {
                format_series(m.name, m.labels): m.value
                for m in registry().iter_metrics()
                if isinstance(m, Counter)
                and m.name.startswith("serve.fleet.router.")}
            obs_fleet.disable()  # final exit snapshot
            view = obs_fleet.FleetCollector(
                str(tmp_path)).collect(include_ring=False)
            merged = {
                format_series(m.name, m.labels): m.value
                for m in view.registry.iter_metrics()
                if isinstance(m, Counter)
                and m.name.startswith("serve.fleet.router.")}
            assert merged == expected
            assert merged["serve.fleet.router.requests"] == 5.0
            assert merged["serve.fleet.router.relayed"] == 5.0
            assert stub.served == 5  # across the hop: nothing lost
        finally:
            router.close()
            stub.close()
            obs_fleet.disable()
            obs.disable()
            obs.clear()
            registry().reset()
            leaked = [t.name for t in threading.enumerate()
                      if t.name in ("FleetExporter",
                                    "TimeSeriesSampler")]
            assert not leaked, f"fleet threads leaked: {leaked}"

"""One-call parallel training across ALL mesh axes through the Trainer.

Round-4 verdict: dp/fsdp/tp had the reference's one-flag UX
(``parallelTrain=true`` → the launcher does the rest, reference:
cntk-train/src/main/scala/CommandBuilders.scala:79-93), but sp/pp/ep were
library-only — ``Trainer(mesh_spec={'pp': 2})`` silently replicated work.
These tests hold the round-5 fix to the standard that matters: a Trainer
on a dp×{sp,pp,ep} mesh trains with LOSS PARITY against the same model on
a dp-only mesh (parallelism is an execution detail, not a model change),
and a mesh axis nothing uses raises loudly instead of wasting devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.models.sequence import TransformerTagger
from mmlspark_tpu.models.vit import ViT
from mmlspark_tpu.models.zoo import ConvNetCifar
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.train.loop import TrainConfig, Trainer


def _losses(module, mesh_spec, x, y, steps_cfg=None):
    cfg = TrainConfig(batch_size=16, epochs=2, optimizer="adam",
                      learning_rate=3e-3, log_every=1, seed=0,
                      mesh_spec=mesh_spec, **(steps_cfg or {}))
    t = Trainer(module, cfg)
    t.fit_arrays(x, y)
    return np.asarray(t.history)


def test_unused_mesh_axis_raises():
    """An sp/pp/ep axis the module can't use must fail loudly, not
    silently replicate (round-4 verdict weakness 2)."""
    module = ConvNetCifar(num_classes=10, widths=(8, 16), dense_width=32)
    for axis in ("sp", "pp", "ep"):
        with pytest.raises(ValueError, match="silently replicate"):
            Trainer(module, TrainConfig(mesh_spec={"dp": 2, axis: 4}))


def test_unused_ep_on_dense_transformer_raises():
    """ep > 1 without moe_experts has nothing to dispatch — loud error."""
    module = TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                               num_layers=1, mlp_dim=32, num_tags=4,
                               max_len=16, dtype=jnp.float32)
    with pytest.raises(ValueError, match="silently replicate"):
        Trainer(module, TrainConfig(mesh_spec={"dp": 2, "ep": 4}))


def test_trainer_dp_pp_loss_parity():
    """ViT on dp×pp trains with the SAME losses as on dp-only — the
    pipelined encoder stack (mesh_hooks → pipeline_apply) is exact."""
    r = np.random.default_rng(0)
    x = r.normal(size=(48, 16, 16, 3)).astype(np.float32)
    y = r.integers(0, 4, size=48)

    def module():
        return ViT(num_classes=4, patch=8, dim=32, depth=4, heads=4,
                   mlp_dim=64, dtype=jnp.float32, pipeline_microbatches=4)

    ref = _losses(module(), {"dp": 2}, x, y)
    pp = _losses(module(), {"dp": 2, "pp": 4}, x, y)
    assert len(ref) == len(pp) > 2
    np.testing.assert_allclose(pp, ref, rtol=2e-4, atol=2e-5)


def test_trainer_dp_ep_loss_parity():
    """MoE TransformerTagger on dp×ep (expert-parallel all-to-all
    dispatch, auto-built moe_fn, expert params sharded over ep) matches
    dp-only dense routing when capacity is ample."""
    r = np.random.default_rng(1)
    toks = r.integers(1, 64, size=(48, 16)).astype(np.int32)
    tags = r.integers(0, 4, size=(48, 16)).astype(np.int64)

    def module():
        return TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                                 num_layers=1, mlp_dim=32, num_tags=4,
                                 max_len=16, moe_experts=4,
                                 moe_capacity_factor=8.0,
                                 pad_token_id=0, dtype=jnp.float32)

    ref = _losses(module(), {"dp": 2}, toks, tags)
    ep = _losses(module(), {"dp": 2, "ep": 4}, toks, tags)
    assert len(ref) == len(ep) > 2
    np.testing.assert_allclose(ep, ref, rtol=2e-4, atol=2e-5)
    # the expert stacks really shard over ep
    t = Trainer(module(), TrainConfig(batch_size=16,
                                      mesh_spec={"dp": 2, "ep": 4}))
    state = t.init_state((16,))
    spec = state["params"]["moe0_w_in"].sharding.spec
    assert "ep" in str(spec), spec


def test_trainer_dp_sp_loss_parity():
    """TransformerTagger on dp×sp (ring attention, auto-built
    attention_fn) matches dp-only local attention."""
    r = np.random.default_rng(2)
    toks = r.integers(1, 64, size=(48, 16)).astype(np.int32)
    tags = r.integers(0, 4, size=(48, 16)).astype(np.int64)

    def module():
        return TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                                 num_layers=1, mlp_dim=32, num_tags=4,
                                 max_len=16, pad_token_id=0,
                                 dtype=jnp.float32)

    ref = _losses(module(), {"dp": 2}, toks, tags)
    sp = _losses(module(), {"dp": 2, "sp": 4}, toks, tags)
    assert len(ref) == len(sp) > 2
    np.testing.assert_allclose(sp, ref, rtol=5e-4, atol=5e-5)


def test_vit_pp_checkpoint_layout_unchanged():
    """The pipelined path keeps the sequential block{i} param layout, so
    dp-trained checkpoints load into pp runs unchanged (and vice versa)."""
    module = ViT(num_classes=4, patch=8, dim=32, depth=4, heads=4,
                 mlp_dim=64, dtype=jnp.float32)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 16, 16, 3)))["params"]
    assert {f"block{i}" for i in range(4)} <= set(params.keys())
    mesh = make_mesh(MeshSpec(dp=2, pp=4))
    x = jnp.asarray(np.random.default_rng(0)
                    .normal(size=(16, 16, 16, 3)).astype(np.float32))
    seq = module.apply({"params": params}, x)
    pipe = module.apply({"params": params}, x, pipeline_mesh=mesh)
    np.testing.assert_allclose(np.asarray(pipe), np.asarray(seq),
                               rtol=1e-5, atol=1e-6)


def test_vit_pp_depth_divisibility_raises():
    module = ViT(num_classes=4, patch=8, dim=32, depth=2, heads=4,
                 mlp_dim=64, dtype=jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(module, TrainConfig(mesh_spec={"dp": 2, "pp": 4}))


def test_mesh_hooks_contract_across_model_families():
    """Protocol contract: every module implementing mesh_hooks must return
    {apply_kwargs: dict, param_rules: callable|None, handled: set} and
    claim only axes it was given reason to handle; modules without the
    method fall back to the dp/fsdp/tp-only baseline (loud error for the
    rest — covered above)."""
    from mmlspark_tpu.train.loop import resolve_mesh_hooks

    mesh = make_mesh(MeshSpec(dp=2, sp=2, ep=2))
    cases = [
        (TransformerTagger(vocab_size=32, embed_dim=8, num_heads=2,
                           num_layers=1, mlp_dim=16, num_tags=2,
                           max_len=8, moe_experts=2), {"sp", "ep"}),
        (TransformerTagger(vocab_size=32, embed_dim=8, num_heads=2,
                           num_layers=1, mlp_dim=16, num_tags=2,
                           max_len=8), {"sp"}),  # no experts -> no ep claim
        (ConvNetCifar(widths=(4, 8), dense_width=8), set()),
    ]
    for module, want in cases:
        hooks = resolve_mesh_hooks(module, mesh)
        assert set(hooks) == {"apply_kwargs", "param_rules", "handled"}
        assert isinstance(hooks["apply_kwargs"], dict)
        assert hooks["handled"] == want, (type(module).__name__, want)

    pp_mesh = make_mesh(MeshSpec(dp=2, pp=4))
    vit = ViT(num_classes=2, patch=8, dim=16, depth=4, heads=2, mlp_dim=32)
    hooks = resolve_mesh_hooks(vit, pp_mesh)
    assert hooks["handled"] == {"pp"}
    assert hooks["apply_kwargs"]["pipeline_mesh"] is pp_mesh


def test_jax_learner_stage_trains_on_pp_mesh():
    """The ESTIMATOR tier inherits the one-call mesh UX: JaxLearner (the
    CNTKLearner analog) with mesh_spec={'dp':2,'pp':4} and a ViT module
    trains pipeline-parallel through the stage API — the
    parallelTrain=true flag generalized (CommandBuilders.scala:79-93)."""
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.train.learner import JaxLearner

    r = np.random.default_rng(3)
    imgs = r.normal(size=(48, 16, 16, 3)).astype(np.float64)
    labels = r.integers(0, 4, size=48)
    table = DataTable({"vec": list(imgs.reshape(48, -1)), "label": labels})

    module = ViT(num_classes=4, patch=8, dim=32, depth=4, heads=4,
                 mlp_dim=64, dtype=jnp.float32, pipeline_microbatches=2)
    learner = JaxLearner(module=module, label_col="label", input_col="vec",
                         input_shape=(16, 16, 3), epochs=2, batch_size=16,
                         mesh_spec={"dp": 2, "pp": 4})
    fitted = learner.fit(table)
    assert fitted.final_loss is not None and np.isfinite(fitted.final_loss)
    scored = fitted.transform(table)
    assert "scored_labels" in scored.columns or "scores" in scored.columns

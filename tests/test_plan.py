"""Planner parity suite: fused pipeline execution must match the
stage-by-stage host path — bit-for-bit for integer/indexing ops and the
uint8 image assembly, to documented float tolerance where compiler
rewrites (fma, fusion) may legally perturb the last ulp:

* resize: device mirrors the native align-corners bilinear tap-for-tap;
  the +0.5 truncating round leaves at most ±1 count on knife-edge halves;
* model forwards / unroll affine: same math, compared at 1e-5.

Also covers the fallback rules: host stages interleaved with fused runs,
empty tables, tail padding, and ragged images (entry coercion declines →
host path, identical output).
"""

import numpy as np
import pytest

import jax

from mmlspark_tpu.core import plan
from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.schema import make_image
from mmlspark_tpu.core.stage import LambdaTransformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.stages.featurize import AssembleFeatures
from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage


def image_table(n=10, h=24, w=18, seed=0):
    r = np.random.default_rng(seed)
    return DataTable({"image": [
        make_image(f"p{k}", r.integers(0, 255, (h, w, 3)))
        for k in range(n)]})


def mlp_bundle(in_dim, out_dim=4, seed=0):
    module = MLP(features=(8,), num_outputs=out_dim)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, in_dim), np.float32))["params"]
    return ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(in_dim,),
        output_names=getattr(type(module), "OUTPUT_NAMES", ("logits",)))


def host_reference(stages, table):
    """The unfused stage-by-stage result."""
    for s in stages:
        table = s.transform(table)
    return table


def assert_images_equal(a_col, b_col, atol=0):
    for a, b in zip(a_col, b_col):
        diff = np.abs(a["data"].astype(int) - b["data"].astype(int)).max()
        assert diff <= atol, f"image diff {diff} > {atol}"
        assert a["path"] == b["path"]
        assert (a["height"], a["width"], a["channels"]) == \
               (b["height"], b["width"], b["channels"])


# ---- image pipelines ----

def test_crop_flip_unroll_bit_for_bit():
    table = image_table()
    stages = [ImageTransformer().crop(2, 3, 16, 12).flip(-1),
              UnrollImage(scale=1.0, offset=0.0)]
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    assert [(k, len(ss)) for k, ss in plan.describe_plan(stages, table)] \
        == [("device", 2)]
    assert_images_equal(fused["image"], ref["image"], atol=0)
    np.testing.assert_array_equal(np.stack(list(fused["features"])),
                                  np.stack(list(ref["features"])))


def test_resize_parity_within_one_count():
    table = image_table(h=29, w=23)
    stages = [ImageTransformer().resize(16, 12), UnrollImage()]
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    assert_images_equal(fused["image"], ref["image"], atol=1)
    f = np.stack(list(fused["features"]))
    r = np.stack(list(ref["features"]))
    assert np.abs(f - r).max() <= 1.0


def test_unroll_affine_and_rgb_swap_parity():
    table = image_table()
    stages = [ImageTransformer().flip(1),
              UnrollImage(scale=1 / 255.0, offset=-0.5, to_rgb=True)]
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    np.testing.assert_allclose(np.stack(list(fused["features"])),
                               np.stack(list(ref["features"])),
                               rtol=0, atol=1e-5)


def test_three_stage_image_pipeline_with_model_and_tail_padding():
    # 10 rows at minibatch 4 → two full minibatches + a padded tail
    table = image_table(n=10, h=12, w=10)
    afm = AssembleFeatures(columns_to_featurize=["image"],
                           allow_images=True,
                           features_col="features").fit(table)
    # dp=1 pins both paths to one device: minibatch stays 4 (no rounding
    # to the test mesh's 8 virtual devices) and parity is exact
    jm = JaxModel(model=mlp_bundle(2 + 12 * 10 * 3), input_col="features",
                  output_col="scores", minibatch_size=4,
                  mesh_spec={"dp": 1})
    stages = [ImageTransformer().flip(0), afm, jm]
    ref = host_reference(stages, table)
    pm = PipelineModel(stages)
    with plan.count_crossings() as c:
        fused = pm.transform(table)
    assert c.uploads == 3 and c.fetches == 3  # ceil(10/4) minibatches
    assert fused.columns == ref.columns
    assert_images_equal(fused["image"], ref["image"], atol=0)
    # image assembly is integer-exact in f32 → features bit-for-bit
    np.testing.assert_array_equal(np.stack(list(fused["features"])),
                                  np.stack(list(ref["features"])))
    np.testing.assert_allclose(np.stack(list(fused["scores"])),
                               np.stack(list(ref["scores"])),
                               rtol=0, atol=1e-5)
    assert fused.column_meta("features") == ref.column_meta("features")


# ---- vector pipelines ----

def test_chained_models_fuse_on_vector_column():
    r = np.random.default_rng(3)
    table = DataTable({"x": list(r.normal(size=(9, 6)).astype(np.float32))})
    jm1 = JaxModel(model=mlp_bundle(6, out_dim=5, seed=1), input_col="x",
                   output_col="h", minibatch_size=4)
    jm2 = JaxModel(model=mlp_bundle(5, out_dim=3, seed=2), input_col="h",
                   output_col="scores", minibatch_size=4)
    stages = [jm1, jm2]
    assert [(k, len(ss)) for k, ss in plan.describe_plan(stages, table)] \
        == [("device", 2)]
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    np.testing.assert_allclose(np.stack(list(fused["h"])),
                               np.stack(list(ref["h"])), rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.stack(list(fused["scores"])),
                               np.stack(list(ref["scores"])),
                               rtol=0, atol=1e-5)


# ---- mixed host/device, fallback, and edge cases ----

def test_mixed_host_device_pipeline():
    table = image_table(n=6)
    tag = LambdaTransformer(fn=lambda t: t.with_column(
        "tag", [1] * len(t)))
    renorm = LambdaTransformer(fn=lambda t: t.with_column(
        "features", [v * 2.0 for v in t["features"]]))
    stages = [tag, ImageTransformer().flip(1), UnrollImage(), renorm]
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    segs = [(k, len(ss)) for k, ss in plan.describe_plan(stages, table)]
    assert segs == [("host", 1), ("device", 2), ("host", 1)]
    assert fused.columns == ref.columns
    np.testing.assert_array_equal(np.stack(list(fused["features"])),
                                  np.stack(list(ref["features"])))
    np.testing.assert_array_equal(fused["tag"], ref["tag"])


def test_single_device_stage_keeps_its_own_path():
    # a lone device-capable stage must NOT go through segment fusion
    table = image_table(n=4)
    stages = [ImageTransformer().flip(1)]
    assert plan.describe_plan(stages, table)[0][0] == "host"
    out = PipelineModel(stages).transform(table)
    ref = stages[0].transform(table)
    assert_images_equal(out["image"], ref["image"], atol=0)


def test_empty_table_runs_host_path():
    table = DataTable({"image": []})
    stages = [ImageTransformer().flip(1), UnrollImage()]
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    assert len(fused) == 0
    assert fused.columns == ref.columns


def test_ragged_images_fall_back_to_host():
    r = np.random.default_rng(5)
    rows = [make_image(f"p{k}", r.integers(0, 255, (10 + k, 8, 3)))
            for k in range(5)]
    table = DataTable({"image": rows})
    stages = [ImageTransformer().flip(1), UnrollImage()]
    ref = host_reference(stages, table)
    with plan.count_crossings() as c:
        fused = PipelineModel(stages).transform(table)
    assert c.uploads == 0  # coercion declined → pure host execution
    for a, b in zip(fused["features"], ref["features"]):
        np.testing.assert_array_equal(a, b)


def test_unsupported_op_falls_back_to_host():
    table = image_table(n=4)
    stages = [ImageTransformer().blur(3, 3), UnrollImage()]
    segs = plan.describe_plan(stages, table)
    assert segs[0][0] == "host"  # blur has no device impl
    ref = host_reference(stages, table)
    fused = PipelineModel(stages).transform(table)
    np.testing.assert_array_equal(np.stack(list(fused["features"])),
                                  np.stack(list(ref["features"])))


def test_segment_cache_reused_and_invalidated():
    table = image_table(n=6)
    it = ImageTransformer().flip(1)
    stages = [it, UnrollImage()]
    pm = PipelineModel(stages)
    pm.transform(table)
    cache = pm.__dict__["_plan_cache"]
    assert len(cache) == 1
    entry_before = next(iter(cache.values()))
    pm.transform(table)
    assert next(iter(cache.values())) is entry_before  # cache hit
    # changing a stage's config invalidates via the cache token
    it.set(ops=list(it.ops) + [{"op": "flip", "flip_code": 0}])
    fused = pm.transform(table)
    assert next(iter(cache.values())) is not entry_before
    ref = host_reference(stages, table)
    np.testing.assert_array_equal(np.stack(list(fused["features"])),
                                  np.stack(list(ref["features"])))


def test_pipeline_model_survives_save_load_after_fusion(tmp_path):
    table = image_table(n=4)
    pm = PipelineModel([ImageTransformer().flip(1), UnrollImage()])
    before = pm.transform(table)  # populates the compiled-segment cache
    path = str(tmp_path / "pm")
    pm.save(path)
    loaded = PipelineModel.load(path)
    after = loaded.transform(table)
    np.testing.assert_array_equal(np.stack(list(before["features"])),
                                  np.stack(list(after["features"])))


# ---- bridge integration: fused pipeline behind the Arrow offload ----

def test_fused_pipeline_through_arrow_bridge():
    from mmlspark_tpu.bridge import ArrowBatchBridge
    from mmlspark_tpu.bridge.offload import stream_table

    table = image_table(n=24, h=12, w=10)
    afm = AssembleFeatures(columns_to_featurize=["image"],
                           allow_images=True,
                           features_col="features").fit(table)
    jm = JaxModel(model=mlp_bundle(2 + 12 * 10 * 3), input_col="features",
                  output_col="scores", minibatch_size=8)
    pm = PipelineModel([ImageTransformer().flip(1), afm, jm])
    ref = pm.transform(table)

    bridge = ArrowBatchBridge(pm, workers=2)
    chunks = [DataTable.from_arrow(rb)
              for rb in bridge.process(stream_table(table, 6))]
    got = chunks[0]
    for c in chunks[1:]:
        got = got.concat(c)
    assert len(got) == len(ref)
    np.testing.assert_allclose(np.stack(list(got["scores"])),
                               np.stack(list(ref["scores"])),
                               rtol=0, atol=1e-5)
    # the compiled segment was cached across chunks on the PipelineModel
    assert len(pm.__dict__["_plan_cache"]) == 1


# ---- the shared minibatch pipeline helper ----

def test_pipeline_minibatches_trims_padding_and_orders_outputs():
    import jax.numpy as jnp
    dev = jax.local_devices()[0]
    batch = np.arange(10, dtype=np.float32).reshape(10, 1)
    fn = jax.jit(lambda p, x: (x + p, x * 2))
    outs = plan.pipeline_minibatches(fn, jnp.float32(1.0), batch, 4, dev, 2)
    np.testing.assert_array_equal(outs[0], batch + 1)
    np.testing.assert_array_equal(outs[1], batch * 2)

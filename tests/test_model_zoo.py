"""Model-zoo content tests: real architectures (ResNet/ViT/BiLSTM), the
publish → download → featurize pretrained-model flow (reference:
ModelDownloader.scala:184-252 + ImageFeaturizer.scala:116-140), and
JaxModel.set_model_location (CNTKModel.scala:151-154 analog)."""

import os

import numpy as np
import pytest

from mmlspark_tpu.data.downloader import (
    ModelDownloader, Repository, load_bundle_file,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.image_featurizer import ImageFeaturizer
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import ZOO, get_model



def image_struct_table(n, hw=32, seed=0):
    r = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        data = r.integers(0, 255, size=(hw, hw, 3)).astype(np.uint8)
        rows.append({"path": f"img{i}.png", "height": hw, "width": hw,
                     "channels": 3, "data": data})
    t = DataTable({"image": rows})
    return t.with_meta("image", image=True)


class TestArchitectures:
    def test_zoo_has_real_model_families(self):
        for name in ("ResNet50", "ViT_B16", "BiLSTM_MedTag",
                     "ResNet_Small", "ViT_Tiny"):
            assert name in ZOO

    def test_resnet_small_forward_nodes(self):
        b = get_model("ResNet_Small", num_classes=7)
        x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)
                                            ).astype(np.float32)
        logits = b.module.apply({"params": b.params}, x)
        feats = b.module.apply({"params": b.params}, x, output="features")
        assert logits.shape == (2, 7)
        # thin ResNet (2,2) stages end at width*2*4 channels
        assert feats.shape == (2, 16 * 2 * 4)
        assert np.all(np.isfinite(np.asarray(logits, np.float32)))

    def test_resnet50_structure(self):
        # full-size init is heavy; just check the architecture builds its
        # tabulated parameter count in the ResNet-50 ballpark (~25M)
        import jax
        from mmlspark_tpu.models.resnet import resnet50
        m = resnet50(num_classes=1000)
        params = jax.eval_shape(
            lambda: m.init(jax.random.PRNGKey(0),
                           np.zeros((1, 224, 224, 3), np.float32)))
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(params))
        assert 20e6 < n < 30e6

    def test_vit_tiny_forward_nodes(self):
        b = get_model("ViT_Tiny", num_classes=5)
        x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)
                                            ).astype(np.float32)
        logits = b.module.apply({"params": b.params}, x)
        feats = b.module.apply({"params": b.params}, x, output="features")
        assert logits.shape == (2, 5) and feats.shape == (2, 64)

    def test_vit_bhtd_attention_matches_flax_bit_for_bit(self):
        """The TPU-layout attention (BhtdSelfAttention) must be a pure
        compute-layout change: identical param tree to flax's
        MultiHeadDotProductAttention and identical outputs on the SAME
        params — checkpoints stay interchangeable."""
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.models.vit import ViT
        kw = dict(num_classes=5, patch=8, dim=64, depth=2, heads=4,
                  mlp_dim=128, dtype=jnp.float32)
        m_flax = ViT(attn_impl="flax", **kw)
        m_bhtd = ViT(attn_impl="bhtd", **kw)
        x = np.random.default_rng(0).normal(size=(3, 32, 32, 3)
                                            ).astype(np.float32)
        p = m_flax.init(jax.random.PRNGKey(0), x[:1])["params"]
        p2 = m_bhtd.init(jax.random.PRNGKey(0), x[:1])["params"]
        assert jax.tree_util.tree_map(lambda a: a.shape, p) == \
            jax.tree_util.tree_map(lambda a: a.shape, p2)
        np.testing.assert_allclose(
            np.asarray(m_flax.apply({"params": p}, x)),
            np.asarray(m_bhtd.apply({"params": p}, x)),
            rtol=2e-5, atol=2e-5)

    def test_vit_b16_structure(self):
        import jax
        from mmlspark_tpu.models.vit import vit_b16
        m = vit_b16(num_classes=1000)
        params = jax.eval_shape(
            lambda: m.init(jax.random.PRNGKey(0),
                           np.zeros((1, 224, 224, 3), np.float32)))
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(params))
        assert 80e6 < n < 95e6  # B/16 (GAP head) ≈ 86M

    def test_bilstm_bundle_scores_tokens_through_jax_model(self):
        b = get_model("BiLSTM_MedTag", vocab_size=64, num_tags=4,
                      max_len=16, embed_dim=8, hidden=8)
        r = np.random.default_rng(0)
        toks = [r.integers(0, 64, 16).astype(np.int32) for _ in range(6)]
        t = DataTable({"tokens": toks})
        jm = JaxModel(input_col="tokens", output_col="tags",
                      minibatch_size=4)
        jm.set(model=b)
        out = jm.transform(t)
        tags = np.stack(list(out["tags"]))
        assert tags.shape == (6, 16, 4)


@pytest.fixture(scope="module")
def model_repo(tmp_path_factory):
    """Build the local pretrained repo once (the no-egress CDN analog)."""
    from mmlspark_tpu.tools import build_model_repo
    repo = str(tmp_path_factory.mktemp("model_repo"))
    entries = build_model_repo.build(repo, scale="small")
    return repo, {e.name: e for e in entries}


@pytest.mark.slow  # depends on the ~3-min model-repo build fixture
class TestPretrainedFlow:
    def test_manifest_lists_all_published(self, model_repo):
        repo, entries = model_repo
        names = {s.name for s in ModelDownloader(repo).list_models()}
        assert {"ConvNet_CIFAR10", "ResNet_Small", "ViT_Tiny",
                "BiLSTM_MedTag"} <= names

    def test_downloaded_model_is_genuinely_pretrained(self, model_repo):
        # the download-a-pretrained-model contract: scoring the REAL
        # held-out split (digits-rgb32, never seen in training) must
        # reproduce the held-out accuracy the publisher recorded in the
        # manifest — proves the weights are genuinely trained, and that
        # the manifest's eval claim is honest
        from mmlspark_tpu.tools import build_model_repo
        repo, _ = model_repo
        entry = next(e for e in ModelDownloader(repo).list_models()
                     if e.name == "ConvNet_CIFAR10")
        assert entry.eval_metric == "accuracy"
        assert entry.eval_value > 0.9, entry
        path = ModelDownloader(repo).download_by_name("ConvNet_CIFAR10")
        jm = JaxModel(input_col="image", output_col="scores",
                      minibatch_size=128).set_model_location(path)
        _, _, x, y = build_model_repo.digits_rgb32()
        t = DataTable({"image": list(x.reshape(len(x), -1))})
        scores = np.stack(list(jm.transform(t)["scores"]))
        acc = (scores.argmax(-1) == y).mean()
        assert acc > 0.9, f"accuracy {acc} — weights look untrained"
        assert abs(acc - entry.eval_value) < 0.02, (acc, entry.eval_value)

    def test_featurizer_from_repo_on_real_images(self, model_repo):
        repo, _ = model_repo
        t = image_struct_table(5, hw=48)  # featurizer resizes 48 -> 32
        feats = (ImageFeaturizer(output_col="feat")
                 .set_model_from_repo("ResNet_Small", repo=repo)
                 .transform(t))
        mat = np.stack(list(feats["feat"]))
        assert mat.shape == (5, 128)
        assert np.all(np.isfinite(mat))

    def test_featurizer_cut_layers_zero_keeps_head(self, model_repo):
        repo, _ = model_repo
        t = image_struct_table(3)
        out = (ImageFeaturizer(output_col="scores", cut_output_layers=0)
               .set_model_from_repo("ViT_Tiny", repo=repo)
               .transform(t))
        assert np.stack(list(out["scores"])).shape == (3, 10)

    def test_hash_verification_round_trip(self, model_repo):
        repo, entries = model_repo
        e = entries["ConvNet_CIFAR10"]
        assert len(e.hash) == 64 and e.size > 0
        # a corrupted cache entry is detected and refetched
        dl = ModelDownloader(repo)
        path = dl.download(e)
        with open(path, "wb") as f:
            f.write(b"corrupt")
        path2 = dl.download(e)
        bundle = load_bundle_file(path2)
        assert bundle.name == "ConvNet_CIFAR10"


class TestConcurrentDownload:
    """Two server workers loading the same model must not corrupt the
    cache: the fetch holds a per-entry file lock and publishes the
    verified file with an atomic rename (fast: manifests are built by
    hand, no model training)."""

    @staticmethod
    def _tiny_repo(tmp_path, payload=b"x" * 65536):
        import hashlib
        import json as _json

        from mmlspark_tpu.data.downloader import MANIFEST_NAME, ModelSchema
        repo = tmp_path / "repo"
        repo.mkdir()
        (repo / "tiny.model").write_bytes(payload)
        entry = ModelSchema(
            name="tiny", uri="tiny.model",
            hash=hashlib.sha256(payload).hexdigest(), size=len(payload))
        (repo / MANIFEST_NAME).write_text(
            _json.dumps([entry.to_json()]))
        return str(repo), entry, payload

    def test_two_threads_fetch_one_clean_cache_entry(self, tmp_path):
        import hashlib
        import threading

        repo, entry, payload = self._tiny_repo(tmp_path)
        cache = str(tmp_path / "cache")
        dl = ModelDownloader(repo, cache_dir=cache)
        paths, errors = [], []

        def fetch():
            try:
                paths.append(dl.download(entry))
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=fetch) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(set(paths)) == 1
        with open(paths[0], "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == entry.hash
        # no half-written temp files survive the race
        leftovers = [n for n in os.listdir(cache) if ".tmp-" in n]
        assert leftovers == [], leftovers

    def test_atomic_publication_never_exposes_partial_files(self,
                                                            tmp_path):
        # a reader polling the destination path during the fetch must only
        # ever see the complete, hash-verified payload
        import hashlib
        import threading

        repo, entry, payload = self._tiny_repo(tmp_path,
                                               payload=b"y" * (1 << 20))
        cache = str(tmp_path / "cache")
        dl = ModelDownloader(repo, cache_dir=cache)
        dest = dl._cache_path(entry)
        seen, stop = [], threading.Event()

        def watch():
            while not stop.is_set():
                if os.path.exists(dest):
                    with open(dest, "rb") as f:
                        seen.append(len(f.read()))

        t = threading.Thread(target=watch)
        t.start()
        try:
            dl.download(entry)
        finally:
            stop.set()
            t.join(timeout=10)
        assert all(n == len(payload) for n in seen), (
            f"observed partial cache entries of sizes "
            f"{sorted(set(n for n in seen if n != len(payload)))}")


class TestFetchRetry:
    """Round-11 satellite: transient fetch faults during a model pull
    retry with jittered exponential backoff (typed RetryPolicy) and bump
    the ``data.fetch_retries`` counter, instead of aborting a supervised
    run; non-transient failures and exhausted budgets still propagate."""

    class _FlakyRepo(Repository):
        """Repository whose fetch drops the connection (``fail_times``)
        or silently delivers corrupted bytes (``corrupt_times``)."""

        def __init__(self, root, fail_times=0, exc=ConnectionResetError,
                     corrupt_times=0):
            super().__init__(root)
            self.fail_times = fail_times
            self.exc = exc
            self.corrupt_times = corrupt_times
            self.attempts = 0

        def fetch(self, schema, dest):
            self.attempts += 1
            if self.attempts <= self.corrupt_times:
                # the fault that does NOT raise: a short/garbled read
                # that still completes — only the hash check can see it
                with open(dest, "wb") as f:
                    f.write(b"garbled")
                return dest
            if self.attempts - self.corrupt_times <= self.fail_times:
                # half-written partial before the fault: the retry must
                # truncate it, never serve or append to it
                with open(dest, "wb") as f:
                    f.write(b"partial")
                raise self.exc("link dropped")
            return super().fetch(schema, dest)

    def _flaky_downloader(self, tmp_path, fail_times, retry="fast",
                          exc=ConnectionResetError):
        from mmlspark_tpu.core.retry import RetryPolicy
        repo, entry, _ = TestConcurrentDownload._tiny_repo(tmp_path)
        flaky = self._FlakyRepo(repo, fail_times, exc=exc)
        if retry == "fast":
            retry = RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                jitter=0.0)
        dl = ModelDownloader(flaky, cache_dir=str(tmp_path / "cache"),
                             retry=retry)
        return dl, flaky, entry

    def test_transient_faults_retried_to_success(self, tmp_path):
        import hashlib
        dl, flaky, entry = self._flaky_downloader(tmp_path, fail_times=2)
        path = dl.download(entry)
        assert flaky.attempts == 3
        with open(path, "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == entry.hash

    def test_retry_counter_recorded_when_obs_enabled(self, tmp_path):
        from mmlspark_tpu import obs
        dl, flaky, entry = self._flaky_downloader(tmp_path, fail_times=2)
        obs.disable()
        obs.clear()
        obs.registry().reset()
        obs.enable()
        try:
            dl.download(entry)
            assert obs.registry().value("data.fetch_retries",
                                        model="tiny") == 2
        finally:
            obs.disable()
            obs.clear()
            obs.registry().reset()

    def test_budget_exhausted_raises_real_error(self, tmp_path):
        dl, flaky, entry = self._flaky_downloader(tmp_path, fail_times=5)
        with pytest.raises(ConnectionResetError, match="link dropped"):
            dl.download(entry)
        assert flaky.attempts == 3  # max_attempts, not unbounded
        # the failed pull never publishes a cache entry
        assert not os.path.exists(dl._cache_path(entry))

    def test_non_transient_error_not_retried(self, tmp_path):
        dl, flaky, entry = self._flaky_downloader(
            tmp_path, fail_times=5, exc=ValueError)
        with pytest.raises(ValueError):
            dl.download(entry)
        assert flaky.attempts == 1

    def test_corrupted_bytes_spend_the_same_retry_budget(self, tmp_path):
        """A fault that corrupts bytes WITHOUT raising (garbled read
        that completes) surfaces as the sha256-mismatch IOError inside
        the retried callable — it must refetch like a dropped
        connection, not abort the run with the budget unspent."""
        import hashlib

        from mmlspark_tpu.core.retry import RetryPolicy
        repo, entry, _ = TestConcurrentDownload._tiny_repo(tmp_path)
        flaky = self._FlakyRepo(repo, corrupt_times=1)
        dl = ModelDownloader(flaky, cache_dir=str(tmp_path / "cache"),
                             retry=RetryPolicy(max_attempts=3,
                                               base_delay_s=0.0,
                                               jitter=0.0))
        path = dl.download(entry)
        assert flaky.attempts == 2
        with open(path, "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == entry.hash

    def test_retry_none_disables(self, tmp_path):
        dl, flaky, entry = self._flaky_downloader(tmp_path, fail_times=1,
                                                  retry=None)
        with pytest.raises(ConnectionResetError):
            dl.download(entry)
        assert flaky.attempts == 1

    def test_http_permanent_4xx_not_retried_5xx_is(self, tmp_path):
        """A 404/403 is a permanent answer — retrying only delays the
        real error; a 5xx may recover and retries under the default
        policy's predicate."""
        import urllib.error

        from mmlspark_tpu.data.downloader import DEFAULT_FETCH_RETRY

        def http_err(code):
            # a factory so _FlakyRepo can raise fresh instances
            return lambda msg: urllib.error.HTTPError(
                "http://repo/tiny.model", code, msg, None, None)

        fast = DEFAULT_FETCH_RETRY.__class__(
            max_attempts=3, base_delay_s=0.0, jitter=0.0,
            retry_on=DEFAULT_FETCH_RETRY.retry_on,
            retry_if=DEFAULT_FETCH_RETRY.retry_if)
        for code, expected_attempts in ((404, 1), (503, 3)):
            sub = tmp_path / f"http_{code}"
            sub.mkdir()
            dl, flaky, entry = self._flaky_downloader(
                sub, fail_times=9, retry=fast, exc=http_err(code))
            with pytest.raises(urllib.error.HTTPError):
                dl.download(entry)
            # 404 is permanent (no retries burned); 503 spends the budget
            assert flaky.attempts == expected_attempts, (code,
                                                         flaky.attempts)


@pytest.mark.slow  # 224-scale full-size bundles
class TestFullScaleBundles:
    def test_resnet50_publish_download_featurize_224(self, tmp_path):
        """VERDICT r2 weak item 7: the FULL-architecture flow — publish a
        real ResNet-50 bundle, download through the hash-verified cache,
        and featurize genuine 224×224 images through ImageFeaturizer (the
        pipeline resizes 256→224)."""
        from mmlspark_tpu.data.downloader import publish_model

        bundle = get_model("ResNet50", num_classes=1000, input_size=224)
        repo = str(tmp_path / "full_repo")
        entry = publish_model(bundle, repo)
        assert entry.size > 50 * 2 ** 20  # a real 25M-param artifact

        t = image_struct_table(2, hw=256)
        feats = (ImageFeaturizer(output_col="feat", minibatch_size=2)
                 .set_model_from_repo("ResNet50", repo=repo,
                                      cache_dir=str(tmp_path / "cache"))
                 .transform(t))
        mat = np.stack(list(feats["feat"]))
        assert mat.shape == (2, 2048)  # the 2048-d ResNet-50 embedding
        assert np.all(np.isfinite(mat))


    def test_resnet50_infer_folded_publish_download_featurize_224(
            self, tmp_path):
        """The serving-form flow at full architecture scale: the FOLDED
        frozen-BN ResNet-50 (bf16, s2d stem — the variant the bench
        featurizes with) publishes, downloads hash-verified, and
        featurizes 224² images; its embedding matches the same params run
        before download (the fold+bundle round trip is lossless)."""
        from mmlspark_tpu.data.downloader import publish_model

        bundle = get_model("ResNet50_Infer", num_classes=1000,
                           input_size=224)
        repo = str(tmp_path / "full_repo")
        entry = publish_model(bundle, repo)
        assert entry.size > 25 * 2 ** 20  # bf16 folded 25M-param artifact

        t = image_struct_table(2, hw=224)
        direct = np.stack(list(
            ImageFeaturizer(output_col="feat", minibatch_size=2)
            .set(model=bundle).transform(t)["feat"]))
        feats = (ImageFeaturizer(output_col="feat", minibatch_size=2)
                 .set_model_from_repo("ResNet50_Infer", repo=repo,
                                      cache_dir=str(tmp_path / "cache"))
                 .transform(t))
        mat = np.stack(list(feats["feat"]))
        assert mat.shape == (2, 2048) and np.all(np.isfinite(mat))
        np.testing.assert_allclose(mat, direct, rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # depends on the ~3-min model-repo build fixture
class TestHttpRepository:
    """The remote-manifest transport path (reference: the Azure-CDN
    DefaultModelRepo, ModelDownloader.scala:109-155, default URL :184-186).
    The same repository directory the local tests use is served over a
    real HTTP endpoint; manifest read, sha256 verification, and hash-dedup
    transfer must all flow through the http:// code path."""

    @pytest.fixture()
    def http_repo(self, model_repo):
        import http.server
        import threading

        repo_dir, entries = model_repo
        hits: list[str] = []

        class Handler(http.server.SimpleHTTPRequestHandler):
            def __init__(self, *a, **kw):
                super().__init__(*a, directory=repo_dir, **kw)

            def log_message(self, *a):  # keep pytest output clean
                hits.append(self.path)

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            yield f"http://127.0.0.1:{srv.server_address[1]}", entries, hits
        finally:
            srv.shutdown()

    def test_manifest_and_verified_download_over_http(self, http_repo,
                                                      tmp_path):
        url, entries, hits = http_repo
        dl = ModelDownloader(url, cache_dir=str(tmp_path / "cache"))
        names = {s.name for s in dl.list_models()}
        assert "ConvNet_CIFAR10" in names
        path = dl.download_by_name("ConvNet_CIFAR10")
        bundle = load_bundle_file(path)
        assert bundle.name == "ConvNet_CIFAR10"
        # the bytes really crossed HTTP
        assert any(p.endswith("MANIFEST.json") for p in hits)
        assert any(p.endswith("ConvNet_CIFAR10.model") for p in hits)

    def test_hash_dedup_skips_refetch_over_http(self, http_repo, tmp_path):
        """Second download of a cached, hash-verified model must not
        re-transfer the artifact (repoTransfer dedup,
        ModelDownloader.scala:164-181)."""
        url, entries, hits = http_repo
        dl = ModelDownloader(url, cache_dir=str(tmp_path / "cache"))
        dl.download_by_name("ResNet_Small")
        model_fetches = [p for p in hits if p.endswith("ResNet_Small.model")]
        assert len(model_fetches) == 1
        dl.download_by_name("ResNet_Small")  # cache hit: manifest only
        model_fetches = [p for p in hits if p.endswith("ResNet_Small.model")]
        assert len(model_fetches) == 1

    def test_corrupted_transfer_rejected_over_http(self, http_repo,
                                                   tmp_path):
        url, entries, hits = http_repo
        dl = ModelDownloader(url, cache_dir=str(tmp_path / "cache"))
        schemas = {s.name: s for s in dl.list_models()}
        bad = schemas["ViT_Tiny"]
        bad.hash = "0" * 64  # tampered manifest: mismatch must be fatal
        with pytest.raises(IOError, match="sha256 mismatch"):
            dl.download(bad)
        assert not os.path.exists(dl._cache_path(bad))

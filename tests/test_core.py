"""Core framework tests: params, DataTable, stages, pipeline, persistence,
schema metadata protocol."""

import numpy as np
import pytest

from mmlspark_tpu.core.params import Param, ParamValidationError, Params
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.core import schema as S
from mmlspark_tpu.core.stage import (
    Estimator, PipelineStage, STAGE_REGISTRY, Transformer, UnaryTransformer,
)
from mmlspark_tpu.data.table import DataTable


class AddConst(UnaryTransformer):
    amount = Param(default=1.0, doc="value added to input", type_=float)

    def _transform_column(self, values, table):
        return values.astype(np.float64) + self.amount


class MeanCenter(Estimator):
    input_col = Param(default="input", doc="column to center", type_=str)
    output_col = Param(default="centered", doc="output column", type_=str)

    def fit(self, table):
        mu = float(np.mean(table[self.input_col]))
        return MeanCenterModel(input_col=self.input_col,
                               output_col=self.output_col, mean=mu)


class MeanCenterModel(Transformer):
    input_col = Param(default="input", doc="column to center", type_=str)
    output_col = Param(default="centered", doc="output column", type_=str)
    mean = Param(default=0.0, doc="fitted mean", type_=float)

    def transform(self, table):
        return table.with_column(
            self.output_col, table[self.input_col] - self.mean)


# ---- params ----

def test_param_defaults_and_set():
    t = AddConst()
    assert t.amount == 1.0
    t.set(amount=2.5)
    assert t.amount == 2.5
    t.amount = 3.0  # descriptor set
    assert t.amount == 3.0


def test_param_validation_type():
    with pytest.raises(ParamValidationError):
        AddConst(amount="nope")


def test_param_validation_domain():
    class P(Params):
        k = Param(default=1, type_=int, validator=Param.gt(0))
    with pytest.raises(ParamValidationError):
        P(k=0)
    assert P(k=5).k == 5


def test_unknown_param_rejected():
    with pytest.raises(KeyError):
        AddConst(bogus=1)


def test_params_introspection():
    ps = AddConst.params()
    assert {"amount", "input_col", "output_col"} <= set(ps)
    doc = AddConst().explain_params()
    assert "value added to input" in doc


def test_copy_with_override():
    a = AddConst(amount=2.0)
    b = a.copy(amount=5.0)
    assert a.amount == 2.0 and b.amount == 5.0


# ---- DataTable ----

def test_table_basic_ops():
    t = DataTable({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert len(t) == 3
    assert t.columns == ["a", "b"]
    t2 = t.with_column("c", np.arange(3.0))
    assert "c" in t2 and "c" not in t
    assert t2.select("a", "c").columns == ["a", "c"]
    assert t2.drop("a").columns == ["b", "c"]
    assert len(t.head(2)) == 2
    assert t.take([2, 0])["a"].tolist() == [3, 1]
    assert len(t.filter(lambda r: r["a"] > 1)) == 2


def test_from_rows_takes_union_of_row_keys():
    # keys absent from the FIRST row must not be dropped (regression:
    # from_rows used to take the schema from rows[0] alone)
    rows = [{"a": 1}, {"a": 2, "b": "x"}, {"c": 3.0}]
    t = DataTable.from_rows(rows)
    assert t.columns == ["a", "b", "c"]
    assert t["a"].tolist() == [1, 2, None]
    assert t["b"].tolist() == [None, "x", None]
    assert t["c"].tolist() == [None, None, 3.0]
    assert len(t) == 3


def test_table_mismatched_lengths():
    with pytest.raises(ValueError):
        DataTable({"a": [1, 2], "b": [1]})


def test_table_concat_and_partitions():
    t = DataTable({"a": np.arange(10)})
    both = t.concat(t)
    assert len(both) == 20
    parts = both.partitions(4)
    assert sum(len(p) for p in parts) == 20
    assert len(parts) == 4


def test_table_pandas_arrow_roundtrip():
    t = DataTable({"a": np.arange(5.0), "s": ["a", "b", "c", "d", "e"]})
    df = t.to_pandas()
    t2 = DataTable.from_pandas(df)
    np.testing.assert_allclose(t2["a"], t["a"])
    assert list(t2["s"]) == list(t["s"])
    arrow = t.to_arrow()
    t3 = DataTable.from_arrow(arrow)
    np.testing.assert_allclose(t3["a"], t["a"])


def test_column_matrix_vectors():
    t = DataTable({"v": [np.ones(4), np.zeros(4), np.full(4, 2.0)]})
    m = t.column_matrix("v")
    assert m.shape == (3, 4) and m.dtype == np.float32


def test_table_meta_carried():
    t = DataTable({"a": [1, 2]}).with_meta("a", role="label")
    assert t.column_meta("a")["role"] == "label"
    assert t.select("a").column_meta("a")["role"] == "label"


# ---- stages & pipeline ----

def test_unary_transformer():
    t = DataTable({"input": np.arange(3.0)})
    out = AddConst(amount=10.0).transform(t)
    np.testing.assert_allclose(out["output"], [10, 11, 12])


def test_pipeline_fit_transform():
    t = DataTable({"input": np.array([1.0, 2.0, 3.0])})
    pipe = Pipeline([
        AddConst(amount=1.0),
        MeanCenter(input_col="output", output_col="centered"),
    ])
    model = pipe.fit(t)
    assert isinstance(model, PipelineModel)
    out = model.transform(t)
    np.testing.assert_allclose(out["centered"], [-1, 0, 1])


def test_stage_registry_contains_classes():
    names = {cls.__name__ for cls in STAGE_REGISTRY.values()}
    assert {"Pipeline", "PipelineModel", "AddConst"} <= names


# ---- persistence round-trips (RoundTripTestBase analog) ----

def test_stage_save_load(tmp_path):
    a = AddConst(amount=7.0, input_col="x", output_col="y")
    p = str(tmp_path / "addconst")
    a.save(p)
    b = PipelineStage.load(p)
    assert isinstance(b, AddConst)
    assert b.amount == 7.0 and b.input_col == "x"


def test_fitted_pipeline_save_load(tmp_path):
    t = DataTable({"input": np.array([1.0, 2.0, 3.0])})
    model = Pipeline([
        AddConst(amount=1.0),
        MeanCenter(input_col="output", output_col="centered"),
    ]).fit(t)
    p = str(tmp_path / "pipe")
    model.save(p)
    loaded = PipelineStage.load(p)
    out1 = model.transform(t)
    out2 = loaded.transform(t)
    np.testing.assert_allclose(out1["centered"], out2["centered"])


def test_pipeline_unfitted_save_load(tmp_path):
    pipe = Pipeline([AddConst(amount=2.0)])
    p = str(tmp_path / "unfitted")
    pipe.save(p)
    loaded = PipelineStage.load(p)
    t = DataTable({"input": np.arange(3.0)})
    out = loaded.fit(t).transform(t)
    np.testing.assert_allclose(out["output"], [2, 3, 4])


# ---- schema metadata protocol ----

def test_score_column_protocol():
    t = DataTable({"scores": np.zeros(3), "other": np.ones(3)})
    t = S.set_score_column(t, "model_1", "scores",
                           S.SchemaConstants.SCORES_COLUMN,
                           S.SchemaConstants.CLASSIFICATION_KIND)
    assert S.find_score_column(t, S.SchemaConstants.SCORES_COLUMN) == "scores"
    assert S.get_score_value_kind(t, "scores") == \
        S.SchemaConstants.CLASSIFICATION_KIND


def test_categorical_levels_roundtrip():
    t = DataTable({"c": np.array([0, 1, 2])})
    t = S.set_categorical_levels(t, "c", ["a", "b", "c"])
    assert S.is_categorical(t, "c")
    assert S.get_categorical_levels(t, "c") == ["a", "b", "c"]


def test_image_helpers():
    img = S.make_image("p.png", np.zeros((4, 6, 3), dtype=np.uint8))
    assert img["height"] == 4 and img["width"] == 6 and img["channels"] == 3
    t = DataTable({"image": [img, img]})
    assert S.is_image_column(t, "image")


def test_find_unused_column_name():
    t = DataTable({"x": [1], "x_1": [2]})
    assert S.find_unused_column_name(t, "x") == "x_2"


def test_profiler_trace_writes_events(tmp_path):
    """utils/profiling.trace captures a real device trace (SURVEY §5
    tracing: profiler hooks beyond the Timer stage's wall clocks)."""
    import glob
    import os

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.utils.profiling import annotate, trace

    d = str(tmp_path / "prof")
    with trace(d):
        with annotate("tiny-matmul"):
            a = jnp.ones((64, 64))
            float(jnp.sum(jax.jit(lambda m: m @ m)(a)))
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(f.endswith((".pb", ".json.gz", ".xplane.pb"))
               or "trace" in os.path.basename(f) for f in files), files


def test_log_level_config_change_applies_to_existing_loggers():
    """Regression: get_logger snapshotted the log level at first call, so
    a later ``config.set("log_level", ...)`` silently did nothing for
    already-created loggers (every module-level ``_log``)."""
    import logging

    from mmlspark_tpu.core import config
    from mmlspark_tpu.core.logging_utils import get_logger

    logger = get_logger("mmlspark_tpu.test_loglevel_regression")
    assert logger.level == logging.INFO
    try:
        config.set("log_level", "DEBUG")
        assert logger.level == logging.DEBUG, (
            "config.set('log_level') must re-level existing loggers")
        # a logger created AFTER the change picks the level up directly
        late = get_logger("mmlspark_tpu.test_loglevel_regression2")
        assert late.level == logging.DEBUG
    finally:
        config.reset("log_level")
    assert logger.level == logging.INFO  # reset notifies too

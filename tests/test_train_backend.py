"""Tests for the distributed training backend (SURVEY §2.5/§7.5 parity):
mesh-sharded training, fsdp parameter sharding, checkpoint/resume, and the
JaxLearner estimator (CNTKLearner analog — the ValidateCntkTrain mirror,
run on the virtual 8-device CPU mesh like all 'distributed' reference tests
run on local[*])."""

import os

import numpy as np
import pytest

import jax

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.parallel.mesh import (
    MeshSpec, make_mesh, param_shardings,
)
from mmlspark_tpu.train import (
    JaxLearner, TrainCheckpointer, TrainConfig, Trainer,
)


def xor_data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 8)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    return x, y


class TestMeshTraining:
    def test_dp_mesh_trains(self):
        x, y = xor_data()
        mesh = make_mesh(MeshSpec(dp=-1))
        cfg = TrainConfig(batch_size=64, epochs=30, learning_rate=5e-3)
        tr = Trainer(MLP(features=(32,), num_outputs=2), cfg, mesh=mesh)
        tr.fit_arrays(x, y)
        assert tr.history[0] > tr.history[-1]
        assert np.isfinite(tr.history[-1])

    def test_fsdp_params_actually_sharded(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4))
        x, y = xor_data(128)
        cfg = TrainConfig(batch_size=32, epochs=2)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
        tr.fit_arrays(x, y)
        # at least one param leaf must be sharded over fsdp
        leaves = jax.tree_util.tree_leaves(tr.params)
        assert any(
            "fsdp" in str(l.sharding.spec) for l in leaves
            if hasattr(l, "sharding")), \
            [str(l.sharding) for l in leaves]
        assert np.isfinite(tr.history[-1])

    def test_fsdp_matches_dp_numerics(self):
        # same data+seed on dp-only vs dp×fsdp meshes → same loss trajectory
        x, y = xor_data(128)
        losses = {}
        for name, spec in [("dp", MeshSpec(dp=-1)),
                           ("fsdp", MeshSpec(dp=2, fsdp=4))]:
            cfg = TrainConfig(batch_size=64, epochs=3, log_every=1, seed=7)
            tr = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                         mesh=make_mesh(spec))
            tr.fit_arrays(x, y)
            losses[name] = tr.history
        np.testing.assert_allclose(losses["dp"], losses["fsdp"],
                                   rtol=1e-4, atol=1e-5)

    def test_param_shardings_rule(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4))
        params = {"w": np.zeros((8, 3)), "b": np.zeros((3,)),
                  "scalar": np.zeros(())}
        sh = param_shardings(mesh, params)
        assert "fsdp" in str(sh["w"].spec)      # 8 % 4 == 0 → sharded
        assert str(sh["b"].spec) == "PartitionSpec()"   # 3 % 4 != 0
        assert str(sh["scalar"].spec) == "PartitionSpec()"


class TestCheckpointResume:
    def test_save_restore_roundtrip(self, tmp_path):
        ck = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
        state = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
                 "step": np.asarray(5, dtype=np.int32)}
        ck.save(state)
        assert ck.steps() == [5]
        restored = ck.restore()
        np.testing.assert_allclose(restored["params"]["w"],
                                   state["params"]["w"])
        assert int(restored["step"]) == 5

    def test_max_to_keep(self, tmp_path):
        ck = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
        for s in (1, 2, 3):
            ck.save({"x": np.zeros(2)}, step=s)
        assert ck.steps() == [2, 3]

    def test_trainer_resume_continues_from_step(self, tmp_path):
        x, y = xor_data(128)
        ckdir = str(tmp_path / "run")
        cfg = TrainConfig(batch_size=32, epochs=2, checkpoint_dir=ckdir,
                          seed=3)
        tr1 = Trainer(MLP(features=(16,), num_outputs=2), cfg)
        tr1.fit_arrays(x, y)
        saved_step = int(np.asarray(tr1.state["step"]))
        assert saved_step == 2 * (128 // 32)

        # a fresh trainer with the same config resumes instead of restarting
        tr2 = Trainer(MLP(features=(16,), num_outputs=2), cfg)
        tr2.state = tr2.init_state(x.shape[1:])
        resumed = tr2.maybe_restore()
        assert resumed == saved_step
        np.testing.assert_allclose(
            np.asarray(tr2.state["params"]["dense0"]["kernel"]),
            np.asarray(tr1.state["params"]["dense0"]["kernel"]),
            rtol=1e-6)

    def test_resume_completes_remainder_not_double(self, tmp_path):
        # a completed run re-executed with the same checkpoint_dir must NOT
        # train the configured schedule again on top of the restored state
        x, y = xor_data(128)
        ckdir = str(tmp_path / "run")
        cfg = TrainConfig(batch_size=32, epochs=2, checkpoint_dir=ckdir,
                          seed=3)
        tr1 = Trainer(MLP(features=(16,), num_outputs=2), cfg)
        tr1.fit_arrays(x, y)
        done = int(np.asarray(tr1.state["step"]))

        tr2 = Trainer(MLP(features=(16,), num_outputs=2), cfg)
        tr2.fit_arrays(x, y)
        assert int(np.asarray(tr2.state["step"])) == done
        np.testing.assert_allclose(
            np.asarray(tr2.state["params"]["dense0"]["kernel"]),
            np.asarray(tr1.state["params"]["dense0"]["kernel"]), rtol=1e-6)

    def test_resume_schedule_mismatch_raises(self, tmp_path):
        # resuming with a changed batch size would silently replay the wrong
        # batches; the recorded schedule fingerprint must catch it
        x, y = xor_data(128)
        ckdir = str(tmp_path / "run")
        cfg = TrainConfig(batch_size=32, epochs=2, checkpoint_dir=ckdir,
                          seed=3)
        Trainer(MLP(features=(16,), num_outputs=2), cfg).fit_arrays(x, y)

        cfg2 = TrainConfig(batch_size=64, epochs=2, checkpoint_dir=ckdir,
                           seed=3)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg2)
        with pytest.raises(ValueError, match="fingerprint mismatch"):
            tr.fit_arrays(x, y)

    def test_resume_false_ignores_checkpoints(self, tmp_path):
        x, y = xor_data(64)
        ckdir = str(tmp_path / "run")
        cfg = TrainConfig(batch_size=32, epochs=1, checkpoint_dir=ckdir)
        Trainer(MLP(features=(8,), num_outputs=2), cfg).fit_arrays(x, y)
        cfg2 = TrainConfig(batch_size=32, epochs=1, checkpoint_dir=ckdir,
                           resume=False)
        tr = Trainer(MLP(features=(8,), num_outputs=2), cfg2)
        tr.state = tr.init_state(x.shape[1:])
        assert tr.maybe_restore() is None


class TestCheckpointIntegrity:
    """Round-11 hardening: torn/corrupt step dirs are detected by the
    per-step digest and fall back to the previous manifest step; GC is
    crash-safe (manifest rewritten BEFORE deletes)."""

    @staticmethod
    def _truncate_largest_leaf(step_dir):
        import glob as _glob
        files = [p for p in _glob.glob(os.path.join(step_dir, "**"),
                                       recursive=True) if os.path.isfile(p)]
        victim = max(files, key=os.path.getsize)
        with open(victim, "r+b") as f:
            f.truncate(max(os.path.getsize(victim) // 2, 1))
        return victim

    def _two_step_ckpt(self, tmp_path):
        from mmlspark_tpu.train.checkpoint import TrainCheckpointer
        ck = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=3)
        for s in (1, 2):
            ck.save({"w": np.full((64,), float(s), np.float32),
                     "step": np.asarray(s, np.int32)}, step=s)
        return ck

    def test_truncated_leaf_falls_back_to_previous_step(self, tmp_path):
        ck = self._two_step_ckpt(tmp_path)
        self._truncate_largest_leaf(os.path.join(ck.directory, "step_2"))
        assert ck.verify_step(2) is not None
        assert ck.verify_step(1) is None
        restored = ck.restore()  # recovery path: digest-validated
        assert int(np.asarray(restored["step"])) == 1

    def test_explicit_corrupt_step_raises_typed(self, tmp_path):
        from mmlspark_tpu.train.checkpoint import CheckpointCorruptError
        ck = self._two_step_ckpt(tmp_path)
        self._truncate_largest_leaf(os.path.join(ck.directory, "step_2"))
        with pytest.raises(CheckpointCorruptError, match="digest"):
            ck.restore(step=2)

    def test_all_steps_corrupt_raises_typed(self, tmp_path):
        from mmlspark_tpu.train.checkpoint import CheckpointCorruptError
        ck = self._two_step_ckpt(tmp_path)
        for s in (1, 2):
            self._truncate_largest_leaf(
                os.path.join(ck.directory, f"step_{s}"))
        with pytest.raises(CheckpointCorruptError, match="every manifest"):
            ck.restore()

    def test_missing_step_dir_falls_back(self, tmp_path):
        import shutil as _shutil
        ck = self._two_step_ckpt(tmp_path)
        _shutil.rmtree(os.path.join(ck.directory, "step_2"))
        restored = ck.restore()
        assert int(np.asarray(restored["step"])) == 1

    def test_corruption_records_event_and_counter(self, tmp_path):
        from mmlspark_tpu import obs
        ck = self._two_step_ckpt(tmp_path)
        self._truncate_largest_leaf(os.path.join(ck.directory, "step_2"))
        obs.disable()
        obs.clear()
        obs.registry().reset()
        obs.enable()
        try:
            ck.restore()
            assert obs.registry().value("train.checkpoint_corrupt") == 1
            names = {getattr(r, "name", "") for r in obs.captured()}
            assert "train/checkpoint_corrupt" in names
        finally:
            obs.disable()
            obs.clear()
            obs.registry().reset()

    def test_gc_crash_between_manifest_and_delete_is_restorable(
            self, tmp_path, monkeypatch):
        """max_to_keep pruning interrupted between manifest rewrite and
        directory delete must leave a restorable manifest (the manifest
        commits FIRST; orphan dirs are swept by the next save)."""
        import shutil as _shutil

        from mmlspark_tpu.train import checkpoint as ckpt_mod
        ck = ckpt_mod.TrainCheckpointer(str(tmp_path / "ck"),
                                        max_to_keep=2)
        for s in (1, 2):
            ck.save({"w": np.full((8,), float(s), np.float32),
                     "step": np.asarray(s, np.int32)}, step=s)

        real_rmtree = _shutil.rmtree

        def crash_on_prune(path, *a, **kw):
            if os.path.basename(path) == "step_1":
                raise RuntimeError("induced crash mid-GC")
            return real_rmtree(path, *a, **kw)

        monkeypatch.setattr(ckpt_mod.shutil, "rmtree", crash_on_prune)
        with pytest.raises(RuntimeError, match="mid-GC"):
            ck.save({"w": np.full((8,), 3.0, np.float32),
                     "step": np.asarray(3, np.int32)}, step=3)
        monkeypatch.undo()

        # the manifest never points at the dropped step, and the latest
        # checkpoint restores
        assert ck.steps() == [2, 3]
        restored = ck.restore()
        assert int(np.asarray(restored["step"])) == 3
        # the orphan dir from the interrupted delete is swept next save
        assert os.path.isdir(os.path.join(ck.directory, "step_1"))
        ck.save({"w": np.full((8,), 4.0, np.float32),
                 "step": np.asarray(4, np.int32)}, step=4)
        assert not os.path.exists(os.path.join(ck.directory, "step_1"))
        assert ck.steps() == [3, 4]

    def test_trainer_resumes_past_torn_latest(self, tmp_path):
        """End-to-end: a fit whose LATEST checkpoint was torn by a crash
        resumes from the previous one instead of dying mid-recovery."""
        x, y = xor_data(128)
        ckdir = str(tmp_path / "run")
        cfg = TrainConfig(batch_size=32, epochs=2, checkpoint_dir=ckdir,
                          checkpoint_every=2, seed=3, max_to_keep=4)
        tr1 = Trainer(MLP(features=(16,), num_outputs=2), cfg)
        tr1.fit_arrays(x, y)
        from mmlspark_tpu.train.checkpoint import TrainCheckpointer
        ck = TrainCheckpointer(ckdir)
        latest = ck.latest_step()
        self._truncate_largest_leaf(
            os.path.join(ck.directory, f"step_{latest}"))
        tr2 = Trainer(MLP(features=(16,), num_outputs=2), cfg)
        tr2.state = tr2.init_state(x.shape[1:])
        resumed = tr2.maybe_restore()
        assert resumed is not None and resumed < latest
        assert resumed in ck.steps()


class TestJaxLearner:
    def test_fit_on_featurized_table(self):
        r = np.random.default_rng(0)
        n = 300
        y = r.integers(0, 2, n)
        t = DataTable({
            "a": r.normal(size=n) + 2.0 * y,
            "b": r.normal(size=n),
            "cat": [["u", "v"][int(v)] for v in r.integers(0, 2, n)],
            "label": y,
        })
        model = JaxLearner(label_col="label", epochs=80,
                           learning_rate=0.01).fit(t)
        # JaxLearnerModel featurizes internally
        scored = model.transform(t)
        logits = scored.column_matrix("scores")
        acc = (logits.argmax(axis=1) == y).mean()
        assert acc > 0.85, acc
        assert model.label_levels == [0, 1]

    def test_fit_on_vector_column_with_mesh(self):
        x, y = xor_data(256)
        t = DataTable({"vec": list(x), "label": y})
        model = JaxLearner(label_col="label", input_col="vec", epochs=30,
                           learning_rate=5e-3, batch_size=64,
                           mesh_spec={"dp": 4, "fsdp": 2}).fit(t)
        scored = model.transform(t)
        logits = scored.column_matrix("scores")
        assert (logits.argmax(axis=1) == y).mean() > 0.8

    def test_regression_loss(self):
        r = np.random.default_rng(1)
        x = r.normal(size=(200, 4)).astype(np.float32)
        y = x @ np.array([1.0, -2.0, 0.5, 3.0]) + 1.0
        t = DataTable({"vec": list(x), "target": y})
        model = JaxLearner(label_col="target", input_col="vec", loss="mse",
                           epochs=200, learning_rate=0.01).fit(t)
        pred = model.transform(t).column_matrix("scores").reshape(-1)
        assert np.sqrt(np.mean((pred - y) ** 2)) < 1.0

    def test_checkpointing_through_learner(self, tmp_path):
        x, y = xor_data(128)
        t = DataTable({"vec": list(x), "label": y})
        ckdir = str(tmp_path / "jl")
        JaxLearner(label_col="label", input_col="vec", epochs=2,
                   batch_size=32, checkpoint_dir=ckdir).fit(t)
        assert TrainCheckpointer(ckdir).latest_step() is not None

    def test_learner_model_roundtrip(self, tmp_path):
        from mmlspark_tpu.core.stage import PipelineStage
        r = np.random.default_rng(4)
        n = 100
        y = r.integers(0, 2, n)
        t = DataTable({"a": r.normal(size=n) + 2.0 * y, "label": y})
        model = JaxLearner(label_col="label", epochs=20).fit(t)
        p = str(tmp_path / "jl_model")
        model.save(p)
        loaded = PipelineStage.load(p)
        np.testing.assert_allclose(
            loaded.transform(t).column_matrix("scores"),
            model.transform(t).column_matrix("scores"), rtol=1e-5)
        assert loaded.label_levels == model.label_levels

    def test_conv_module_with_input_shape(self):
        from mmlspark_tpu.models.zoo import ConvNetCifar
        r = np.random.default_rng(2)
        n = 64
        x = r.normal(size=(n, 8 * 8 * 3)).astype(np.float32)
        y = r.integers(0, 2, n)
        t = DataTable({"v": list(x), "label": y})
        model = JaxLearner(
            label_col="label", input_col="v", input_shape=[8, 8, 3],
            module=ConvNetCifar(num_classes=2, widths=(4,), dense_width=8),
            epochs=1, batch_size=16).fit(t)
        out = model.transform(
            DataTable({"v": list(x.reshape(n, -1))}).with_column("label", y))
        assert out.column_matrix("scores").shape == (n, 2)


class TestTailBatches:
    """Round-3 fix: the final partial batch is padded + masked, not dropped
    (VERDICT r2 weak item 2)."""

    def test_tail_rows_are_trained(self):
        x, y = xor_data(80)  # 80 rows, bs 64 → 64 + padded 16
        cfg = TrainConfig(batch_size=64, epochs=3)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                     mesh=make_mesh(MeshSpec(dp=-1)))
        tr.fit_arrays(x, y)
        # 2 steps per epoch (ceil(80/64)), not 1 (drop_remainder behavior)
        assert int(tr.state["step"]) == 6

    def test_padded_tail_matches_exact_batch_numerics(self):
        # one masked step over a padded tail must equal one step over just
        # the real rows (same weights out), proving the mask removes the
        # padding's influence on loss AND gradients
        import jax
        from mmlspark_tpu.parallel.mesh import batch_sharding

        x, y = xor_data(64)
        mesh = make_mesh(MeshSpec(dp=-1))
        cfg = TrainConfig(batch_size=64, epochs=1, learning_rate=1e-2,
                          donate_state=False)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
        tr.state = tr.init_state(x.shape[1:])
        data = batch_sharding(mesh)

        # padded: 48 real rows + 16 zero rows, mask zeros the padding
        pad_x = np.concatenate([x[:48], np.zeros((16, 8), np.float32)])
        pad_y = np.concatenate([y[:48], np.zeros(16, np.int64)])
        w = np.concatenate([np.ones(48, np.float32),
                            np.zeros(16, np.float32)])
        s_pad, m_pad = tr.step_masked(
            tr.state, jax.device_put(pad_x, data),
            jax.device_put(pad_y, data), jax.device_put(w, data))

        # against a direct unmasked 48-row step
        cfg48 = TrainConfig(batch_size=48, epochs=1, learning_rate=1e-2,
                            donate_state=False)
        tr48 = Trainer(MLP(features=(16,), num_outputs=2), cfg48, mesh=mesh)
        tr48.state = tr48.init_state(x.shape[1:])
        s48, m48 = tr48.step(
            tr48.state, jax.device_put(x[:48], data),
            jax.device_put(y[:48], data))
        np.testing.assert_allclose(float(m_pad["loss"]), float(m48["loss"]),
                                   rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(s_pad["params"]),
                        jax.tree_util.tree_leaves(s48["params"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


def test_multilabel_sigmoid_loss_trains_with_tail():
    # [B,K] sigmoid labels through the masked step (review finding r3)
    r = np.random.default_rng(0)
    x = r.normal(size=(40, 6)).astype(np.float32)
    y = (r.normal(size=(40, 3)) > 0).astype(np.float32)
    cfg = TrainConfig(batch_size=32, epochs=2, loss="sigmoid_xent")
    tr = Trainer(MLP(features=(8,), num_outputs=3), cfg,
                 mesh=make_mesh(MeshSpec(dp=-1)))
    tr.fit_arrays(x, y)  # 40 % 32 != 0 → exercises pad+mask with [B,K]
    assert np.isfinite(tr.history[-1])


class TestTensorParallel:
    """Round-3: the tp axis is wired — last param dim column-shards and
    GSPMD inserts the collectives (VERDICT r2 weak item 6)."""

    def test_param_shardings_tp_rule(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=4))
        params = {"kernel": np.zeros((8, 16)), "bias": np.zeros((16,)),
                  "odd": np.zeros((8, 5))}
        sh = param_shardings(mesh, params)
        assert "'tp'" in str(sh["kernel"].spec)
        assert str(sh["bias"].spec) == "PartitionSpec()"  # 1-D replicates
        assert str(sh["odd"].spec) == "PartitionSpec()"   # 5 % 4 != 0

    def test_param_shardings_tp_and_fsdp_compose(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        sh = param_shardings(mesh, {"k": np.zeros((8, 16))})
        s = str(sh["k"].spec)
        assert "'tp'" in s and "'fsdp'" in s and s.index("fsdp") < s.index(
            "tp")  # fsdp on dim 0, tp on dim 1

    def test_tp_training_matches_dp_numerics(self):
        x, y = xor_data(128)
        losses = {}
        for name, spec in [("dp", MeshSpec(dp=-1)),
                           ("tp", MeshSpec(dp=2, tp=4)),
                           ("dp_fsdp_tp", MeshSpec(dp=2, fsdp=2, tp=2))]:
            cfg = TrainConfig(batch_size=64, epochs=3, log_every=1, seed=7)
            tr = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                         mesh=make_mesh(spec))
            tr.fit_arrays(x, y)
            losses[name] = tr.history
        np.testing.assert_allclose(losses["dp"], losses["tp"],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(losses["dp"], losses["dp_fsdp_tp"],
                                   rtol=1e-4, atol=1e-5)

    def test_tp_params_actually_sharded(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=4))
        x, y = xor_data(64)
        cfg = TrainConfig(batch_size=32, epochs=1)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
        tr.fit_arrays(x, y)
        leaves = jax.tree_util.tree_leaves(tr.params)
        assert any("tp" in str(l.sharding.spec) for l in leaves
                   if hasattr(l, "sharding"))


class TestSingleDeviceFastPathAndParamDtype:
    def test_single_device_mesh_trains_and_matches_multi(self):
        """The 1-device plain-jit fast path (no NamedSharding machinery)
        must produce the same loss walk as the 8-device dp mesh."""
        from mmlspark_tpu.models.zoo import MLP
        x, y = xor_data(96)
        losses = {}
        for name, spec in [("one", MeshSpec(dp=1)), ("all", MeshSpec(dp=-1))]:
            cfg = TrainConfig(batch_size=32, epochs=2, log_every=1, seed=3)
            tr = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                         mesh=make_mesh(spec))
            tr.fit_arrays(x, y)
            losses[name] = tr.history
        np.testing.assert_allclose(losses["one"], losses["all"],
                                   rtol=1e-4, atol=1e-5)

    def test_single_device_checkpoint_resume(self, tmp_path):
        """Resume must work through the fast path (plain device arrays,
        no NamedSharding) — restore targets carry SingleDeviceShardings."""
        from mmlspark_tpu.models.zoo import MLP
        x, y = xor_data(64)
        cfg = TrainConfig(batch_size=32, epochs=2, log_every=1, seed=1,
                          checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=1, donate_state=False)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                     mesh=make_mesh(MeshSpec(dp=1)))
        tr.fit_arrays(x, y)
        full = [np.asarray(l) for l in jax.tree_util.tree_leaves(tr.params)]
        # fresh trainer resumes from the final checkpoint: no extra steps,
        # params identical
        tr2 = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                      mesh=make_mesh(MeshSpec(dp=1)))
        tr2.fit_arrays(x, y)
        for a, b in zip(full,
                        jax.tree_util.tree_leaves(tr2.params)):
            np.testing.assert_array_equal(a, np.asarray(b))

    def test_param_dtype_bfloat16_halves_state_and_trains(self):
        """Master-free bf16 fine-tune: params AND momentum come out
        bfloat16 (the zeros_like inheritance), and the loss still falls."""
        import jax.numpy as jnp

        from mmlspark_tpu.models.zoo import MLP
        x, y = xor_data(96)
        cfg = TrainConfig(batch_size=32, epochs=4, log_every=1, seed=0,
                          optimizer="momentum", learning_rate=5e-2,
                          param_dtype="bfloat16")
        tr = Trainer(MLP(features=(32,), num_outputs=2), cfg)
        tr.fit_arrays(x, y)
        for leaf in jax.tree_util.tree_leaves(tr.params):
            assert leaf.dtype == jnp.bfloat16
        mom_leaves = [l for l in jax.tree_util.tree_leaves(
            tr.state["opt_state"]) if hasattr(l, "dtype") and l.ndim > 0]
        assert any(l.dtype == jnp.bfloat16 for l in mom_leaves)
        assert tr.history[-1] < tr.history[0]

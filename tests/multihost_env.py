"""Shared preamble for the multi-host worker scripts.

Importing this module — BEFORE importing jax — pins the worker onto the
virtual-CPU simulation platform (env fallbacks for hand runs; the launcher
presets them) and puts the repo root on sys.path. Kept in one place so the
platform-pinning workaround cannot silently diverge between workers.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pin_platform() -> None:
    """The env var alone is not enough where an experimental TPU platform
    plugin is installed — pin the platform through the config too."""
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def params_checksum(params) -> float:
    import jax
    import numpy as np
    leaves = jax.tree_util.tree_leaves(params)
    return float(sum(float(np.asarray(l).sum()) for l in leaves))


def write_result(pid: int, result: dict, prefix: str = "out") -> None:
    """One JSON result file per rank under $MULTIHOST_OUT_DIR + stdout."""
    out_dir = os.environ.get("MULTIHOST_OUT_DIR")
    if out_dir:
        with open(os.path.join(out_dir, f"{prefix}_{pid}.json"), "w") as f:
            json.dump(result, f)
    print(json.dumps(result), flush=True)

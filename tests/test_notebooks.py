"""The notebook demo surface: freshness + real-kernel execution.

The reference ships runnable sample notebooks and executes them in CI
(reference: notebooks/samples/, tools/notebook/tester/
NotebookTestSuite.py:13-60, TestNotebooksLocally.py:9-29). Here the
notebooks are derived from ``examples/*.py`` by
``mmlspark_tpu.tools.make_notebooks``:

* the freshness test (default lane) regenerates the set and fails if the
  committed ``notebooks/samples/`` drifted from the examples,
* the execution tests (slow lane) run every notebook through a REAL
  jupyter kernel via nbclient — the demo artifact a user opens in the
  Docker image's jupyter entry must actually run.
"""

import glob
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NB_DIR = os.path.join(REPO, "notebooks", "samples")


def committed_notebooks() -> list[str]:
    return sorted(glob.glob(os.path.join(NB_DIR, "*.ipynb")))


def test_notebooks_fresh(tmp_path):
    """notebooks/samples/ must be regenerable byte-stable from examples/
    (same freshness contract as the generated API docs)."""
    import nbformat

    from mmlspark_tpu.tools.make_notebooks import build

    regen = build(str(tmp_path))
    committed = committed_notebooks()
    assert len(committed) == len(regen) == 11, (
        f"expected 11 notebooks, committed={len(committed)} "
        f"regenerated={len(regen)} — run python -m "
        "mmlspark_tpu.tools.make_notebooks")
    for new_path in regen:
        old_path = os.path.join(NB_DIR, os.path.basename(new_path))
        assert os.path.exists(old_path), f"missing committed {old_path}"
        old = nbformat.read(old_path, as_version=4)
        new = nbformat.read(new_path, as_version=4)
        assert [c.source for c in old.cells] == \
            [c.source for c in new.cells], (
                f"{os.path.basename(old_path)} is stale — regenerate with "
                "python -m mmlspark_tpu.tools.make_notebooks")


@pytest.mark.slow
@pytest.mark.parametrize("nb_path", committed_notebooks(),
                         ids=[os.path.basename(p).split(" - ")[0]
                              for p in committed_notebooks()])
def test_notebook_executes(nb_path, tmp_path):
    """Every sample notebook runs top to bottom in a real kernel."""
    import nbformat
    from nbclient import NotebookClient

    nb = nbformat.read(nb_path, as_version=4)
    # test-only preamble (NOT in the committed notebook): pin the kernel
    # to the CPU backend (the environment's sitecustomize presets a TPU
    # tunnel platform that plain env vars don't override) and put the
    # repo on sys.path since the kernel cwd is a scratch dir
    pin = nbformat.v4.new_code_cell(
        "import sys; sys.path.insert(0, %r)\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')" % REPO)
    nb.cells.insert(0, pin)
    client = NotebookClient(nb, timeout=600, kernel_name="python3",
                            resources={"metadata": {"path": str(tmp_path)}})
    client.execute()  # raises CellExecutionError on any failing cell
    # at least one cell produced output (the examples all print results)
    outs = [o for c in nb.cells if c.cell_type == "code"
            for o in c.get("outputs", [])]
    assert outs, "notebook executed but produced no output"

"""Test harness: single-host JAX on a virtual 8-device CPU mesh.

The reference tests all "distributed" logic on a multi-threaded local
SparkSession (``local[*]``, reference:
core/test/base/src/main/scala/SparkSessionFactory.scala:39-51); the analog
here is the JAX CPU backend with 8 virtual devices via
``--xla_force_host_platform_device_count``, so every sharding/collective
path compiles and executes without TPU hardware.
"""

import os

# must run before jax initializes; the environment presets JAX_PLATFORMS to
# the TPU tunnel (axon) via sitecustomize, which survives env overrides —
# jax.config.update below is what actually forces CPU
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def thread_names(*prefixes):
    """Live threads whose names start with one of ``prefixes``."""
    import threading
    return [t.name for t in threading.enumerate()
            if any(t.name.startswith(p) for p in prefixes)]


def assert_no_leaked_threads(*prefixes, timeout=5.0):
    """Assert that no thread named with one of ``prefixes`` survives,
    polling up to ``timeout`` — shutdown paths signal their workers
    before join returns, so a just-closed subsystem may need a few ms
    to finish unwinding. The one leak assertion every suite shares
    (serve lanes, train loaders, beacons, obs samplers); prefix
    allowlisting keeps it scoped to the subsystem under test instead
    of flaking on pytest's own machinery threads."""
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not thread_names(*prefixes):
            return
        time.sleep(0.02)
    raise AssertionError(
        f"leaked threads (prefixes {prefixes}): {thread_names(*prefixes)}")


@pytest.fixture(name="assert_no_leaked_threads")
def _assert_no_leaked_threads_fixture():
    return assert_no_leaked_threads


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture()
def tmp_save_path(tmp_path):
    return str(tmp_path / "stage")


def make_tabular(n=100, seed=0):
    """Small mixed-type table used across suites (GenerateDataset analog)."""
    from mmlspark_tpu.data.table import DataTable
    r = np.random.default_rng(seed)
    return DataTable({
        "num": r.normal(size=n),
        "int": r.integers(0, 10, size=n),
        "cat": [["red", "green", "blue"][i % 3] for i in range(n)],
        "text": [f"word{i % 7} tok{i % 3}" for i in range(n)],
        "label": (r.random(n) > 0.5).astype(np.int64),
    })

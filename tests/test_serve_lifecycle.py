"""Zero-downtime model lifecycle + self-healing serve plane:

* hot-swap — a version flip under live traffic drops nothing, and every
  answer is bit-identical to the offline transform of WHICHEVER version
  served it;
* shadow/canary routing — a deterministic fraction of admissions is
  mirrored (shadow: stable answers, outputs diffed) or split (canary:
  candidate answers);
* SLO-driven promotion — the pure PromotionPolicy rolls back on canary
  fast-burn / parity drift and promotes after consecutive clean
  windows, every decision journaled;
* lane self-healing — an injected non-request exception killing a lane
  worker (the motivating stranded-queue bug) requeues undispatched
  work, fails in-flight typed, restarts the lane, and degrades health
  while capacity is down;
* versioned-repo serving — a torn or corrupt version is refused typed
  while the prior version keeps serving.
"""

import threading
import time

import numpy as np
import pytest

import jax

from mmlspark_tpu.core.retry import RetryPolicy
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models import ModelBundle, ModelRepo, RepoCorruptError
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.core.stage import LambdaTransformer
from mmlspark_tpu.serve import (
    CanarySignal, Client, FaultPlan, FaultSpec, Hold, LaneFailed,
    ModelServer, Promote, PromotionLedger, PromotionPolicy, Rollback,
    ServeConfig, THREAD_PREFIX, faults,
)

IN_DIM = 6


def mlp_bundle(seed=0):
    module = MLP(features=(8,), num_outputs=4)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, IN_DIM), np.float32))["params"]
    return ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(IN_DIM,),
        output_names=("features", "logits"),
        name="mlp")


def jax_model(seed=0):
    return JaxModel(model=mlp_bundle(seed), input_col="x",
                    output_col="s")


def vec_table(rows):
    return DataTable({"x": list(rows)})


def rows_of(n, seed=0):
    return np.random.default_rng(seed).normal(
        size=(n, IN_DIM)).astype(np.float32)


def scores(table):
    return np.stack([np.asarray(v) for v in table["s"]])


def failing_model(out_col="s"):
    """Host-path model that fails every non-empty transform (the
    analyzer's 0-row probe passes) — the canary-burn inducer."""
    def fn(table):
        if len(table) == 0:
            return table.with_column(out_col, np.asarray([], object))
        raise RuntimeError("canary model is broken")
    return LambdaTransformer(fn=fn)


def serve_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith(THREAD_PREFIX)]


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    yield
    faults.clear()


# ---- hot swap ----


class TestHotSwap:
    def test_swap_under_traffic_zero_dropped_outputs_pinned(self):
        rows = rows_of(24)
        jm1, jm2 = jax_model(seed=0), jax_model(seed=1)
        off1 = scores(jm1.transform(vec_table(rows)))
        off2 = scores(jm2.transform(vec_table(rows)))
        assert not np.array_equal(off1, off2)

        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=256))
        server.add_model("m", jm1, example=vec_table(rows[:1]),
                         version=1)
        results: list[tuple] = []
        errors: list[BaseException] = []
        lock = threading.Lock()

        def worker(k):
            try:
                for i in range(12):
                    off = (k * 12 + i) % 22
                    out = server.predict("m", vec_table(rows[off:off + 2]),
                                         timeout=60)
                    with lock:
                        results.append((off, scores(out)))
                    time.sleep(0.01)  # keep traffic alive across the swap
            except BaseException as e:  # noqa: BLE001 — reported
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        # the hot-swap, mid-burst: v2 loads + warms while v1 serves
        time.sleep(0.02)
        server.add_model("m", jm2, example=vec_table(rows[:1]),
                         version=2)
        for t in threads:
            t.join()
        try:
            assert errors == []          # zero dropped requests
            assert len(results) == 48
            v1_served = v2_served = 0
            for off, got in results:
                if np.array_equal(got, off1[off:off + 2]):
                    v1_served += 1
                elif np.array_equal(got, off2[off:off + 2]):
                    v2_served += 1
                else:
                    raise AssertionError(
                        "served output matches NEITHER version's "
                        "offline transform bit-for-bit")
            assert v1_served + v2_served == 48
            assert v2_served > 0         # the flip actually happened
            # post-swap requests are v2, and the journal knows
            out = scores(server.predict("m", vec_table(rows[:2])))
            assert np.array_equal(out, off2[:2])
            swaps = server.lifecycle_decisions("swap")
            assert len(swaps) == 1
            assert swaps[0]["from_version"] == 1
            assert swaps[0]["to_version"] == 2
            assert server.snapshot()["m"]["version"] == 2
        finally:
            server.close()
        assert serve_threads() == []

    def test_swap_supersedes_inflight_canary(self):
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=32))
        try:
            server.add_model("m", jax_model(0), version=1,
                             example=vec_table(rows_of(1)))
            server.deploy_canary("m", jax_model(1), mode="shadow",
                                 fraction=1.0, version=2,
                                 example=vec_table(rows_of(1)))
            assert server.canary_status("m")["version"] == 2
            server.add_model("m", jax_model(2), version=3,
                             example=vec_table(rows_of(1)))
            assert server.canary_status("m") is None
            swap = server.lifecycle_decisions("swap")[0]
            assert swap["canary_superseded"] is True
        finally:
            server.close()
        assert serve_threads() == []


# ---- canary / shadow routing ----


class TestCanaryRouting:
    def test_canary_split_is_deterministic_and_answers_from_canary(self):
        rows = rows_of(16)
        jm1, jm2 = jax_model(seed=0), jax_model(seed=1)
        off1 = scores(jm1.transform(vec_table(rows)))
        off2 = scores(jm2.transform(vec_table(rows)))
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=64))
        try:
            server.add_model("m", jm1, example=vec_table(rows[:1]),
                             version=1)
            server.deploy_canary("m", jm2, mode="canary", fraction=0.5,
                                 version=2,
                                 example=vec_table(rows[:1]))
            served = []
            for i in range(8):
                out = scores(server.predict("m",
                                            vec_table(rows[i:i + 1])))
                if np.array_equal(out, off2[i:i + 1]):
                    served.append("canary")
                else:
                    assert np.array_equal(out, off1[i:i + 1])
                    served.append("stable")
            # Bresenham at 0.5: strict alternation, stable first
            assert served == ["stable", "canary"] * 4
        finally:
            server.close()
        assert serve_threads() == []

    def test_shadow_mirrors_never_change_stable_answers(self):
        rows = rows_of(12)
        jm1, jm2 = jax_model(seed=0), jax_model(seed=1)
        off1 = scores(jm1.transform(vec_table(rows)))
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=64))
        try:
            server.add_model("m", jm1, example=vec_table(rows[:1]),
                             version=1)
            server.deploy_canary("m", jm2, mode="shadow", fraction=1.0,
                                 version=2,
                                 example=vec_table(rows[:1]))
            for i in range(0, 12, 2):
                out = scores(server.predict("m",
                                            vec_table(rows[i:i + 2])))
                assert np.array_equal(out, off1[i:i + 2])
            deadline = time.monotonic() + 10
            status = server.canary_status("m")
            while time.monotonic() < deadline:
                server.lifecycle_tick("m")
                status = server.canary_status("m")
                if status and status["pairs_compared"] >= 6:
                    break
                time.sleep(0.02)
            assert status["pairs_compared"] >= 6
            # two different seeds: the mirrored outputs REALLY differ
            assert status["parity_max"] > 1e-3
            snap = server.snapshot()["m"]
            assert snap["canary"]["mode"] == "shadow"
            assert snap["canary"]["stats_admitted"] >= 6
        finally:
            server.close()
        assert serve_threads() == []

    def test_bad_fraction_and_mode_are_typed(self):
        server = ModelServer(ServeConfig(buckets=(1,), max_queue=8))
        try:
            server.add_model("m", jax_model(0),
                             example=vec_table(rows_of(1)))
            with pytest.raises(ValueError, match="fraction"):
                server.deploy_canary("m", jax_model(1), fraction=0.0,
                                     example=vec_table(rows_of(1)))
            with pytest.raises(ValueError, match="mode"):
                server.deploy_canary("m", jax_model(1), mode="blue",
                                     example=vec_table(rows_of(1)))
            assert server.canary_status("m") is None
        finally:
            server.close()
        assert serve_threads() == []


# ---- the pure promotion policy ----


class TestPromotionPolicy:
    POLICY = PromotionPolicy(fast_burn=14.0, slow_burn=2.0,
                             promote_after=3)

    def test_fast_burn_rolls_back(self):
        act = self.POLICY.decide(
            CanarySignal(burn_short=20.0, terminal_window=50),
            PromotionLedger())
        assert isinstance(act, Rollback)
        assert "fast-burn" in act.reason

    def test_parity_drift_rolls_back_even_with_clean_burn(self):
        act = self.POLICY.decide(
            CanarySignal(burn_short=0.0, parity_drift=0.5,
                         parity_tolerance=0.1),
            PromotionLedger(clean_windows=10))
        assert isinstance(act, Rollback)
        assert "parity" in act.reason

    def test_no_traffic_holds_without_banking(self):
        act = self.POLICY.decide(CanarySignal(), PromotionLedger())
        assert isinstance(act, Hold) and not act.clean

    def test_long_burn_holds_and_resets(self):
        act = self.POLICY.decide(
            CanarySignal(burn_short=0.5, burn_long=3.0),
            PromotionLedger(clean_windows=2))
        assert isinstance(act, Hold) and not act.clean

    def test_clean_windows_bank_to_promotion(self):
        ledger = PromotionLedger()
        sig = CanarySignal(burn_short=0.1, burn_long=0.1,
                           terminal_window=40)
        for expected_clean in (1, 2):
            act = self.POLICY.decide(sig, ledger)
            assert isinstance(act, Hold) and act.clean
            ledger.clean_windows = expected_clean
        act = self.POLICY.decide(sig, ledger)
        assert isinstance(act, Promote)

    def test_policy_validates(self):
        with pytest.raises(ValueError):
            PromotionPolicy(promote_after=0)


# ---- the closed loop: burn -> rollback, clean -> promote ----


class TestAutoRollbackAndPromote:
    SLO = {"objective": 0.99, "min_requests": 4, "window_s": 30.0,
           "long_window_s": 60.0}

    def test_canary_fast_burn_auto_rolls_back(self, tmp_path):
        rows = rows_of(12)
        jm1 = jax_model(seed=0)
        off1 = scores(jm1.transform(vec_table(rows)))
        server = ModelServer(ServeConfig(
            buckets=(1, 4), max_queue=64, slo=self.SLO,
            lifecycle_dir=str(tmp_path)))
        try:
            server.add_model("m", jm1, example=vec_table(rows[:1]),
                             version=1)
            server.deploy_canary("m", failing_model(), mode="shadow",
                                 fraction=1.0, version=2)
            first = server.lifecycle_tick("m")
            assert first["action"] == "hold"  # no canary traffic yet
            for i in range(8):
                out = scores(server.predict(
                    "m", vec_table(rows[i:i + 1]), timeout=30))
                assert np.array_equal(out, off1[i:i + 1])
            # let the mirrors reach terminal state AND the tick step
            # past the burn ring's coalescing resolution (a tick inside
            # the same step would fold into the pre-traffic baseline)
            time.sleep(0.1)
            deadline = time.monotonic() + 10
            decision = None
            while time.monotonic() < deadline:
                decision = server.lifecycle_tick("m")
                if decision is None or decision["action"] == "rollback":
                    break
                time.sleep(0.05)
            assert decision is not None
            assert decision["action"] == "rollback"
            assert decision["burn_short"] >= 14.0
            assert server.canary_status("m") is None
            # stable untouched, decisions on disk
            out = scores(server.predict("m", vec_table(rows[:2])))
            assert np.array_equal(out, off1[:2])
            kinds = [e["kind"] for e in server.lifecycle_decisions()]
            assert "canary_deploy" in kinds and "rollback" in kinds
            with open(tmp_path / "decisions.jsonl") as f:
                lines = f.read().strip().splitlines()
            import json
            assert any(json.loads(ln)["kind"] == "rollback"
                       for ln in lines)
        finally:
            server.close()
        assert serve_threads() == []

    def test_clean_canary_promotes_and_takes_the_name(self):
        rows = rows_of(12)
        jm1, jm2 = jax_model(seed=0), jax_model(seed=1)
        off2 = scores(jm2.transform(vec_table(rows)))
        server = ModelServer(ServeConfig(
            buckets=(1, 4), max_queue=64, slo=self.SLO))
        try:
            server.add_model("m", jm1, example=vec_table(rows[:1]),
                             version=1)
            server.deploy_canary("m", jm2, mode="canary", fraction=1.0,
                                 version=2, promote_after=2,
                                 example=vec_table(rows[:1]))
            deadline = time.monotonic() + 15
            decision = None
            while time.monotonic() < deadline:
                for i in range(6):
                    server.predict("m", vec_table(rows[i:i + 1]),
                                   timeout=30)
                decision = server.lifecycle_tick("m")
                if decision is None or decision["action"] == "promote":
                    break
                time.sleep(0.05)
            assert decision is not None
            assert decision["action"] == "promote"
            assert server.canary_status("m") is None
            assert server.snapshot()["m"]["version"] == 2
            out = scores(server.predict("m", vec_table(rows[:2])))
            assert np.array_equal(out, off2[:2])
            kinds = [e["kind"] for e in server.lifecycle_decisions()]
            assert "promote" in kinds
        finally:
            server.close()
        assert serve_threads() == []

    def test_slo_polling_drives_the_rollout(self):
        """An HTTP-only operator never calls lifecycle_tick: polling
        /slo (= slo_snapshot) must itself advance the rollout loop, so
        a burning canary rolls back with no in-process caller."""
        rows = rows_of(8)
        server = ModelServer(ServeConfig(
            buckets=(1, 4), max_queue=64, slo=self.SLO))
        try:
            server.add_model("m", jax_model(0), version=1,
                             example=vec_table(rows[:1]))
            server.deploy_canary("m", failing_model(), mode="shadow",
                                 fraction=1.0, version=2)
            server.slo_snapshot()  # banks the pre-traffic baseline
            for i in range(8):
                server.predict("m", vec_table(rows[i:i + 1]), timeout=30)
            time.sleep(0.1)
            deadline = time.monotonic() + 10
            rolled = False
            while time.monotonic() < deadline and not rolled:
                body = server.slo_snapshot()["m"]
                decision = body.get("lifecycle")
                rolled = (decision or {}).get("action") == "rollback" \
                    or server.canary_status("m") is None
                time.sleep(0.05)
            assert rolled
            assert server.canary_status("m") is None
            kinds = [e["kind"] for e in server.lifecycle_decisions()]
            assert "rollback" in kinds
        finally:
            server.close()
        assert serve_threads() == []

    def test_manual_rollback(self):
        server = ModelServer(ServeConfig(buckets=(1,), max_queue=8))
        try:
            server.add_model("m", jax_model(0), version=1,
                             example=vec_table(rows_of(1)))
            server.deploy_canary("m", jax_model(1), version=2,
                                 example=vec_table(rows_of(1)))
            out = server.rollback("m", reason="operator said so")
            assert out["action"] == "rollback"
            assert server.canary_status("m") is None
            assert server.rollback("m") is None  # idempotent-ish
        finally:
            server.close()
        assert serve_threads() == []


# ---- lane self-healing (the motivating regression) ----


class TestLaneSelfHealing:
    def test_lane_death_requeues_restarts_and_answers_everything(self):
        """Regression for the motivating bug: a lane worker killed by a
        non-request exception previously stranded its queued requests
        past their deadlines — no reject, no health change, capacity
        silently gone. Now: requeued, restarted, counted."""
        rows = rows_of(12)
        jm = jax_model(seed=0)
        offline = scores(jm.transform(vec_table(rows)))
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=64))
        try:
            server.add_model("m", jm, example=vec_table(rows[:1]))
            plan = FaultPlan([FaultSpec("lane_death", model="m")])
            with faults.inject(plan):
                handles = [server.submit("m", vec_table(rows[i:i + 2]))
                           for i in range(0, 12, 2)]
                outs = [h.result(timeout=30) for h in handles]
            assert plan.counts().get("lane_death") == 1
            for k, out in enumerate(outs):
                assert np.array_equal(scores(out),
                                      offline[2 * k:2 * k + 2])
            snap = server.snapshot()["m"]
            assert snap["lane_deaths"] == 1
            assert snap["lane_restarts"] == 1
            assert snap["completed"] == 6
            assert snap["lane_health"]["alive"] == \
                snap["lane_health"]["lanes"] == 1
            kinds = [e["kind"] for e in server.lifecycle_decisions()]
            assert "lane_death" in kinds and "lane_restart" in kinds
        finally:
            server.close()
        assert serve_threads() == []

    def test_lane_death_with_survivors_requeues_onto_them(self):
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices for dp=2")
        rows = rows_of(16)
        jm = jax_model(seed=0)
        offline = scores(jm.transform(vec_table(rows)))
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=64,
                                         mesh="dp=2"))
        try:
            server.add_model("m", jm, example=vec_table(rows[:1]))
            plan = FaultPlan([FaultSpec("lane_death", model="m",
                                        lane=0)])
            with faults.inject(plan):
                handles = [server.submit("m", vec_table(rows[i:i + 2]))
                           for i in range(0, 16, 2)]
                outs = [h.result(timeout=30) for h in handles]
            for k, out in enumerate(outs):
                assert np.array_equal(scores(out),
                                      offline[2 * k:2 * k + 2])
            # the survivor answered the requeued work immediately; the
            # replacement lane arrives after the restart backoff
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if server.stats("m").lane_restarts == 1:
                    break
                time.sleep(0.02)
            snap = server.snapshot()["m"]
            assert snap["lane_deaths"] == 1
            assert snap["lane_restarts"] == 1
            assert snap["lane_health"]["alive"] == 2
        finally:
            server.close()
        assert serve_threads() == []

    def test_inflight_batch_fails_typed_lane_failed(self):
        """A batch already dispatched when its lane dies loses its
        result with the worker: typed, retryable LaneFailed — never a
        silent hang, never a speculative resolve."""
        rows = rows_of(4)
        jm = jax_model(seed=0)
        server = ModelServer(ServeConfig(buckets=(1, 2), max_queue=64,
                                         max_inflight=2))
        try:
            server.add_model("m", jm, example=vec_table(rows[:1]))
            # two layout-INcompatible requests (1 row vs 2 rows in one
            # batch slot -> different bucket shapes is not enough; the
            # compat key differs on row-count layout of object cells) —
            # force two separate batches via distinct column layouts
            plan = FaultPlan([FaultSpec("lane_death", model="m",
                                        after=1)])
            with faults.inject(plan):
                # batch 1 dispatches (enters the async window), batch 2
                # is the in-hand item when the fault fires
                a = server.submit("m", vec_table(rows[:1]))
                time.sleep(0.15)  # let batch 1 reach the window
                b = server.submit("m", vec_table(rows[1:3]))
                got_a = None
                try:
                    got_a = a.result(timeout=30)
                except LaneFailed:
                    pass
                out_b = b.result(timeout=30)
            # b was undispatched at death: requeued, answered correctly
            offline = scores(jm.transform(vec_table(rows)))
            assert np.array_equal(scores(out_b), offline[1:3])
            if got_a is not None:
                # the race where batch 1 drained before the fault —
                # then nothing was in flight and a is simply correct
                assert np.array_equal(scores(got_a), offline[:1])
            snap = server.snapshot()["m"]
            assert snap["lane_deaths"] == 1
            assert snap["lane_restarts"] == 1
        finally:
            server.close()
        assert serve_threads() == []

    def test_exhausted_restart_budget_degrades_health(self):
        from mmlspark_tpu.obs.health import DEGRADED
        rows = rows_of(4)
        server = ModelServer(ServeConfig(
            buckets=(1, 2), max_queue=64,
            lane_restart=RetryPolicy(max_attempts=1, jitter=0.0)))
        try:
            server.add_model("m", jax_model(0),
                             example=vec_table(rows[:1]))
            plan = FaultPlan([FaultSpec("lane_death", model="m")])
            with faults.inject(plan):
                h = server.submit("m", vec_table(rows[:2]))
                with pytest.raises(LaneFailed):
                    h.result(timeout=30)
            snap = server.snapshot()["m"]
            assert snap["lane_deaths"] == 1
            assert snap["lane_restarts"] == 0
            assert snap["lane_health"]["alive"] == 0
            health = server.health()
            verdict = health["model_health"]["m"]
            assert verdict["state"] == DEGRADED
            assert "lane(s) down" in verdict["reason"]
            kinds = [e["kind"] for e in server.lifecycle_decisions()]
            assert "lane_down" in kinds
        finally:
            server.close()
        assert serve_threads() == []

    def test_dispatch_raise_fault_is_relayed_per_request(self):
        from mmlspark_tpu.serve.faults import InjectedFault
        rows = rows_of(2)
        server = ModelServer(ServeConfig(buckets=(1, 2), max_queue=16))
        try:
            server.add_model("m", jax_model(0),
                             example=vec_table(rows[:1]))
            plan = FaultPlan([FaultSpec("dispatch_raise", model="m")])
            with faults.inject(plan):
                h = server.submit("m", vec_table(rows))
                with pytest.raises(InjectedFault):
                    h.result(timeout=30)
            # a dispatch-time raise fails the batch, not the lane
            snap = server.snapshot()["m"]
            assert snap["failed"] == 1
            assert snap["lane_deaths"] == 0
            out = server.predict("m", vec_table(rows))  # lane fine
            assert len(out) == 2
        finally:
            server.close()
        assert serve_threads() == []


# ---- versioned-repo serving ----


class TestRepoServing:
    def test_serve_current_and_pinned_versions(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        repo.publish("m", mlp_bundle(seed=0))
        repo.publish("m", mlp_bundle(seed=1))
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=16))
        try:
            info = server.add_model_from_repo(repo, "m")
            assert info.version == 2
            assert server.snapshot()["m"]["version"] == 2
            info = server.add_model_from_repo(repo, "m", version=1)
            assert info.version == 1
            assert server.snapshot()["m"]["version"] == 1
        finally:
            server.close()
        assert serve_threads() == []

    def test_corrupt_version_refused_prior_keeps_serving(self, tmp_path):
        """Satellite: torn-publish recovery. A version directory whose
        digests don't match its manifest is refused with a typed error
        and NO partial load reaches the batcher — the server keeps
        serving the version it already has."""
        import os
        from mmlspark_tpu.models.repo import BUNDLE_FILE
        repo = ModelRepo(str(tmp_path))
        repo.publish("m", mlp_bundle(seed=0))
        rows = rows_of(4)
        # a repo-served bundle is wrapped reading column "input" and
        # writing "scores" (the CLI's bundle contract)
        table = DataTable({"input": list(rows)})
        ref_model = JaxModel(model=mlp_bundle(seed=0), input_col="input",
                             output_col="scores")
        off1 = np.stack([np.asarray(v) for v in
                         ref_model.transform(table)["scores"]])
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=16))
        try:
            server.add_model_from_repo(repo, "m")
            v2 = repo.publish("m", mlp_bundle(seed=1))
            bundle_path = os.path.join(repo._version_dir("m", v2),
                                       BUNDLE_FILE)
            with open(bundle_path, "r+b") as f:
                f.seek(64)
                byte = f.read(1)
                f.seek(64)
                f.write(bytes([byte[0] ^ 0xFF]))
            with pytest.raises(RepoCorruptError):
                server.add_model_from_repo(repo, "m")
            # the swap never happened: v1 still serving, bit-identical
            assert server.snapshot()["m"]["version"] == 1
            out = server.predict("m", table)
            got = np.stack([np.asarray(v) for v in out["scores"]])
            assert np.array_equal(got, off1)
        finally:
            server.close()
        assert serve_threads() == []

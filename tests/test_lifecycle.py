"""The deployment plane (mmlspark_tpu/lifecycle): eval-gated
publication, provenance stamps, the rollout state machine, torn-publish
recovery, fleet convergence, and journal replay (docs/lifecycle.md)."""

import json
import os

import numpy as np
import pytest

import jax

from mmlspark_tpu.lifecycle import (
    Abort, Advance, Deployer, EvalGate, EvalLedger, FleetTarget, Hold,
    Publish, Publisher, PublishPolicy, Reject, RolloutLedger,
    RolloutPolicy, RolloutSignal, bundle_from_npz, replay_decisions,
)
from mmlspark_tpu.models import ModelBundle, ModelRepo, RepoCorruptError
from mmlspark_tpu.models.repo import ModelRepoError
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.serve import faults
from mmlspark_tpu.serve.faults import FaultPlan, FaultSpec
from mmlspark_tpu.serve.lifecycle import CanarySignal


def mlp_bundle(seed=0, in_dim=6):
    module = MLP(features=(8,), num_outputs=4)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, in_dim), np.float32))["params"]
    return ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(in_dim,), output_names=("features", "logits"),
        name="mlp")


def good_provenance(step=10):
    return {"checkpoint_step": step, "run_id": "train-1",
            "generation": 0, "eval": {"metric": 0.25}}


def journal_kinds(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line)["kind"] for line in f if line.strip()]


# ---------------------------------------------------------------- gate

class TestEvalGate:
    def test_needs_enough_evidence(self):
        gate = EvalGate(min_points=4, tail=4)
        d = gate.decide([1.0, 0.9], EvalLedger())
        assert isinstance(d, Reject) and "need >= 4" in d.reason

    def test_diverged_runs_never_ship(self):
        gate = EvalGate(min_points=2, tail=2)
        d = gate.decide([1.0, float("nan"), 0.5, 0.4], EvalLedger())
        assert isinstance(d, Reject) and "non-finite" in d.reason

    def test_quality_floor(self):
        gate = EvalGate(min_points=2, tail=2, max_metric=0.1)
        d = gate.decide([1.0, 0.9, 0.5, 0.4], EvalLedger())
        assert isinstance(d, Reject) and "quality floor" in d.reason

    def test_training_that_went_nowhere(self):
        gate = EvalGate(min_points=2, tail=2)
        d = gate.decide([0.5, 0.5, 0.6, 0.7], EvalLedger())
        assert isinstance(d, Reject) and "did not improve" in d.reason

    def test_regression_vs_best_published(self):
        gate = EvalGate(min_points=2, tail=2)
        ledger = EvalLedger(published=[(10, 0.1)])
        d = gate.decide([1.0, 0.9, 0.5, 0.4], ledger)
        assert isinstance(d, Reject) and "regresses" in d.reason

    def test_publish_carries_the_tail_mean(self):
        gate = EvalGate(min_points=2, tail=2)
        d = gate.decide([1.0, 0.9, 0.5, 0.3], EvalLedger())
        assert isinstance(d, Publish)
        assert d.metric == pytest.approx(0.4)

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            EvalGate(min_points=0)
        with pytest.raises(ValueError):
            EvalGate(min_improvement=-0.1)


# -------------------------------------------------------------- policy

def sig(stage="shadow", burn=0.0, drift=None, tol=None, **kw):
    return RolloutSignal(
        stage=stage,
        serve=CanarySignal(burn_short=burn, parity_drift=drift,
                           parity_tolerance=tol), **kw)


class TestRolloutPolicy:
    def test_serve_side_rollback_is_honored(self):
        pol = RolloutPolicy()
        a = pol.decide(RolloutSignal(stage="shadow", action="rollback"),
                       RolloutLedger(stage="shadow"))
        assert isinstance(a, Abort)

    def test_parity_drift_aborts(self):
        a = RolloutPolicy().decide(sig(drift=0.5, tol=1e-3),
                                   RolloutLedger(stage="shadow"))
        assert isinstance(a, Abort) and "parity drift" in a.reason

    def test_fast_burn_aborts(self):
        a = RolloutPolicy(fast_burn=14.0).decide(
            sig(burn=20.0), RolloutLedger(stage="shadow"))
        assert isinstance(a, Abort) and "fast-burn" in a.reason

    def test_stage_budget_aborts(self):
        led = RolloutLedger(stage="shadow", stage_ticks=240)
        a = RolloutPolicy(max_stage_ticks=240).decide(sig(), led)
        assert isinstance(a, Abort) and "budget" in a.reason

    def test_unhealthy_holds_and_resets(self):
        a = RolloutPolicy().decide(sig(healthy=False),
                                   RolloutLedger(stage="shadow"))
        assert isinstance(a, Hold) and not a.clean

    def test_no_evidence_neither_banks_nor_advances(self):
        a = RolloutPolicy().decide(
            RolloutSignal(stage="shadow", serve=None),
            RolloutLedger(stage="shadow"))
        assert isinstance(a, Hold) and not a.clean

    def test_clean_streak_advances(self):
        pol = RolloutPolicy(advance_after=2)
        led = RolloutLedger(stage="shadow")
        a1 = pol.decide(sig(), led)
        assert isinstance(a1, Hold) and a1.clean
        led.clean_ticks = 1
        a2 = pol.decide(sig(), led)
        assert isinstance(a2, Advance)

    def test_promotion_blocks_on_lagging_backends(self):
        pol = RolloutPolicy()
        led = RolloutLedger(stage="promoting")
        a = pol.decide(RolloutSignal(stage="promoting", converged=False,
                                     lagging=(1, 2)), led)
        assert isinstance(a, Hold) and "1,2" in a.reason
        a = pol.decide(RolloutSignal(stage="promoting", converged=True),
                       led)
        assert isinstance(a, Advance)

    def test_stage_names_validated(self):
        with pytest.raises(ValueError):
            RolloutPolicy(stages=("blue_green",))


# ---------------------------------------------------------- provenance

class TestProvenance:
    def test_roundtrips_through_the_manifest(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        prov = good_provenance()
        v = repo.publish("mlp", mlp_bundle(), provenance=prov)
        _, info = repo.load("mlp", v)
        assert info.provenance == prov
        assert info.describe()["provenance"] == prov

    def test_unpublishable_stamp_is_refused(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        for bad in ({"run_id": "x"},                       # no step
                    {**good_provenance(), "checkpoint_step": -1},
                    {**good_provenance(), "run_id": ""},
                    {**good_provenance(), "eval": {"metric": "hi"}}):
            with pytest.raises(ModelRepoError):
                repo.publish("mlp", mlp_bundle(), provenance=bad)
        assert repo.versions("mlp") == []

    def test_tampered_stamp_fails_verification(self, tmp_path):
        from mmlspark_tpu.models.repo import VERSION_MANIFEST
        repo = ModelRepo(str(tmp_path))
        v = repo.publish("mlp", mlp_bundle(),
                         provenance=good_provenance())
        mpath = os.path.join(repo._version_dir("mlp", v),
                             VERSION_MANIFEST)
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        manifest["provenance"]["checkpoint_step"] = -5
        with open(mpath, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
        with pytest.raises(RepoCorruptError):
            repo.load("mlp", v)


# ----------------------------------------------------------- publisher

def result_dict(tmp_path, history, steps=16):
    return {"history": history, "steps": steps,
            "params_npz": str(tmp_path / "params.npz")}


class TestPublisher:
    def policy(self, tmp_path, **kw):
        kw.setdefault("gate", EvalGate(min_points=2, tail=2))
        kw.setdefault("bundle_from_result",
                      lambda result: mlp_bundle(seed=1))
        return PublishPolicy(model="mlp", repo_root=str(tmp_path / "repo"),
                             **kw)

    def test_pass_publishes_dark_with_provenance(self, tmp_path):
        repo = ModelRepo(str(tmp_path / "repo"))
        repo.publish("mlp", mlp_bundle(seed=0))  # v1 = CURRENT
        pub = Publisher(self.policy(tmp_path), str(tmp_path / "svc"),
                        run_id="train-run", train_journal="tj.jsonl")
        rec = pub.on_complete(0, result_dict(tmp_path,
                                             [1.0, 0.8, 0.5, 0.4]))
        assert rec is not None and rec["version"] == 2 and rec["dark"]
        assert repo.current_version("mlp") == 1  # dark: CURRENT held
        _, info = repo.load("mlp", 2)
        assert info.provenance["checkpoint_step"] == 16
        assert info.provenance["run_id"] == "train-run"
        assert info.provenance["eval"]["metric"] == pytest.approx(0.45)
        assert info.provenance["train_journal"] == "tj.jsonl"
        assert journal_kinds(pub.journal.path) == ["publish"]

    def test_reject_is_journaled_not_published(self, tmp_path):
        pub = Publisher(self.policy(tmp_path), str(tmp_path / "svc"),
                        run_id="r")
        rec = pub.on_complete(0, result_dict(tmp_path,
                                             [0.4, 0.4, 0.5, 0.6]))
        assert rec is None and pub.ledger.rejects == 1
        assert ModelRepo(str(tmp_path / "repo")).versions("mlp") == []
        assert journal_kinds(pub.journal.path) == ["publish_reject"]

    def test_torn_publish_is_pending_then_retried(self, tmp_path):
        pub = Publisher(self.policy(tmp_path), str(tmp_path / "svc"),
                        run_id="r")
        plan = FaultPlan([FaultSpec("repo_torn_publish", model="mlp")])
        with faults.inject(plan):
            rec = pub.on_complete(0, result_dict(tmp_path,
                                                 [1.0, 0.8, 0.5, 0.4]))
        assert rec is None
        repo = ModelRepo(str(tmp_path / "repo"))
        assert repo.versions("mlp") == []  # nothing partial visible
        rec = pub.retry_pending()
        assert rec is not None and rec["version"] == 1
        assert journal_kinds(pub.journal.path) == ["publish_torn",
                                                   "publish"]
        assert pub.retry_pending() is None

    def test_bundle_from_npz_rebuilds_the_tree(self, tmp_path):
        src = mlp_bundle(seed=3)
        flat = {}

        def walk(node, prefix):
            for k, v in node.items():
                if isinstance(v, dict):
                    walk(v, prefix + [k])
                else:
                    flat["/".join(prefix + [k])] = np.asarray(v)
        walk(src.params, [])
        npz = tmp_path / "params.npz"
        np.savez(npz, **flat)
        rebuilt = bundle_from_npz(
            {"params_npz": str(npz)}, MLP(features=(8,), num_outputs=4),
            input_spec=(6,), output_names=("features", "logits"))
        la = jax.tree_util.tree_leaves(src.params)
        lb = jax.tree_util.tree_leaves(rebuilt.params)
        assert len(la) == len(lb)
        assert all(np.array_equal(a, b) for a, b in zip(la, lb))


# ------------------------------------------------------------ deployer

class ScriptedTarget:
    """A rollout target whose observations come off a script — the
    Deployer's mechanics (stages, journal, repo flips) isolated from
    any real serve plane."""

    def __init__(self, script=None):
        self.script = list(script or [])
        self.calls = []

    def _next(self):
        return self.script.pop(0) if self.script else {}

    def begin(self, repo, rollout, stage, fraction, tolerance,
              fast_burn):
        self.calls.append(("begin", stage, fraction))

    def observe(self, rollout, stage):
        bits = {"serve": CanarySignal(burn_short=0.0), "action": None,
                "converged": True, "lagging": (), "healthy": True}
        bits.update(self._next())
        return bits

    def promote(self, rollout):
        self.calls.append(("promote", rollout.version))

    def rollback(self, rollout, reason):
        self.calls.append(("rollback", rollout.version, reason))


class TestDeployer:
    def deployer(self, tmp_path, target, **kw):
        kw.setdefault("policy", RolloutPolicy(advance_after=1))
        return Deployer(str(tmp_path / "lc"), str(tmp_path / "repo"),
                        target, refs={"train_journal": "tj.jsonl"}, **kw)

    def test_happy_path_promotes_and_flips_current(self, tmp_path):
        repo = ModelRepo(str(tmp_path / "repo"))
        repo.publish("mlp", mlp_bundle(seed=0))              # v1 live
        repo.publish("mlp", mlp_bundle(seed=1),
                     provenance=good_provenance(),
                     set_current=False)                      # v2 dark
        target = ScriptedTarget()
        dep = self.deployer(tmp_path, target)
        rollout = dep.start_rollout("mlp", version=2)
        assert rollout.prior_version == 1
        outcome = dep.run(rollout, tick_s=0.0, timeout_s=10.0)
        assert outcome == "promoted"
        assert repo.current_version("mlp") == 2
        assert [c[0] for c in target.calls] == ["begin", "begin",
                                                "promote"]
        assert journal_kinds(dep.journal.path) == [
            "rollout", "stage", "stage", "stage", "promote"]

    def test_torn_publish_mid_tick_holds_then_retries(self, tmp_path):
        repo = ModelRepo(str(tmp_path / "repo"))
        repo.publish("mlp", mlp_bundle(seed=0))              # v1 live
        dep = self.deployer(tmp_path, ScriptedTarget())
        rollout = dep.start_rollout("mlp", bundle=mlp_bundle(seed=1),
                                    provenance=good_provenance())
        plan = FaultPlan([FaultSpec("repo_torn_publish", model="mlp")])
        with faults.inject(plan):
            out = dep.tick(rollout)
        # the tear is invisible: no new version, CURRENT untouched,
        # the rollout holds in the publish stage
        assert out["action"] == "publish_torn"
        assert rollout.ledger.stage == "publish"
        assert rollout.version is None
        assert repo.versions("mlp") == [1]
        assert repo.current_version("mlp") == 1
        # the next tick re-publishes cleanly and the rollout proceeds
        out = dep.tick(rollout)
        assert out["action"] == "publish" and out["version"] == 2
        assert repo.current_version("mlp") == 1  # still dark
        assert dep.run(rollout, tick_s=0.0, timeout_s=10.0) \
            == "promoted"
        assert repo.current_version("mlp") == 2

    def test_burn_aborts_and_rolls_back_both_sides(self, tmp_path):
        repo = ModelRepo(str(tmp_path / "repo"))
        repo.publish("mlp", mlp_bundle(seed=0))
        repo.publish("mlp", mlp_bundle(seed=1), set_current=False)
        target = ScriptedTarget(script=[
            {"serve": CanarySignal(burn_short=99.0)}])
        dep = self.deployer(tmp_path, target)
        rollout = dep.start_rollout("mlp", version=2)
        outcome = dep.run(rollout, tick_s=0.0, timeout_s=10.0)
        assert outcome == "rolled_back"
        assert repo.current_version("mlp") == 1
        assert ("rollback", 2) == target.calls[-1][:2]
        kinds = journal_kinds(dep.journal.path)
        assert kinds[0] == "rollout" and kinds[-1] == "rollback"

    def test_replay_reconstructs_the_journeys(self, tmp_path):
        repo = ModelRepo(str(tmp_path / "repo"))
        repo.publish("mlp", mlp_bundle(seed=0))
        repo.publish("mlp", mlp_bundle(seed=1), set_current=False)
        dep = self.deployer(tmp_path, ScriptedTarget())
        r1 = dep.start_rollout("mlp", version=2)
        dep.run(r1, tick_s=0.0, timeout_s=10.0)
        dep2 = self.deployer(
            tmp_path, ScriptedTarget(
                script=[{"serve": CanarySignal(burn_short=99.0)}]))
        r2 = dep2.start_rollout("mlp", bundle=mlp_bundle(seed=2))
        dep2.run(r2, tick_s=0.0, timeout_s=10.0)
        replayed = replay_decisions(dep.journal.path)
        assert [r["outcome"] for r in replayed] == ["promoted",
                                                    "rolled_back"]
        assert replayed[0]["version"] == 2
        assert replayed[0]["stages"] == ["shadow", "canary",
                                         "promoting"]
        assert replayed[1]["version"] == 3  # filled by its publish
        assert replayed[1]["prior_version"] == 2

    def test_admission_needs_exactly_one_source(self, tmp_path):
        dep = self.deployer(tmp_path, ScriptedTarget())
        with pytest.raises(ValueError):
            dep.start_rollout("mlp")
        with pytest.raises(ValueError):
            dep.start_rollout("mlp", version=1,
                              bundle=mlp_bundle())


# -------------------------------------------------------- fleet target

def write_beacon(d, bid, versions, status="running", burn=0.0):
    from mmlspark_tpu.service.core import atomic_write_json
    atomic_write_json(os.path.join(d, f"beacon_{bid}.json"), {
        "rank": bid, "status": status, "host": "127.0.0.1",
        "port": 9000 + bid, "burn_short": burn, "versions": versions})


class TestFleetTarget:
    def test_canary_scope_then_fleet_wide_promotion(self, tmp_path):
        from mmlspark_tpu.lifecycle import Rollout
        d = str(tmp_path)
        write_beacon(d, 0, {"mlp": 1})
        write_beacon(d, 1, {"mlp": 1})
        target = FleetTarget(d, "/repo", canary_backends=1)
        rollout = Rollout(model="mlp", version=2, prior_version=1)
        target.begin(None, rollout, "canary", 0.5, None, 14.0)
        with open(os.path.join(d, "deploy.json")) as f:
            cmd = json.load(f)
        assert cmd == {"seq": 1, "model": "mlp", "version": 2,
                       "repo": "/repo", "backends": [0]}
        # scoped backend still on v1 → lagging, no canary evidence
        bits = target.observe(rollout, "canary")
        assert bits["lagging"] == (0,) and bits["serve"] is None
        # it applies the swap → converged, burn evidence flows
        write_beacon(d, 0, {"mlp": 2}, burn=0.5)
        bits = target.observe(rollout, "canary")
        assert bits["converged"] and bits["lagging"] == ()
        assert bits["serve"].burn_short == 0.5
        # promotion re-targets the whole fleet and blocks on backend 1
        target.promote(rollout)
        with open(os.path.join(d, "deploy.json")) as f:
            assert json.load(f)["backends"] == "all"
        bits = target.observe(rollout, "promoting")
        assert bits["lagging"] == (1,) and not bits["converged"]
        write_beacon(d, 1, {"mlp": 2})
        assert target.observe(rollout, "promoting")["converged"]

    def test_rollback_recommands_the_prior_version(self, tmp_path):
        from mmlspark_tpu.lifecycle import Rollout
        d = str(tmp_path)
        write_beacon(d, 0, {"mlp": 2})
        target = FleetTarget(d, "/repo")
        rollout = Rollout(model="mlp", version=2, prior_version=1)
        target.begin(None, rollout, "canary", 0.5, None, 14.0)
        target.rollback(rollout, "burn")
        with open(os.path.join(d, "deploy.json")) as f:
            cmd = json.load(f)
        assert cmd["version"] == 1 and cmd["backends"] == "all"
        assert cmd["seq"] == 2  # monotonic across commands

    def test_dead_backend_reads_unhealthy(self, tmp_path):
        from mmlspark_tpu.lifecycle import Rollout
        d = str(tmp_path)
        write_beacon(d, 0, {"mlp": 1})
        target = FleetTarget(d, "/repo")
        rollout = Rollout(model="mlp", version=2, prior_version=1)
        target.begin(None, rollout, "canary", 0.5, None, 14.0)
        write_beacon(d, 0, {"mlp": 2}, status="exited")
        bits = target.observe(rollout, "canary")
        assert not bits["healthy"] and not bits["converged"]

"""Tier-1 wiring of tools/perf_smoke.py: the planner must fuse the
canonical image pipeline into exactly one H2D upload and one async D2H
fetch round per minibatch (counted at the planner's crossing seams)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from perf_smoke import check_fused_crossings  # noqa: E402


def test_canonical_image_pipeline_fuses_to_one_round_trip():
    result = check_fused_crossings()
    assert result["h2d_uploads"] == result["minibatches"]
    assert result["d2h_fetch_rounds"] == result["minibatches"]
    assert result["segments"] == [("device", 3)]

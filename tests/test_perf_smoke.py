"""Tier-1 wiring of tools/perf_smoke.py: the planner must fuse the
canonical image pipeline into exactly one H2D upload and one async D2H
fetch round per minibatch (counted at the planner's crossing seams), the
train input pipeline must actually commit batches ahead of consumption
(counted at the DeviceLoader's producer/consumer seams), and the model
server must quantize a request burst onto its bucket ladder (compiles
bounded by the ladder, mean occupancy > 1 — counted at the jit compile
cache and the dispatch-shape seam)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from perf_smoke import (  # noqa: E402
    check_compile_cache, check_concurrency_clean, check_fleet_obs,
    check_fused_crossings, check_flight_recorder, check_obs_overhead,
    check_obs_request_tracing, check_serve_batching,
    check_serve_fleet, check_serve_generate, check_serve_lifecycle,
    check_serve_lowprec, check_serve_sharded,
    check_spmd_clean, check_train_device_preprocess, check_train_elastic,
    check_train_prefetch, check_train_to_serve,
)


def test_canonical_image_pipeline_fuses_to_one_round_trip():
    result = check_fused_crossings()
    assert result["h2d_uploads"] == result["minibatches"]
    assert result["d2h_fetch_rounds"] == result["minibatches"]
    assert result["segments"] == [("device", 3)]


def test_train_loader_commits_ahead_of_consumption():
    result = check_train_prefetch()
    assert result["committed_ahead_max"] >= result["prefetch_depth"]
    assert result["batches"] == result["steps"]
    assert 0.0 <= result["input_bound_fraction"] <= 1.0


def test_train_device_preprocess_ships_thin_and_replays_exactly():
    """On-device preprocessing (round 10): full-augment thin-wire
    training ships >= 4x fewer H2D image bytes than the host-preprocess
    baseline (obs registry counters at the train_commit seam), loss
    histories agree to <= 1e-5 across the wire forms, exactly one step
    program compiles per input shape, a mid-epoch resume replays the
    augmentation stream bit-identically, and the Pallas fused-geometry
    kernel stays <= 1 ULP from its XLA reference in interpret mode."""
    result = check_train_device_preprocess()
    assert result["h2d_reduction"] >= result["min_reduction"]
    assert result["loss_history_max_diff"] <= 1e-5
    assert result["programs_thin"] in (None, 1)
    assert result["resume_history_len"] == result["steps"] - 7
    assert result["wire_mb_thin"] < result["wire_mb_host"]


def test_train_elastic_recovery_is_bit_compatible():
    """Elastic fault tolerance (round 11): an induced worker kill on the
    dryrun mesh is detected by the supervisor, policy re-scales onto the
    surviving topology (8 -> 4 devices, fsdp state re-sharded from the
    checkpoint), ingest stays on the deterministic elastic walk, and the
    completed run's loss tail + final params are bit-identical to an
    uninterrupted continuation at the surviving topology; dead workers'
    heartbeat rows are forgotten and no service/loader threads leak."""
    result = check_train_elastic()
    assert result["generations"] == 2
    assert result["rescales"] == 1 and result["evictions"] == 1
    assert result["topology_survivors"] == {"world": 1, "devices": 4}
    assert result["mesh_survivors"] == {"dp": 2, "fsdp": 2}
    assert result["resumed_step"] >= 1
    assert result["tail_max_diff"] == 0.0
    assert result["params_bit_identical"] is True
    assert "rescale" in result["decision_kinds"]


def test_obs_disabled_path_overhead_bounded():
    result = check_obs_overhead()
    assert result["overhead_fraction_bound"] < result["max_fraction"]
    assert result["spans_when_enabled"] > 0  # the seams actually exist


def test_obs_request_tracing_links_intact_across_replica_lanes():
    """Request-scoped tracing: a 200-request burst over dp=4 replica
    lanes yields exactly one trace per completed request with the
    admission -> pack -> dispatch -> drain -> complete links intact,
    real fan-in on the bucket-batch spans, all four lanes used, and one
    exported Perfetto flow per request."""
    result = check_obs_request_tracing()
    assert result["traces"] == result["requests"] == 200
    assert result["intact"] == result["requests"]
    assert result["replicas_used"] == [0, 1, 2, 3]
    assert result["max_pack_fan_in"] > 1
    assert result["flow_ids_exported"] == result["requests"]


def test_fleet_obs_merges_bit_equal_and_renders_aligned_timeline():
    """Fleet telemetry plane (round 17): a dp=4 serve burst plus a
    2-worker supervised run under one MMLSPARK_TPU_FLEET dir merge into
    fleet counters bit-equal to the summed per-process registries, the
    clock-aligned fleet Perfetto trace renders exit-0 through
    tools/trace.py with >= 1 flow stitched at the fence seams, the
    supervisor aggregates worker beacon deltas into train.fleet.*, and
    every serve.slo_burn_* gauge has >= 3 timeseries history samples;
    no exporter/sampler threads survive teardown."""
    result = check_fleet_obs()
    assert result["processes"] == 3  # this process + 2 workers
    assert result["serve_counters"] > 0 and result["train_counters"] > 0
    assert result["stitched_flows"] >= 1
    assert result["trace_render_rc"] == 0
    assert result["fleet_steps_rank0"] == 24
    for gauge, series in result["burn_gauge_history"].items():
        assert series and all(n >= 3 for n in series.values()), (
            f"{gauge}: {series}")


def test_serve_fleet_survives_kill_and_scales_bit_identical():
    """Fleet serving tier (round 19): two supervised serve backends
    behind the router, warmed from the compile cache the single-process
    reference published; kill -9 one mid-burst — zero dropped requests
    and every router answer bit-identical to single-process serving; an
    induced fast-burn scales a third backend up whose beacon proves
    zero fresh XLA compiles (pure cache warm); restart + scale_up land
    in decisions.jsonl; the router's counters merge bit-equal into the
    fleet view; no router/supervisor/exporter threads leak."""
    result = check_serve_fleet()
    assert result["burst_errors"] == 0
    assert result["bit_identical"] is True
    assert result["scaled_backend_cache"]["compiles"] == 0
    assert result["scaled_backend_cache"]["hits"] >= 1
    assert "restart" in result["journal_kinds"]
    assert "scale_up" in result["journal_kinds"]
    assert result["scale_ups"] >= 1
    assert result["router_counters"]["reroutes"] >= 1
    assert result["fleet_processes"] >= 2


def test_flight_recorder_dumps_on_crash_and_hang():
    """Forensics contract: an induced NaN-loss crash inside
    Trainer.fit_arrays and a serve-lane dispatch stalled past the hang
    threshold each produce a well-formed flight-recorder dump (intact
    ring, per-thread stacks, registry snapshot) that
    tools/trace.py postmortem renders; the hang dump names the lane."""
    result = check_flight_recorder()
    assert result["crash_exception"] == "NonFiniteLossError"
    assert result["crash_ring_records"] > 0
    assert result["crash_threads"] >= 1
    assert result["hang_heartbeat"].startswith("serve/")
    assert result["hang_stalled_for_s"] >= 0.3
    assert result["hang_threads"] >= 2


def test_spmd_verifier_and_lint_are_clean():
    """The symbolic SPMD verifier (parallel-layer contracts, partial-sum
    escapes, capacity/divisibility, fences), the multi-chip plan audit,
    and the JX lint (incl. JX201–JX204) all gate at zero findings."""
    result = check_spmd_clean()
    assert result["findings"] == 0
    assert result["shard_map_sites"] >= 4  # every parallel entry point
    assert result["plan_segments"] == 1
    # the declared contracts actually communicate (a schedule that went
    # empty means the extractor silently lost the collectives)
    assert result["collectives"]["moe_apply"].get("psum_scatter") == 1
    assert result["collectives"]["pipeline_apply"].get("ppermute") == 1


def test_concurrency_verifier_clean_and_witnessed():
    """The whole-repo concurrency verifier gates at zero unsuppressed
    findings inside its wall budget, the runtime lock-order witness
    confirms the static graph on a dp=4 serve burst (no inversions),
    and the witness's disabled path stays under the obs cost bound."""
    result = check_concurrency_clean()
    assert result["findings"] == 0
    assert result["violations"] == 0
    assert result["confirmed"] >= 5
    # every hot subsystem contributes locks to the inventory — a pass
    # that stops seeing them would trivially "confirm" nothing
    assert result["locks"] >= 20
    assert result["static_edges"] >= 10
    assert result["overhead_fraction_bound"] < result["max_fraction"]


def test_serve_burst_compiles_bounded_and_coalesces():
    result = check_serve_batching()
    assert result["programs_compiled"] is None \
        or result["programs_compiled"] <= len(result["buckets"])
    assert result["distinct_batch_shapes"] <= len(result["buckets"])
    assert result["batch_occupancy_mean"] > 1.0


def test_serve_compile_cache_warm_starts_without_compiling():
    """Persistent AOT compile cache (round 18): a cold load publishes
    every compiled bucket program to the cache dir; a second cold-start
    PROCESS deserializes all of them (zero fresh XLA compiles, counted
    at the cache stats, the jit-cache hook, and the obs
    plan.compile_cache.hits counter), serves bit-identical outputs, and
    loads with a measurably smaller warm wall."""
    result = check_compile_cache()
    assert result["cold"]["puts"] >= 1
    assert result["cold"]["puts"] <= len(result["buckets"])
    assert result["warm"]["compiles"] == 0
    assert result["warm"]["hits"] == result["cold"]["puts"]
    assert result["bit_identical"] is True
    assert result["warm_wall_s"] < result["cold_wall_s"]


def test_serve_lowprec_parity_programs_and_audit():
    """Low-precision serving (round 12): an int8w+bf16-served model's
    outputs stay within its pinned tolerance of the f32 offline
    transform across packings, the load-time calibration measured a
    real parity, compiled programs stay <= len(buckets) per
    (model, precision), quantized params ship <= 0.35x the f32 bytes,
    and audit_plan_spmd verifies the quantized segment clean."""
    result = check_serve_lowprec()
    assert 0 < result["serve_parity_max_abs"] <= result["pinned_tolerance"]
    assert 0 < result["calibration_parity"] <= result["pinned_tolerance"]
    assert result["programs_compiled"] is None \
        or result["programs_compiled"] <= len(result["buckets"])
    assert result["distinct_batch_shapes"] <= len(result["buckets"])
    assert result["weight_bytes_ratio"] <= 0.35
    assert result["audit_findings"] == 0
    assert result["audit_collectives"] == 0


def test_serve_lifecycle_survives_seeded_chaos():
    """Zero-downtime lifecycle (round 13): under the seeded fault plan
    a lane kill mid-burst self-heals (1 death, 1 restart, work
    requeued, every response delivered and bit-identical to the stable
    offline transform), a hot-swap mid-burst answers from both versions
    with nothing dropped, the induced canary fast-burn auto-rolls back
    through the pure PromotionPolicy with the decision journaled, and
    compiled programs stay on the ladder per (model, version)."""
    result = check_serve_lifecycle()
    lane = result["lane_kill"]
    assert lane["responses"] == 32
    assert lane["lane_deaths"] == 1 and lane["lane_restarts"] == 1
    assert lane["faults_fired"] == {"lane_death": 1}
    swap = result["hot_swap"]
    assert swap["served_v1"] > 0 and swap["served_v2"] >= 4
    assert swap["served_v1"] + swap["served_v2"] == swap["responses"]
    for key in ("programs_v1", "programs_v2"):
        programs = (lane if key == "programs_v1" else swap)[key]
        assert programs is None or programs <= len(result["buckets"])
    canary = result["canary"]
    assert canary["burn_short"] >= 14.0
    assert "rollback" in canary["decision_kinds"]
    assert "swap" in canary["decision_kinds"]
    assert "lane_restart" in canary["decision_kinds"]


def test_train_to_serve_deploys_gated_checkpoints_end_to_end():
    """Continuous deployment (round 20): a supervised fine-tune's
    eval-gated checkpoint is dark-published with provenance and driven
    by the deployer through shadow -> canary -> promoted under live
    traffic (repo CURRENT flipped, every answer bit-identical to a
    published version's offline transform, zero drops); a degraded run
    dark-publishes but rolls back on shadow parity drift with CURRENT
    pinned to the good version; the journey journals across train +
    serve + lifecycle decisions, replays from the lifecycle journal
    alone, and stitches >= 1 flow at the publish-fence seam."""
    result = check_train_to_serve()
    assert result["outcomes"] == ["promoted", "rolled_back"]
    assert result["versions"] == [1, 2, 3]
    assert result["current"] == 2  # promoted v2; v3 rolled back
    assert result["dropped"] == 0 and result["responses"] > 0
    assert result["rollouts"] == 2 and result["rollbacks"] == 1
    assert result["deploy_wall_s"] > 0
    assert result["provenance_v2"]["checkpoint_step"] == 16
    assert result["stitched_flows"] >= 1
    for kind in ("publish", "rollout", "stage", "promote", "rollback"):
        assert kind in result["lifecycle_kinds"]


def test_serve_generate_streams_bit_identical_and_batches():
    """Autoregressive token serving (round 18): a streaming burst with
    seeded join/leave churn delivers every token stream bit-identical
    to the one-shot whole-sequence decode (cancelled streams exact
    prefixes), compiled programs stay <= len(prefill_buckets) + 1 (ONE
    fixed-shape decode program), TTFT/ITL gauges reach /slo and the
    timeseries MetricHistory, no engine threads leak, and continuous
    batching sustains >= 2x the request-serial tokens/s on a
    latency-bound decode with >= 2x fewer decode dispatches."""
    result = check_serve_generate()
    burst = result["burst"]
    assert burst["cancelled"] >= 1
    assert burst["programs_compiled"] is None \
        or burst["programs_compiled"] <= burst["program_budget"]
    assert burst["ttft_ms"]["p50"] > 0 and burst["itl_ms"]["p99"] > 0
    for gauge, series in burst["slo_gauge_history"].items():
        assert series and all(n >= 3 for n in series.values()), (
            f"{gauge}: {series}")
    tp = result["throughput"]
    assert tp["speedup"] >= tp["min_speedup"]
    assert tp["step_ratio"] >= 2.0
    assert tp["batched"]["tokens"] == tp["serial"]["tokens"]


def test_serve_dp_replica_fanout_multiplies_throughput():
    """Sharded serving: dp=4 replica fan-out on the 8-device dryrun mesh
    sustains >= 2.5x the dp=1 throughput on a latency-bound model, with
    bit-identical outputs, every replica used, and the compiled-program
    count per model (not per replica x buckets) still on the ladder."""
    result = check_serve_sharded()
    assert result["speedup"] >= result["min_speedup"]
    assert result["dp4"]["replicas_used"] == [0, 1, 2, 3]
    for key in ("dp1", "dp4"):
        programs = result[key]["programs_compiled"]
        assert programs is None or programs <= 1

"""Tier-1 wiring of tools/lint_jax.py.

Two gates: the codebase itself must be clean (zero findings after the
curated allowlist — DEFAULT_ALLOWLIST documents every intentional
exception), and a fixture seeded with each anti-pattern must yield
exactly the expected findings (the lint finds what it claims to find).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from lint_jax import (  # noqa: E402
    DEFAULT_ALLOWLIST, lint_paths, lint_source, lint_source_full,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_codebase_is_clean():
    findings = lint_paths([os.path.join(REPO, "mmlspark_tpu")])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_allowlist_is_curated_not_dead():
    # every allowlist entry must still suppress something real — a stale
    # entry silently widens the gate
    for suffix, rules in DEFAULT_ALLOWLIST.items():
        path = os.path.join(REPO, suffix)
        assert os.path.exists(path), f"allowlisted file {suffix} is gone"
        raw = lint_paths([path], allowlist={})
        hit_rules = {f.rule for f in raw}
        for rule in rules:
            assert rule in hit_rules, (
                f"allowlist entry ({suffix}, {rule}) suppresses nothing")


FIXTURE = '''
import jax
import numpy as np
from functools import partial
from jax.experimental.shard_map import shard_map          # JX103
from mmlspark_tpu.core.params import Param


class BadStage:
    tags = Param(default=[], doc="mutable default")       # JX104


@jax.jit
def step(params, x):
    y = np.asarray(x) + 1                                 # JX101
    s = float(x.sum())                                    # JX101
    return y, s, x.item()                                 # JX101


@partial(jax.jit, static_argnums=(1,))
def step2(x, k):
    return x.tolist()                                     # JX101


def fit(batches):
    for b in batches:
        f = jax.jit(lambda p, v: v + b)                   # JX102
    g = jax.shard_map(step, None, None, None)             # JX103
    h = getattr(jax, "shard_map")                         # JX103
    return f, g, h


def traced_by_name(params, x):
    return int(x[0])                                      # JX101


jitted = jax.jit(traced_by_name)


def train(batches, state, step_masked):
    history = []
    for b in batches:
        state, metrics = step_masked(state, b)
        history.append(float(metrics["loss"]))            # JX105
        pending = metrics["loss"]
        x = float(pending)                                # JX105
        y = metrics["loss"].item()                        # JX105
    final = float(metrics["loss"])  # after the loop: drains, no stall
    return state, history, x, y, final


def serve_loop(batches, dispatch_async):
    results = []
    for b in batches:
        outs = dispatch_async(b)
        results.append(np.asarray(outs))              # JX106
        v = float(outs)                               # JX106
        w = outs.item()                               # JX106
    return results, v, w


def host_side_is_fine(x):
    # not jitted: host syncs here are intentional and unflagged
    return float(np.asarray(x).sum())


@jax.jit
def allowed(params, x):
    return x.item()  # lint-jax: allow(JX101)
'''


def test_fixture_yields_exactly_the_seeded_findings():
    findings = lint_source(FIXTURE, "fixture.py")
    got = sorted((f.rule, f.line) for f in findings)
    lines = FIXTURE.splitlines()
    want = sorted(
        (rule, i + 1)
        for i, text in enumerate(lines)
        for rule in ("JX101", "JX102", "JX103", "JX104", "JX105", "JX106")
        if f"# {rule}" in text)
    assert got == want, (got, want)


def test_shim_surface_is_not_flagged():
    # calling THROUGH the compat shim is what JX103 tells you to do; the
    # rule must only fire on jax-rooted spellings
    src = ("from mmlspark_tpu.parallel import mesh as mesh_lib\n"
           "def f(body, m, i, o):\n"
           "    return mesh_lib.shard_map(body, m, i, o)\n")
    assert lint_source(src, "x.py") == []
    src2 = "import jax\ng = jax.shard_map(None, None, None, None)\n"
    assert [f.rule for f in lint_source(src2, "x.py")] == ["JX103"]


def test_jx105_lagged_fetch_is_clean():
    # the one-step-lagged idiom: record the device scalar in the loop,
    # resolve it AFTER (or pragma the in-loop resolution of the previous
    # step's scalar, as train/loop.py does)
    src = ("def fit(batches, state, step):\n"
           "    for b in batches:\n"
           "        state, m = step(state, b)\n"
           "        pending = m['loss']\n"
           "    return float(pending)\n")
    assert lint_source(src, "x.py") == []
    src_sync = src.replace("        pending = m['loss']\n",
                           "        v = float(m['loss'])\n")
    assert [f.rule for f in lint_source(src_sync, "x.py")] == ["JX105"]


def test_jx105_pragma_suppresses():
    src = ("def fit(batches, state, step):\n"
           "    for b in batches:\n"
           "        state, m = step(state, b)\n"
           "        v = float(m['loss'])  # lint-jax: allow(JX105)\n"
           "    return v\n")
    assert lint_source(src, "x.py") == []


def test_jx105_ignores_non_step_calls():
    # scalar fetches on values from non-step calls in a loop are host-side
    # bookkeeping, not a pipeline stall — out of JX105's scope
    src = ("def walk(rows, measure):\n"
           "    total = 0.0\n"
           "    for r in rows:\n"
           "        v = measure(r)\n"
           "        total += float(v)\n"
           "    return total\n")
    assert lint_source(src, "x.py") == []


def test_jx106_windowed_drain_is_clean():
    # the sanctioned serve idiom (serve/batcher.py): push the dispatched
    # handle through a bounded window and fetch the OLDEST entry — the
    # fetch target comes off the window, not the fresh dispatch, so
    # packing of batch i+1 overlaps compute of batch i
    src = ("import numpy as np\n"
           "from collections import deque\n"
           "def serve(batches, transform_async):\n"
           "    window = deque()\n"
           "    for b in batches:\n"
           "        pending = transform_async(b)\n"
           "        window.append(pending)\n"
           "        if len(window) >= 2:\n"
           "            oldest = window.popleft()\n"
           "            out = np.asarray(oldest)\n"
           "    return [np.asarray(p) for p in window]\n")
    assert lint_source(src, "x.py") == []
    # the anti-pattern: immediate full-batch fetch of the fresh dispatch
    src_sync = ("import numpy as np\n"
                "def serve(batches, transform_async):\n"
                "    out = []\n"
                "    for b in batches:\n"
                "        pending = transform_async(b)\n"
                "        out.append(np.asarray(pending))\n"
                "    return out\n")
    assert [f.rule for f in lint_source(src_sync, "x.py")] == ["JX106"]


def test_jx106_pragma_suppresses_and_ignores_plain_calls():
    src = ("import numpy as np\n"
           "def serve(batches, dispatch):\n"
           "    for b in batches:\n"
           "        outs = dispatch(b)\n"
           "        v = float(outs)  # lint-jax: allow(JX106)\n"
           "    return v\n")
    assert lint_source(src, "x.py") == []
    # fetches on values from non-dispatch calls are host bookkeeping
    src_ok = ("import numpy as np\n"
              "def walk(rows, score):\n"
              "    total = 0.0\n"
              "    for r in rows:\n"
              "        v = score(r)\n"
              "        total += float(np.asarray(v))\n"
              "    return total\n")
    assert lint_source(src_ok, "x.py") == []


def test_jx109_lagged_decode_fetch_is_clean():
    # the serve/generate.py discipline: dispatch step t+1, then consume
    # step t's output — the in-loop fetch target is the PREVIOUS
    # dispatch, so device decode overlaps host token fan-out
    src = ("import numpy as np\n"
           "def loop(engine, steps, bufs):\n"
           "    prev = None\n"
           "    for _ in range(steps):\n"
           "        bufs, out = engine._decode.dispatch(bufs)\n"
           "        if prev is not None:\n"
           "            toks = np.asarray(prev)  # lint-jax: allow(JX109)\n"
           "        prev = out\n"
           "    return np.asarray(prev)\n")
    assert lint_source(src, "x.py") == []
    # the anti-pattern: fetch the CURRENT step's tokens before the next
    # dispatch — every token pays a full device round-trip
    src_sync = ("import numpy as np\n"
                "def loop(engine, steps, bufs):\n"
                "    for _ in range(steps):\n"
                "        bufs, out = engine._decode.dispatch(bufs)\n"
                "        toks = np.asarray(out)\n"
                "    return toks\n")
    assert [f.rule for f in lint_source(src_sync, "x.py")] == ["JX109"]


def test_jx109_matches_full_dotted_spelling():
    # JX109's source predicate sees the WHOLE dotted call spelling —
    # "self._decode.jitted" is decode-flavored even though the leaf
    # attribute ("jitted") says nothing about decoding
    src = ("import numpy as np\n"
           "def loop(self, steps, bufs, carry):\n"
           "    for _ in range(steps):\n"
           "        bufs, carry = self._decode.jitted(bufs, carry)\n"
           "        tok = int(np.asarray(carry)[0])\n"
           "    return tok\n")
    assert [f.rule for f in lint_source(src, "x.py")] == ["JX109"]


def test_jx109_wins_over_jx105_and_jx106_on_decode_calls():
    # "decode_step" is both step- and decode-flavored; "decode_dispatch"
    # both dispatch- and decode-flavored — one site, one rule: the
    # decode-aware JX109 claims them and JX105/JX106 stand down
    src = ("def gen(state, steps, decode_step):\n"
           "    for _ in range(steps):\n"
           "        state, tok = decode_step(state)\n"
           "        t = int(tok)\n"
           "    return t\n")
    assert [f.rule for f in lint_source(src, "x.py")] == ["JX109"]
    src2 = ("import numpy as np\n"
            "def gen(bufs, steps, decode_dispatch):\n"
            "    for _ in range(steps):\n"
            "        out = decode_dispatch(bufs)\n"
            "        toks = np.asarray(out)\n"
            "    return toks\n")
    assert [f.rule for f in lint_source(src2, "x.py")] == ["JX109"]


def test_jx109_pragma_suppresses_and_ignores_plain_calls():
    src = ("import numpy as np\n"
           "def loop(engine, steps, bufs):\n"
           "    for _ in range(steps):\n"
           "        bufs, out = engine.decode(bufs)\n"
           "        toks = np.asarray(out)  # lint-jax: allow(JX109)\n"
           "    return toks\n")
    assert lint_source(src, "x.py") == []
    # fetches on values from non-decode calls stay out of JX109's scope
    src_ok = ("import numpy as np\n"
              "def walk(rows, score):\n"
              "    for r in rows:\n"
              "        v = score(r)\n"
              "        s = float(np.asarray(v))\n"
              "    return s\n")
    assert lint_source(src_ok, "x.py") == []


JX107_FLAGGED = '''
import cv2
from mmlspark_tpu.native import imgops
from mmlspark_tpu.train import DeviceLoader, DevicePreprocess


def fit(batches, state, step_masked):
    for b in batches:
        img = imgops.resize(b, 32, 32)                # JX107
        raw = cv2.imdecode(b, 1)                      # JX107
        state, m = step_masked(state, img, raw)
    return state


def producer(chunks):
    for c in chunks:
        yield imgops.resize(c, 32, 32)                # JX107


def run(chunks, commit):
    return DeviceLoader(producer(chunks), commit, depth=2)
'''


def test_jx107_flags_host_image_work_when_spec_active():
    findings = lint_source(JX107_FLAGGED, "fixture107.py")
    got = sorted((f.rule, f.line) for f in findings)
    lines = JX107_FLAGGED.splitlines()
    want = sorted(("JX107", i + 1) for i, text in enumerate(lines)
                  if "# JX107" in text)
    assert got == want, (got, want)


def test_jx107_clean_counterparts():
    # 1) the same host image work with NO DevicePreprocess in the module:
    #    the legacy host-preprocess path is legitimate, not a finding
    clean = JX107_FLAGGED.replace(
        "from mmlspark_tpu.train import DeviceLoader, DevicePreprocess",
        "from mmlspark_tpu.train import DeviceLoader")
    assert lint_source(clean, "x.py") == []
    # 2) spec active, but the resize happens OUTSIDE the step loop /
    #    producer (one-off warmup, eval-time thumbnailing): clean
    src = ("from mmlspark_tpu.train import DevicePreprocess\n"
           "from mmlspark_tpu.native import imgops\n"
           "def thumbnail(img):\n"
           "    return imgops.resize(img, 8, 8)\n"
           "def fit(batches, state, step):\n"
           "    for b in batches:\n"
           "        state, m = step(state, b)\n"
           "    return state\n")
    assert lint_source(src, "x.py") == []
    # 3) pragma suppresses
    src_pragma = JX107_FLAGGED.replace(
        "imgops.resize(b, 32, 32)                # JX107",
        "imgops.resize(b, 32, 32)  # lint-jax: allow(JX107)").replace(
        "cv2.imdecode(b, 1)                      # JX107",
        "cv2.imdecode(b, 1)  # lint-jax: allow(JX107)").replace(
        "imgops.resize(c, 32, 32)                # JX107",
        "imgops.resize(c, 32, 32)  # lint-jax: allow(JX107)")
    assert lint_source(src_pragma, "x.py") == []


JX108_FLAGGED = '''
import jax
import numpy as np
import jax.numpy as jnp


@jax.jit
def step(params, x):
    scale = np.float64(0.5)                           # JX108
    y = x * scale
    return jnp.zeros((4,), dtype=np.float64) + y      # JX108


class Stage:
    def device_fn(self, meta):
        offset = np.double(1.0)                       # JX108

        def fwd(params, x):
            z = jnp.asarray(0.1, dtype="float64")     # JX108
            return x * offset + z

        return fwd


def train(batches, state, step_masked):
    for b in batches:
        lr = np.float64(1e-3)                         # JX108
        state, metrics = step_masked(state, b, lr)
    return state


def serve_loop(batches, dispatch_async):
    outs = []
    for b in batches:
        outs.append(dispatch_async(b * np.float64(2)))    # JX108
    return outs
'''


def test_jx108_flags_f64_in_device_code():
    findings = lint_source(JX108_FLAGGED, "fixture108.py")
    got = sorted((f.rule, f.line) for f in findings)
    lines = JX108_FLAGGED.splitlines()
    want = sorted(("JX108", i + 1) for i, text in enumerate(lines)
                  if "# JX108" in text)
    assert got == want, (got, want)


def test_jx108_clean_counterparts():
    # f32 spellings and python literals are the prescribed fix; f64 in
    # plain host code (no step/dispatch loop, not traced) is fine
    clean = JX108_FLAGGED.replace("float64", "float32").replace(
        "np.double", "np.float32")
    assert [f.rule for f in lint_source(clean, "x.py")
            if f.rule == "JX108"] == []
    host = ("import numpy as np\n"
            "def offline_report(rows):\n"
            "    acc = np.float64(0)\n"
            "    for r in rows:\n"
            "        acc += np.mean(r, dtype=np.float64)\n"
            "    return acc\n")
    assert lint_source(host, "x.py") == []


def test_jx108_pragma_suppresses():
    src = ("import jax\nimport numpy as np\n"
           "@jax.jit\n"
           "def step(x):\n"
           "    s = np.float64(0.5)  # lint-jax: allow(JX108)\n"
           "    return x * s\n")
    assert lint_source(src, "x.py") == []


JX30X_FLAGGED = '''
import threading
import time
import subprocess


_lock = threading.Lock()


def hold():
    with _lock:
        time.sleep(0.5)                               # JX301
        subprocess.run(["true"])                      # JX301


def manual():
    _lock.acquire()                                   # JX302
    work()
    _lock.release()


def spawn():
    t = threading.Thread(target=work)                 # JX303
    t.start()
    t.join()


def work():
    pass
'''


def test_jx30x_flags_the_shallow_concurrency_face():
    findings = lint_source(JX30X_FLAGGED, "fixture30x.py")
    got = sorted((f.rule, f.line) for f in findings)
    lines = JX30X_FLAGGED.splitlines()
    want = sorted((rule, i + 1) for i, text in enumerate(lines)
                  for rule in ("JX301", "JX302", "JX303")
                  if f"# {rule}" in text)
    assert got == want, (got, want)


def test_jx30x_clean_counterparts():
    # sleep outside the critical section, acquire chained to
    # try/finally, spawn with an explicit lifecycle: all clean
    src = ("import threading\nimport time\n"
           "_lock = threading.Lock()\n"
           "def hold():\n"
           "    with _lock:\n"
           "        pass\n"
           "    time.sleep(0.5)\n"
           "def manual():\n"
           "    _lock.acquire()\n"
           "    try:\n"
           "        pass\n"
           "    finally:\n"
           "        _lock.release()\n"
           "def spawn(work):\n"
           "    t = threading.Thread(target=work, daemon=True)\n"
           "    t.start()\n")
    assert lint_source(src, "x.py") == []
    # non-lockish receivers are out of scope for the shallow face
    src2 = ("import time\n"
            "def hold(session):\n"
            "    with session:\n"
            "        time.sleep(0.5)\n")
    assert lint_source(src2, "x.py") == []


def test_jx300_unjustified_jx3xx_pragma_is_a_finding():
    src = ("import threading\nimport time\n"
           "_lock = threading.Lock()\n"
           "def hold():\n"
           "    with _lock:\n"
           "        time.sleep(0.5)  # lint-jax: allow(JX301)\n")
    assert [f.rule for f in lint_source(src, "x.py")] == ["JX300"]


def test_justified_jx3xx_pragma_suppresses_and_records():
    src = ("import threading\nimport time\n"
           "_lock = threading.Lock()\n"
           "def hold():\n"
           "    with _lock:\n"
           "        time.sleep(0.5)"
           "  # lint-jax: allow(JX301): warm wait is the contract\n")
    findings, suppressed = lint_source_full(src, "x.py")
    assert findings == []
    assert len(suppressed) == 1
    f, why = suppressed[0]
    assert f.rule == "JX301"
    assert why == "warm wait is the contract"


def test_jx1xx_pragma_needs_no_justification():
    # the justification requirement is scoped to the concurrency face;
    # the established JX1xx pragma form stays valid
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return x.item()  # lint-jax: allow(JX101)\n")
    assert lint_source(src, "x.py") == []


def test_allowlist_justifications_are_nonempty():
    for suffix, rules in DEFAULT_ALLOWLIST.items():
        for rule, why in rules.items():
            assert why.strip(), (
                f"allowlist entry ({suffix}, {rule}) has no justification")


def test_pragma_suppresses():
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return x.item()  # lint-jax: allow(JX101)\n")
    assert lint_source(src, "x.py") == []
    src_no = src.replace("  # lint-jax: allow(JX101)", "")
    assert [f.rule for f in lint_source(src_no, "x.py")] == ["JX101"]

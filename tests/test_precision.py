"""The plan-level precision/quantization pass (core/precision.py,
round 12): policy parsing and identity, per-channel int8 weight
quantization, the bf16-activation composite transform through
``core/plan``, compile-cache separation per policy, serve-load
calibration against the f32 offline transform, and the SPMD audit of
the quantized segment. docs/quantization.md documents the contracts
pinned here."""

import numpy as np
import pytest

import jax

from mmlspark_tpu.core import plan
from mmlspark_tpu.core.precision import (
    DEFAULT_TOLERANCES, PrecisionPolicy, QuantizedLeaf, cast_activation,
    materialize, quantize_channelwise, quantize_params, quantized_bytes,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import MLP


def mlp_stage(d_in=32, width=64, n_out=8, seed=0):
    module = MLP(features=(width,), num_outputs=n_out)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, d_in), np.float32))["params"]
    bundle = ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(d_in,), output_names=("features", "logits"))
    return JaxModel(model=bundle, input_col="x", output_col="scores",
                    mesh_spec={"dp": 1})


def vec_table(n=16, d=32, seed=0, scale=2.0):
    r = np.random.default_rng(seed)
    return DataTable({"x": list(
        (r.normal(size=(n, d)) * scale).astype(np.float32))})


class TestPolicy:
    def test_parse_forms(self):
        assert PrecisionPolicy.parse(None) is None
        p = PrecisionPolicy.parse("bf16")
        assert p.mode == "bf16" and p.active
        q = PrecisionPolicy.parse({"mode": "int8w", "tolerance": 0.5})
        assert q.mode == "int8w" and q.resolve_tolerance() == 0.5
        assert PrecisionPolicy.parse(p) is p

    def test_f32_is_inactive(self):
        p = PrecisionPolicy.parse("f32")
        assert not p.active
        assert p.resolve_tolerance() == 0.0

    def test_invalid_inputs_raise(self):
        with pytest.raises(ValueError, match="unknown precision mode"):
            PrecisionPolicy(mode="fp8")
        with pytest.raises(ValueError, match="tolerance"):
            PrecisionPolicy(mode="bf16", tolerance=-1.0)
        with pytest.raises(TypeError, match="cannot parse"):
            PrecisionPolicy.parse(3.14)

    def test_defaults_and_describe(self):
        for mode, tol in DEFAULT_TOLERANCES.items():
            p = PrecisionPolicy(mode=mode)
            assert p.resolve_tolerance() == tol
            assert mode in p.describe()
        # cache tokens differ per mode (program identity)
        tokens = {PrecisionPolicy(mode=m).cache_token
                  for m in ("bf16", "int8w")}
        assert len(tokens) == 2


class TestQuantization:
    def test_channelwise_roundtrip_error_bounded(self):
        r = np.random.default_rng(0)
        w = (r.normal(size=(48, 24)) * r.uniform(0.1, 10, size=24)
             ).astype(np.float32)  # per-channel dynamic ranges
        leaf = quantize_channelwise(w)
        assert leaf.q.dtype == np.int8 and leaf.scale.shape == (24,)
        deq = leaf.q.astype(np.float32) * leaf.scale
        # symmetric rounding: error ≤ scale/2 per element, per channel
        assert (np.abs(deq - w) <= leaf.scale / 2 + 1e-7).all()

    def test_zero_channel_is_safe(self):
        w = np.zeros((8, 4), np.float32)
        leaf = quantize_channelwise(w)
        assert (leaf.q == 0).all() and np.isfinite(leaf.scale).all()

    def test_quantize_params_leaf_rules(self):
        import jax.numpy as jnp
        params = {
            "kernel": np.ones((64, 32), np.float32),   # → int8
            "tiny": np.ones((2, 2), np.float32),       # small → bf16
            "bias": np.ones((32,), np.float32),        # 1-D → f32
            "ids": np.arange(4, dtype=np.int32),       # non-float → as-is
        }
        out = quantize_params(params, PrecisionPolicy(mode="int8w"))
        assert isinstance(out["kernel"], QuantizedLeaf)
        assert out["tiny"].dtype == jnp.bfloat16
        assert out["bias"].dtype == np.float32
        assert out["ids"].dtype == np.int32
        # bf16 mode: kernels narrow, no int8
        out16 = quantize_params(params, PrecisionPolicy(mode="bf16"))
        assert out16["kernel"].dtype == jnp.bfloat16
        assert out16["bias"].dtype == np.float32

    def test_materialize_and_cast_roundtrip(self):
        import jax.numpy as jnp
        pol = PrecisionPolicy(mode="int8w")
        stored = quantize_params(
            {"w": np.full((32, 16), 0.5, np.float32)}, pol)
        live = materialize(stored, pol)
        assert live["w"].dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(live["w"], np.float32),
                                   0.5, rtol=2e-2)
        x = jnp.ones((4, 3), jnp.float32)
        assert cast_activation(x, pol).dtype == jnp.bfloat16
        u8 = jnp.ones((4, 3), jnp.uint8)
        assert cast_activation(u8, pol).dtype == jnp.uint8

    def test_int8_storage_ships_thin(self):
        jm = mlp_stage()
        seg = plan.collect_segment(
            [jm], 0, lambda c: plan._entry_meta(vec_table(), c),
            min_stages=1, precision=PrecisionPolicy(mode="int8w"))
        _fn, stored = plan.segment_composite(seg, plan._segment_mesh(seg))
        nbytes, f32_bytes = quantized_bytes(stored)
        assert nbytes < 0.35 * f32_bytes  # ~4x weight cut (+scales)


class TestPlanPass:
    def test_parity_and_output_dtype_per_mode(self):
        jm = mlp_stage()
        table = vec_table()
        ref = np.stack(list(jm.transform(table)["scores"]))
        for mode, tol in (("f32", 0.0), ("bf16", 5e-2), ("int8w", 2e-1)):
            out = plan.transform_async(
                [jm], table, jm,
                precision=PrecisionPolicy(mode=mode)).result()
            got = np.stack(list(out["scores"]))
            assert got.dtype == np.float32  # declared dtype restored
            diff = np.abs(got - ref).max()
            if mode == "f32":
                assert diff == 0.0  # inactive policy: byte-identical
            else:
                assert 0 < diff <= tol, (mode, diff)

    def test_policies_never_share_compiled_entries(self):
        jm = mlp_stage()
        table = vec_table(n=4)
        for mode in (None, "bf16", "int8w"):
            pol = PrecisionPolicy.parse(mode)
            plan.transform_async([jm], table, jm,
                                 precision=pol).result()
        cache = jm.__dict__["_plan_cache"]
        assert len(cache) == 3  # one entry per (layout, policy)
        # and an explicit f32 policy shares the unset-policy entry
        plan.transform_async([jm], table, jm,
                             precision=PrecisionPolicy(mode="f32")
                             ).result()
        assert len(jm.__dict__["_plan_cache"]) == 3

    def test_audit_plan_spmd_verifies_quantized_segment_clean(self):
        from mmlspark_tpu.analysis.spmd import audit_plan_spmd
        jm = mlp_stage()
        table = vec_table()
        audit = audit_plan_spmd(
            [jm], lambda c: plan._entry_meta(table, c), n_rows=len(table),
            precision=PrecisionPolicy(mode="int8w"))
        assert audit.ok, audit.format()
        assert len(audit.segments) == 1
        assert audit.segments[0].schedule.ops == []


class TestServeCalibration:
    def test_load_measures_parity_and_serves_within_it(self):
        from mmlspark_tpu.serve import ModelServer, ServeConfig
        jm = mlp_stage()
        table = vec_table(n=20)
        ref = np.stack(list(jm.transform(table)["scores"]))
        server = ModelServer(ServeConfig(buckets=(1, 8), max_queue=64,
                                         deadline_ms=None))
        try:
            server.add_model("m", mlp_stage(), precision="int8w",
                             example=table.take(np.arange(8)))
            snap = server.snapshot()["m"]
            assert snap["precision"].startswith("int8w")
            assert 0 < snap["precision_parity"] <= 2e-1
            # mixed packings: single rows and multi-row requests
            handles = [server.submit(
                "m", table.take(np.arange(i, min(i + 5, 20))))
                for i in range(0, 20, 5)]
            handles += [server.submit("m", table.take(np.arange(i, i + 1)))
                        for i in range(4)]
            outs = [h.result(timeout=60) for h in handles]
        finally:
            server.close()
        got = np.concatenate(
            [np.stack(list(o["scores"])) for o in outs[:4]])
        assert np.abs(got - ref).max() <= 2e-1
        for i, o in enumerate(outs[4:]):
            assert np.abs(np.asarray(o["scores"][0]) - ref[i]).max() \
                <= 2e-1

    def test_drift_past_pinned_tolerance_fails_the_load(self):
        from mmlspark_tpu.serve import ModelServer, ServeConfig
        from mmlspark_tpu.serve.errors import ModelLoadError
        server = ModelServer(ServeConfig(buckets=(1, 8),
                                         deadline_ms=None))
        try:
            with pytest.raises(ModelLoadError, match="diverges"):
                server.add_model(
                    "m", mlp_stage(),
                    precision={"mode": "int8w", "tolerance": 1e-9},
                    example=vec_table(n=4))
        finally:
            server.close()

    def test_invalid_policy_is_a_typed_load_error(self):
        from mmlspark_tpu.serve import ModelServer, ServeConfig
        from mmlspark_tpu.serve.errors import ModelLoadError
        server = ModelServer(ServeConfig())
        try:
            with pytest.raises(ModelLoadError, match="invalid precision"):
                server.add_model("m", mlp_stage(), precision="fp4")
        finally:
            server.close()

"""Device-level observability: the flight recorder (obs/flight.py), the
device-attribution pillar (obs/device.py), and the train anomaly plane
(obs/anomaly.py).

The contracts under test:

* a crash, a hang, or an explicit ``on_crash`` each produce ONE
  self-contained post-mortem dump (recent ring, per-thread stacks,
  registry snapshot, heartbeat table, fingerprint) — bounded by the dump
  budget, never repeated for the same stall, never fired for idle seams;
* the registry's interning and the span ring survive a ≥8-thread hammer
  with no lost counter updates, no duplicate interned series, and the
  ring inside its bound;
* the non-finite sentinel fires EXACTLY once per offending step, in both
  ``fit_arrays`` and ``fit_stream``, and the typed raise carries the
  step;
* the straggler detector names the artificially-delayed host from the
  gathered per-host step-time vector;
* device attribution populates ``plan.segment.*`` cost/memory gauges per
  fused segment and decomposes captured plan spans into an honest
  compute/transfer/idle split.
"""

import glob
import json
import math
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from test_plan import mlp_bundle  # noqa: E402

from mmlspark_tpu import obs
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.obs import device as obs_device
from mmlspark_tpu.obs import flight
from mmlspark_tpu.obs import runtime as obs_rt
from mmlspark_tpu.obs.anomaly import (
    NonFiniteLossError, NonFiniteSentinel, StragglerDetector,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.train import TrainConfig, Trainer


@pytest.fixture(autouse=True)
def flight_isolated():
    """Tracer off, flight recorder off, registry/ring/memos clean on both
    sides of every test — the obs flag-isolation contract extended to
    the new pillars."""
    flight.disable()
    obs.disable()
    obs.clear()
    obs.registry().reset()
    obs_device.reset()
    yield
    flight.disable()
    obs.disable()
    obs.clear()
    obs.registry().reset()
    obs_device.reset()


# ---- flight recorder ----


def test_crash_dump_is_self_contained(tmp_path):
    rec = flight.enable(str(tmp_path))
    assert flight.enabled() and obs.enabled()  # the ring must be live
    with obs.span("train/step", "train"):
        pass
    obs.registry().counter("train.steps").add(3)
    try:
        raise RuntimeError("induced")
    except RuntimeError as e:
        path = flight.on_crash(e, context="test")
    assert path is not None and os.path.exists(path)
    dump = json.loads(open(path).read())
    assert dump["reason"] == "crash"
    assert dump["exception"]["type"] == "RuntimeError"
    assert dump["extra"] == {"context": "test"}
    assert any(r["name"] == "train/step" for r in dump["ring"])
    assert dump["registry"]["counters"]["train.steps"] == 3
    # every live thread's stack is present, including this one's
    names = {t["name"] for t in dump["threads"].values()}
    assert "MainThread" in names and flight.THREAD_NAME in names
    assert all(t["stack"] for t in dump["threads"].values())
    # fingerprint makes the dump interpretable off-box
    assert dump["fingerprint"]["python"]
    assert "mesh" in dump["fingerprint"]  # jax is imported in the suite
    assert rec is flight.recorder()


def test_hang_dump_fires_once_per_stall_and_never_for_idle(tmp_path):
    rec = flight.enable(str(tmp_path), hang_threshold_s=0.15, poll_s=0.03)
    rec.arm("busy/lane")
    rec.arm("idle/lane")
    rec.disarm("idle/lane")  # idle seams are never hangs
    time.sleep(0.6)  # several polls past the threshold
    dumps = glob.glob(os.path.join(str(tmp_path), "flight_hang_*.json"))
    assert len(dumps) == 1, (
        "one stall must produce exactly one dump (stalled flag), and an "
        f"idle heartbeat none — got {len(dumps)}")
    dump = json.loads(open(dumps[0]).read())
    assert dump["extra"]["heartbeat"] == "busy/lane"
    assert dump["extra"]["stalled_for_s"] >= 0.15
    assert dump["heartbeats"]["busy/lane"]["busy"] is True
    assert dump["heartbeats"]["idle/lane"]["busy"] is False
    # a beat resets the stall; a new stall dumps again
    rec.beat("busy/lane")
    time.sleep(0.4)
    dumps = glob.glob(os.path.join(str(tmp_path), "flight_hang_*.json"))
    assert len(dumps) == 2


def test_dump_budget_bounds_a_crash_loop(tmp_path):
    rec = flight.enable(str(tmp_path), max_dumps=2)
    assert rec.dump("crash") is not None
    assert rec.dump("crash") is not None
    assert rec.dump("crash") is None  # budget exhausted, disk protected
    assert len(glob.glob(os.path.join(str(tmp_path), "*.json"))) == 2


def test_thread_excepthook_dumps_and_chains(tmp_path):
    chained = []
    prev = threading.excepthook
    threading.excepthook = lambda args: chained.append(args.exc_type)
    try:
        flight.enable(str(tmp_path))  # chains to the capture hook above

        def boom():
            raise ValueError("thread death")

        t = threading.Thread(target=boom, name="DoomedWorker")
        t.start()
        t.join()
        dumps = glob.glob(os.path.join(str(tmp_path),
                                       "flight_crash_*.json"))
        assert len(dumps) == 1, "an unhandled thread exception must dump"
        dump = json.loads(open(dumps[0]).read())
        assert dump["exception"]["type"] == "ValueError"
        assert dump["extra"]["thread"] == "DoomedWorker"
        assert chained == [ValueError], (
            "the previous threading.excepthook must run after the dump")
        flight.disable()
        assert threading.excepthook is not prev  # ours, restored by
        #                                          uninstall, not pytest's
    finally:
        flight.disable()
        threading.excepthook = prev


def test_enable_is_idempotent_and_disable_restores_hooks(tmp_path):
    prev_except = sys.excepthook
    prev_thread = threading.excepthook
    rec = flight.enable(str(tmp_path))
    assert sys.excepthook is not prev_except
    assert flight.enable(str(tmp_path)) is rec  # same dir → same recorder
    flight.disable()
    # same dir + IDENTICAL kwargs is idempotent too: an "ensure on"
    # call per work cycle must not rebuild the recorder (that would
    # reset the dump budget and wipe heartbeats/crash-dedup state)
    rec2 = flight.enable(str(tmp_path), hang_threshold_s=30.0)
    rec2._dumps = 3  # pretend a crash loop already spent budget
    assert flight.enable(str(tmp_path), hang_threshold_s=30.0) is rec2
    assert rec2._dumps == 3
    # changed kwargs DO rebuild
    rec3 = flight.enable(str(tmp_path), hang_threshold_s=60.0)
    assert rec3 is not rec2 and rec3._dumps == 0
    flight.disable()
    assert sys.excepthook is prev_except
    assert threading.excepthook is prev_thread
    assert flight.recorder() is None
    # the watchdog thread is gone
    assert not any(t.name == flight.THREAD_NAME
                   for t in threading.enumerate())


def test_interning_and_ring_survive_concurrent_hammer(tmp_path):
    """≥8 threads hammering metric interning, flight heartbeats, and
    ring writes concurrently: no lost counter updates, no duplicate
    interned series, the ring inside its bound."""
    n_threads, iters = 8, 400
    obs.enable(buffer_size=512)
    rec = flight.enable(str(tmp_path), hang_threshold_s=60.0)
    reg = obs.registry()
    errors: list = []
    start = threading.Barrier(n_threads)

    def hammer(k: int):
        try:
            start.wait(timeout=10)
            for i in range(iters):
                # same (name, labels) from every thread — interning must
                # hand back ONE series
                reg.counter("hammer.total", lane="shared").add()
                reg.histogram("hammer.ms", lane="shared").observe(float(i))
                rec.beat(f"hammer/{k}")
                with obs.span("hammer/span", "test", {"k": k}):
                    pass
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    assert not any(t.is_alive() for t in threads)
    # no lost updates
    assert reg.value("hammer.total", lane="shared") == n_threads * iters
    series = reg.series("hammer.total")
    assert len(series) == 1, (
        f"{len(series)} interned series for one (name, labels) — "
        "concurrent interning duplicated the counter")
    assert series[0].value == n_threads * iters
    hist = reg.series("hammer.ms")
    assert len(hist) == 1 and hist[0].count == n_threads * iters
    # ring bounded; every heartbeat registered and busy
    assert obs_rt.captured_count() <= 512
    beats = rec.heartbeats()
    assert {f"hammer/{k}" for k in range(n_threads)} <= set(beats)
    assert all(beats[f"hammer/{k}"]["busy"] for k in range(n_threads))


# ---- non-finite sentinel ----


def test_sentinel_unit_fires_once_per_step_and_validates_mode():
    with pytest.raises(ValueError, match="nonfinite_loss"):
        NonFiniteSentinel("x", mode="explode")
    obs.enable()
    s = NonFiniteSentinel("unit", mode="event")
    assert s.check(1, 1.5) == 1.5
    s.check(2, float("nan"))
    s.check(2, float("nan"))  # same step consulted twice → one event
    s.check(3, float("inf"))
    reg = obs.registry()
    assert reg.value("train.nonfinite_losses", loop="unit") == 2
    events = [r for r in obs.captured()
              if getattr(r, "name", "") == "train/nonfinite"]
    assert len(events) == 2
    assert events[0].labels["step"] == 2
    # off mode: no counting, no raise
    off = NonFiniteSentinel("off", mode="off")
    assert math.isnan(off.check(1, float("nan")))
    assert reg.value("train.nonfinite_losses", loop="off") is None


def _nan_xy(n=32):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    x[:] = np.nan  # every batch's loss is NaN from step 1
    y = np.zeros(n, np.int64)
    return x, y


def _cfg(**kw):
    base = dict(batch_size=16, epochs=1, learning_rate=1e-2, log_every=1,
                prefetch_depth=0, donate_state=False)
    base.update(kw)
    return TrainConfig(**base)


def test_fit_arrays_raises_typed_error_at_the_divergence():
    x, y = _nan_xy()
    tr = Trainer(MLP(features=(8,), num_outputs=2),
                 _cfg(nonfinite_loss="raise"))
    with pytest.raises(NonFiniteLossError) as ei:
        tr.fit_arrays(x, y)
    assert ei.value.step == 1 and ei.value.loop == "fit_arrays"
    assert not math.isfinite(ei.value.value)


def test_fit_arrays_event_mode_fires_exactly_once_per_offending_step():
    obs.enable()
    x, y = _nan_xy(32)  # 2 steps, both NaN
    tr = Trainer(MLP(features=(8,), num_outputs=2),
                 _cfg(nonfinite_loss="event", epochs=2))
    tr.fit_arrays(x, y)  # records and continues
    assert len(tr.history) == 4 and all(math.isnan(v) for v in tr.history)
    assert obs.registry().value(
        "train.nonfinite_losses", loop="fit_arrays") == 4
    events = [r for r in obs.captured()
              if getattr(r, "name", "") == "train/nonfinite"]
    assert [e.labels["step"] for e in events] == [1, 2, 3, 4]


def test_fit_stream_event_mode_fires_exactly_once_per_offending_step():
    obs.enable()
    x, y = _nan_xy(32)
    sizes = [5, 11, 3, 13]  # ragged chunks, 32 rows → 2 steps/epoch

    def source():
        off = 0
        for n in sizes:
            yield x[off:off + n], y[off:off + n]
            off += n

    tr = Trainer(MLP(features=(8,), num_outputs=2),
                 _cfg(nonfinite_loss="event", epochs=2))
    tr.fit_stream(source)
    assert len(tr.history) == 4 and all(math.isnan(v) for v in tr.history)
    assert obs.registry().value(
        "train.nonfinite_losses", loop="fit_stream") == 4


def test_fit_stream_raise_mode_dies_at_step_one():
    x, y = _nan_xy(32)
    tr = Trainer(MLP(features=(8,), num_outputs=2),
                 _cfg(nonfinite_loss="raise"))
    with pytest.raises(NonFiniteLossError) as ei:
        tr.fit_stream(iter([(x, y)]))
    assert ei.value.step == 1 and ei.value.loop == "fit_stream"


def test_nonfinite_raise_leaves_a_flight_dump(tmp_path):
    """The run dies AT the divergence WITH forensics: the typed raise
    passes through fit_arrays' crash hook before propagating."""
    flight.enable(str(tmp_path))
    x, y = _nan_xy()
    tr = Trainer(MLP(features=(8,), num_outputs=2), _cfg())
    with pytest.raises(NonFiniteLossError):
        tr.fit_arrays(x, y)
    dumps = glob.glob(os.path.join(str(tmp_path), "flight_crash_*.json"))
    assert len(dumps) == 1
    dump = json.loads(open(dumps[0]).read())
    assert dump["exception"]["type"] == "NonFiniteLossError"
    assert dump["extra"]["context"] == "Trainer.fit_arrays"
    assert any(r["name"] == "train/step" for r in dump["ring"])


# ---- straggler detector ----


def test_straggler_detector_names_the_delayed_host():
    obs.enable()
    det = StragglerDetector("fit_stream", factor=2.0)
    # consumer side accumulates; producer drains the mean
    for ms in (100.0, 110.0, 90.0):
        det.observe(ms)
    assert det.local_mean_ms() == pytest.approx(100.0)
    assert det.local_mean_ms() == 0.0  # drained → the no-data marker
    # host 2 is artificially 3.5× the median → flagged by name
    verdict = det.ingest(np.array([100.0, 110.0, 350.0, 95.0]),
                         process_index=0)
    assert verdict["straggler"] is True and verdict["slow_host"] == 2
    assert verdict["skew"] == pytest.approx((350 - 95) / 350, abs=1e-3)
    reg = obs.registry()
    assert reg.value("train.host_skew", loop="fit_stream") \
        == pytest.approx(verdict["skew"], abs=1e-4)
    assert reg.value("train.host_step_ms", loop="fit_stream",
                     host=2) == 350.0
    assert reg.value("train.stragglers", loop="fit_stream") == 1
    events = [r for r in obs.captured()
              if getattr(r, "name", "") == "train/straggler"]
    assert len(events) == 1 and events[0].labels["host"] == 2
    assert det.last is verdict


def test_straggler_balanced_hosts_and_empty_window():
    obs.enable()
    det = StragglerDetector("fit_stream")
    # balanced: skew published, nobody flagged
    v = det.ingest(np.array([100.0, 105.0, 98.0, 102.0]))
    assert v["straggler"] is False
    assert obs.registry().value("train.stragglers",
                                loop="fit_stream") is None
    # zero-mean hosts (filler-only blocks) are excluded from the
    # baseline; an all-idle window has no verdict
    assert det.ingest(np.zeros(4)) is None
    v = det.ingest(np.array([0.0, 100.0, 101.0, 99.0]))
    assert v["straggler"] is False  # idle host never drags the median


def test_dump_is_strict_json_even_with_nonfinite_metrics(tmp_path):
    """Regression: json.dump emits bare NaN/Infinity tokens (invalid
    JSON) — a dump shipped off-box must parse in strict consumers."""
    rec = flight.enable(str(tmp_path))
    obs.registry().gauge("train.loss").set(float("nan"))
    obs.registry().gauge("train.lr").set(float("inf"))
    path = rec.dump("crash")
    raw = open(path).read()

    def _no_constants(name):
        raise AssertionError(f"non-strict JSON token {name!r} in dump")

    dump = json.loads(raw, parse_constant=_no_constants)
    assert dump["registry"]["gauges"]["train.loss"] == "NaN"
    assert dump["registry"]["gauges"]["train.lr"] == "Infinity"


def test_straggler_flagged_on_a_two_host_mesh():
    """Regression: a self-inclusive median made 2 active hosts
    unflaggable for any factor >= 2 (hi > factor*(hi+lo)/2 has no
    solution) — and 2 processes is the common multi-host config. The
    baseline is now the median of the OTHER active hosts."""
    obs.enable()
    det = StragglerDetector("fit_stream", factor=2.0)
    v = det.ingest(np.array([10.0, 1000.0]))
    assert v["straggler"] is True and v["slow_host"] == 1
    assert v["median_ms"] == 10.0  # the peer, not (10+1000)/2
    # balanced 2-host window stays quiet
    assert det.ingest(np.array([10.0, 11.0]))["straggler"] is False
    # 2 hosts but one idle: no peer baseline, never flagged
    assert det.ingest(np.array([0.0, 50.0]))["straggler"] is False


def test_crash_dump_dedups_on_crash_then_excepthook(tmp_path):
    """Regression: fit loops dump at the failure point (on_crash) and
    re-raise; the same exception then reaches the chained excepthook —
    which must NOT burn a second dump-budget slot on it."""
    rec = flight.enable(str(tmp_path))
    try:
        raise RuntimeError("induced once")
    except RuntimeError as e:
        first = flight.on_crash(e, context="fit")
        assert first is not None
        # the uncaught-exception path fires next with the SAME object
        sys.excepthook(type(e), e, e.__traceback__)
    dumps = glob.glob(os.path.join(str(tmp_path), "flight_crash_*.json"))
    assert len(dumps) == 1, f"duplicate dumps for one exception: {dumps}"
    # a DIFFERENT exception still dumps
    try:
        raise ValueError("another")
    except ValueError as e2:
        assert rec.dump("crash", exc=e2) is not None


# ---- device attribution ----


def test_segment_gauges_and_compile_attribution():
    obs.enable(device=True)
    assert obs_device.enabled()
    bundle = mlp_bundle(6)
    jm = JaxModel(model=bundle, input_col="x", output_col="scores",
                  minibatch_size=8)
    rng = np.random.default_rng(0)
    table = DataTable({"x": list(rng.normal(size=(16, 6))
                                 .astype(np.float32))})
    jm.transform(table)
    snap = obs.registry().snapshot()
    seg_gauges = {k: v for k, v in snap["gauges"].items()
                  if k.startswith("plan.segment.")}
    for kind in ("flops", "bytes", "peak_hbm"):
        keys = [k for k in seg_gauges if f"plan.segment.{kind}" in k]
        assert keys, f"plan.segment.{kind} gauge not populated"
        assert all(seg_gauges[k] >= 0 for k in keys)
    compiles = [v for k, v in snap["counters"].items()
                if k.startswith("plan.xla_compiles")]
    assert compiles and sum(compiles) >= 1
    hists = [k for k in snap["histograms"]
             if k.startswith("plan.compile_ms")]
    assert hists, "compile-time histogram not recorded"
    # warm re-run: no new compile attributed, gauges unchanged
    before = sum(compiles)
    jm.transform(table)
    snap2 = obs.registry().snapshot()
    after = sum(v for k, v in snap2["counters"].items()
                if k.startswith("plan.xla_compiles"))
    assert after == before
    # obs.disable() switches the pillar off with the tracer
    obs.disable()
    assert not obs_device.enabled()


def test_device_split_decomposes_plan_spans():
    obs.enable()
    bundle = mlp_bundle(6)
    jm = JaxModel(model=bundle, input_col="x", output_col="scores",
                  minibatch_size=8)
    rng = np.random.default_rng(0)
    table = DataTable({"x": list(rng.normal(size=(24, 6))
                                 .astype(np.float32))})
    jm.transform(table)
    split = obs.device_time_split()
    assert split is not None
    parts = (split["compute_ms"] + split["h2d_ms"] + split["d2h_ms"]
             + split["idle_ms"])
    assert parts == pytest.approx(split["wall_ms"], rel=0.02)
    fr = (split["compute_fraction"] + split["h2d_fraction"]
          + split["d2h_fraction"] + split["idle_fraction"])
    assert fr == pytest.approx(1.0, abs=0.02)
    assert all(split[k] >= 0 for k in split)
    # no plan spans → no split (never a division by zero)
    obs.clear()
    assert obs.device_time_split() is None
    assert obs.device_time_split(records=[]) is None


def test_device_split_is_sane_for_concurrent_serve_lanes():
    """Regression: dp>1 serve lanes emit OVERLAPPING plan/dispatch
    spans; a per-span duration sum reported compute > wall and
    fractions > 1. The split now measures the union of intervals."""
    from mmlspark_tpu.obs.events import SpanRecord

    def span(name, start_ms, dur_ms, tid):
        return SpanRecord(name, "plan", int(start_ms * 1e6),
                          int(dur_ms * 1e6), tid, f"lane{tid}",
                          tid * 100, None, 0, None)

    # 4 lanes dispatching [0, 10] ms concurrently, then one 2 ms drain
    records = [span("plan/dispatch", 0, 10, t) for t in range(4)]
    records.append(span("plan/d2h", 10, 2, 0))
    split = obs.device_time_split(records)
    assert split["wall_ms"] == pytest.approx(12.0)
    assert split["compute_ms"] == pytest.approx(10.0)  # union, not 40
    assert split["d2h_ms"] == pytest.approx(2.0)
    total_fraction = sum(split[k] for k in split if k.endswith("_fraction"))
    assert total_fraction == pytest.approx(1.0, abs=0.01)
    # h2d nested in dispatch still subtracts from compute, once
    records = [span("plan/dispatch", 0, 10, t) for t in range(2)]
    records += [span("plan/h2d", 0, 3, t) for t in range(2)]
    split = obs.device_time_split(records)
    assert split["h2d_ms"] == pytest.approx(3.0)
    assert split["compute_ms"] == pytest.approx(7.0)


def test_poll_memory_never_initializes_a_backend():
    """Regression: ``jax.local_devices()`` INITIALIZES the default
    backend — fatal for a headless-forensics process that imports jax
    early but calls ``jax.distributed.initialize()`` later. The watchdog
    poll must stay a no-op until the app brings a backend up itself."""
    import subprocess
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax\n"
        "from jax._src import xla_bridge as xb\n"
        "from mmlspark_tpu import obs\n"
        "obs.enable(device=True)\n"
        "out = obs.poll_memory()\n"
        "assert out == {}, out\n"
        "assert not xb.backends_are_initialized(), "
        "'poll_memory initialized the backend'\n"
        "print('OK')\n" % os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "OK" in r.stdout, r.stderr


def test_poll_memory_is_dryrun_safe():
    # CPU devices report no memory_stats: the poll is a quiet no-op that
    # publishes nothing and never raises (the watchdog calls this)
    out = obs.poll_memory()
    assert isinstance(out, dict)
    snap = obs.registry().snapshot()
    for key in snap["gauges"]:
        assert not key.startswith("device.mem_") or out, (
            "memory gauges appeared without any device reporting stats")


def test_env_flag_precedence_enable_kwargs_override():
    """obs.enable(device=...) after an env-style enable() overrides it —
    the documented precedence (the env is read once at import)."""
    obs.enable()  # the MMLSPARK_TPU_OBS=1 path
    assert not obs_device.enabled()
    obs.enable(device=True)  # explicit kwargs win
    assert obs_device.enabled()
    obs.disable()
    assert not obs_device.enabled()

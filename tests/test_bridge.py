"""Tests for the Arrow offload bridge (SURVEY §7.6): record-batch streaming
through fitted transformers with order preservation and latency capture —
the CNTKModel executor-minibatching path recast as host-side batching."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from mmlspark_tpu.bridge import ArrowBatchBridge, make_map_in_arrow_fn
from mmlspark_tpu.bridge.offload import stream_table
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import get_model


def make_table(n=100, d=16, seed=0):
    r = np.random.default_rng(seed)
    return DataTable({
        "id": np.arange(n),
        "vec": [r.normal(size=d).astype(np.float32) for _ in range(n)],
    })


@pytest.fixture(scope="module")
def mlp_model():
    bundle = get_model("MLP", input_dim=16, num_outputs=3)
    return JaxModel(model=bundle, input_col="vec", output_col="out",
                    minibatch_size=32)


class TestArrowBatchBridge:
    def test_roundtrip_preserves_rows_and_order(self, mlp_model):
        t = make_table(100)
        direct = mlp_model.transform(t).column_matrix("out")

        bridge = ArrowBatchBridge(mlp_model)
        out_batches = list(bridge.process(stream_table(t, 17)))
        merged = pa.Table.from_batches(out_batches)
        out = DataTable.from_arrow(merged)
        assert len(out) == 100
        np.testing.assert_array_equal(out["id"], np.arange(100))
        # sharded batches can accumulate in a different order than the
        # direct path's slicing → tiny float drift
        np.testing.assert_allclose(out.column_matrix("out"), direct,
                                   rtol=1e-4, atol=1e-6)

    def test_latency_recorded(self, mlp_model):
        bridge = ArrowBatchBridge(mlp_model)
        list(bridge.process(stream_table(make_table(64), 16)))
        assert bridge.p50_latency_ms() is not None
        assert bridge.p50_latency_ms() > 0
        assert len(bridge.latencies_ms) == 4

    def test_empty_stream(self, mlp_model):
        bridge = ArrowBatchBridge(mlp_model)
        assert list(bridge.process(iter([]))) == []
        assert bridge.p50_latency_ms() is None

    def test_source_error_propagates(self, mlp_model):
        # a mid-stream failure in the Arrow source must surface, not end
        # the stream cleanly with truncated output
        def broken_source():
            yield from stream_table(make_table(32), 16)
            raise RuntimeError("executor died mid-partition")

        bridge = ArrowBatchBridge(mlp_model)
        with pytest.raises(RuntimeError, match="executor died"):
            list(bridge.process(broken_source()))

    def test_workers_overlap_preserves_order(self, mlp_model):
        t = make_table(100)
        direct = mlp_model.transform(t).column_matrix("out")
        bridge = ArrowBatchBridge(mlp_model, workers=3)
        merged = pa.Table.from_batches(
            list(bridge.process(stream_table(t, 9))))
        out = DataTable.from_arrow(merged)
        np.testing.assert_array_equal(out["id"], np.arange(100))
        np.testing.assert_allclose(out.column_matrix("out"), direct,
                                   rtol=1e-4, atol=1e-6)
        assert len(bridge.latencies_ms) == 12

    def test_workers_error_still_propagates(self, mlp_model):
        def broken_source():
            yield from stream_table(make_table(32), 16)
            raise RuntimeError("executor died mid-partition")

        bridge = ArrowBatchBridge(mlp_model, workers=2)
        with pytest.raises(RuntimeError, match="executor died"):
            list(bridge.process(broken_source()))

    def test_map_in_arrow_contract(self, mlp_model):
        # fn(iterator) -> iterator, the exact mapInArrow shape
        fn = make_map_in_arrow_fn(mlp_model)
        out = list(fn(stream_table(make_table(40), 10)))
        assert sum(b.num_rows for b in out) == 40
        assert "out" in out[0].schema.names

    def test_bridge_with_full_pipeline(self):
        # bridge is transformer-agnostic: run a fitted TrainClassifier
        from mmlspark_tpu.ml import TrainClassifier
        r = np.random.default_rng(1)
        n = 120
        y = r.integers(0, 2, n)
        t = DataTable({"f": r.normal(size=n) + 3.0 * y, "label": y})
        model = TrainClassifier(label_col="label").fit(t)
        fn = make_map_in_arrow_fn(model)
        out = pa.Table.from_batches(
            list(fn(stream_table(t.drop("label"), 30))))
        table = DataTable.from_arrow(out)
        assert "scored_labels" in table.columns
        acc = (np.asarray(table["scored_labels"]) == y).mean()
        assert acc > 0.95


class TestImageWireFormat:
    """Image-struct columns cross the Arrow boundary losslessly (the
    ImageSchema wire shape — reference ImageSchema.scala:12-17), so image
    tables score through the Spark bridge without manual flattening."""

    def test_image_table_round_trips_arrow(self):
        import pyarrow as pa

        from mmlspark_tpu.core.schema import is_image_column, make_image
        r = np.random.default_rng(0)
        rows = [make_image(f"i{k}", r.integers(0, 255, (6, 5, 3)))
                for k in range(3)] + [None]
        t = DataTable({"image": rows, "id": np.arange(4)})
        back = DataTable.from_arrow(t.to_arrow())
        assert is_image_column(back, "image")
        assert back["image"][3] is None
        for a, b in zip(rows[:3], back["image"][:3]):
            assert a["path"] == b["path"]
            np.testing.assert_array_equal(np.asarray(a["data"]),
                                          np.asarray(b["data"]))
        np.testing.assert_array_equal(back["id"], t["id"])

    def test_float_image_data_round_trips(self):
        from mmlspark_tpu.core.schema import make_image
        img = make_image("f", np.zeros((4, 4, 3)))
        img["data"] = np.linspace(0, 1, 48).reshape(4, 4, 3
                                                    ).astype(np.float32)
        t = DataTable({"image": [img]})
        back = DataTable.from_arrow(t.to_arrow())
        got = np.asarray(back["image"][0]["data"])
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, img["data"])

    def test_bridge_scores_image_struct_table(self, tmp_path):
        from mmlspark_tpu.core.schema import make_image, mark_image_column
        from mmlspark_tpu.models.zoo import get_model

        r = np.random.default_rng(1)
        t = DataTable({"image": [make_image(f"x{k}",
                                            r.integers(0, 255, (32, 32, 3)))
                                 for k in range(10)]})
        t = mark_image_column(t, "image")
        bundle = get_model("ConvNet_CIFAR10", widths=(4, 8), dense_width=16)
        jm = JaxModel(model=bundle, input_col="image", output_col="scores",
                      minibatch_size=4)
        fn = make_map_in_arrow_fn(jm)
        out = DataTable.from_arrow(
            pa.Table.from_batches(list(fn(stream_table(t, 3)))))
        direct = jm.transform(t)
        np.testing.assert_allclose(
            np.stack(list(out["scores"])),
            np.stack(list(direct["scores"])), rtol=1e-4, atol=1e-4)

    def test_null_first_row_still_marked_image(self):
        # from_arrow must mark via the canonical meta key, not rely on
        # structurally sniffing row 0 (which can be null)
        from mmlspark_tpu.core.schema import is_image_column, make_image
        r = np.random.default_rng(2)
        rows = [None, make_image("a", r.integers(0, 255, (4, 4, 3)))]
        t = DataTable({"image": rows})
        back = DataTable.from_arrow(t.to_arrow())
        assert is_image_column(back, "image")
        assert back["image"][0] is None

    def test_malformed_image_rows_raise_clearly(self):
        from mmlspark_tpu.core.schema import make_image, mark_image_column
        img = make_image("a", np.zeros((4, 4, 3)))
        t = DataTable({"image": [img, {"path": "not-an-image"}]})
        t = mark_image_column(t, "image")
        with pytest.raises(ValueError, match="not an image struct"):
            t.to_arrow()
        bad = make_image("b", np.zeros((4, 4, 3)))
        bad["height"] = 5  # dims lie about the buffer
        t2 = mark_image_column(DataTable({"image": [bad]}), "image")
        with pytest.raises(ValueError, match="dims say"):
            t2.to_arrow()

    def test_generic_dict_column_still_serializes(self):
        # non-image dicts keep the old generic path
        t = DataTable({"d": [{"a": 1}, {"a": 2}]})
        back = DataTable.from_arrow(t.to_arrow())
        assert list(back["d"]) == [{"a": 1}, {"a": 2}]

    def test_unmarked_dict_column_with_extra_keys_stays_generic(self):
        # dicts sharing image key names PLUS extras must not be hijacked
        # into the wire struct (their extra keys would silently vanish)
        from mmlspark_tpu.core.schema import make_image
        img = dict(make_image("a", np.zeros((2, 2, 3))), label=7)
        img["data"] = img["data"].tolist()  # keep it arrow-serializable
        t = DataTable({"d": [img]})
        back = DataTable.from_arrow(t.to_arrow())
        assert back["d"][0]["label"] == 7  # extra key survived

    def test_rebuilt_image_data_is_writable(self):
        from mmlspark_tpu.core.schema import make_image
        t = DataTable({"image": [make_image("a", np.ones((3, 3, 3)))]})
        back = DataTable.from_arrow(t.to_arrow())
        arr = back["image"][0]["data"]
        arr[0, 0, 0] = 42  # in-place normalization must not crash
        assert arr[0, 0, 0] == 42

"""Pallas device kernels: fused GroupNorm (ops/group_norm.py).

Runs in interpreter mode on the CPU backend (the kernel itself executes,
not a shadow implementation), checking numerical equivalence against the
jnp reference, the custom-vjp gradient path, the VMEM-fit fallback gate,
and checkpoint-compatible wiring into ResNet."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops import group_norm, group_norm_reference
from mmlspark_tpu.ops.group_norm import _fits_vmem


class TestKernelEquivalence:
    @pytest.mark.parametrize("shape,groups", [
        ((2, 8, 8, 32), 8), ((3, 4, 4, 16), 4), ((1, 16, 16, 64), 8),
        ((2, 5, 7, 24), 3),  # non-square, odd spatial
    ])
    def test_matches_reference(self, shape, groups):
        r = np.random.default_rng(0)
        x = jnp.asarray(r.normal(size=shape).astype(np.float32) * 3 + 1)
        s = jnp.asarray(r.normal(size=shape[-1]).astype(np.float32))
        b = jnp.asarray(r.normal(size=shape[-1]).astype(np.float32))
        for relu in (False, True):
            got = group_norm(x, s, b, groups, relu=relu)
            want = group_norm_reference(x, s, b, groups, relu=relu)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5)

    def test_bfloat16_input(self):
        r = np.random.default_rng(1)
        x = jnp.asarray(r.normal(size=(2, 8, 8, 32))).astype(jnp.bfloat16)
        s = jnp.ones(32); b = jnp.zeros(32)
        got = group_norm(x, s, b, 8)
        assert got.dtype == jnp.bfloat16
        want = group_norm_reference(x, s, b, 8)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_gradients_through_custom_vjp(self):
        r = np.random.default_rng(2)
        x = jnp.asarray(r.normal(size=(2, 4, 4, 16)).astype(np.float32))
        s, b = jnp.ones(16), jnp.zeros(16)

        def loss(x, s, b):
            return jnp.sum(group_norm(x, s, b, 4, relu=True) ** 2)

        def loss_ref(x, s, b):
            return jnp.sum(group_norm_reference(x, s, b, 4, relu=True) ** 2)

        got = jax.grad(loss, argnums=(0, 1, 2))(x, s, b)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, s, b)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=1e-4, atol=1e-4)


class TestF32Accumulation:
    """Regression pins for the accumulate-in-f32 contract (round 12): a
    bf16 activation policy (docs/quantization.md) feeds norms bf16
    inputs, so the statistics math must hold up independent of the
    input's numeric range. The kernel's former one-pass E[x²]−E[x]²
    variance cancelled catastrophically in f32 for offset feature maps
    (measured max err 69.2 at mean=200, spread=0.02 — vs 1e-3 for the
    two-pass form); both implementations are now pinned against the f64
    numpy oracle."""

    @staticmethod
    def oracle_f64(x, scale, bias, groups, eps=1e-6):
        n, h, w, c = x.shape
        cg = c // groups
        xf = np.asarray(x, np.float64).reshape(n, h * w, groups, cg)
        mean = xf.mean(axis=(1, 3), keepdims=True)
        var = ((xf - mean) ** 2).mean(axis=(1, 3), keepdims=True)
        out = (xf - mean) / np.sqrt(var + eps)
        return (out.reshape(n, h, w, c)
                * np.asarray(scale, np.float64)
                + np.asarray(bias, np.float64))

    @pytest.mark.parametrize("center,spread", [
        (0.0, 1.0), (8.0, 0.05), (64.0, 0.05), (200.0, 0.02),
    ])
    def test_offset_feature_maps_match_f64_oracle(self, center, spread):
        from mmlspark_tpu.ops.group_norm import _group_norm_fwd_pallas
        r = np.random.default_rng(0)
        x = jnp.asarray(
            r.normal(center, spread, (2, 8, 8, 32)).astype(np.float32))
        s = jnp.asarray(r.normal(size=32).astype(np.float32))
        b = jnp.asarray(r.normal(size=32).astype(np.float32))
        want = self.oracle_f64(np.asarray(x, np.float64), s, b, 8)
        for got in (_group_norm_fwd_pallas(x, s, b, 8, 1e-6, False),
                    group_norm_reference(x, s, b, 8)):
            err = np.abs(np.asarray(got, np.float64) - want).max()
            assert err < 5e-3, (center, spread, err)

    def test_bf16_inputs_track_f64_oracle(self):
        # bf16 input: the error floor is the input's own quantization —
        # the f32 statistics must not add to it materially
        from mmlspark_tpu.ops.group_norm import _group_norm_fwd_pallas
        r = np.random.default_rng(1)
        for center in (0.0, 64.0):
            x = jnp.asarray(r.normal(center, 0.05, (2, 8, 8, 32)),
                            jnp.bfloat16)
            s, b = jnp.ones(32), jnp.zeros(32)
            # the oracle consumes the SAME bf16-quantized values
            want = self.oracle_f64(np.asarray(x, np.float64), s, b, 8)
            got = np.asarray(_group_norm_fwd_pallas(
                x, s, b, 8, 1e-6, False), np.float64)
            assert np.abs(got - want).max() < 3e-2, center


class TestVmemGate:
    def test_large_blocks_fall_back(self):
        # the ResNet stem shape (112·112·64): C=64 pads to 128 lanes → 2×
        assert not _fits_vmem(112, 112, 64, 2)
        assert _fits_vmem(56, 56, 256, 2)      # biggest mid-stage block
        assert _fits_vmem(28, 28, 512, 2)

    def test_fallback_still_correct(self):
        # a shape routed to the reference path must match it exactly
        r = np.random.default_rng(3)
        x = jnp.asarray(r.normal(size=(1, 112, 112, 64)).astype(np.float32))
        s, b = jnp.ones(64), jnp.zeros(64)
        np.testing.assert_allclose(
            np.asarray(group_norm(x, s, b, 8)),
            np.asarray(group_norm_reference(x, s, b, 8)), rtol=1e-6)


class TestResNetWiring:
    @pytest.mark.slow
    def test_pallas_gn_params_are_checkpoint_compatible(self):
        """gn_impl='pallas' must produce the identical param tree as the
        default, so published bundles load into either variant."""
        from mmlspark_tpu.models.resnet import resnet18_thin

        r = np.random.default_rng(0)
        x = jnp.asarray(r.normal(size=(2, 32, 32, 3)).astype(np.float32))
        m_x = resnet18_thin(num_classes=5)
        m_p = resnet18_thin(num_classes=5, gn_impl="pallas")
        p_x = m_x.init(jax.random.PRNGKey(0), x)["params"]
        p_p = m_p.init(jax.random.PRNGKey(0), x)["params"]
        tx = jax.tree_util.tree_structure(p_x)
        tp = jax.tree_util.tree_structure(p_p)
        assert tx == tp

        # same weights → same outputs (within bf16 tolerance)
        a = np.asarray(m_x.apply({"params": p_x}, x, output="features"))
        c = np.asarray(m_p.apply({"params": p_x}, x, output="features"))
        np.testing.assert_allclose(a, c, rtol=3e-2, atol=3e-2)

    def test_zoo_exposes_gn_impl(self):
        from mmlspark_tpu.models.zoo import get_model
        b = get_model("ResNet_Small", num_classes=3, gn_impl="pallas")
        assert b.module.gn_impl == "pallas"


def test_indivisible_groups_raise():
    x = jnp.zeros((1, 4, 4, 20))
    s, b = jnp.ones(20), jnp.zeros(20)
    with pytest.raises(ValueError, match="not divisible"):
        group_norm(x, s, b, 3)
    with pytest.raises(ValueError, match="not divisible"):
        group_norm_reference(x, s, b, 3)


def test_unknown_gn_impl_raises():
    from mmlspark_tpu.models.resnet import resnet18_thin
    m = resnet18_thin(num_classes=2, gn_impl="Pallas")  # typo'd case
    with pytest.raises(ValueError, match="unknown gn_impl"):
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))


class TestDeviceAugment:
    """Device-side batched augmentation (ops/augment.py): jit-safe,
    per-sample randomness, exact semantics (SURVEY §2.5 item 4 — the
    in-step counterpart to the host-side ImageSetAugmenter)."""

    @staticmethod
    def batch(n=8, h=8, w=6, seed=0):
        r = np.random.default_rng(seed)
        return jnp.asarray(r.normal(size=(n, h, w, 3)).astype(np.float32))

    def test_flip_semantics_and_per_sample_independence(self):
        from mmlspark_tpu.ops import random_flip_lr
        x = self.batch(64)
        out = jax.jit(random_flip_lr)(jax.random.PRNGKey(0), x)
        flipped = np.asarray(out) == np.asarray(x[:, :, ::-1, :])
        kept = np.asarray(out) == np.asarray(x)
        per_sample_flip = flipped.all(axis=(1, 2, 3))
        per_sample_keep = kept.all(axis=(1, 2, 3))
        # every sample is exactly one of the two, and both occur
        assert (per_sample_flip | per_sample_keep).all()
        assert per_sample_flip.any() and per_sample_keep.any()

    def test_crop_matches_manual_slice(self):
        from mmlspark_tpu.ops import random_crop
        x = self.batch(4, h=8, w=8)
        out = jax.jit(lambda k, b: random_crop(k, b, 2))(
            jax.random.PRNGKey(3), x)
        assert out.shape == x.shape
        # each crop must appear verbatim inside the reflect-padded image
        padded = np.pad(np.asarray(x), ((0, 0), (2, 2), (2, 2), (0, 0)),
                        mode="reflect")
        for i in range(4):
            found = any(
                np.array_equal(padded[i, y:y + 8, xo:xo + 8], out[i])
                for y in range(5) for xo in range(5))
            assert found, f"crop {i} not a valid window"

    def test_brightness_and_contrast_bounds(self):
        from mmlspark_tpu.ops import random_brightness, random_contrast
        x = self.batch(16)
        out = random_brightness(jax.random.PRNGKey(1), x, 0.5)
        shift = (np.asarray(out) - np.asarray(x)).reshape(16, -1)
        assert (np.ptp(shift, axis=1) < 1e-5).all()  # per-sample constant
        assert (np.abs(shift[:, 0]) <= 0.5).all()
        out2 = random_contrast(jax.random.PRNGKey(2), x, 0.5, 1.5)
        m_in = np.asarray(x).mean(axis=(1, 2, 3))
        m_out = np.asarray(out2).mean(axis=(1, 2, 3))
        np.testing.assert_allclose(m_out, m_in, atol=1e-5)  # mean preserved

    def test_augment_batch_composes_under_jit(self):
        from mmlspark_tpu.ops import augment_batch
        x = self.batch(8)
        fn = jax.jit(lambda k, b: augment_batch(
            k, b, flip_lr=True, crop_pad=2, brightness=0.1,
            contrast=(0.9, 1.1)))
        a = fn(jax.random.PRNGKey(0), x)
        b = fn(jax.random.PRNGKey(0), x)
        c = fn(jax.random.PRNGKey(1), x)
        assert a.shape == x.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # keyed
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    @pytest.mark.parametrize("dtype", [np.uint8, np.int16, np.float32])
    def test_ops_equal_numpy_oracle_given_the_drawn_params(self, dtype):
        """Property pin (round 10): each augmentation, fed the SAME
        per-sample draws (replayed through the documented key schedule),
        equals its numpy oracle — EXACTLY for integer dtypes (the
        round-half-even + clip edge semantics at the dtype bounds), and
        to reduction-order ULPs for float (XLA sums the contrast mean in
        a different order than numpy)."""
        from mmlspark_tpu.ops import (
            random_brightness, random_contrast, random_crop,
        )
        from mmlspark_tpu.ops.augment import (
            host_brightness, host_contrast, host_crop,
        )

        r = np.random.default_rng(11)
        if dtype == np.float32:
            x = r.normal(size=(24, 9, 7, 3)).astype(np.float32)
            delta = 0.3
        else:
            info = np.iinfo(dtype)
            # include exact boundary pixels so the clip edges are hit
            x = r.integers(info.min, int(info.max) + 1,
                           (24, 9, 7, 3)).astype(dtype)
            x[0] = info.max
            x[1] = info.min
            delta = 25.0
        key = jax.random.PRNGKey(7)

        def check(dev, host):
            dev = np.asarray(dev)
            if dtype == np.float32:
                np.testing.assert_allclose(dev, host, rtol=1e-6,
                                           atol=1e-6)
            else:
                np.testing.assert_array_equal(dev, host)

        shift = np.asarray(jax.random.uniform(
            key, (24, 1, 1, 1), minval=-delta, maxval=delta))
        check(random_brightness(key, jnp.asarray(x), delta),
              host_brightness(x, shift))

        factor = np.asarray(jax.random.uniform(
            key, (24, 1, 1, 1), minval=0.7, maxval=1.4))
        check(random_contrast(key, jnp.asarray(x), 0.7, 1.4),
              host_contrast(x, factor))

        ky, kx = jax.random.split(key)
        oy = np.asarray(jax.random.randint(ky, (24,), 0, 5))
        ox = np.asarray(jax.random.randint(kx, (24,), 0, 5))
        # pad+crop is pure indexing: exact for EVERY dtype
        np.testing.assert_array_equal(
            np.asarray(random_crop(key, jnp.asarray(x), 2)),
            host_crop(x, 2, oy, ox))

    def test_uint8_brightness_saturates_exactly_at_bounds(self):
        # an all-255 batch under any positive shift stays exactly 255;
        # an all-0 batch under any negative shift stays exactly 0 — the
        # boundary half of the round-and-clip contract
        from mmlspark_tpu.ops import random_brightness
        top = jnp.full((8, 4, 4, 3), 255, jnp.uint8)
        bot = jnp.zeros((8, 4, 4, 3), jnp.uint8)
        for seed in range(3):
            key = jax.random.PRNGKey(seed)
            shift = np.asarray(jax.random.uniform(
                key, (8, 1, 1, 1), minval=-30.0, maxval=30.0))
            up = np.asarray(random_brightness(key, top, 30.0))
            dn = np.asarray(random_brightness(key, bot, 30.0))
            assert (up[shift[:, 0, 0, 0] >= 0.5] == 255).all()
            assert (dn[shift[:, 0, 0, 0] <= -0.5] == 0).all()

    def test_uint8_batches_clip_instead_of_wrapping(self):
        # review finding r3: integer pixels must not wrap modularly on a
        # negative brightness draw nor truncate contrast factors to 0/1
        from mmlspark_tpu.ops import random_brightness, random_contrast
        r = np.random.default_rng(5)
        x = jnp.asarray(r.integers(0, 255, (32, 6, 6, 3)), jnp.uint8)
        out = random_brightness(jax.random.PRNGKey(0), x, 25.0)
        assert out.dtype == jnp.uint8
        diff = np.asarray(out, np.int32) - np.asarray(x, np.int32)
        # shifts stay bounded (no modular wrap to ~246)
        assert np.abs(diff).max() <= 26
        assert (diff < 0).any() and (diff > 0).any()  # darken AND brighten
        out2 = random_contrast(jax.random.PRNGKey(1), x, 0.8, 1.2)
        d2 = np.asarray(out2, np.int32) - np.asarray(x, np.int32)
        # intermediate contrast jitter occurs (not all samples 0-or-mean)
        changed = np.abs(d2).reshape(32, -1).max(axis=1)
        assert ((changed > 0) & (changed < 100)).any()

"""Direct coverage of core/schema.py helpers — the metadata protocol's
single point of truth (score-column roles, categorical levels, image
detection, unused-name generation)."""

import numpy as np

from mmlspark_tpu.core.schema import (
    SchemaConstants, find_score_column, find_unused_column_name,
    get_categorical_levels, get_score_value_kind, is_categorical,
    is_image_column, make_image, mark_image_column, set_categorical_levels,
    set_score_column,
)
from mmlspark_tpu.data.table import DataTable


def scored_table():
    t = DataTable({"a": np.arange(3.0), "b": np.arange(3.0),
                   "c": np.arange(3.0)})
    t = set_score_column(t, "model_1", "a", SchemaConstants.SCORES_COLUMN,
                        SchemaConstants.CLASSIFICATION_KIND)
    t = set_score_column(t, "model_2", "b", SchemaConstants.SCORES_COLUMN,
                        SchemaConstants.REGRESSION_KIND)
    return t


# ---- find_score_column with model_uid filtering ----

def test_find_score_column_first_match_without_uid():
    t = scored_table()
    assert find_score_column(t, SchemaConstants.SCORES_COLUMN) == "a"


def test_find_score_column_filters_by_model_uid():
    t = scored_table()
    assert find_score_column(t, SchemaConstants.SCORES_COLUMN,
                             model_uid="model_2") == "b"
    assert find_score_column(t, SchemaConstants.SCORES_COLUMN,
                             model_uid="model_3") is None


def test_find_score_column_purpose_mismatch_returns_none():
    t = scored_table()
    assert find_score_column(t, SchemaConstants.SCORED_LABELS_COLUMN) is None


def test_score_value_kind_round_trip():
    t = scored_table()
    assert get_score_value_kind(t, "a") == \
        SchemaConstants.CLASSIFICATION_KIND
    assert get_score_value_kind(t, "b") == SchemaConstants.REGRESSION_KIND
    assert get_score_value_kind(t, "c") is None


# ---- categorical levels ----

def test_set_get_categorical_levels_round_trip():
    t = DataTable({"cat": np.array([0, 1, 2], np.int32)})
    t = set_categorical_levels(t, "cat", ["lo", "mid", "hi"])
    assert is_categorical(t, "cat")
    assert get_categorical_levels(t, "cat") == ["lo", "mid", "hi"]


def test_get_categorical_levels_requires_flag():
    # a levels list without the is_categorical flag is not categorical
    t = DataTable({"cat": np.array([0, 1], np.int32)})
    t = t.with_meta(
        "cat", **{SchemaConstants.K_CATEGORICAL_LEVELS: ["x", "y"]})
    assert get_categorical_levels(t, "cat") is None
    assert not is_categorical(t, "cat")


def test_categorical_levels_survive_with_column_rebuild():
    t = DataTable({"cat": np.array([0, 1], np.int32)})
    t = set_categorical_levels(t, "cat", [10, 20])
    t = t.with_column("other", np.arange(2.0))
    assert get_categorical_levels(t, "cat") == [10, 20]


# ---- find_unused_column_name collision chains ----

def test_find_unused_column_name_no_collision():
    t = DataTable({"x": np.arange(2.0)})
    assert find_unused_column_name(t, "features") == "features"


def test_find_unused_column_name_walks_collision_chain():
    t = DataTable({"features": np.arange(2.0),
                   "features_1": np.arange(2.0),
                   "features_2": np.arange(2.0)})
    assert find_unused_column_name(t, "features") == "features_3"


# ---- is_image_column (incl. the leading-None regression) ----

def test_is_image_column_detects_structs_and_meta():
    img = make_image("p", np.zeros((4, 4, 3), np.uint8))
    t = DataTable({"image": [img, img]})
    assert is_image_column(t, "image")
    t2 = DataTable({"blob": [{"weird": 1}, {"weird": 2}]})
    assert not is_image_column(t2, "blob")
    t2 = mark_image_column(t2, "blob")  # explicit meta wins
    assert is_image_column(t2, "blob")


def test_is_image_column_skips_leading_none():
    # regression: a leading None (failed decode / missing row) must not
    # hide an image column from first-cell sniffing
    img = make_image("p", np.zeros((4, 4, 3), np.uint8))
    t = DataTable({"image": [None, None, img]})
    assert is_image_column(t, "image")


def test_is_image_column_skips_leading_nan():
    # NaN is the other missing spelling (shared is_missing predicate)
    img = make_image("p", np.zeros((4, 4, 3), np.uint8))
    t = DataTable({"image": [float("nan"), img]})
    assert is_image_column(t, "image")


def test_is_image_column_all_none_and_non_object():
    assert not is_image_column(DataTable({"c": [None, None]}), "c")
    assert not is_image_column(DataTable({"c": np.arange(3.0)}), "c")
    assert not is_image_column(DataTable({"c": [None, "str"]}), "c")

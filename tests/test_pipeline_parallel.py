"""Pipeline parallelism (pp axis): numerics + gradient parity vs the
sequential layer stack, on the 8-virtual-device CPU mesh.

The reference has no PP at all (SURVEY §2.6); these tests hold the
implementation to the only acceptable standard for a parallelism
transform — bit-level agreement (f32) with the unpipelined program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_spec, stack_layer_params,
)

L, D = 8, 16


def block_fn(layer, h):
    # residual MLP block: h + relu(h @ w + b) @ w2
    return h + jnp.tanh(h @ layer["w"] + layer["b"]) @ layer["w2"]


def make_layers(key):
    layers = []
    for i in range(L):
        k1, k2, key = jax.random.split(key, 3)
        layers.append({
            "w": jax.random.normal(k1, (D, D), jnp.float32) * 0.3,
            "b": jnp.zeros((D,), jnp.float32),
            "w2": jax.random.normal(k2, (D, D), jnp.float32) * 0.3,
        })
    return layers


def sequential(layers, x):
    for layer in layers:
        x = block_fn(layer, x)
    return x


@pytest.fixture(scope="module")
def setup():
    layers = make_layers(jax.random.PRNGKey(0))
    stacked = stack_layer_params(layers)
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (16, D)))
    return layers, stacked, x


@pytest.mark.parametrize("spec,micro", [
    (MeshSpec(dp=1, pp=4), 4),
    (MeshSpec(dp=1, pp=8), 2),
    (MeshSpec(dp=2, pp=4), 4),      # PP x DP composed in one program
    (MeshSpec(dp=2, fsdp=2, pp=2), 2),
])
def test_forward_matches_sequential(setup, spec, micro):
    layers, stacked, x = setup
    mesh = make_mesh(spec)
    dev = jax.device_put(stacked, pipeline_spec(mesh, stacked))
    out = pipeline_apply(block_fn, dev, jnp.asarray(x), mesh,
                         num_microbatches=micro)
    ref = sequential(layers, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.slow
def test_gradients_match_sequential(setup):
    layers, stacked, x = setup
    mesh = make_mesh(MeshSpec(dp=2, pp=4))
    dev = jax.device_put(stacked, pipeline_spec(mesh, stacked))
    xj = jnp.asarray(x)

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(block_fn, p, xj, mesh,
                                      num_microbatches=4) ** 2)

    def loss_seq(p):
        h = xj
        def body(h, layer):
            return block_fn(layer, h), None
        h, _ = jax.lax.scan(body, h, p)
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(loss_pipe)(dev)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_training_step_under_jit_converges(setup):
    """One jitted pipelined train step loop: loss falls; params stay
    pp-sharded (leading layer axis over pp)."""
    import optax

    layers, stacked, x = setup
    mesh = make_mesh(MeshSpec(dp=2, pp=4))
    params = jax.device_put(stacked, pipeline_spec(mesh, stacked))
    target = jnp.asarray(np.tanh(x @ np.ones((D, D), np.float32) * 0.1))
    xj = jnp.asarray(x)
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        def loss_fn(pp):
            out = pipeline_apply(block_fn, pp, xj, mesh, num_microbatches=4)
            return jnp.mean((out - target) ** 2)
        l, g = jax.value_and_grad(loss_fn)(p)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    losses = []
    for _ in range(12):
        params, opt, l = step(params, opt)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.8, losses
    spec = jax.tree_util.tree_leaves(params)[0].sharding.spec
    assert "pp" in str(spec)


def test_bad_divisibility_raises(setup):
    _, stacked, x = setup
    mesh = make_mesh(MeshSpec(dp=1, pp=4))
    dev = jax.device_put(stacked, pipeline_spec(mesh, stacked))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(block_fn, dev, jnp.asarray(x), mesh,
                       num_microbatches=3)  # 16 % 3 != 0
    mesh3 = make_mesh(MeshSpec(dp=1, pp=8))
    stacked6 = jax.tree_util.tree_map(lambda a: a[:6], stacked)
    with pytest.raises(ValueError, match="layers not divisible"):
        # host params: pipeline_apply validates L % pp before any commit
        pipeline_apply(block_fn, stacked6, jnp.asarray(x), mesh3,
                       num_microbatches=2)


@pytest.mark.slow
def test_pipelines_real_vit_encoder_blocks():
    """PP on a real model family: the ViT EncoderBlock (flax module)
    pipelines over pp with stacked per-layer params and matches the
    sequential stack — the model-integration proof, same as MoE's."""
    from mmlspark_tpu.models.vit import EncoderBlock

    block = EncoderBlock(dim=32, heads=4, mlp_dim=64, dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    dummy = jnp.zeros((1, 6, 32))
    layer_params = [block.init(jax.random.fold_in(key, i), dummy)["params"]
                    for i in range(4)]
    stacked = stack_layer_params(layer_params)
    mesh = make_mesh(MeshSpec(dp=2, pp=4))
    dev = jax.device_put(stacked, pipeline_spec(mesh, stacked))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 6, 32))
                    .astype(np.float32))

    def vit_block(p, h):
        return block.apply({"params": p}, h)

    out = pipeline_apply(vit_block, dev, x, mesh, num_microbatches=2)
    ref = x
    for p in layer_params:
        ref = block.apply({"params": p}, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

"""Tests for sequence/context parallelism and the sequence model family.

Ring attention and Ulysses all-to-all attention must match single-device
attention numerics on the 8-virtual-device CPU mesh (the local[*] analog);
the BiLSTM tagger is the notebook-304 workload rebuilt with bucketed
batches instead of minibatch-1."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.models.sequence import (
    BiLSTMTagger, TransformerTagger, bucket_batches, pad_sequences,
)
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.parallel.ring_attention import (
    attention_reference, ring_attention, ulysses_attention,
)


def qkv(B=2, L=32, H=4, D=16, seed=0):
    r = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(r.normal(size=(B, L, H, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=1, sp=8))


class TestRingAttention:
    @pytest.mark.slow
    def test_matches_reference(self, sp_mesh):
        q, k, v = qkv()
        ref = attention_reference(q, k, v)
        out = ring_attention(q, k, v, sp_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_causal_matches_reference(self, sp_mesh):
        q, k, v = qkv(seed=1)
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention(q, k, v, sp_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_output_stays_sequence_sharded(self, sp_mesh):
        q, k, v = qkv()
        out = ring_attention(q, k, v, sp_mesh)
        assert "sp" in str(out.sharding.spec)

    @pytest.mark.slow
    def test_long_sequence(self, sp_mesh):
        q, k, v = qkv(B=1, L=512, H=2, D=8, seed=2)
        ref = attention_reference(q, k, v)
        out = ring_attention(q, k, v, sp_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestUlyssesAttention:
    def test_matches_reference(self, sp_mesh):
        q, k, v = qkv(H=8)
        ref = attention_reference(q, k, v)
        out = ulysses_attention(q, k, v, sp_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_causal(self, sp_mesh):
        q, k, v = qkv(H=8, seed=3)
        ref = attention_reference(q, k, v, causal=True)
        out = ulysses_attention(q, k, v, sp_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_head_divisibility_check(self, sp_mesh):
        q, k, v = qkv(H=4)  # 4 heads over 8-way sp → error
        with pytest.raises(ValueError, match="heads"):
            ulysses_attention(q, k, v, sp_mesh)


class TestSequenceModels:
    def test_bilstm_tagger_learns_toy_tagging(self):
        # toy task: tag = 1 where token id is even, else 0
        r = np.random.default_rng(0)
        toks = r.integers(1, 50, size=(64, 16)).astype(np.int32)
        tags = (toks % 2 == 0).astype(np.int64)
        model = BiLSTMTagger(vocab_size=64, embed_dim=16, hidden=32,
                             num_tags=2)
        import optax
        params = model.init(jax.random.PRNGKey(0), toks[:1])["params"]
        tx = optax.adam(1e-2)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt, x, y):
            def loss_fn(p):
                logits = model.apply({"params": p}, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()
            loss, g = jax.value_and_grad(loss_fn)(params)
            up, opt = tx.update(g, opt)
            return optax.apply_updates(params, up), opt, loss

        for _ in range(60):
            params, opt, loss = step(params, opt, toks, tags)
        pred = model.apply({"params": params}, toks).argmax(-1)
        assert (np.asarray(pred) == tags).mean() > 0.95

    @pytest.mark.slow
    def test_transformer_tagger_ring_equals_local(self, sp_mesh):
        # the same fitted params must produce identical outputs whether
        # attention runs locally or sequence-parallel over the mesh
        from mmlspark_tpu.parallel.ring_attention import ring_attention
        model = TransformerTagger(vocab_size=64, embed_dim=32, num_heads=8,
                                  num_layers=1, mlp_dim=32, num_tags=4,
                                  max_len=64)
        toks = np.arange(2 * 32, dtype=np.int32).reshape(2, 32) % 64
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        local = model.apply({"params": params}, toks)
        ring = model.apply(
            {"params": params}, toks,
            attention_fn=lambda q, k, v, m, causal: ring_attention(
                q, k, v, sp_mesh, causal=causal, kv_mask=m))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(local),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_causal_model_stays_causal_on_parallel_path(self, sp_mesh):
        # a causal=True tagger must pass causality through attention_fn —
        # the sequence-parallel path must match the local causal output
        from mmlspark_tpu.parallel.ring_attention import ring_attention
        model = TransformerTagger(vocab_size=64, embed_dim=32, num_heads=8,
                                  num_layers=1, mlp_dim=32, num_tags=4,
                                  max_len=64, causal=True)
        toks = np.arange(2 * 32, dtype=np.int32).reshape(2, 32) % 64
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        local = model.apply({"params": params}, toks)
        ring = model.apply(
            {"params": params}, toks,
            attention_fn=lambda q, k, v, m, causal: ring_attention(
                q, k, v, sp_mesh, causal=causal, kv_mask=m))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(local),
                                   rtol=2e-4, atol=2e-4)
        # and the bidirectional output must genuinely differ (guards against
        # the parallel path silently ignoring causality)
        bidi = TransformerTagger(vocab_size=64, embed_dim=32, num_heads=8,
                                 num_layers=1, mlp_dim=32, num_tags=4,
                                 max_len=64, causal=False)
        assert not np.allclose(
            np.asarray(bidi.apply({"params": params}, toks)),
            np.asarray(local))


class TestPaddingMasks:
    @pytest.mark.slow
    def test_ring_attention_kv_mask_matches_unpadded(self, sp_mesh):
        # attention over a padded sequence with kv_mask must equal attention
        # over the unpadded prefix (for the real query positions)
        B, L, H, D = 1, 32, 4, 8
        r = np.random.default_rng(5)
        real = 16
        q = jnp.asarray(r.normal(size=(B, L, H, D)), jnp.float32)
        k, v = (jnp.asarray(r.normal(size=(B, L, H, D)), jnp.float32)
                for _ in range(2))
        mask = np.zeros((B, L), bool)
        mask[:, :real] = True
        out = ring_attention(q, k, v, sp_mesh, kv_mask=jnp.asarray(mask))
        ref = attention_reference(q[:, :real], k[:, :real], v[:, :real])
        np.testing.assert_allclose(np.asarray(out)[:, :real],
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_transformer_logits_invariant_to_padding(self):
        # the same sentence must score identically in a 16-pad and a 32-pad
        # batch when the mask is supplied
        model = TransformerTagger(vocab_size=32, embed_dim=16, num_heads=2,
                                  num_layers=1, mlp_dim=16, num_tags=3,
                                  max_len=64)
        seq = list(range(1, 11))  # 10 real tokens
        toks16, mask16 = pad_sequences([seq], 16)
        toks32, mask32 = pad_sequences([seq], 32)
        params = model.init(jax.random.PRNGKey(0), toks16)["params"]
        a = model.apply({"params": params}, toks16, mask=jnp.asarray(mask16))
        b = model.apply({"params": params}, toks32, mask=jnp.asarray(mask32))
        np.testing.assert_allclose(np.asarray(a)[0, :10],
                                   np.asarray(b)[0, :10],
                                   rtol=1e-5, atol=1e-5)

    def test_bilstm_respects_seq_lengths(self):
        model = BiLSTMTagger(vocab_size=32, embed_dim=8, hidden=16,
                             num_tags=2)
        seq = [5, 7, 9]
        toks8, mask8 = pad_sequences([seq], 8)
        toks16, mask16 = pad_sequences([seq], 16)
        params = model.init(jax.random.PRNGKey(0), toks8)["params"]
        a = model.apply({"params": params}, toks8, mask=jnp.asarray(mask8))
        b = model.apply({"params": params}, toks16, mask=jnp.asarray(mask16))
        np.testing.assert_allclose(np.asarray(a)[0, :3], np.asarray(b)[0, :3],
                                   rtol=1e-5, atol=1e-5)


class TestBucketing:
    def test_pad_sequences(self):
        toks, mask = pad_sequences([[1, 2], [3, 4, 5, 6]], 4)
        np.testing.assert_array_equal(toks[0], [1, 2, 0, 0])
        np.testing.assert_array_equal(mask[0], [1, 1, 0, 0])
        np.testing.assert_array_equal(toks[1], [3, 4, 5, 6])

    def test_bucket_batches_bounded_shapes(self):
        r = np.random.default_rng(0)
        seqs = [list(range(int(n))) for n in r.integers(1, 600, size=50)]
        shapes = set()
        seen = []
        for toks, mask, idx in bucket_batches(seqs, batch_size=8,
                                              bucket_sizes=(64, 256, 1024)):
            shapes.add(toks.shape[1])
            seen.extend(idx.tolist())
            # every sequence fits its bucket
            assert mask.sum(axis=1).max() <= toks.shape[1]
        assert shapes <= {64, 256, 1024}
        assert sorted(seen) == list(range(50))

    def test_overlong_sequence_is_a_typed_error(self):
        # used to be silently truncated into the top bucket — dropping
        # tokens with no signal; now a ValueError names the sequence
        seqs = [list(range(100))]
        with pytest.raises(ValueError, match="largest bucket"):
            list(bucket_batches(seqs, 4, bucket_sizes=(8, 16)))

    def test_pad_sequences_rejects_overlong(self):
        with pytest.raises(ValueError, match="truncation"):
            pad_sequences([[1, 2, 3, 4, 5]], 4)

    def test_empty_sequence_is_a_typed_error(self):
        with pytest.raises(ValueError, match="empty"):
            pad_sequences([[1, 2], []], 4)
        with pytest.raises(ValueError, match="empty"):
            list(bucket_batches([[]], 4, bucket_sizes=(8,)))

    def test_non_integer_tokens_are_a_typed_error(self):
        with pytest.raises(TypeError, match="non-integer"):
            pad_sequences([[1.5, 2.5]], 4)
        with pytest.raises(TypeError, match="non-integer"):
            list(bucket_batches([["a", "b"]], 4, bucket_sizes=(8,)))
        # float-typed but integer-valued ids pass (numpy upcasts freely)
        toks, _ = pad_sequences([np.asarray([1.0, 2.0])], 4)
        np.testing.assert_array_equal(toks[0], [1, 2, 0, 0])

    def test_nested_sequence_is_a_typed_error(self):
        with pytest.raises(ValueError, match="1-D"):
            pad_sequences([[[1, 2], [3, 4]]], 4)

    def test_unsorted_bucket_sizes_still_smallest_covering(self):
        # an unsorted tuple must not over-pad: a 10-token sequence belongs
        # in the 16 bucket even when 128 is listed first
        seqs = [list(range(10))]
        batches = list(bucket_batches(seqs, 4, bucket_sizes=(128, 16, 64)))
        assert len(batches) == 1
        assert batches[0][0].shape == (1, 16)


@pytest.mark.slow  # 2k-4k token oracles
class TestLongContext:
    """Round-3: genuinely long sequences through the SP paths — the
    first-class long-context claim at lengths where a naive [L, L] score
    matrix would already be the dominant memory term."""

    def test_ring_attention_4k_tokens(self, sp_mesh):
        # blockwise ring: peak per-device score block is (L/sp)² = 512²,
        # 64× smaller than the full 4096² matrix the reference's padded
        # approach would imply
        q, k, v = qkv(B=1, L=4096, H=2, D=8, seed=5)
        ref = attention_reference(q, k, v)
        out = ring_attention(q, k, v, sp_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-5, atol=5e-5)

    def test_causal_2k_with_padding_mask(self, sp_mesh):
        r = np.random.default_rng(7)
        L = 2048
        q, k, v = qkv(B=2, L=L, H=2, D=8, seed=6)
        mask = jnp.asarray(np.arange(L)[None, :] <
                           np.asarray([L, L - 300])[:, None])
        ref = attention_reference(q, k, v, causal=True, kv_mask=mask)
        out = ring_attention(q, k, v, sp_mesh, causal=True, kv_mask=mask)
        # batch 0 is unpadded: compare every query position, including the
        # final causal ring blocks; batch 1 only over its valid prefix
        np.testing.assert_allclose(np.asarray(out)[0], np.asarray(ref)[0],
                                   rtol=5e-5, atol=5e-5)
        np.testing.assert_allclose(np.asarray(out)[1, :L - 300],
                                   np.asarray(ref)[1, :L - 300],
                                   rtol=5e-5, atol=5e-5)

    def test_ulysses_2k_tokens(self, sp_mesh):
        q, k, v = qkv(B=1, L=2048, H=8, D=8, seed=8)
        ref = attention_reference(q, k, v)
        out = ulysses_attention(q, k, v, sp_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-5, atol=5e-5)

"""JaxModel inference, model zoo, trainer, and mesh tests (CPU backend,
8 virtual devices — the local[*] analog)."""

import numpy as np
import pytest

from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.core.schema import make_image
from mmlspark_tpu.models.jax_model import JaxModel, coerce_input_matrix, minibatches
from mmlspark_tpu.models.zoo import ZOO, get_model
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh


def small_cifar_bundle():
    return get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)


def image_table(n=10, seed=0):
    r = np.random.default_rng(seed)
    imgs = [make_image(f"img{i}", r.integers(0, 255, (32, 32, 3)))
            for i in range(n)]
    return DataTable({"image": imgs})


# ---- minibatch iterator ----

def test_minibatches_pads_tail():
    batch = np.arange(10, dtype=np.float32).reshape(10, 1)
    chunks = list(minibatches(batch, 4))
    assert [v for _, v in chunks] == [4, 4, 2]
    assert all(c.shape == (4, 1) for c, _ in chunks)
    assert chunks[-1][0][2:].sum() == 0  # zero padding


def test_coerce_image_column():
    # uint8 image bytes stay uint8 (¼ the transfer bytes; device upcasts)
    t = image_table(3)
    m = coerce_input_matrix(t, "image", (32, 32, 3))
    assert m.shape == (3, 32, 32, 3)
    assert m.dtype in (np.uint8, np.float32)
    src = np.asarray(t["image"][0]["data"])
    assert m.dtype == (np.uint8 if src.dtype == np.uint8 else np.float32)


def test_coerce_vector_column_reshape():
    t = DataTable({"v": [np.arange(12.0) for _ in range(4)]})
    m = coerce_input_matrix(t, "v", (3, 4))
    assert m.shape == (4, 3, 4)


def test_coerce_wrong_size_raises():
    t = DataTable({"v": [np.arange(5.0)]})
    with pytest.raises(ValueError):
        coerce_input_matrix(t, "v", (3, 4))


# ---- JaxModel ----

def test_jax_model_logits_and_nodes():
    bundle = small_cifar_bundle()
    t = image_table(7)
    jm = JaxModel(input_col="image", output_col="scores",
                  minibatch_size=4)
    jm.set(model=bundle)
    out = jm.transform(t)
    scores = np.stack(list(out["scores"]))
    assert scores.shape == (7, 10)
    # features node by name
    jm2 = JaxModel(input_col="image", output_col="feat",
                   output_node="features", minibatch_size=4)
    jm2.set(model=bundle)
    feats = np.stack(list(jm2.transform(t)["feat"]))
    assert feats.shape == (7, 32)
    # node by index
    jm3 = jm2.copy()
    jm3.set(output_node=None, output_node_index=0)
    feats2 = np.stack(list(jm3.transform(t)["feat"]))
    np.testing.assert_allclose(feats, feats2)


def test_jax_model_batch_size_invariance():
    """Output must not depend on minibatch slicing (padding correctness)."""
    bundle = small_cifar_bundle()
    t = image_table(5)
    outs = []
    for bs in (2, 5, 64):
        jm = JaxModel(input_col="image", output_col="s", minibatch_size=bs)
        jm.set(model=bundle)
        outs.append(np.stack(list(jm.transform(t)["s"])))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(outs[0], outs[2], rtol=1e-4, atol=1e-4)


def test_jax_model_empty_table():
    bundle = small_cifar_bundle()
    jm = JaxModel(input_col="image", output_col="s")
    jm.set(model=bundle)
    out = jm.transform(DataTable({"image": []}))
    assert len(out) == 0 and "s" in out


def test_jax_model_bad_node():
    bundle = small_cifar_bundle()
    jm = JaxModel(input_col="image", output_col="s", output_node="nope")
    jm.set(model=bundle)
    with pytest.raises(ValueError):
        jm.transform(image_table(2))


def test_patch_conv_matches_direct_conv():
    """PatchConv3x3 must be numerically the same op as nn.Conv 3x3 SAME —
    identical params, identical output (it's a layout trick, not a model
    change)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.zoo import PatchConv3x3

    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(2, 8, 8, 3)), jnp.float32)
    pc = PatchConv3x3(16, dtype=jnp.float32)
    params = pc.init(jax.random.PRNGKey(0), x)["params"]
    direct = nn.Conv(16, (3, 3), dtype=jnp.float32)
    out_patch = pc.apply({"params": params}, x)
    out_direct = direct.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_patch),
                               np.asarray(out_direct), rtol=1e-5, atol=1e-5)


def test_jax_model_inference_is_mesh_sharded():
    """Scoring must use every device: batches commit to the dp sharding and
    params upload once, replicated (CNTKModel's DP inference, mesh-native)."""
    import jax

    bundle = small_cifar_bundle()
    jm = JaxModel(input_col="image", output_col="s", minibatch_size=16)
    jm.set(model=bundle)
    t = image_table(16)
    single = np.stack(list(jm.transform(t)["s"]))
    # the cached compiled entry carries a replicated device param tree and a
    # dp extent covering all local devices
    node = jm._resolve_node(bundle)
    fn, dev_params, data, dp = jm._compiled_apply(bundle, node)
    assert dp == jax.local_device_count() == 8
    leaf = jax.tree_util.tree_leaves(dev_params)[0]
    assert len(leaf.sharding.device_set) == 8
    # a sharded batch placed through the advertised sharding spans all chips
    probe = jax.device_put(np.zeros((16, 32, 32, 3), np.float32), data)
    assert len(probe.sharding.device_set) == 8
    # numerics match an explicit single-device mesh
    jm1 = JaxModel(input_col="image", output_col="s", minibatch_size=16,
                   mesh_spec={"dp": 1})
    jm1.set(model=bundle)
    jm1.__dict__["_mesh_cache"] = None
    import mmlspark_tpu.parallel.mesh as mesh_lib
    jm1.__dict__["_mesh_cache"] = mesh_lib.make_mesh(
        {"dp": 1}, jax.local_devices()[:1])
    one = np.stack(list(jm1.transform(t)["s"]))
    np.testing.assert_allclose(single, one, rtol=1e-4, atol=1e-4)


def test_jax_model_tiny_table_pads_to_mesh():
    # fewer rows than devices: padding must cover the dp extent
    bundle = small_cifar_bundle()
    jm = JaxModel(input_col="image", output_col="s", minibatch_size=64)
    jm.set(model=bundle)
    out = jm.transform(image_table(3))
    assert np.stack(list(out["s"])).shape == (3, 10)


def test_jax_model_save_load(tmp_path):
    bundle = small_cifar_bundle()
    t = image_table(3)
    jm = JaxModel(input_col="image", output_col="s", minibatch_size=4)
    jm.set(model=bundle)
    p = str(tmp_path / "jm")
    jm.save(p)
    loaded = PipelineStage.load(p)
    a = np.stack(list(jm.transform(t)["s"]))
    b = np.stack(list(loaded.transform(t)["s"]))
    np.testing.assert_allclose(a, b, rtol=1e-5)


# ---- zoo ----

def test_zoo_registry():
    assert "ConvNet_CIFAR10" in ZOO and "MLP" in ZOO
    b = get_model("MLP", input_dim=4, num_outputs=3)
    assert b.num_params() > 0
    with pytest.raises(KeyError):
        get_model("nonexistent")


# ---- mesh ----

def test_mesh_spec_resolution():
    assert MeshSpec(dp=-1).resolve(8)["dp"] == 8
    sizes = MeshSpec(dp=-1, tp=2).resolve(8)
    assert sizes["dp"] == 4 and sizes["tp"] == 2
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)


def test_make_mesh_8_devices():
    mesh = make_mesh(MeshSpec(dp=-1, fsdp=2))
    assert mesh.shape["dp"] == 4 and mesh.shape["fsdp"] == 2


# ---- trainer ----

def test_trainer_loss_decreases():
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    r = np.random.default_rng(0)
    x = r.normal(size=(256, 8)).astype(np.float32)
    w = r.normal(size=(8,))
    y = (x @ w > 0).astype(np.int64)
    cfg = TrainConfig(batch_size=64, epochs=30, learning_rate=5e-3,
                      log_every=1)
    tr = Trainer(MLP(features=(32,), num_outputs=2), cfg)
    tr.fit_arrays(x, y)
    assert tr.history[-1] < tr.history[0] * 0.7


def test_graft_entry_single():
    import __graft_entry__ as ge
    import jax
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


@pytest.mark.slow  # the driver runs dryrun_multichip separately too
def test_graft_entry_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


# ---- round-3 regression tests (ADVICE r2) ----

def test_compiled_apply_no_stale_cache_on_params_reassign():
    """Reassigning bundle.params must not serve stale device weights, even
    if CPython reuses the freed dict's id (ADVICE r2: the cache entry now
    pins the keyed params object alive, so id-reuse is impossible)."""
    bundle = small_cifar_bundle()
    jm = JaxModel(model=bundle, input_col="image", output_col="scores",
                  minibatch_size=4)
    t = image_table(4)
    out1 = np.stack(jm.transform(t)["scores"])
    cache = jm.__dict__["_jit_cache"]
    assert all(entry[-1][1] is bundle.params for entry in cache.values())
    # mutate the model the way tools/build_model_repo does: new params tree
    import jax
    for _ in range(3):
        bundle.params = jax.tree_util.tree_map(
            lambda p: p * 0.0, bundle.params)
        out2 = np.stack(jm.transform(t)["scores"])
    assert not np.allclose(out1, out2)  # zeroed weights → different scores
    # repeated reassignment must not grow the cache (stale device trees
    # would otherwise accumulate until OOM)
    assert len(jm.__dict__["_jit_cache"]) == 1


def test_coerce_heterogeneous_image_dtypes_fall_back_to_float32():
    r = np.random.default_rng(0)
    flt = make_image("b", r.integers(0, 255, (8, 8, 3)))
    # e.g. a normalized image struct: float data in the same schema
    flt["data"] = flt["data"].astype(np.float32) / 255.0 - 0.5
    imgs = [make_image("a", r.integers(0, 255, (8, 8, 3))), flt]
    t = DataTable({"image": imgs})
    m = coerce_input_matrix(t, "image", (8, 8, 3))
    assert m.dtype == np.float32
    assert np.allclose(m[1], np.asarray(t["image"][1]["data"]))


def test_make_mesh_explicit_spec_uses_device_prefix():
    import jax
    n = jax.device_count()
    if n < 2:
        pytest.skip("needs >1 device")
    mesh = make_mesh(MeshSpec(dp=1, fsdp=1))
    assert mesh.devices.size == 1
    mesh2 = make_mesh(MeshSpec(dp=2))
    assert mesh2.devices.size == 2


# ---- frozen-BN fold (the ResNet inference variant) ----

def test_fold_batchnorm_numerics_parity():
    """Folded frozen-BN net must equal the BN net in inference mode —
    the fold is algebra, not an approximation (models/resnet.py). Stats
    are perturbed away from the init (mean 0 / var 1) so the fold is
    non-trivial."""
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.resnet import fold_batchnorm, resnet18_thin

    bn = resnet18_thin(norm="batch", dtype=jnp.float32)
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(4, 32, 32, 3)).astype(np.float32))
    variables = bn.init(jax.random.PRNGKey(0), x)
    rs = np.random.default_rng(1)
    stats = jax.tree_util.tree_map(
        lambda a: jnp.abs(a + rs.normal(size=a.shape).astype(np.float32)
                          * 0.3) + 0.05,
        variables["batch_stats"])
    variables = {"params": variables["params"], "batch_stats": stats}

    ref = bn.apply(variables, x, train=False)
    folded = fold_batchnorm(variables)
    nf = resnet18_thin(norm="none", dtype=jnp.float32)
    got = nf.apply({"params": folded}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_fold_batchnorm_bf16_keeps_constants_f32_and_tracks_reference():
    """The folded-BN constants contract (round 12): under
    ``param_dtype=bf16`` only the ≥2-D kernels narrow — the μ/σ-derived
    ``fold*`` biases (and every 1-D leaf) stay float32 and are added at
    an explicit f32 site, so a bf16 inference variant's error is bounded
    by the conv-output quantization alone, never by quantized
    normalization constants. Pinned on trained-scale statistics (means
    far from 0) against the f32 BN net in inference mode."""
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.resnet import fold_batchnorm, resnet18_thin

    bn = resnet18_thin(norm="batch", dtype=jnp.float32)
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(4, 32, 32, 3)).astype(np.float32)
                    * 50 + 100)  # raw-pixel-scale input
    variables = bn.init(jax.random.PRNGKey(0), x)
    rs = np.random.default_rng(1)

    def inflate(tree):  # trained-like stats: means ~20, vars ~5
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = inflate(v)
            elif k == "mean":
                out[k] = jnp.asarray(rs.normal(20, 10, v.shape),
                                     jnp.float32)
            else:
                out[k] = jnp.asarray(
                    np.abs(rs.normal(5, 2, v.shape)) + 0.5, jnp.float32)
        return out

    variables = {"params": variables["params"],
                 "batch_stats": inflate(variables["batch_stats"])}
    ref = np.asarray(bn.apply(variables, x, train=False,
                              output="features"))
    folded = fold_batchnorm(variables, param_dtype=jnp.bfloat16)
    for path, leaf in jax.tree_util.tree_flatten_with_path(folded)[0]:
        name = "/".join(str(k) for k in path)
        if "fold" in name or leaf.ndim < 2:
            assert leaf.dtype == jnp.float32, (name, leaf.dtype)
        else:
            assert leaf.dtype == jnp.bfloat16, (name, leaf.dtype)
    nf = resnet18_thin(norm="none", dtype=jnp.bfloat16)
    got = np.asarray(nf.apply({"params": folded}, x, output="features"))
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() / scale < 2e-2, (
        np.abs(got - ref).max(), scale)


def test_s2d_stem_matches_direct_stem():
    """The space-to-depth stem is a layout trick: same params, same output
    as the direct 7x7/s2 conv stem."""
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.resnet import resnet18_thin

    direct = resnet18_thin(norm="none", dtype=jnp.float32, stem="direct")
    s2d = resnet18_thin(norm="none", dtype=jnp.float32, stem="s2d")
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(2, 32, 32, 3)).astype(np.float32))
    variables = direct.init(jax.random.PRNGKey(0), x)
    out_d = direct.apply(variables, x)
    out_s = s2d.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                               rtol=1e-4, atol=1e-5)


def test_resnet_infer_zoo_bundle():
    """The zoo inference variant: bf16 folded KERNELS, runnable end to
    end through the bundle API, feature dim matches the train variant.
    The μ/σ-derived fold constants (and every 1-D leaf) stay float32 —
    the accumulate-in-f32 contract of fold_batchnorm: a bf16 centering
    bias added in bf16 silently degraded normalization numerics."""
    import jax
    import jax.numpy as jnp

    b = get_model("ResNet_Small_Infer")
    flat = jax.tree_util.tree_flatten_with_path(b.params)[0]
    for path, leaf in flat:
        want = jnp.bfloat16 if leaf.ndim >= 2 else jnp.float32
        name = "/".join(str(k) for k in path)
        assert leaf.dtype == want, (name, leaf.dtype)
    out = b.apply(np.zeros((2, 32, 32, 3), np.float32), output="features")
    assert out.shape == (2, 128)
    # no norm params anywhere in the folded tree (the fold* sites hold
    # only the f32 constants)
    names = {"/".join(str(k) for k in path) for path, _ in flat}
    assert not any("gn" in n or "bn" in n for n in names), names
    assert any("fold" in n for n in names), names


def test_resnet_infer_featurizer_product_path():
    """ImageFeaturizer with the folded bundle — the BASELINE config-3
    product path (featurize via the zoo inference variant)."""
    from mmlspark_tpu.models.image_featurizer import ImageFeaturizer

    feat = ImageFeaturizer(input_col="image", output_col="features")
    feat.set_model_by_name("ResNet_Small_Infer")
    out = feat.transform(image_table(6))
    mat = out.column_matrix("features")
    assert mat.shape == (6, 128)
    assert np.isfinite(mat).all()

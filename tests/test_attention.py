"""Fused flash-style attention (ops/pallas/attention.py, round 12).

The PR 10 kernel discipline, applied to the new kernel pair: the Pallas
kernel is pinned ≤ 1 ULP against its XLA reference UNDER JIT (eager
comparisons drift via FMA contraction — repo convention), the numpy
oracle is pinned against the jitted reference, fully-masked rows are
exact zeros, and the ring/ulysses sequence-parallel paths keep their
reference parity with ``impl="pallas"`` (the local block as a kernel).
Runs in interpreter mode on the CPU backend — the kernel body itself
executes, not a shadow path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops.pallas import attention as fa
from mmlspark_tpu.parallel.ring_attention import attention_reference


def bhtd(B=2, H=3, T=48, D=16, seed=0):
    r = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


class TestKernelUlpPins:
    @pytest.mark.parametrize("causal", [False, True])
    def test_kernel_matches_reference_under_jit_one_ulp(self, causal):
        q, k, v = bhtd()
        mask = jnp.asarray(
            np.arange(48)[None, :] < np.asarray([48, 37])[:, None])

        def run(impl):
            fn = jax.jit(lambda a, b, c: fa.flash_attention(
                a, b, c, kv_mask=mask, causal=causal, impl=impl,
                block_k=16))
            return np.asarray(fn(q, k, v))

        np.testing.assert_array_max_ulp(run("xla"), run("pallas"),
                                        maxulp=1)

    def test_numpy_oracle_pinned_against_jitted_reference(self):
        q, k, v = bhtd(seed=1)
        mask = jnp.asarray(
            np.arange(48)[None, :] < np.asarray([48, 30])[:, None])
        ref = np.asarray(jax.jit(
            lambda a, b, c: fa.flash_attention(
                a, b, c, kv_mask=mask, impl="xla", block_k=16))(q, k, v))
        m3 = fa.host_mask3(2, 48, 48, np.asarray(mask), False)
        host = fa.flash_attention_host(
            np.asarray(q), np.asarray(k), np.asarray(v), m3,
            fa._resolve_scale(None, 16), block_k=16)
        np.testing.assert_allclose(host, ref, rtol=1e-5, atol=1e-6)

    def test_matches_plain_softmax_reference(self):
        # the online-softmax recurrence is algebra, not an approximation
        q, k, v = bhtd(seed=2)
        out = fa.flash_attention(q, k, v, impl="pallas", block_k=16)
        ref = attention_reference(q.transpose(0, 2, 1, 3),
                                  k.transpose(0, 2, 1, 3),
                                  v.transpose(0, 2, 1, 3))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref).transpose(0, 2, 1, 3),
            rtol=2e-5, atol=2e-6)

    def test_fully_masked_rows_are_exact_zeros(self):
        q, k, v = bhtd(B=1, seed=3)
        none = jnp.zeros((1, 48), bool)
        for impl in ("xla", "pallas"):
            out = np.asarray(fa.flash_attention(q, k, v, kv_mask=none,
                                                impl=impl))
            assert (out == 0.0).all(), impl

    def test_unknown_impl_raises(self):
        q, k, v = bhtd(B=1, H=1, T=8, D=4)
        with pytest.raises(ValueError, match="unknown attention impl"):
            fa.flash_attention(q, k, v, impl="cuda")

    def test_vmem_gate(self):
        assert fa._fits_vmem(196, 196, 64, 128)     # ViT-B serving tile
        assert not fa._fits_vmem(16384, 16384, 128, 128)


class TestDecodeAttention:
    """The KV-cache decode variant (round 18): one query row per slot
    against the slot-major cache. Same kernel discipline — pallas ≤ 1
    ULP vs the jitted XLA reference, the numpy oracle pinned against the
    jitted reference, fully-masked slots exact zeros — plus the semantic
    anchor: a decode step IS flash attention at ``Tq=1``."""

    def shkd(self, S=4, H=2, Tk=32, D=8, seed=11):
        r = np.random.default_rng(seed)
        q = jnp.asarray(r.normal(size=(S, H, D)), jnp.float32)
        k = jnp.asarray(r.normal(size=(S, H, Tk, D)), jnp.float32)
        v = jnp.asarray(r.normal(size=(S, H, Tk, D)), jnp.float32)
        mask = jnp.asarray(np.arange(Tk)[None, :]
                           <= np.asarray([5, 31, 0, 17])[:, None])
        return q, k, v, mask

    def test_kernel_matches_reference_under_jit_one_ulp(self):
        q, k, v, mask = self.shkd()

        def run(impl):
            fn = jax.jit(lambda a, b, c: fa.decode_attention(
                a, b, c, kv_mask=mask, impl=impl, block_k=16))
            return np.asarray(fn(q, k, v))

        np.testing.assert_array_max_ulp(run("xla"), run("pallas"),
                                        maxulp=1)

    def test_numpy_oracle_pinned_against_jitted_reference(self):
        q, k, v, mask = self.shkd(seed=12)
        ref = np.asarray(jax.jit(
            lambda a, b, c: fa.decode_attention(
                a, b, c, kv_mask=mask, impl="xla", block_k=16))(q, k, v))
        m2 = fa.host_decode_mask2(4, 32, np.asarray(mask))
        host = fa.decode_attention_host(
            np.asarray(q), np.asarray(k), np.asarray(v), m2,
            fa._resolve_scale(None, 8), block_k=16)
        np.testing.assert_allclose(host, ref, rtol=1e-5, atol=1e-6)

    def test_decode_is_flash_attention_at_tq_one(self):
        q, k, v, mask = self.shkd(seed=13)
        out = fa.decode_attention(q, k, v, kv_mask=mask, impl="xla",
                                  block_k=16)
        full = fa.flash_attention(q[:, :, None, :], k, v, kv_mask=mask,
                                  impl="xla", block_k=16)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(full)[:, :, 0],
                                   rtol=2e-5, atol=2e-6)

    def test_fully_masked_slots_are_exact_zeros(self):
        q, k, v, _ = self.shkd(seed=14)
        none = jnp.zeros((4, 32), bool)
        for impl in ("xla", "pallas"):
            out = np.asarray(fa.decode_attention(q, k, v, kv_mask=none,
                                                 impl=impl))
            assert (out == 0.0).all(), impl


class TestBlockUpdate:
    """The ring-hop local block: one online update as a kernel."""

    def test_xla_and_pallas_updates_agree_under_jit(self):
        B, H, T, D = 2, 2, 16, 8
        q, k, v = bhtd(B, H, T, D, seed=4)
        keep = jnp.asarray(
            np.random.default_rng(5).random((B, T, T)) > 0.2)
        m0 = jnp.full((B, H, T, 1), -jnp.inf, jnp.float32)
        d0 = jnp.zeros((B, H, T, 1), jnp.float32)
        a0 = jnp.zeros((B, H, T, D), jnp.float32)
        scale = fa._resolve_scale(None, D)

        def run(impl):
            fn = jax.jit(lambda *a: fa.attention_block_update(
                *a, scale, impl=impl))
            return [np.asarray(x) for x in fn(q, k, v, keep, m0, d0, a0)]

        for got, want in zip(run("pallas"), run("xla")):
            np.testing.assert_array_max_ulp(got, want, maxulp=1)

    def test_one_update_equals_one_flash_tile(self):
        # a single full-width update + the final division IS flash
        # attention — the recurrence the ring accumulates hop by hop
        B, H, T, D = 1, 2, 24, 8
        q, k, v = bhtd(B, H, T, D, seed=6)
        keep = jnp.ones((B, T, T), bool)
        m0 = jnp.full((B, H, T, 1), -jnp.inf, jnp.float32)
        d0 = jnp.zeros((B, H, T, 1), jnp.float32)
        a0 = jnp.zeros((B, H, T, D), jnp.float32)
        scale = fa._resolve_scale(None, D)
        m, den, acc = fa.attention_block_update(q, k, v, keep, m0, d0,
                                                a0, scale, impl="xla")
        one_shot = acc / jnp.maximum(den, np.float32(1e-30))
        full = fa.flash_attention(q, k, v, scale=scale, impl="xla",
                                  block_k=T)
        np.testing.assert_allclose(np.asarray(one_shot),
                                   np.asarray(full), rtol=1e-6, atol=0)


@pytest.fixture(scope="module")
def sp_mesh():
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    return make_mesh(MeshSpec(dp=1, sp=8))


class TestSequenceParallelImpls:
    """ring/ulysses behind ``impl: auto|xla|pallas`` — the collective
    schedule is impl-independent; parity vs the single-device reference
    must hold either way (small shapes here; the long-context pins ride
    the slow suite below)."""

    def test_ring_parity_pallas(self, sp_mesh):
        # the xla path is covered transitively: attention_block_update's
        # xla/pallas agreement is pinned bitwise above, and the slow
        # suite (test_sequence_parallel) runs ring's default path — one
        # sp=8 shard_map compile here is the tier-1 budget's worth
        from mmlspark_tpu.parallel.ring_attention import ring_attention
        r = np.random.default_rng(7)
        B, L, H, D = 1, 16, 2, 8
        q, k, v = (jnp.asarray(r.normal(size=(B, L, H, D)), jnp.float32)
                   for _ in range(3))
        mask = jnp.asarray(np.arange(L)[None, :] < L - 5)
        ref = attention_reference(q, k, v, causal=True, kv_mask=mask)
        out = ring_attention(q, k, v, sp_mesh, causal=True, kv_mask=mask,
                             impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_ulysses_parity(self, sp_mesh, impl):
        from mmlspark_tpu.parallel.ring_attention import ulysses_attention
        r = np.random.default_rng(8)
        B, L, H, D = 1, 16, 8, 8
        q, k, v = (jnp.asarray(r.normal(size=(B, L, H, D)), jnp.float32)
                   for _ in range(3))
        ref = attention_reference(q, k, v)
        out = ulysses_attention(q, k, v, sp_mesh, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # long-context oracles (the acceptance pin: slow-suite
#                    parity unchanged with impl=pallas)
class TestLongContextPallas:
    def test_ring_2k_causal_masked_pallas(self, sp_mesh):
        from mmlspark_tpu.parallel.ring_attention import ring_attention
        r = np.random.default_rng(9)
        L = 2048
        q, k, v = (jnp.asarray(r.normal(size=(2, L, 2, 8)), jnp.float32)
                   for _ in range(3))
        mask = jnp.asarray(np.arange(L)[None, :] <
                           np.asarray([L, L - 300])[:, None])
        ref = attention_reference(q, k, v, causal=True, kv_mask=mask)
        out = ring_attention(q, k, v, sp_mesh, causal=True, kv_mask=mask,
                             impl="pallas")
        np.testing.assert_allclose(np.asarray(out)[0], np.asarray(ref)[0],
                                   rtol=5e-5, atol=5e-5)
        np.testing.assert_allclose(np.asarray(out)[1, :L - 300],
                                   np.asarray(ref)[1, :L - 300],
                                   rtol=5e-5, atol=5e-5)

    def test_ulysses_2k_pallas(self, sp_mesh):
        from mmlspark_tpu.parallel.ring_attention import ulysses_attention
        r = np.random.default_rng(10)
        q, k, v = (jnp.asarray(r.normal(size=(1, 2048, 8, 8)),
                               jnp.float32) for _ in range(3))
        ref = attention_reference(q, k, v)
        out = ulysses_attention(q, k, v, sp_mesh, impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-5, atol=5e-5)


class TestViTFlashWiring:
    """The serving-path attention of models/vit.py: same param tree as
    the einsum path (checkpoints interchangeable), flash_xla and
    flash_pallas bit-identical under jit, outputs close to the bhtd
    baseline."""

    def test_flash_variants_share_params_and_agree(self):
        from mmlspark_tpu.models.vit import vit_tiny
        r = np.random.default_rng(0)
        x = jnp.asarray(r.normal(size=(2, 16, 16, 3)), jnp.float32)
        base_model = vit_tiny(num_classes=4, image_patch=8)
        params = base_model.init(jax.random.PRNGKey(0), x)["params"]
        base = np.asarray(base_model.apply({"params": params}, x))
        outs = {}
        for ai in ("flash_xla", "flash_pallas"):
            m = vit_tiny(num_classes=4, image_patch=8, attn_impl=ai)
            tree = jax.tree_util.tree_structure(
                m.init(jax.random.PRNGKey(0), x)["params"])
            assert tree == jax.tree_util.tree_structure(params)
            outs[ai] = np.asarray(jax.jit(
                lambda xx, m=m: m.apply({"params": params}, xx))(x))
            np.testing.assert_allclose(outs[ai], base, rtol=1e-4,
                                       atol=1e-5)
        np.testing.assert_array_equal(outs["flash_xla"],
                                      outs["flash_pallas"])

    def test_unknown_flash_impl_raises(self):
        from mmlspark_tpu.models.vit import vit_tiny
        m = vit_tiny(num_classes=2, image_patch=8, attn_impl="flashy")
        with pytest.raises(ValueError, match="unknown attention impl"):
            m.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))

"""Every example runs in CI at small scale with asserted outcomes.

The reference executes every sample notebook in its test suite
(tools/notebook/tester/NotebookTestSuite.py:13-60, TestNotebooksLocally.py);
these are the analogs for the 101/102/201/301/302/303/304 family — dead
examples cannot rot silently."""

import importlib
import os
import sys

import pytest

slow = pytest.mark.slow  # runtime tests execute every example end-to-end


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples"))


# examples exposing a build_pipeline() → (pipeline, TableSchema) hook; the
# analyzer validates each statically in tier-1 so example drift (renamed
# columns, mis-wired stages, broken geometry) is caught without running
# anything end-to-end
ANALYZABLE_EXAMPLES = [
    "tabular_classification_101",
    "flight_delay_regression_102",
    "book_reviews_text_201",
    "cifar_eval_301",
    "image_transforms_302",
    "flowers_featurizer_305",
]


@pytest.mark.parametrize("module_name", ANALYZABLE_EXAMPLES)
def test_example_pipelines_analyze_clean(module_name):
    from mmlspark_tpu.analysis import analyze
    mod = importlib.import_module(module_name)
    pipeline, schema = mod.build_pipeline()
    report = analyze(pipeline, schema, n_rows=64)
    assert report.ok, (module_name,
                       [str(d) for d in report.errors])


@pytest.fixture(scope="module")
def zoo_repo(tmp_path_factory):
    """One shared pretrained repo for the 301/303/304 examples."""
    from cifar_eval_301 import ensure_repo
    return ensure_repo(str(tmp_path_factory.mktemp("examples_zoo")))


@slow
def test_example_101_tabular_classification():
    import tabular_classification_101 as ex
    out = ex.run("small")
    assert out["accuracy"] > 0.72, out  # noisy synthetic census task
    assert out["auc"] is None or out["auc"] > 0.85


@slow
def test_example_102_flight_delay_regression():
    import flight_delay_regression_102 as ex
    out = ex.run("small")
    assert out["R^2"] > 0.2, out
    assert out["root_mean_squared_error"] < 12.0


@slow
def test_example_201_text_featurizer():
    import book_reviews_text_201 as ex
    out = ex.run("small")
    assert out["accuracy"] > 0.85, out


@slow
def test_example_301_cifar_eval(zoo_repo):
    import cifar_eval_301 as ex
    out = ex.run("small", repo_dir=zoo_repo)
    # genuinely pretrained zoo weights on REAL held-out data (digits-rgb32
    # split): 10 classes, chance = 0.1 — and the scored accuracy must
    # reproduce the held-out accuracy the publisher recorded in the
    # manifest (the download-a-pretrained-model contract)
    assert out["accuracy"] > 0.9, out
    assert out["manifest_accuracy"] > 0.9, out
    assert abs(out["accuracy"] - out["manifest_accuracy"]) < 0.02, out


@slow
def test_example_302_image_transforms():
    import image_transforms_302 as ex
    out = ex.run("small")
    assert out["transformed_hw"] == [48, 48]
    assert out["feature_dim"] == 3 * 48 * 48
    assert 0.0 < out["feature_mean"] < 1.0


@slow
def test_example_303_transfer_learning(zoo_repo):
    import transfer_learning_303 as ex
    out = ex.run("small", repo_dir=zoo_repo)
    assert out["accuracy"] > 0.85, out


@slow
def test_example_304_medical_entity(zoo_repo):
    import medical_entity_304 as ex
    out = ex.run("small", repo_dir=zoo_repo)
    assert out["token_accuracy"] > 0.9, out
    assert out["bucket_shapes"] == [16, 32, 64]


@slow
def test_example_103_before_after():
    import before_after_103 as ex
    out = ex.run("small")
    # both paths must land in the same accuracy regime (the notebook's
    # point: the one-call API does the same work)
    assert out["after_accuracy"] > 0.72, out
    assert abs(out["before_accuracy"] - out["after_accuracy"]) < 0.12, out


@slow
def test_example_202_word2vec():
    import book_reviews_word2vec_202 as ex
    out = ex.run("small")
    assert out["accuracy"] > 0.85, out
    # embeddings must cluster sentiment vocabulary
    from book_reviews_text_201 import NEGATIVE, POSITIVE
    assert set(out["synonym_probe"]) <= set(POSITIVE + NEGATIVE), out
    assert len(set(out["synonym_probe"]) & set(POSITIVE)) >= 2, out


@slow
def test_example_305_flowers_featurizer(zoo_repo):
    import flowers_featurizer_305 as ex
    out = ex.run("small", repo_dir=zoo_repo)
    # transfer learning must beat the raw-pixel baseline decisively.
    # The genuinely-pretrained (digits-rgb32) backbone measures ~0.63
    # here vs ~0.16 raw pixels; the bar sits below that with margin but
    # well above what untrained features could pass (chance = 0.2)
    assert out["deep_accuracy"] > 0.55, out
    assert out["deep_accuracy"] > 2 * out["raw_pixel_accuracy"], out


@slow
def test_example_306_distributed_finetune():
    import distributed_finetune_306 as ex
    ex.main()  # asserts dp vs dp×pp and dp vs dp×ep loss parity inside

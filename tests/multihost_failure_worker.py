"""Failure-injection worker for the distributed-training failure e2e.

Trains an MLP through ``Trainer.fit_stream`` with per-step checkpointing
(``TrainConfig.checkpoint_dir``). When ``MULTIHOST_FAIL_AT_STEP`` is set
and this process is ``MULTIHOST_FAIL_RANK``, the worker hard-dies
(``os._exit``) from inside its data stream after that many chunks —
mid-training, without cleanup, like a preempted pod worker. The launcher
(mmlspark_tpu.tools.launch) must detect the death and terminate the
survivor instead of leaving it hung in a collective; re-running the same
command with no fail env resumes from the last checkpoint
(SURVEY §5: job-level restart + checkpoint/resume is the recovery story;
the reference only checks one process exit code,
cntk-train/src/main/scala/CNTKLearner.scala:147-151).
"""

import os

import multihost_env  # noqa: F401  (env setup BEFORE jax import)

import jax

multihost_env.pin_platform()

import numpy as np

FAIL_EXIT_CODE = 17


def main() -> None:
    from mmlspark_tpu.utils.env import distributed_init
    distributed_init()
    pid = jax.process_index()

    fail_at = int(os.environ.get("MULTIHOST_FAIL_AT_STEP", "0"))
    fail_rank = int(os.environ.get("MULTIHOST_FAIL_RANK", "1"))
    ckpt_dir = os.environ["MULTIHOST_CKPT_DIR"]

    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    from mmlspark_tpu.train import TrainConfig, Trainer

    def source():
        # deterministic per-process stream: 6 chunks x 8 rows each
        for c in range(6):
            if fail_at and pid == fail_rank and c == fail_at:
                os._exit(FAIL_EXIT_CODE)  # hard mid-training death
            r = np.random.default_rng(1000 + 10 * pid + c)
            xs = r.normal(size=(8, 8)).astype(np.float32)
            ys = ((xs[:, 0] > 0) ^ (xs[:, 1] > 0)).astype(np.int64)
            yield xs, ys

    mesh = make_mesh(MeshSpec(dp=-1))
    cfg = TrainConfig(batch_size=8, epochs=1, learning_rate=5e-3,
                      log_every=1, donate_state=False,
                      checkpoint_dir=ckpt_dir, checkpoint_every=1,
                      resume=True,
                      # sync liveness every step so the failure window is
                      # deterministic for the test
                      liveness_sync_every=1)
    tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
    tr.fit_stream(source, input_spec=(8,))

    multihost_env.write_result(pid, {
        "pid": pid, "steps": int(tr.state["step"]),
        "checksum": multihost_env.params_checksum(tr.params),
        "losses": tr.history}, prefix="fail_out")


if __name__ == "__main__":
    main()

"""Generic fuzzing over EVERY registered stage.

The Fuzzing.scala analog (reference: fuzzing/src/test/scala/Fuzzing.scala:
33-119 serialization coverage with explicit exemption lists, :200-221
reflection-driven discovery; random inputs from core/test/datagen/
GenerateDataset.scala:36-59). Discovery is the stage registry; every stage
is constructed, run against a randomly generated table, saved, loaded, and
re-run — a new stage that breaks persistence or crashes on missing values
fails this suite unless it has an explicit, documented exemption.
"""

from __future__ import annotations

import numpy as np
import pytest

from mmlspark_tpu.core.datagen import labeled_table, random_table
from mmlspark_tpu.core.registry import all_stages
from mmlspark_tpu.core.stage import Estimator, PipelineStage, Transformer
from mmlspark_tpu.data.table import DataTable

# ---------------------------------------------------------------------------
# Per-stage fuzz configuration (the requirements/exemption table,
# Fuzzing.scala:33-119 analog). Keys are class names; every registered stage
# with no entry gets the DEFAULT treatment: construct with defaults, run on
# the generic mixed table. A stage that needs more must add an entry here —
# silently shipping an unfuzzed stage is impossible.
# ---------------------------------------------------------------------------

SKIP: dict[str, str] = {
    # abstract stage contracts: transform/fit are the NotImplementedError
    # interface itself (instantiable, but not runnable by design)
    "Transformer": "abstract contract (transform raises NotImplementedError)",
    "Estimator": "abstract contract (fit raises NotImplementedError)",
    "UnaryTransformer": "abstract contract (_transform_column)",
}


def _tabular(ctx):
    return random_table(seed=ctx["seed"],
                        kinds=("numeric", "integer", "boolean", "string",
                               "categorical", "tokens", "date"))


def _text_table(ctx):
    from mmlspark_tpu.stages.text import HashingTF, Tokenizer
    t = random_table(seed=ctx["seed"], kinds=("string", "tokens"))
    t = t.rename({"string": "text", "tokens": "toks"})
    # None text rows → empty string (tokenizer contract: strings in)
    t = t.with_column("text", [v or "" for v in t["text"]])
    tf = HashingTF(input_col="toks", output_col="tf", num_features=64)
    return tf.transform(t)


def _image_table(ctx):
    return random_table(seed=ctx["seed"], kinds=("image", "numeric"))


def _image_table_32(ctx):
    from mmlspark_tpu.core.schema import make_image, mark_image_column
    r = np.random.default_rng(ctx["seed"])
    t = DataTable({"image": [make_image(f"i{k}", r.integers(0, 255,
                                                            (32, 32, 3)))
                             for k in range(6)]})
    return mark_image_column(t, "image")


def _labeled(ctx):
    return labeled_table(seed=ctx["seed"])


def _labeled_reg(ctx):
    return labeled_table(seed=ctx["seed"], classification=False)


def _vector_table(ctx):
    r = np.random.default_rng(ctx["seed"])
    return DataTable({"input": [r.normal(size=4).astype(np.float32)
                                for _ in range(10)]})


def _scored_table(ctx):
    from mmlspark_tpu.ml.train_classifier import TrainClassifier
    t = _labeled(ctx)
    return TrainClassifier(label_col="label").fit(t).transform(t)


def _small_bundle():
    from mmlspark_tpu.models.zoo import get_model
    return get_model("MLP", input_dim=4, num_outputs=3)


def _conv_bundle():
    from mmlspark_tpu.models.zoo import get_model
    return get_model("ConvNet_CIFAR10", widths=(4, 8), dense_width=16)


def _identity_fn(table):
    # module-level so LambdaTransformer's pickled fn round-trips
    return table


def _fitted(est_name, ctx):
    spec = CONFIG[est_name]
    est = spec["build"](ctx)
    return spec["table"](ctx), est


CONFIG: dict[str, dict] = {
    # ---- core ----
    "LambdaTransformer": dict(
        build=lambda ctx: _cls("LambdaTransformer")(fn=_identity_fn),
        table=_tabular),
    "Pipeline": dict(
        build=lambda ctx: _cls("Pipeline")(stages=[
            _cls("Tokenizer")(input_col="text", output_col="toks2"),
            _cls("ValueIndexer")(input_col="categorical",
                                 output_col="cat_idx"),
        ]),
        table=lambda ctx: _tabular(ctx).rename({"string": "text"})
        .with_column("text", [v or "" for v in _tabular(ctx)["string"]])),
    # (PipelineModel is fuzzed via Pipeline — see _MODEL_VIA)
    # ---- data prep ----
    "SelectColumns": dict(
        build=lambda ctx: _cls("SelectColumns")(cols=["numeric"]),
        table=_tabular),
    "DropColumns": dict(
        build=lambda ctx: _cls("DropColumns")(cols=["numeric"]),
        table=_tabular),
    "RenameColumns": dict(
        build=lambda ctx: _cls("RenameColumns")(
            mapping={"numeric": "numeric2"}),
        table=_tabular),
    "Repartition": dict(
        build=lambda ctx: _cls("Repartition")(n=2), table=_tabular),
    "CheckpointData": dict(
        build=lambda ctx: _cls("CheckpointData")(
            path=str(ctx["tmp"] / "ck.parquet")),
        table=_tabular),
    "ClassBalancer": dict(
        build=lambda ctx: _cls("ClassBalancer")(input_col="categorical"),
        table=_tabular),
    "Timer": dict(
        build=lambda ctx: _cls("Timer")(
            stage=_cls("SelectColumns")(cols=["numeric"])),
        table=_tabular),
    "MultiColumnAdapter": dict(
        build=lambda ctx: _cls("MultiColumnAdapter")(
            base_stage=_cls("Tokenizer")(),
            input_cols=["text"], output_cols=["text_toks"]),
        table=_text_table),
    "ValueIndexer": dict(
        build=lambda ctx: _cls("ValueIndexer")(input_col="categorical",
                                               output_col="idx"),
        table=_tabular),
    "IndexToValue": dict(
        build=lambda ctx: _cls("IndexToValue")(input_col="idx",
                                               output_col="orig"),
        table=lambda ctx: _cls("ValueIndexer")(
            input_col="categorical", output_col="idx").fit(
            _tabular(ctx)).transform(_tabular(ctx))),
    "DataConversion": dict(
        build=lambda ctx: _cls("DataConversion")(cols=["integer"],
                                                 convert_to="double"),
        table=_tabular),
    "CleanMissingData": dict(
        build=lambda ctx: _cls("CleanMissingData")(
            input_cols=["numeric"], output_cols=["numeric_clean"]),
        table=_tabular),
    "EnsembleByKey": dict(
        build=lambda ctx: _cls("EnsembleByKey")(keys=["categorical"],
                                                cols=["numeric"]),
        table=lambda ctx: random_table(
            seed=ctx["seed"], kinds=("numeric", "categorical"),
            missing=0.0)),
    # ---- text ----
    "Tokenizer": dict(
        build=lambda ctx: _cls("Tokenizer")(input_col="text",
                                            output_col="out_toks"),
        table=_text_table),
    "StopWordsRemover": dict(
        build=lambda ctx: _cls("StopWordsRemover")(input_col="toks",
                                                   output_col="kept"),
        table=_text_table),
    "NGram": dict(
        build=lambda ctx: _cls("NGram")(input_col="toks",
                                        output_col="grams"),
        table=_text_table),
    "HashingTF": dict(
        build=lambda ctx: _cls("HashingTF")(input_col="toks",
                                            output_col="tf2",
                                            num_features=32),
        table=_text_table),
    "IDF": dict(
        build=lambda ctx: _cls("IDF")(input_col="tf", output_col="tfidf"),
        table=_text_table),
    "TextFeaturizer": dict(
        build=lambda ctx: _cls("TextFeaturizer")(input_col="text",
                                                 output_col="feats",
                                                 num_features=64),
        table=_text_table),
    "Word2Vec": dict(
        build=lambda ctx: _cls("Word2Vec")(input_col="toks",
                                           output_col="w2v",
                                           vector_size=8, epochs=1,
                                           min_count=1),
        table=_text_table),
    # ---- featurize ----
    "AssembleFeatures": dict(
        build=lambda ctx: _cls("AssembleFeatures")(number_of_features=64),
        table=_tabular),
    "Featurize": dict(
        build=lambda ctx: _cls("Featurize")(number_of_features=64),
        table=_tabular),
    # ---- images ----
    "ImageTransformer": dict(
        build=lambda ctx: _cls("ImageTransformer")().resize(8, 8).flip(1),
        table=_image_table),
    "UnrollImage": dict(
        build=lambda ctx: _cls("UnrollImage")(input_col="image",
                                              output_col="vec"),
        table=_image_table),
    "ImageSetAugmenter": dict(
        build=lambda ctx: _cls("ImageSetAugmenter")(),
        table=_image_table),
    "ImageFeaturizer": dict(
        build=lambda ctx: _cls("ImageFeaturizer")(model=_conv_bundle(),
                                                  minibatch_size=8),
        table=_image_table_32),
    # ---- train/eval ----
    "TrainClassifier": dict(
        build=lambda ctx: _cls("TrainClassifier")(label_col="label"),
        table=_labeled),
    "TrainRegressor": dict(
        build=lambda ctx: _cls("TrainRegressor")(label_col="label"),
        table=_labeled_reg),
    "ComputeModelStatistics": dict(
        build=lambda ctx: _cls("ComputeModelStatistics")(),
        table=_scored_table),
    "ComputePerInstanceStatistics": dict(
        build=lambda ctx: _cls("ComputePerInstanceStatistics")(),
        table=_scored_table),
    "FindBestModel": dict(
        build=lambda ctx: _cls("FindBestModel")(models=[
            _cls("TrainClassifier")(label_col="label").fit(_labeled(ctx)),
            _cls("TrainClassifier")(label_col="label",
                                    number_of_features=32).fit(
                                        _labeled(ctx)),
        ]),
        table=_labeled),
    "JaxLearner": dict(
        build=lambda ctx: _cls("JaxLearner")(label_col="label", epochs=2,
                                             batch_size=16),
        table=_labeled),
    "JaxModel": dict(
        build=lambda ctx: _cls("JaxModel")(model=_small_bundle(),
                                           input_col="input",
                                           output_col="scores",
                                           minibatch_size=8),
        table=_vector_table),
}


_REGISTRY = all_stages()
_BY_NAME = {cls.__name__: cls for cls in _REGISTRY.values()}


def _cls(name: str) -> type:
    return _BY_NAME[name]


# model classes produced by estimators: fuzzed through their estimator
_MODEL_VIA = {
    "PipelineModel": "Pipeline",
    "ValueIndexerModel": "ValueIndexer",
    "CleanMissingDataModel": "CleanMissingData",
    "ClassBalancerModel": "ClassBalancer",
    "TimerModel": "Timer",
    "IDFModel": "IDF",
    "AssembleFeaturesModel": "AssembleFeatures",
    "TrainedClassifierModel": "TrainClassifier",
    "TrainedRegressorModel": "TrainRegressor",
    "BestModel": "FindBestModel",
    "JaxLearnerModel": "JaxLearner",
    "Word2VecModel": "Word2Vec",
}


# ---------------------------------------------------------------------------
# comparison helpers
# ---------------------------------------------------------------------------

def _values_equal(a, b) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        if a.shape != b.shape:
            return False
        if a.dtype.kind in "fc" or b.dtype.kind in "fc":
            return bool(np.allclose(a.astype(np.float64),
                                    b.astype(np.float64), equal_nan=True,
                                    atol=1e-5, rtol=1e-4))
        return bool(np.array_equal(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return (a.keys() == b.keys()
                and all(_values_equal(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return (len(a) == len(b)
                and all(_values_equal(x, y) for x, y in zip(a, b)))
    if isinstance(a, float) and isinstance(b, float):
        return (np.isnan(a) and np.isnan(b)) or bool(np.isclose(a, b))
    if a is None or b is None:
        return a is None and b is None
    try:
        return bool(a == b)
    except Exception:
        return False


def assert_tables_equal(a: DataTable, b: DataTable) -> None:
    assert sorted(a.columns) == sorted(b.columns)
    assert len(a) == len(b)
    for c in a.columns:
        va, vb = list(a[c]), list(b[c])
        for i, (x, y) in enumerate(zip(va, vb)):
            assert _values_equal(x, y), \
                f"column {c!r} row {i}: {x!r} != {y!r}"


# ---------------------------------------------------------------------------
# the fuzz tests
# ---------------------------------------------------------------------------

_ALL_NAMES = sorted(cls.__name__ for cls in _REGISTRY.values())


def _ctx(tmp_path, seed=7):
    return {"tmp": tmp_path, "seed": seed}


@pytest.mark.parametrize("seed", [7, 19])
@pytest.mark.parametrize("name", _ALL_NAMES)
def test_fuzz_stage(name, seed, tmp_path):
    """Construct → run on random data → save → load → identical re-run.
    Two seeds: random_table draws a different schema subset and different
    edge content (missing rates, category counts) per seed."""
    if name in SKIP:
        pytest.skip(SKIP[name])
    ctx = _ctx(tmp_path, seed=seed)
    via = _MODEL_VIA.get(name)
    if via is not None:
        spec = CONFIG[via]
        table = spec["table"](ctx)
        stage = spec["build"](ctx).fit(table)
        assert isinstance(stage, _cls(name)), \
            f"{via}.fit produced {type(stage).__name__}, expected {name}"
    else:
        spec = CONFIG.get(name, {})
        build = spec.get("build", lambda c: _cls(name)())
        table_fn = spec.get("table", _tabular)
        table = table_fn(ctx)
        stage = build(ctx)

    if isinstance(stage, Estimator):
        model = stage.fit(table)
        out = model.transform(table)
        # estimator persistence
        stage.save(str(tmp_path / "est"))
        loaded_est = PipelineStage.load(str(tmp_path / "est"))
        assert type(loaded_est) is type(stage)
        # fitted-model persistence + behavioral equality
        model.save(str(tmp_path / "model"))
        loaded = PipelineStage.load(str(tmp_path / "model"))
        assert_tables_equal(out, loaded.transform(table))
    else:
        out = stage.transform(table)
        assert isinstance(out, DataTable)
        stage.save(str(tmp_path / "stage"))
        loaded = PipelineStage.load(str(tmp_path / "stage"))
        assert type(loaded) is type(stage)
        assert_tables_equal(out, loaded.transform(table))


def test_every_stage_is_covered():
    """Config hygiene: no dangling names, no stage accidentally exempted."""
    for name in list(CONFIG) + list(SKIP) + list(_MODEL_VIA):
        assert name in _BY_NAME, f"fuzz config references unknown {name!r}"
    for name, via in _MODEL_VIA.items():
        assert via in CONFIG, f"{name} routed via unconfigured {via!r}"
    assert len(SKIP) <= 3, "exemption list must stay short and justified"


def test_random_table_determinism():
    a = random_table(seed=3)
    b = random_table(seed=3)
    assert_tables_equal(a, b)
    assert sorted(a.columns) != [] and len(a) == 24


def test_random_table_has_missing_values():
    t = random_table(seed=1, kinds=("numeric", "string"), missing=0.3)
    assert np.isnan(t["numeric"]).any()
    assert any(v is None for v in t["string"])


# ---------------------------------------------------------------------------
# pipeline-level round trips (RoundTripTestBase.testRoundTrip analog,
# reference: core/test/base/src/main/scala/TestBase.scala:179-256): stages
# composed into a Pipeline must fit, save/load as an UNFITTED pipeline,
# save/load as a FITTED PipelineModel, and transform identically.
# ---------------------------------------------------------------------------

PIPELINES = {
    "tabular": lambda: [
        _cls("CleanMissingData")(input_cols=["numeric"],
                                 output_cols=["numeric"]),
        _cls("ValueIndexer")(input_col="categorical", output_col="cat_idx"),
        _cls("AssembleFeatures")(number_of_features=64,
                                 columns_to_featurize=[
                                     "numeric", "integer", "cat_idx"]),
    ],
    "text": lambda: [
        _cls("Tokenizer")(input_col="text", output_col="toks2"),
        _cls("StopWordsRemover")(input_col="toks2", output_col="kept"),
        _cls("HashingTF")(input_col="kept", output_col="tf2",
                          num_features=32),
        _cls("IDF")(input_col="tf2", output_col="tfidf"),
    ],
    "word2vec": lambda: [
        _cls("Tokenizer")(input_col="text", output_col="toks2"),
        _cls("Word2Vec")(input_col="toks2", output_col="emb",
                         vector_size=8, epochs=1, min_count=1),
    ],
}


@pytest.mark.parametrize("name", sorted(PIPELINES))
def test_pipeline_round_trip(name, tmp_path):
    from mmlspark_tpu.core.pipeline import Pipeline

    ctx = _ctx(tmp_path)
    table = _text_table(ctx) if name != "tabular" else _tabular(ctx)
    pipe = Pipeline(stages=PIPELINES[name]())

    fitted = pipe.fit(table)
    out = fitted.transform(table)

    # unfitted pipeline round trip → refit → same outputs
    pipe.save(str(tmp_path / "pipe"))
    pipe2 = PipelineStage.load(str(tmp_path / "pipe"))
    out2 = pipe2.fit(table).transform(table)
    assert_tables_equal(out, out2)

    # fitted model round trip → same outputs without refitting
    fitted.save(str(tmp_path / "model"))
    model2 = PipelineStage.load(str(tmp_path / "model"))
    assert_tables_equal(out, model2.transform(table))

"""core/compile_cache.py — the persistent AOT compile cache: stable
content-addressed fingerprints, atomic publish + digest-verified load
(the ModelRepo discipline applied to XLA programs), typed refusal of
torn/corrupt/version-mismatched entries with in-memory-compile
fallback, benign publish races, the LRU byte budget, and the
unwritable-dir degrade that must never fail a model load."""

import json
import os
import threading

import numpy as np
import pytest

from mmlspark_tpu.core import compile_cache as cc
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import get_model
from mmlspark_tpu.serve import FaultPlan, FaultSpec, ModelServer, ServeConfig
from mmlspark_tpu.serve import faults as serve_faults

FP = "ab" * 32  # a syntactically-valid fingerprint for direct-API tests


@pytest.fixture(autouse=True)
def _no_process_cache():
    """Tests own the process-wide cache state; never leak it."""
    cc.reset()
    yield
    cc.reset()


def _jitted():
    import jax

    return jax.jit(lambda p, x: x * p + 1.0)


def _args():
    return (np.float32(2.0), np.arange(8, dtype=np.float32))


def _cached(tmp_path, fp=FP):
    cache = cc.CompileCache(str(tmp_path / "cache"))
    return cc.CachedJit(_jitted(), fp, cache), cache


def _bundle():
    return get_model("ConvNet_CIFAR10", widths=(4, 8), dense_width=16)


# ---- fingerprints ----


def test_fingerprint_stable_across_fresh_objects():
    """The fingerprint is a CONTENT identity: two independently
    constructed stage objects over the same weights agree (unlike
    device_cache_token, which is deliberately id()-based)."""
    from mmlspark_tpu.core.stage import ArrayMeta

    # two INDEPENDENT object graphs over the same content (zoo init is
    # seeded): same fingerprint, different in-process cache tokens
    jm1 = JaxModel(model=_bundle(), input_col="image",
                   output_col="scores")
    jm2 = JaxModel(model=_bundle(), input_col="image",
                   output_col="scores")
    meta = ArrayMeta((32 * 32 * 3,), "uint8")
    fp1 = cc.plan_fingerprint([jm1], meta)
    fp2 = cc.plan_fingerprint([jm2], meta)
    assert fp1 is not None and fp1 == fp2
    assert jm1.device_cache_token() != jm2.device_cache_token()

    # different weights -> different program -> different key
    perturbed = _perturb(_bundle())
    jm3 = JaxModel(model=perturbed, input_col="image",
                   output_col="scores")
    assert cc.plan_fingerprint([jm3], meta) != fp1

    # a different entry layout is a different program
    meta2 = ArrayMeta((16 * 16 * 3,), "uint8")
    assert cc.plan_fingerprint([jm1], meta2) != fp1


def _perturb(bundle):
    import dataclasses

    import jax

    leaves, treedef = jax.tree_util.tree_flatten(bundle.params)
    leaves = [np.asarray(a).copy() for a in leaves]
    leaves[0] = leaves[0] + 1.0
    try:
        return dataclasses.replace(
            bundle, params=jax.tree_util.tree_unflatten(treedef, leaves))
    except TypeError:
        bundle.params = jax.tree_util.tree_unflatten(treedef, leaves)
        return bundle


def test_unfingerprintable_stage_disables_caching():
    """A stage whose device_fingerprint() is None (e.g. a closure-y
    complex param) makes the segment compile in memory — fingerprint
    None, never a wrong cache key."""
    from mmlspark_tpu.core.stage import ArrayMeta

    class NoFp:
        def device_fingerprint(self):
            return None

    meta = ArrayMeta((4,), "float32")
    assert cc.plan_fingerprint([NoFp()], meta) is None


# ---- round trip + integrity ----


def test_round_trip_hits_and_identical_outputs(tmp_path):
    fn1, cache1 = _cached(tmp_path)
    out1 = np.asarray(fn1(*_args()))
    assert cache1.stats["misses"] == 1 and cache1.stats["puts"] == 1
    assert cache1.stats["compiles"] == 1
    assert fn1._cache_size() == 1

    # a fresh CachedJit over the same dir (a new process, effectively)
    fn2, cache2 = _cached(tmp_path)
    out2 = np.asarray(fn2(*_args()))
    assert cache2.stats["hits"] == 1 and cache2.stats["compiles"] == 0
    assert cache2.stats["load_ms"] > 0
    np.testing.assert_array_equal(out1, out2)

    # a second shape is its own entry under the same fingerprint
    out3 = fn2(np.float32(2.0), np.arange(16, dtype=np.float32))
    assert np.asarray(out3).shape == (16,)
    assert cache2.stats["misses"] == 1 and cache2.stats["puts"] == 1


def test_put_is_idempotent(tmp_path):
    fn, cache = _cached(tmp_path)
    fn(*_args())
    assert cache.put(FP, cc.CachedJit.shape_key(_args()), b"x",
                     (None, None)) is False  # entry already published
    assert cache.stats["puts"] == 1


def _entry_dirs(root):
    return [d for _t, _n, d in cc.CompileCache(root).entries()]


def test_digest_tamper_refused_quarantined_then_recompiled(tmp_path):
    fn1, cache1 = _cached(tmp_path)
    out1 = np.asarray(fn1(*_args()))
    (d,) = _entry_dirs(cache1.root)
    with open(os.path.join(d, cc.PROGRAM_FILE), "r+b") as f:
        f.write(b"\xff\xff\xff\xff")  # corrupt the payload in place

    fn2, cache2 = _cached(tmp_path)
    out2 = np.asarray(fn2(*_args()))  # refusal -> in-memory compile
    np.testing.assert_array_equal(out1, out2)
    assert cache2.stats["refused"] == 1 and cache2.stats["hits"] == 0
    assert cache2.stats["compiles"] == 1
    # quarantined AND re-published: the next reader hits clean
    fn3, cache3 = _cached(tmp_path)
    np.testing.assert_array_equal(np.asarray(fn3(*_args())), out1)
    assert cache3.stats["hits"] == 1 and cache3.stats["refused"] == 0


def test_jax_version_mismatch_refused(tmp_path):
    fn1, cache1 = _cached(tmp_path)
    fn1(*_args())
    (d,) = _entry_dirs(cache1.root)
    epath = os.path.join(d, cc.ENTRY_FILE)
    with open(epath, encoding="utf-8") as f:
        entry = json.load(f)
    entry["versions"]["jax"] = "0.0.0-someone-elses-toolchain"
    with open(epath, "w", encoding="utf-8") as f:
        json.dump(entry, f)

    fn2, cache2 = _cached(tmp_path)
    fn2(*_args())
    assert cache2.stats["refused"] == 1 and cache2.stats["hits"] == 0
    assert cache2.stats["compiles"] == 1


def test_torn_entry_missing_manifest_refused(tmp_path):
    fn1, cache1 = _cached(tmp_path)
    fn1(*_args())
    (d,) = _entry_dirs(cache1.root)
    os.remove(os.path.join(d, cc.ENTRY_FILE))
    fn2, cache2 = _cached(tmp_path)
    fn2(*_args())
    assert cache2.stats["refused"] == 1 and cache2.stats["compiles"] == 1


# ---- crash + race ----


def test_torn_put_fault_degrades_and_next_process_publishes(tmp_path):
    """serve/faults.py compile_cache_torn_put: a crash after staging,
    before the atomic rename — the dispatch still serves the in-memory
    program, no partial entry is visible, and an unfaulted process
    publishes cleanly afterwards."""
    plan = FaultPlan([FaultSpec(point="compile_cache_torn_put")])
    with serve_faults.inject(plan):
        fn1, cache1 = _cached(tmp_path)
        out1 = np.asarray(fn1(*_args()))  # publish crashes, call works
    assert plan.counts() == {"compile_cache_torn_put": 1}
    assert cache1.stats["puts"] == 0 and cache1.stats["compiles"] == 1
    assert _entry_dirs(cache1.root) == []  # nothing half-published

    fn2, cache2 = _cached(tmp_path)
    np.testing.assert_array_equal(np.asarray(fn2(*_args())), out1)
    assert cache2.stats["puts"] == 1
    fn3, cache3 = _cached(tmp_path)
    fn3(*_args())
    assert cache3.stats["hits"] == 1


def test_publish_race_loser_adopts_winner(tmp_path, monkeypatch):
    """Two processes publish the same entry: both stage, one rename
    wins, the loser's rename fails against the winner's directory and
    the loser adopts it (counted, staging cleaned, no exception)."""
    root = str(tmp_path / "cache")
    loser = cc.CompileCache(root)
    winner = cc.CompileCache(root)
    real_replace = os.replace
    state = {"raced": False}

    def racing_replace(src, dst):
        if not state["raced"]:
            state["raced"] = True
            # the winner publishes in the window between the loser's
            # staging and its rename
            assert winner.put(FP, "shape0", b"WINNER", (None, None))
        return real_replace(src, dst)

    monkeypatch.setattr(cc.os, "replace", racing_replace)
    assert loser.put(FP, "shape0", b"LOSER", (None, None)) is False
    assert loser.stats["put_races"] == 1 and loser.stats["puts"] == 0
    (d,) = _entry_dirs(root)
    with open(os.path.join(d, cc.PROGRAM_FILE), "rb") as f:
        assert f.read() == b"WINNER"
    # no staging litter from the lost race
    assert not [p for p in os.listdir(os.path.dirname(d))
                if p.startswith(".staging")]


def test_concurrent_threads_share_one_publish(tmp_path):
    fn, cache = _cached(tmp_path)
    outs = [None] * 8

    def call(i):
        outs[i] = np.asarray(fn(*_args()))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert cache.stats["compiles"] == 1 and cache.stats["puts"] == 1
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)


# ---- LRU budget ----


def test_lru_byte_budget_evicts_oldest(tmp_path):
    cache = cc.CompileCache(str(tmp_path), max_bytes=3000)
    now = 1_000_000.0
    for i in range(4):
        fp = f"{i:02d}" * 32
        assert cache.put(fp, "s", b"p" * 900, (None, None))
        d = cache._entry_dir(fp, "s")
        os.utime(d, (now + i, now + i))  # deterministic LRU order
    cache._evict_over_budget()
    assert cache.stats["evicted"] >= 1
    assert cache.size_bytes() <= 3000
    survivors = {d for _t, _n, d in cache.entries()}
    assert cache._entry_dir("03" * 32, "s") in survivors  # newest lives
    assert cache._entry_dir("00" * 32, "s") not in survivors  # oldest out


# ---- process-wide wiring + degrade ----


def test_env_var_installs_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("MMLSPARK_TPU_COMPILE_CACHE",
                       str(tmp_path / "envcache"))
    cc.reset()
    cache = cc.active()
    assert cache is not None
    assert cache.root == str(tmp_path / "envcache")


def test_unwritable_dir_degrades_to_one_warning():
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = _Capture()  # the repo's loggers don't propagate; attach directly
    cc._log.addHandler(h)
    try:
        assert cc.configure("/proc/definitely/not/writable") is None
    finally:
        cc._log.removeHandler(h)
    assert cc.active() is None
    assert any("compile cache disabled" in m for m in records)


def test_server_load_survives_unwritable_cache_dir(rng):
    """ServeConfig.compile_cache pointing at an unwritable dir (the
    tools/serve.py --compile-cache path) must degrade to in-memory
    compiles — the model still loads and serves."""
    bundle = _bundle()
    jm = JaxModel(model=bundle, input_col="image", output_col="scores")
    img = rng.integers(0, 255, (32 * 32 * 3,)).astype(np.uint8)
    server = ModelServer(ServeConfig(
        buckets=(1,), deadline_ms=None,
        compile_cache="/proc/definitely/not/writable"))
    try:
        server.add_model("m", jm, example=DataTable({"image": [img]}))
        out = server.submit(
            "m", DataTable({"image": [img]})).result(timeout=120)
        assert len(out) == 1 and "scores" in out
    finally:
        server.close()
    assert cc.active() is None  # degraded, not installed


def test_static_fingerprint_predicts_on_disk_entry(tmp_path, rng):
    """analysis.plan_fingerprints derived over an abstract TableSchema —
    no data, no compilation — names EXACTLY the entry directory a real
    cache-backed server load writes: the static fingerprint IS the
    runtime cache key, not an approximation of it."""
    from mmlspark_tpu.analysis import TableSchema, plan_fingerprints

    img = rng.integers(0, 255, (32 * 32 * 3,)).astype(np.uint8)
    jm = JaxModel(model=_bundle(), input_col="image",
                  output_col="scores")
    schema = TableSchema.from_table(DataTable({"image": [img]}))
    fps = plan_fingerprints([jm], schema)
    assert len(fps) == 1 and isinstance(fps[0], str) and len(fps[0]) == 64
    # precision is part of the key; a policy change is a different entry
    assert plan_fingerprints([jm], schema, precision="int8w")[0] != fps[0]

    server = ModelServer(ServeConfig(buckets=(1,), deadline_ms=None,
                                     compile_cache=str(tmp_path / "c")))
    try:
        server.add_model("m", jm, example=DataTable({"image": [img]}))
    finally:
        server.close()
    on_disk = {os.path.basename(os.path.dirname(root))
               for root, _dirs, files in os.walk(tmp_path / "c")
               if cc.ENTRY_FILE in files}
    assert on_disk == {fps[0]}


def test_server_warm_start_round_trip(tmp_path, rng):
    """In-process analog of the perf_smoke cross-process gate: a second
    ModelServer over FRESH model objects and the same cache dir loads
    every program from disk (hits == first load's puts, zero fresh
    compiles) and serves bit-identical outputs."""
    img = rng.integers(0, 255, (4, 32 * 32 * 3)).astype(np.uint8)
    outs, stats = [], []
    for _round in range(2):
        cc.reset()
        jm = JaxModel(model=_bundle(), input_col="image",
                      output_col="scores")
        server = ModelServer(ServeConfig(
            buckets=(1, 4), deadline_ms=None,
            compile_cache=str(tmp_path / "cache")))
        try:
            server.add_model("m", jm,
                             example=DataTable({"image": [img[0]]}))
            out = server.submit(
                "m", DataTable({"image": list(img)})).result(timeout=300)
            outs.append(np.stack(list(out["scores"])))
            stats.append(dict(cc.active().stats))
        finally:
            server.close()
    cold, warm = stats
    assert cold["puts"] >= 1 and cold["hits"] == 0
    assert warm["compiles"] == 0 and warm["puts"] == 0
    assert warm["hits"] == cold["puts"]
    np.testing.assert_array_equal(outs[0], outs[1])

"""Versioned model repo (models/repo.py): atomic publish, digest
verification, typed corrupt/missing errors, CURRENT pointer semantics —
the artifact-side guarantees the serving lifecycle builds on."""

import json
import os

import numpy as np
import pytest

import jax

from mmlspark_tpu.models import (
    ModelBundle, ModelRepo, RepoCorruptError, VersionNotFound,
)
from mmlspark_tpu.models.repo import BUNDLE_FILE, VERSION_MANIFEST
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.serve import faults
from mmlspark_tpu.serve.faults import FaultPlan, FaultSpec, InjectedFault


def mlp_bundle(seed=0, in_dim=6):
    module = MLP(features=(8,), num_outputs=4)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, in_dim), np.float32))["params"]
    return ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(in_dim,),
        output_names=("features", "logits"),
        name="mlp")


def params_equal(a, b):
    la = jax.tree_util.tree_leaves(a.params)
    lb = jax.tree_util.tree_leaves(b.params)
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb))


class TestPublishLoad:
    def test_roundtrip_and_versioning(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        v1 = repo.publish("mlp", mlp_bundle(seed=0))
        assert v1 == 1
        assert repo.versions("mlp") == [1]
        assert repo.current_version("mlp") == 1

        v2 = repo.publish("mlp", mlp_bundle(seed=1))
        assert v2 == 2
        assert repo.current_version("mlp") == 2

        loaded2, info2 = repo.load("mlp")
        assert info2.version == 2 and info2.kind == "bundle"
        assert params_equal(loaded2, mlp_bundle(seed=1))
        loaded1, info1 = repo.load("mlp", version=1)
        assert info1.version == 1
        assert params_equal(loaded1, mlp_bundle(seed=0))
        assert not params_equal(loaded1, loaded2)

    def test_set_current_is_the_repo_side_rollback(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        repo.publish("mlp", mlp_bundle(seed=0))
        repo.publish("mlp", mlp_bundle(seed=1))
        repo.set_current("mlp", 1)
        assert repo.current_version("mlp") == 1
        _, info = repo.load("mlp")
        assert info.version == 1
        with pytest.raises(VersionNotFound):
            repo.set_current("mlp", 9)

    def test_dark_publish_keeps_current(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        repo.publish("mlp", mlp_bundle(seed=0))
        repo.publish("mlp", mlp_bundle(seed=1), set_current=False)
        assert repo.versions("mlp") == [1, 2]
        assert repo.current_version("mlp") == 1  # dark until promoted

    def test_stage_artifacts_roundtrip(self, tmp_path):
        from mmlspark_tpu.stages.image import ImageTransformer
        repo = ModelRepo(str(tmp_path))
        v = repo.publish("resize", ImageTransformer().resize(8, 8))
        model, info = repo.load("resize", v)
        assert info.kind == "stage"
        assert hasattr(model, "transform")

    def test_listing_and_missing(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        assert repo.models() == []
        with pytest.raises(VersionNotFound):
            repo.current_version("nope")
        repo.publish("a", mlp_bundle())
        repo.publish("b", mlp_bundle())
        assert repo.models() == ["a", "b"]
        assert repo.describe()["a"] == {"versions": [1], "current": 1}
        with pytest.raises(VersionNotFound):
            repo.load("a", version=7)

    def test_prune_keeps_current(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        for s in range(4):
            repo.publish("mlp", mlp_bundle(seed=s))
        repo.set_current("mlp", 1)
        doomed = repo.prune("mlp", keep=2)
        assert doomed == [2]  # v1 is CURRENT, v3/v4 the newest two
        assert repo.versions("mlp") == [1, 3, 4]
        assert repo.current_version("mlp") == 1


class TestIntegrity:
    def test_torn_publish_leaves_prior_version_live(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        repo.publish("mlp", mlp_bundle(seed=0))
        plan = FaultPlan([FaultSpec("repo_torn_publish", model="mlp")])
        with faults.inject(plan):
            with pytest.raises(InjectedFault):
                repo.publish("mlp", mlp_bundle(seed=1))
        # the torn publish is invisible: no v2, CURRENT untouched, no
        # staging litter, and the next publish takes the same number
        assert repo.versions("mlp") == [1]
        assert repo.current_version("mlp") == 1
        assert not [d for d in os.listdir(tmp_path / "mlp")
                    if d.startswith(".staging")]
        _, info = repo.load("mlp")
        assert info.version == 1
        assert repo.publish("mlp", mlp_bundle(seed=1)) == 2

    def test_digest_mismatch_is_typed_and_scoped(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        repo.publish("mlp", mlp_bundle(seed=0))
        v2 = repo.publish("mlp", mlp_bundle(seed=1))
        bundle_path = os.path.join(repo._version_dir("mlp", v2),
                                   BUNDLE_FILE)
        with open(bundle_path, "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(RepoCorruptError) as ei:
            repo.load("mlp", v2)
        assert ei.value.version == 2
        assert "digest mismatch" in str(ei.value)
        # the corruption is scoped to v2: v1 still verifies and loads
        loaded, info = repo.load("mlp", 1)
        assert info.version == 1
        assert params_equal(loaded, mlp_bundle(seed=0))

    def test_missing_manifest_and_missing_file(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        v = repo.publish("mlp", mlp_bundle(seed=0))
        vdir = repo._version_dir("mlp", v)
        os.rename(os.path.join(vdir, VERSION_MANIFEST),
                  os.path.join(vdir, VERSION_MANIFEST + ".bak"))
        with pytest.raises(RepoCorruptError, match="manifest missing"):
            repo.verify("mlp", v)
        os.rename(os.path.join(vdir, VERSION_MANIFEST + ".bak"),
                  os.path.join(vdir, VERSION_MANIFEST))
        os.remove(os.path.join(vdir, BUNDLE_FILE))
        with pytest.raises(RepoCorruptError, match="missing file"):
            repo.load("mlp", v)

    def test_stale_current_pointer_falls_back(self, tmp_path):
        repo = ModelRepo(str(tmp_path))
        repo.publish("mlp", mlp_bundle(seed=0))
        repo.publish("mlp", mlp_bundle(seed=1))
        with open(tmp_path / "mlp" / "CURRENT", "w") as f:
            f.write("42")  # pruned/never-existed version
        assert repo.current_version("mlp") == 2


class TestFaultPlanDeterminism:
    def test_same_plan_same_seed_fires_identically(self):
        def run():
            plan = FaultPlan(
                [FaultSpec("dispatch_raise", after=1, times=2),
                 FaultSpec("dispatch_raise", prob=0.5, times=100)],
                seed=7)
            fired = []
            for k in range(12):
                try:
                    plan.fire("dispatch_raise", "m", 0)
                    fired.append(("ok", k))
                except InjectedFault:
                    fired.append(("fault", k))
            return fired, plan.counts()

        a, ca = run()
        b, cb = run()
        assert a == b
        assert ca == cb
        assert ca.get("dispatch_raise", 0) >= 2

    def test_scope_matching(self):
        plan = FaultPlan([FaultSpec("lane_death", model="m", lane=1)])
        plan.fire("lane_death", "other", 1)   # wrong model: no fault
        plan.fire("lane_death", "m", 0)       # wrong lane: no fault
        with pytest.raises(InjectedFault):
            plan.fire("lane_death", "m", 1)
        plan.fire("lane_death", "m", 1)       # times=1: spent
        assert [f[3] for f in plan.fired] == ["raise"]

    def test_delay_spec_sleeps_instead_of_raising(self):
        plan = FaultPlan([FaultSpec("dispatch_slow", delay_s=0.01)])
        plan.fire("dispatch_slow", "m", 0)    # no raise
        assert plan.fired[0][3] == "delay"

"""Pre-flight analyzer suite.

Three contracts:

* **Static rejection** — deliberately broken pipelines yield stage-indexed
  typed diagnostics with ZERO DataTable construction and ZERO device
  crossings (the transformSchema-before-any-data-moves guarantee).
* **Prediction parity** — for every parity pipeline in tests/test_plan.py
  the predicted output schema (columns, dtypes, shapes) and predicted
  H2D/D2H crossing counts match what actual execution produces.
* **Audit semantics** — fusion breaks, recompile hazards, categorical
  drift, purpose collisions, and Pipeline.fit's analyzer-backed stage-kind
  error.
"""

import numpy as np
import pytest

import test_plan  # the parity-pipeline builders (image_table, mlp_bundle)

from mmlspark_tpu.analysis import (
    ColumnInfo, SchemaError, TableSchema, analyze,
)
from mmlspark_tpu.core import plan
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.core.schema import SchemaConstants, make_image
from mmlspark_tpu.core.stage import LambdaTransformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.stages.featurize import AssembleFeatures
from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage
from mmlspark_tpu.stages.indexers import ValueIndexerModel


def assert_schema_matches(pred: TableSchema, obs: TableSchema,
                          strict_dtypes: bool = False) -> None:
    """Every concretely-predicted fact must hold in the observed schema;
    unknown-marked columns must at least exist. ``strict_dtypes`` (the
    round-12 dtype-flow pin) additionally requires every non-unknown
    prediction to CARRY a dtype equal to the observed one — a stage
    whose ``infer_schema`` stops predicting output dtypes fails here,
    not downstream when a precision policy trusts the declared dtype."""
    assert list(pred.columns) == list(obs.columns)
    for name, p in pred.columns.items():
        o = obs.columns[name]
        if p.kind == "unknown" or o.kind == "unknown":
            continue
        assert p.kind == o.kind, f"{name}: {p.kind} != {o.kind}"
        if strict_dtypes and o.dtype is not None:
            assert p.dtype is not None, \
                f"{name}: no predicted dtype (observed {o.dtype})"
        if p.dtype is not None and o.dtype is not None:
            assert p.dtype == o.dtype, f"{name}: {p.dtype} != {o.dtype}"
        if p.shape is not None and o.shape is not None:
            assert len(p.shape) == len(o.shape), name
            for a, b in zip(p.shape, o.shape):
                if a is not None and b is not None:
                    assert a == b, f"{name}: {p.shape} != {o.shape}"


# ---- prediction parity against every test_plan pipeline ----

def _case_crop_flip_unroll():
    return ([ImageTransformer().crop(2, 3, 16, 12).flip(-1),
             UnrollImage(scale=1.0, offset=0.0)], test_plan.image_table())


def _case_resize():
    return ([ImageTransformer().resize(16, 12), UnrollImage()],
            test_plan.image_table(h=29, w=23))


def _case_affine_rgb():
    return ([ImageTransformer().flip(1),
             UnrollImage(scale=1 / 255.0, offset=-0.5, to_rgb=True)],
            test_plan.image_table())


def _case_three_stage_model():
    table = test_plan.image_table(n=10, h=12, w=10)
    afm = AssembleFeatures(columns_to_featurize=["image"],
                           allow_images=True,
                           features_col="features").fit(table)
    jm = JaxModel(model=test_plan.mlp_bundle(2 + 12 * 10 * 3),
                  input_col="features", output_col="scores",
                  minibatch_size=4, mesh_spec={"dp": 1})
    return [ImageTransformer().flip(0), afm, jm], table


def _case_chained_models():
    r = np.random.default_rng(3)
    table = DataTable({"x": list(r.normal(size=(9, 6)).astype(np.float32))})
    jm1 = JaxModel(model=test_plan.mlp_bundle(6, out_dim=5, seed=1),
                   input_col="x", output_col="h", minibatch_size=4)
    jm2 = JaxModel(model=test_plan.mlp_bundle(5, out_dim=3, seed=2),
                   input_col="h", output_col="scores", minibatch_size=4)
    return [jm1, jm2], table


def _case_mixed_host_device():
    table = test_plan.image_table(n=6)
    tag = LambdaTransformer(fn=lambda t: t.with_column(
        "tag", [1] * len(t)))
    renorm = LambdaTransformer(fn=lambda t: t.with_column(
        "features", [v * 2.0 for v in t["features"]]))
    return [tag, ImageTransformer().flip(1), UnrollImage(), renorm], table


def _case_single_device_stage():
    return [ImageTransformer().flip(1)], test_plan.image_table(n=4)


def _case_empty_table():
    return ([ImageTransformer().flip(1), UnrollImage()],
            DataTable({"image": []}))


def _case_ragged_images():
    r = np.random.default_rng(5)
    rows = [make_image(f"p{k}", r.integers(0, 255, (10 + k, 8, 3)))
            for k in range(5)]
    return ([ImageTransformer().flip(1), UnrollImage()],
            DataTable({"image": rows}))


def _case_unsupported_op():
    return ([ImageTransformer().blur(3, 3), UnrollImage()],
            test_plan.image_table(n=4))


def _case_lone_jax_model():
    r = np.random.default_rng(9)
    table = DataTable({"x": list(r.normal(size=(10, 6)).astype(np.float32))})
    jm = JaxModel(model=test_plan.mlp_bundle(6, out_dim=3, seed=4),
                  input_col="x", output_col="scores", minibatch_size=4)
    return [jm], table


PARITY_CASES = {
    "crop_flip_unroll": _case_crop_flip_unroll,
    "resize": _case_resize,
    "affine_rgb": _case_affine_rgb,
    "three_stage_model": _case_three_stage_model,
    "chained_models": _case_chained_models,
    "mixed_host_device": _case_mixed_host_device,
    "single_device_stage": _case_single_device_stage,
    "empty_table": _case_empty_table,
    "ragged_images": _case_ragged_images,
    "unsupported_op": _case_unsupported_op,
    "lone_jax_model": _case_lone_jax_model,
}


@pytest.mark.parametrize("case", sorted(PARITY_CASES))
def test_prediction_matches_execution(case):
    stages, table = PARITY_CASES[case]()
    report = analyze(stages, TableSchema.from_table(table),
                     n_rows=len(table))
    assert report.ok, [str(d) for d in report.errors]
    with plan.count_crossings() as c:
        out = PipelineModel(stages).transform(table)
    assert report.plan.uploads == c.uploads, report.plan.format()
    assert report.plan.fetches == c.fetches
    assert_schema_matches(report.schema, TableSchema.from_table(out),
                          strict_dtypes=True)
    # the dtype flow (round 12): every device segment's report carries
    # its per-column output dtypes, equal to what execution produced
    obs = TableSchema.from_table(out)
    for seg in report.plan.device_segments:
        assert seg.out_dtypes, seg.describe()
        for col, dt in seg.out_dtypes.items():
            o = obs.columns.get(col)
            if o is not None and o.dtype is not None:
                assert dt == o.dtype, (col, dt, o.dtype)


def test_plan_report_resolves_precision_and_tolerance():
    """tools/analyze.py pipeline --precision: each device segment's
    report names its resolved precision policy and the expected parity
    tolerance (docs/quantization.md); the predicted schema is
    policy-independent (outputs restore their declared dtypes)."""
    stages, table = _case_three_stage_model()
    base = analyze(stages, TableSchema.from_table(table),
                   n_rows=len(table))
    quant = analyze(stages, TableSchema.from_table(table),
                    n_rows=len(table), precision="int8w")
    assert quant.ok
    seg = quant.plan.device_segments[0]
    assert seg.precision == "int8w" and seg.tolerance == 0.2
    assert "precision int8w" in seg.describe()
    assert "scores:float32" in seg.describe()
    assert base.plan.device_segments[0].precision == "f32"
    assert base.schema.summary() == quant.schema.summary()
    assert "precision int8w" in quant.format()


def test_audit_structure_matches_describe_plan():
    stages, table = _case_three_stage_model()
    report = analyze(stages, TableSchema.from_table(table))
    described = [(k, len(ss)) for k, ss in plan.describe_plan(stages, table)]
    assert report.plan.structure() == described == [("device", 3)]


# ---- static rejection: broken pipelines, zero data, zero transfers ----

def _forbid_datatable(monkeypatch):
    def boom(self, *a, **k):
        raise AssertionError(
            "static analysis must not construct a DataTable")
    monkeypatch.setattr(DataTable, "__init__", boom)


def test_broken_pipelines_flagged_without_data_or_transfers(monkeypatch):
    schema = TableSchema.from_spec({
        "image": {"kind": "image", "shape": [24, 18, 3]},
        "vec": {"kind": "vector", "shape": [10], "dtype": "float32"},
    })
    jm = JaxModel(model=test_plan.mlp_bundle(6, out_dim=3),
                  input_col="vec", output_col="scores", minibatch_size=4)
    afm = AssembleFeatures(columns_to_featurize=["vec"],
                           features_col="assembled").fit(
        DataTable({"vec": list(np.zeros((3, 10), np.float32))}))
    _forbid_datatable(monkeypatch)
    with plan.count_crossings() as c:
        # missing input column
        r1 = analyze([UnrollImage(input_col="imagezz")], schema)
        # image column fed to a vector-only stage (numeric/vector plan)
        bad_plan = [{"col": "image", "kind": "vector", "size": 10}]
        afm2 = afm.copy(plan=bad_plan)
        r2 = analyze([afm2], schema)
        # dtype/size mismatch into a fused device segment: the vector is
        # 10-wide, the model wants 6
        r3 = analyze([UnrollImage(input_col="image", output_col="vec"),
                      jm], schema)
    assert c.uploads == 0 and c.fetches == 0

    d1 = r1.errors[0]
    assert d1.code == "missing-input-column" and d1.stage_index == 0
    assert "imagezz" in d1.message

    d2 = r2.errors[0]
    assert d2.code == "plan-schema-mismatch" and d2.stage_index == 0
    assert "image" in d2.message

    d3 = r3.errors[0]
    assert d3.code == "input-size-mismatch" and d3.stage_index == 1
    assert d3.stage == "JaxModel"
    # the unroll output (24*18*3) does not match the model spec either way
    assert "1296" in d3.message and "6" in d3.message


def test_analysis_of_saved_pipeline_without_data(monkeypatch, tmp_path):
    pm = PipelineModel([ImageTransformer().resize(16, 12), UnrollImage()])
    path = str(tmp_path / "pm")
    pm.save(path)
    loaded = PipelineModel.load(path)
    schema = TableSchema.from_spec(
        {"image": {"kind": "image", "shape": [32, 32, 3]}})
    _forbid_datatable(monkeypatch)
    with plan.count_crossings() as c:
        report = analyze(loaded, schema, n_rows=64)
    assert c.uploads == 0
    assert report.ok
    assert report.schema.columns["features"].summary() == \
        ("vector", "float32", (16 * 12 * 3,))
    assert report.plan.structure() == [("device", 2)]
    assert report.plan.uploads == 1  # 64 rows, one dp-rounded minibatch


# ---- diagnostics ----

def test_crop_out_of_bounds_and_unknown_op():
    schema = TableSchema.from_spec(
        {"image": {"kind": "image", "shape": [16, 16, 3]}})
    r = analyze([ImageTransformer().crop(10, 10, 16, 16)], schema)
    assert r.errors[0].code == "crop-out-of-bounds"
    r = analyze([ImageTransformer(ops=[{"op": "sharpen"}])], schema)
    assert r.errors[0].code == "unknown-image-op"


def test_image_expected_and_model_not_set():
    schema = TableSchema.from_spec(
        {"vec": {"kind": "vector", "shape": [8]}})
    r = analyze([UnrollImage(input_col="vec")], schema)
    assert r.errors[0].code == "image-column-expected"
    r = analyze([JaxModel(input_col="vec")], schema)
    assert r.errors[0].code == "model-not-set"


def test_recompile_hazard_on_polymorphic_entry():
    schema = TableSchema.from_spec(
        {"image": {"kind": "image", "shape": [None, None, 3]}})
    r = analyze([ImageTransformer().resize(8, 8), UnrollImage()], schema,
                n_rows=10)
    assert any(d.code == "shape-polymorphic-entry" for d in r.warnings)
    # the geometry still resolves once the resize pins it
    assert r.schema.columns["features"].summary() == \
        ("vector", "float32", (8 * 8 * 3,))


def test_categorical_drift_and_shadowing():
    info = ColumnInfo.scalar("int32")
    info.meta[SchemaConstants.K_IS_CATEGORICAL] = True
    info.meta[SchemaConstants.K_CATEGORICAL_LEVELS] = ["a", "b", "z"]
    schema = TableSchema({"cat": info})
    fitted = AssembleFeatures(columns_to_featurize=["cat"]).fit(
        DataTable({"cat": np.array([0, 1, 2], np.int32)},
                  {"cat": {SchemaConstants.K_IS_CATEGORICAL: True,
                           SchemaConstants.K_CATEGORICAL_LEVELS:
                               ["a", "b", "c"]}}))
    r = analyze([fitted], schema)
    assert any(d.code == "categorical-level-drift" for d in r.warnings)

    # overwriting an image column with a vector is flagged at the write
    schema2 = TableSchema.from_spec(
        {"image": {"kind": "image", "shape": [8, 8, 3]}})
    r2 = analyze([UnrollImage(input_col="image", output_col="image")],
                 schema2)
    assert any(d.code == "column-shadowed" for d in r2.diagnostics)


def test_score_purpose_collision():
    stamped = {SchemaConstants.K_COLUMN_PURPOSE:
               SchemaConstants.SCORES_COLUMN,
               SchemaConstants.K_MODEL_UID: "m1"}
    schema = TableSchema({
        "s1": ColumnInfo.vector(3, "float64", meta=dict(stamped)),
        "s2": ColumnInfo.vector(3, "float64", meta=dict(stamped)),
    })
    r = analyze([], schema)
    assert any(d.code == "score-purpose-collision" for d in r.warnings)


def test_unfitted_indexer_chain_analyzes_clean():
    # ValueIndexer → IndexToValue and ValueIndexer → AssembleFeatures are
    # valid pipelines whose levels/widths are fit-time artifacts: analysis
    # must stay clean and report the width as unknown, never a wrong number
    from mmlspark_tpu.stages.indexers import IndexToValue, ValueIndexer
    schema = TableSchema.from_spec({
        "cat": "text", "x": {"kind": "scalar", "dtype": "float64"}})
    r = analyze(Pipeline([
        ValueIndexer(input_col="cat", output_col="idx"),
        IndexToValue(input_col="idx", output_col="back")]), schema)
    assert r.ok, [str(d) for d in r.errors]
    r2 = analyze(Pipeline([
        ValueIndexer(input_col="cat", output_col="cat_idx"),
        AssembleFeatures(columns_to_featurize=["cat_idx", "x"])]), schema)
    assert r2.ok
    feats = r2.schema.columns["features"]
    assert feats.row_size is None  # one-hot width unknown until fit
    assert SchemaConstants.K_VECTOR_SIZE not in feats.meta


def test_unknown_color_format_rejected_preflight():
    schema = TableSchema.from_spec(
        {"image": {"kind": "image", "shape": [8, 8, 3]}})
    r = analyze([ImageTransformer().color_format("foo")], schema)
    assert r.errors[0].code == "unknown-color-format"
    r2 = analyze([ImageTransformer().color_format("gray")], schema)
    assert r2.ok
    assert r2.schema.columns["image"].shape == (8, 8, 1)


def test_value_indexer_levels_flow_into_assembly():
    vim = ValueIndexerModel(input_col="color", output_col="color_idx",
                            levels=["blue", "green", "red"])
    schema = TableSchema.from_spec({"color": "text"})
    r = analyze([vim], schema)
    info = r.schema.columns["color_idx"]
    assert info.summary() == ("scalar", "int32", ())
    assert info.meta[SchemaConstants.K_CATEGORICAL_LEVELS] == \
        ["blue", "green", "red"]


def test_estimator_pipeline_with_train_classifier():
    from mmlspark_tpu.ml import TrainClassifier
    schema = TableSchema.from_spec({
        "age": {"kind": "scalar", "dtype": "float64"},
        "income": "text",
    })
    p = Pipeline([TrainClassifier(label_col="income")])
    r = analyze(p, schema)
    assert r.ok
    assert SchemaConstants.SCORED_LABELS_COLUMN in r.schema.columns
    # label column missing → stage-indexed error
    r2 = analyze(Pipeline([TrainClassifier(label_col="nope")]), schema)
    assert r2.errors[0].code == "missing-input-column"


def test_lambda_probe_tracks_columns():
    schema = TableSchema.from_spec(
        {"x": {"kind": "vector", "shape": [4]}})
    add = LambdaTransformer(fn=lambda t: t.with_column("y", [0] * len(t)))
    r = analyze([add, UnrollImage(input_col="nope")], schema)
    assert "y" in r.schema.columns
    # schema stayed exact, so the bad column is still an error
    assert r.errors[0].code == "missing-input-column"

    crashy = LambdaTransformer(fn=lambda t: t.take([0]))  # dies on 0 rows?
    r2 = analyze([crashy], schema)
    assert r2.ok  # worst case: schema degrades, never a crash


def test_trained_model_rows_unknown_when_na_drop_possible():
    # the featurization's na.drop analog makes the scored row count
    # unknowable when a feature column can hold missing values — the
    # model must not claim an exact count (and with it, exact crossings)
    from mmlspark_tpu.ml import TrainClassifier
    t = DataTable({"x": np.array([1.0, np.nan, 3.0, 4.0]),
                   "label": ["a", "b", "a", "b"]})
    model = TrainClassifier(label_col="label").fit(t)
    schema = TableSchema.from_table(t)
    assert model.infer_rows(4, schema) is None
    assert len(model.transform(t)) == 3  # na.drop actually fires
    clean = DataTable({"x": np.arange(4.0), "label": ["a", "b", "a", "b"]})
    assert model.infer_rows(4, TableSchema.from_table(clean)) == 4


def test_nested_lambda_probe_runs_once_per_analysis():
    calls = []

    def fn(t):
        calls.append(len(t))
        return t.with_column("y", [0] * len(t))

    nested = PipelineModel([LambdaTransformer(fn=fn)])
    schema = TableSchema.from_spec({"x": {"kind": "vector", "shape": [4]}})
    analyze([nested], schema, n_rows=10)
    assert len(calls) == 1, calls  # the 0-row probe, exactly once


def test_nested_fold_preserves_warnings_through_lambda():
    # a warning attached inside a nested Pipeline must survive a following
    # opaque stage's schema rebuild and surface at the outer walk
    info = ColumnInfo.scalar("int32")
    info.meta[SchemaConstants.K_IS_CATEGORICAL] = True
    info.meta[SchemaConstants.K_CATEGORICAL_LEVELS] = ["a", "b", "z"]
    schema = TableSchema({"cat": info})
    fitted = AssembleFeatures(columns_to_featurize=["cat"]).fit(
        DataTable({"cat": np.array([0, 1, 2], np.int32)},
                  {"cat": {SchemaConstants.K_IS_CATEGORICAL: True,
                           SchemaConstants.K_CATEGORICAL_LEVELS:
                               ["a", "b", "c"]}}))
    ident = LambdaTransformer(fn=lambda t: t.with_column(
        "extra", [0] * len(t)))
    nested = PipelineModel([fitted, ident])
    r = analyze([nested], schema)
    assert any(d.code == "categorical-level-drift" for d in r.warnings)


# ---- Pipeline.fit stage-kind diagnostic (via the analyzer) ----

def test_pipeline_fit_rejects_non_stage_with_indexed_message():
    table = DataTable({"x": np.arange(4.0)})
    bad = Pipeline([ImageTransformer(), {"not": "a stage"}, 42])
    with pytest.raises(TypeError) as exc:
        bad.fit(table)
    msg = str(exc.value)
    assert "stage 1 (dict)" in msg and "stage 2 (int)" in msg
    assert "neither Transformer nor Estimator" in msg


def test_schema_error_formatting():
    err = SchemaError("some-code", "the message")
    assert err.code == "some-code" and str(err) == "the message"

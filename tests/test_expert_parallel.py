"""Expert parallelism (ep axis): the all-to-all MoE dispatch must
reproduce the dense top-1 oracle exactly when capacity is ample, train
end-to-end, and degrade by dropping (not corrupting) tokens when
capacity binds. Runs on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.parallel.moe import (
    init_moe_params, moe_apply, moe_param_spec, moe_reference,
)

E, D, DH, N = 8, 16, 32, 64


@pytest.fixture(scope="module")
def setup():
    params = init_moe_params(jax.random.PRNGKey(0), E, D, DH)
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (N, D)),
                   np.float32)
    return params, x


@pytest.mark.parametrize("spec", [
    MeshSpec(dp=1, ep=1), MeshSpec(dp=1, ep=4), MeshSpec(dp=1, ep=8),
    MeshSpec(dp=2, ep=4),
])
def test_matches_dense_oracle_with_ample_capacity(setup, spec):
    params, x = setup
    mesh = make_mesh(spec)
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    y, aux = moe_apply(dev, jnp.asarray(x), mesh, capacity_factor=float(E))
    ref = moe_reference(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # Switch aux lower bound at balance


def test_gradients_match_dense_oracle(setup):
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    xj = jnp.asarray(x)

    g_ep = jax.grad(lambda p: jnp.sum(
        moe_apply(p, xj, mesh, capacity_factor=float(E))[0] ** 2))(dev)
    g_ref = jax.grad(lambda p: jnp.sum(moe_reference(p, xj) ** 2))(params)
    for (ka, a), (kb, b) in zip(
            sorted((k, v) for k, v in g_ep.items()),
            sorted((k, v) for k, v in g_ref.items())):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"grad mismatch for {ka}")


def test_capacity_drops_are_zeros_not_garbage(setup):
    """With capacity 1 slot/expert/shard, overflow tokens must come back
    exactly zero (the pass-through-residual contract)."""
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    y, _ = moe_apply(dev, jnp.asarray(x), mesh, capacity_factor=1e-9)
    y = np.asarray(y)
    ref = np.asarray(moe_reference(params, jnp.asarray(x)))
    kept = ~np.all(y == 0.0, axis=-1)
    # every non-dropped row matches the oracle; at capacity 1 some rows
    # must actually be dropped
    assert kept.sum() < N
    np.testing.assert_allclose(y[kept], ref[kept], rtol=1e-5, atol=1e-5)


def test_moe_trains_with_aux_loss(setup):
    import optax

    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    p = jax.device_put(params, moe_param_spec(mesh, params))
    xj = jnp.asarray(x)
    target = jnp.asarray(np.sin(x.sum(axis=1, keepdims=True))
                         * np.ones((1, D), np.float32))
    tx = optax.adam(3e-3)
    opt = tx.init(p)

    @jax.jit
    def step(p, o):
        def loss_fn(pp):
            y, aux = moe_apply(pp, xj, mesh, capacity_factor=2.0)
            return jnp.mean((xj + y - target) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss_fn)(p)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    losses = []
    for _ in range(15):
        p, opt, l = step(p, opt)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, losses
    assert "ep" in str(jax.tree_util.tree_leaves(
        {k: v for k, v in p.items() if k != "gate"})[0].sharding.spec)


def test_bad_divisibility_raises(setup):
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=8))
    with pytest.raises(ValueError, match="tokens not divisible"):
        moe_apply(params, jnp.asarray(x[:30]), mesh)
    p6 = {k: (v[:6] if k != "gate" else v) for k, v in params.items()}
    with pytest.raises(ValueError, match="experts not divisible"):
        moe_apply(p6, jnp.asarray(x), mesh)


class TestMoETransformer:
    """MoE wired into a model family: TransformerTagger(moe_experts=K)."""

    def test_dense_moe_tagger_trains_and_sows_aux(self):
        import optax

        from mmlspark_tpu.models.sequence import TransformerTagger
        model = TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                                  num_layers=2, mlp_dim=32, num_tags=4,
                                  max_len=16, moe_experts=4)
        r = np.random.default_rng(0)
        toks = jnp.asarray(r.integers(0, 64, (8, 16)).astype(np.int32))
        tags = jnp.asarray((np.asarray(toks) % 4).astype(np.int32))
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        assert any("moe0_w_in" in k for k in params)  # experts exist
        tx = optax.adam(3e-3)
        opt = tx.init(params)

        @jax.jit
        def step(p, o):
            def loss_fn(pp):
                logits, mut = model.apply(
                    {"params": pp}, toks, mutable=["intermediates"])
                ce = jnp.mean(
                    -jax.nn.log_softmax(logits)[
                        jnp.arange(8)[:, None], jnp.arange(16)[None, :],
                        tags])
                aux = sum(jnp.asarray(a).mean() for a in
                          jax.tree_util.tree_leaves(mut["intermediates"]))
                return ce + 0.01 * aux
            l, g = jax.value_and_grad(loss_fn)(p)
            up, o = tx.update(g, o)
            return optax.apply_updates(p, up), o, l

        losses = []
        for _ in range(20):
            params, opt, l = step(params, opt)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.9, losses

    def test_expert_parallel_path_matches_dense(self):
        """The SAME tagger params routed through moe_apply on an ep mesh
        must reproduce the dense single-device forward."""
        from mmlspark_tpu.models.sequence import TransformerTagger
        from mmlspark_tpu.parallel.moe import moe_apply

        model = TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                                  num_layers=1, mlp_dim=32, num_tags=4,
                                  max_len=16, moe_experts=4)
        r = np.random.default_rng(1)
        toks = jnp.asarray(r.integers(0, 64, (8, 16)).astype(np.int32))
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        mesh = make_mesh(MeshSpec(dp=1, ep=4))

        def ep_moe(p, flat, m):
            return moe_apply(p, flat, mesh, capacity_factor=4.0,
                             token_mask=m)

        dense = model.apply({"params": params}, toks)
        par = model.apply({"params": params}, toks, moe_fn=ep_moe)
        np.testing.assert_allclose(np.asarray(par), np.asarray(dense),
                                   rtol=1e-5, atol=1e-5)


def test_padding_tokens_cannot_claim_capacity(setup):
    """The padding invariant: masked (pad) tokens must not consume
    capacity slots, so real tokens' routing is independent of how much
    padding the bucket added. Pads are placed FIRST so that, without the
    mask, they would grab the slots before any real token."""
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    from mmlspark_tpu.parallel.moe import moe_dense
    real = jnp.asarray(x[:8])
    padded = jnp.concatenate([jnp.asarray(x[8:32]), real])   # 24 pads + 8
    mask = jnp.concatenate([jnp.zeros(24), jnp.ones(8)])
    y, aux = moe_apply(dev, padded, mesh, capacity_factor=2.0,
                       token_mask=mask)
    y = np.asarray(y)
    assert np.all(y[:24] == 0.0), "pad tokens must output exact zeros"
    ref, aux_ref = moe_dense(params, real)
    np.testing.assert_allclose(y[24:], np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # aux statistics exclude pads: the masked parallel aux matches the
    # dense aux over only the real tokens
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


class TestTrainerIntegration:
    """The standard Trainer must train MoE models correctly: the sown
    load-balance aux reaches gradients, and pad-token routing is masked
    via the model's pad_token_id (Trainer batches carry no mask kwarg)."""

    def _tagger(self, **kw):
        from mmlspark_tpu.models.sequence import TransformerTagger
        return TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                                 num_layers=1, mlp_dim=32, num_tags=4,
                                 max_len=12, moe_experts=4, **kw)

    def test_aux_loss_reaches_trainer_gradients(self):
        """moe_aux_weight must change the training walk — if the Trainer's
        intermediate capture silently broke (flax dict-type drift, sow key
        rename), the two runs would be identical."""
        from mmlspark_tpu.train import TrainConfig, Trainer
        r = np.random.default_rng(0)
        toks = r.integers(1, 64, (48, 12)).astype(np.int32)
        tags = (toks % 4).astype(np.int64)
        hist = {}
        for w in (0.0, 0.5):
            tr = Trainer(self._tagger(), TrainConfig(
                batch_size=16, epochs=2, log_every=1, learning_rate=3e-3,
                moe_aux_weight=w))
            tr.fit_arrays(toks, tags)
            hist[w] = tr.history
        assert hist[0.0] != hist[0.5], \
            "aux weight had no effect — the Trainer dropped the sown aux"
        assert hist[0.5][-1] < hist[0.5][0]

    def test_pad_token_id_masks_routing_through_trainer_path(self):
        """With pad_token_id set, a padded batch's real-token logits are
        identical however much padding the bucket added — through plain
        model.apply with NO mask kwarg (the Trainer calling convention)."""
        model = self._tagger(pad_token_id=0)
        r = np.random.default_rng(1)
        sent = r.integers(1, 64, (4, 6)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(np.zeros((1, 6), np.int32)))["params"]
        outs = {}
        for L in (8, 12):
            padded = np.zeros((4, L), np.int32)
            padded[:, :6] = sent
            lg = model.apply({"params": params}, jnp.asarray(padded))
            outs[L] = np.asarray(lg)[:, :6]
        np.testing.assert_allclose(outs[8], outs[12], rtol=1e-5, atol=1e-5)

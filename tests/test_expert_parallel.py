"""Expert parallelism (ep axis): the all-to-all MoE dispatch must
reproduce the dense top-1 oracle exactly when capacity is ample, train
end-to-end, and degrade by dropping (not corrupting) tokens when
capacity binds. Runs on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.parallel.moe import (
    init_moe_params, moe_apply, moe_param_spec, moe_reference,
)

E, D, DH, N = 8, 16, 32, 64


@pytest.fixture(scope="module")
def setup():
    params = init_moe_params(jax.random.PRNGKey(0), E, D, DH)
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (N, D)),
                   np.float32)
    return params, x


@pytest.mark.parametrize("spec", [
    MeshSpec(dp=1, ep=1), MeshSpec(dp=1, ep=4), MeshSpec(dp=1, ep=8),
    MeshSpec(dp=2, ep=4),
])
def test_matches_dense_oracle_with_ample_capacity(setup, spec):
    params, x = setup
    mesh = make_mesh(spec)
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    y, aux = moe_apply(dev, jnp.asarray(x), mesh, capacity_factor=float(E))
    ref = moe_reference(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # Switch aux lower bound at balance


def test_gradients_match_dense_oracle(setup):
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    xj = jnp.asarray(x)

    g_ep = jax.grad(lambda p: jnp.sum(
        moe_apply(p, xj, mesh, capacity_factor=float(E))[0] ** 2))(dev)
    g_ref = jax.grad(lambda p: jnp.sum(moe_reference(p, xj) ** 2))(params)
    for (ka, a), (kb, b) in zip(
            sorted((k, v) for k, v in g_ep.items()),
            sorted((k, v) for k, v in g_ref.items())):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"grad mismatch for {ka}")


def test_capacity_drops_are_zeros_not_garbage(setup):
    """With capacity 1 slot/expert/shard, overflow tokens must come back
    exactly zero (the pass-through-residual contract)."""
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    dev = jax.device_put(params, moe_param_spec(mesh, params))
    y, _ = moe_apply(dev, jnp.asarray(x), mesh, capacity_factor=1e-9)
    y = np.asarray(y)
    ref = np.asarray(moe_reference(params, jnp.asarray(x)))
    kept = ~np.all(y == 0.0, axis=-1)
    # every non-dropped row matches the oracle; at capacity 1 some rows
    # must actually be dropped
    assert kept.sum() < N
    np.testing.assert_allclose(y[kept], ref[kept], rtol=1e-5, atol=1e-5)


def test_moe_trains_with_aux_loss(setup):
    import optax

    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    p = jax.device_put(params, moe_param_spec(mesh, params))
    xj = jnp.asarray(x)
    target = jnp.asarray(np.sin(x.sum(axis=1, keepdims=True))
                         * np.ones((1, D), np.float32))
    tx = optax.adam(3e-3)
    opt = tx.init(p)

    @jax.jit
    def step(p, o):
        def loss_fn(pp):
            y, aux = moe_apply(pp, xj, mesh, capacity_factor=2.0)
            return jnp.mean((xj + y - target) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss_fn)(p)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    losses = []
    for _ in range(15):
        p, opt, l = step(p, opt)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, losses
    assert "ep" in str(jax.tree_util.tree_leaves(
        {k: v for k, v in p.items() if k != "gate"})[0].sharding.spec)


def test_bad_divisibility_raises(setup):
    params, x = setup
    mesh = make_mesh(MeshSpec(dp=1, ep=8))
    with pytest.raises(ValueError, match="tokens not divisible"):
        moe_apply(params, jnp.asarray(x[:30]), mesh)
    p6 = {k: (v[:6] if k != "gate" else v) for k, v in params.items()}
    with pytest.raises(ValueError, match="experts not divisible"):
        moe_apply(p6, jnp.asarray(x), mesh)

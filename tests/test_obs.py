"""Obs subsystem suite: span tracer correctness (nesting, threads,
enable/disable isolation), histogram percentiles vs numpy, Chrome-trace
export validity, and the acceptance contract that obs counters EXACTLY
equal the independently observed crossing/compile values the PR 1/PR 4
tests assert at the planner's own seams — one telemetry substrate, not a
second set of numbers."""

import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from test_plan import image_table, mlp_bundle  # noqa: E402

from mmlspark_tpu import obs
from mmlspark_tpu.core import plan
from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.schema import make_image
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.obs.events import SpanRecord
from mmlspark_tpu.stages.featurize import AssembleFeatures
from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage


@pytest.fixture(autouse=True)
def obs_isolated():
    """Every test starts AND ends with the tracer off and all state
    dropped — enabling obs in one test must never leak spans, counters,
    or the enabled flag into the next (the flag-isolation contract)."""
    obs.disable()
    obs.clear()
    obs.registry().reset()
    yield
    obs.disable()
    obs.clear()
    obs.registry().reset()


# ---- span tracer ----

def test_disabled_span_is_shared_null_and_records_nothing():
    assert not obs.enabled()
    s1, s2 = obs.span("a"), obs.span("b", "cat", {"k": 1})
    assert s1 is s2  # one shared null context: no allocation when off
    with s1:
        pass
    obs.event("instant")
    assert obs.captured() == []


def test_nested_spans_record_parentage_and_containment():
    obs.enable()
    with obs.span("outer", "t"):
        with obs.span("mid", "t"):
            with obs.span("inner", "t", {"k": "v"}):
                pass
        with obs.span("mid2", "t"):
            pass
    recs = {r.name: r for r in obs.captured()}
    assert set(recs) == {"outer", "mid", "inner", "mid2"}
    outer, mid, inner, mid2 = (recs[n]
                               for n in ("outer", "mid", "inner", "mid2"))
    assert outer.parent_id is None and outer.depth == 0
    assert mid.parent_id == outer.span_id and mid.depth == 1
    assert inner.parent_id == mid.span_id and inner.depth == 2
    assert mid2.parent_id == outer.span_id and mid2.depth == 1
    assert inner.labels == {"k": "v"}
    # wall-clock containment: children lie inside their parent
    for child, parent in ((mid, outer), (inner, mid), (mid2, outer)):
        assert child.start_ns >= parent.start_ns
        assert child.end_ns <= parent.end_ns
    # siblings are ordered, not overlapping
    assert mid.end_ns <= mid2.start_ns


def test_span_records_survive_exceptions():
    obs.enable()
    with pytest.raises(ValueError):
        with obs.span("dies", "t"):
            raise ValueError("boom")
    (rec,) = obs.captured()
    assert rec.name == "dies" and rec.dur_ns >= 0
    # the thread-local stack unwound: a new root span has no parent
    with obs.span("next", "t"):
        pass
    assert [r.parent_id for r in obs.captured()] == [None, None]


def test_threaded_spans_keep_independent_stacks():
    obs.enable()
    barrier = threading.Barrier(2)

    def work(tag: str) -> None:
        barrier.wait()
        with obs.span(f"{tag}/outer", "t"):
            with obs.span(f"{tag}/inner", "t"):
                pass

    threads = [threading.Thread(target=work, args=(t,), name=f"W{t}")
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = {r.name: r for r in obs.captured()}
    assert len(recs) == 4
    for tag in ("a", "b"):
        outer, inner = recs[f"{tag}/outer"], recs[f"{tag}/inner"]
        # nesting resolved per-thread: never across threads
        assert inner.parent_id == outer.span_id
        assert outer.parent_id is None
        assert inner.tid == outer.tid
    assert recs["a/outer"].tid != recs["b/outer"].tid
    assert recs["a/outer"].thread_name == "Wa"


def test_enable_disable_toggles_capture():
    obs.enable()
    with obs.span("while-on", "t"):
        pass
    obs.disable()
    with obs.span("while-off", "t"):
        pass
    names = [r.name for r in obs.captured()]
    assert names == ["while-on"]  # captured records stay readable


def test_ring_buffer_bounded():
    obs.enable(buffer_size=16)
    for k in range(64):
        with obs.span(f"s{k}", "t"):
            pass
    recs = obs.captured()
    assert len(recs) == 16
    assert recs[0].name == "s48" and recs[-1].name == "s63"  # newest kept


# ---- metrics registry ----

def test_counter_gauge_interning_and_labels():
    reg = obs.registry()
    c1 = reg.counter("x.total", model="m", bucket=8)
    c2 = reg.counter("x.total", bucket=8, model="m")  # order-insensitive
    assert c1 is c2
    c1.add(2)
    c2.add(0.5)
    assert reg.counter("x.total", model="m", bucket=8).value == 2.5
    assert reg.counter("x.total", model="other").value == 0  # distinct
    with pytest.raises(ValueError):
        c1.add(-1)
    g = reg.gauge("x.depth")
    assert g.value is None
    g.set(3)
    g.add(1)
    assert g.value == 4.0
    snap = reg.snapshot()
    assert snap["counters"]["x.total{bucket=8,model=m}"] == 2.5
    assert snap["gauges"]["x.depth"] == 4.0


def test_histogram_percentiles_match_numpy():
    rng = np.random.default_rng(7)
    values = rng.normal(size=500).tolist()
    h = obs.registry().histogram("lat", window=1024)
    for v in values:
        h.observe(v)
    p = h.percentiles(ndigits=None)
    p50, p95, p99 = np.percentile(np.asarray(values), [50, 95, 99])
    assert p["n"] == 500
    assert p["p50"] == pytest.approx(float(p50))
    assert p["p95"] == pytest.approx(float(p95))
    assert p["p99"] == pytest.approx(float(p99))
    assert h.count == 500 and h.sum == pytest.approx(sum(values))


def test_histogram_window_bounds_memory_but_not_count():
    h = obs.registry().histogram("w", window=8)
    for v in range(100):
        h.observe(v)
    assert h.count == 100  # lifetime count exact
    assert h.values() == list(range(92, 100))  # window keeps the newest
    assert h.percentiles()["n"] == 8


def test_empty_histogram_is_snapshot_safe():
    h = obs.registry().histogram("never")
    assert h.percentiles() is None and h.mean() is None
    snap = obs.registry().snapshot()["histograms"]["never"]
    assert snap["count"] == 0 and snap["percentiles"] is None
    json.dumps(snap)


# ---- Chrome-trace export ----

def test_chrome_trace_is_valid_trace_event_json():
    obs.enable()
    with obs.span("parent", "plan", {"rows": 4}):
        with obs.span("child", "plan"):
            pass
    obs.event("mark", "serve", {"model": "m"})
    payload = json.loads(json.dumps(obs.chrome_trace()))  # JSON-safe
    events = payload["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(complete) == 2 and len(instants) == 1 and len(meta) >= 1
    for e in complete:
        # the trace_event contract chrome://tracing / Perfetto require
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid"}
        assert isinstance(e["ts"], float) and e["dur"] >= 0
    by_name = {e["name"]: e for e in complete}
    parent, child = by_name["parent"], by_name["child"]
    # nesting: same lane, child interval inside the parent's
    assert child["tid"] == parent["tid"]
    assert child["ts"] >= parent["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"]
    assert child["args"]["parent_id"] == parent["args"]["span_id"]
    assert parent["args"]["rows"] == 4
    assert meta[0]["name"] == "thread_name"


def test_summarize_spans_aggregates_by_name():
    obs.enable()
    for _ in range(3):
        with obs.span("hot", "t"):
            pass
    with obs.span("cold", "t"):
        pass
    from mmlspark_tpu.obs.export import summarize_spans
    rows = {r["name"]: r for r in summarize_spans()}
    assert rows["hot"]["calls"] == 3 and rows["cold"]["calls"] == 1
    assert rows["hot"]["total_ms"] >= rows["hot"]["mean_ms"]


# ---- the acceptance contract: obs counters == the PR 1 seam counts ----

def _registry_crossings() -> dict:
    counters = obs.registry().snapshot()["counters"]
    shapes = obs.registry().series("plan.h2d_shapes")
    return {
        "uploads": counters.get("plan.h2d_uploads", 0),
        "fetches": counters.get("plan.d2h_fetches", 0),
        "upload_bytes": counters.get("plan.h2d_bytes", 0),
        "distinct_shapes": len(shapes),
    }


def parity_pipelines():
    """The tests/test_plan.py parity scenarios, rebuilt here: every fused
    shape the PR 1 suite pins, plus the host-fallback case that must
    count ZERO crossings."""
    return [
        ("crop_flip_unroll",
         [ImageTransformer().crop(2, 3, 16, 12).flip(-1),
          UnrollImage(scale=1.0, offset=0.0)],
         image_table()),
        ("resize_unroll",
         [ImageTransformer().resize(16, 12), UnrollImage()],
         image_table(h=29, w=23)),
        ("three_stage_model_tail_padding",
         [ImageTransformer().flip(0),
          AssembleFeatures(columns_to_featurize=["image"],
                           allow_images=True,
                           features_col="features").fit(
              image_table(n=10, h=12, w=10)),
          JaxModel(model=mlp_bundle(2 + 12 * 10 * 3),
                   input_col="features", output_col="scores",
                   minibatch_size=4, mesh_spec={"dp": 1})],
         image_table(n=10, h=12, w=10)),
        ("chained_models",
         [JaxModel(model=mlp_bundle(6, out_dim=5, seed=1), input_col="x",
                   output_col="h", minibatch_size=4),
          JaxModel(model=mlp_bundle(5, out_dim=3, seed=2), input_col="h",
                   output_col="scores", minibatch_size=4)],
         DataTable({"x": list(np.random.default_rng(3).normal(
             size=(9, 6)).astype(np.float32))})),
        ("ragged_host_fallback",
         [ImageTransformer().flip(1), UnrollImage()],
         DataTable({"image": [
             make_image(f"p{k}",
                        np.random.default_rng(5).integers(
                            0, 255, (10 + k, 8, 3)))
             for k in range(5)]})),
    ]


@pytest.mark.parametrize("name,stages,table",
                         parity_pipelines(),
                         ids=[p[0] for p in parity_pipelines()])
def test_obs_counters_equal_seam_counts_for_parity_pipelines(
        name, stages, table):
    """For every PR 1 parity pipeline the registry's crossing counters
    must EXACTLY equal what the independent seam-patching counter
    observes: crossings, bytes, and the distinct-upload-shape recompile
    surface. (The ragged case pins the zero: a host fallback records no
    phantom crossings.)"""
    obs.enable()
    with plan.count_crossings() as c:
        PipelineModel(stages).transform(table)
    got = _registry_crossings()
    assert got["uploads"] == c.uploads
    assert got["fetches"] == c.fetches
    assert got["upload_bytes"] == c.upload_bytes
    assert got["distinct_shapes"] == len(c.upload_shapes)
    if name == "ragged_host_fallback":
        assert got["uploads"] == 0 and got["upload_bytes"] == 0


def test_obs_compile_counter_counts_segment_builds():
    obs.enable()
    table = image_table(n=6)
    pm = PipelineModel([ImageTransformer().flip(1), UnrollImage()])
    pm.transform(table)
    first = obs.registry().value("plan.segment_compiles")
    assert first == 1
    pm.transform(table)  # cache hit: no new compile
    assert obs.registry().value("plan.segment_compiles") == first
    assert obs.compiled_programs(pm) == 1


# ---- serve burst: one substrate across the PR 4 observables ----

def test_serve_burst_obs_counters_match_pr4_observables():
    """One serve burst: the registry's crossing/shape counters, the
    obs-owned compile-cache hook, and the re-backed ServerStats snapshot
    must all agree with the independently counted values the PR 4 tests
    assert."""
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    buckets, n_req = (1, 8, 32), 48
    bundle = get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)
    jm = JaxModel(model=bundle, input_col="image", output_col="scores")
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 255, (n_req, 32 * 32 * 3)).astype(np.uint8)

    server = ModelServer(ServeConfig(buckets=buckets, max_queue=n_req,
                                     deadline_ms=None))
    try:
        server.add_model("cnn", jm,
                         example=DataTable({"image": [rows[0]]}))
        obs.enable()  # after warmup: count the burst only
        with plan.count_crossings() as c:
            handles = [server.submit("cnn",
                                     DataTable({"image": [rows[i]]}))
                       for i in range(n_req)]
            outs = [h.result(timeout=300) for h in handles]
        snap = server.stats("cnn").snapshot()
        programs = server.compiled_programs("cnn")
        entry = server._entry("cnn")
        obs_programs = obs.compiled_programs(entry.batcher.cache_host)
    finally:
        server.close()

    assert all(len(o) == 1 and "scores" in o for o in outs)
    got = _registry_crossings()
    # crossings + bytes + recompile surface: registry == seam counter
    assert got["uploads"] == c.uploads
    assert got["fetches"] == c.fetches
    assert got["upload_bytes"] == c.upload_bytes
    assert got["distinct_shapes"] == len(c.upload_shapes)
    assert got["distinct_shapes"] <= len(buckets)
    # the compile hook is obs-owned and serve-delegated: same number
    assert programs == obs_programs
    if programs is not None:
        assert programs <= len(buckets)
    # re-backed ServerStats stays value-compatible under real traffic
    assert snap["completed"] == n_req
    assert snap["rows_dispatched"] == n_req
    assert snap["distinct_batch_shapes"] <= len(buckets)
    assert sum(snap["occupancy_by_bucket"].values()) == snap["batches"]
    # serve spans landed on the timeline alongside the plan spans
    cats = {r.cat for r in obs.captured() if isinstance(r, SpanRecord)}
    assert "serve" in cats and "plan" in cats


# ---- train: loader spans + input_stats as a registry view ----

def test_trainer_input_stats_published_as_registry_view():
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    obs.enable()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = rng.integers(0, 4, 64).astype(np.int64)
    cfg = TrainConfig(batch_size=16, epochs=1, prefetch_depth=2,
                      log_every=2)
    tr = Trainer(MLP(features=(8,), num_outputs=4), cfg)
    tr.fit_arrays(x, y)

    stats = tr.input_stats
    assert stats is not None and stats["batches"] == 4
    reg = obs.registry()
    # every input_stats key is a gauge in the shared registry with the
    # SAME value — Trainer.input_stats is a view over the substrate
    for key, val in stats.items():
        g = reg.gauge(f"train.input.{key}", loader="fit_arrays")
        assert g.value == val, (key, g.value, val)
    assert reg.value("train.steps") == 4
    names = {r.name for r in obs.captured() if isinstance(r, SpanRecord)}
    assert "train/step" in names
    assert "fit_arrays/commit" in names
    assert "fit_arrays/wait" in names


def test_decode_chunk_span_and_counters(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from mmlspark_tpu.data.readers import read_images

    img = np.zeros((8, 8, 3), np.uint8)
    for k in range(3):
        cv2.imwrite(str(tmp_path / f"im{k}.png"), img)
    obs.enable()
    out = read_images(str(tmp_path))
    assert len(out) == 3
    names = {r.name for r in obs.captured() if isinstance(r, SpanRecord)}
    assert "data/decode_chunk" in names
    assert obs.registry().value("data.images_decoded") == 3

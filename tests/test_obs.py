"""Obs subsystem suite: span tracer correctness (nesting, threads,
enable/disable isolation), histogram percentiles vs numpy, Chrome-trace
export validity, and the acceptance contract that obs counters EXACTLY
equal the independently observed crossing/compile values the PR 1/PR 4
tests assert at the planner's own seams — one telemetry substrate, not a
second set of numbers."""

import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from test_plan import image_table, mlp_bundle  # noqa: E402

from mmlspark_tpu import obs
from mmlspark_tpu.core import plan
from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.schema import make_image
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.obs.events import SpanRecord
from mmlspark_tpu.stages.featurize import AssembleFeatures
from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage


@pytest.fixture(autouse=True)
def obs_isolated():
    """Every test starts AND ends with the tracer off and all state
    dropped — enabling obs in one test must never leak spans, counters,
    or the enabled flag into the next (the flag-isolation contract)."""
    obs.disable()
    obs.clear()
    obs.registry().reset()
    yield
    obs.disable()
    obs.clear()
    obs.registry().reset()


# ---- span tracer ----

def test_disabled_span_is_shared_null_and_records_nothing():
    assert not obs.enabled()
    s1, s2 = obs.span("a"), obs.span("b", "cat", {"k": 1})
    assert s1 is s2  # one shared null context: no allocation when off
    with s1:
        pass
    obs.event("instant")
    assert obs.captured() == []


def test_nested_spans_record_parentage_and_containment():
    obs.enable()
    with obs.span("outer", "t"):
        with obs.span("mid", "t"):
            with obs.span("inner", "t", {"k": "v"}):
                pass
        with obs.span("mid2", "t"):
            pass
    recs = {r.name: r for r in obs.captured()}
    assert set(recs) == {"outer", "mid", "inner", "mid2"}
    outer, mid, inner, mid2 = (recs[n]
                               for n in ("outer", "mid", "inner", "mid2"))
    assert outer.parent_id is None and outer.depth == 0
    assert mid.parent_id == outer.span_id and mid.depth == 1
    assert inner.parent_id == mid.span_id and inner.depth == 2
    assert mid2.parent_id == outer.span_id and mid2.depth == 1
    assert inner.labels == {"k": "v"}
    # wall-clock containment: children lie inside their parent
    for child, parent in ((mid, outer), (inner, mid), (mid2, outer)):
        assert child.start_ns >= parent.start_ns
        assert child.end_ns <= parent.end_ns
    # siblings are ordered, not overlapping
    assert mid.end_ns <= mid2.start_ns


def test_span_records_survive_exceptions():
    obs.enable()
    with pytest.raises(ValueError):
        with obs.span("dies", "t"):
            raise ValueError("boom")
    (rec,) = obs.captured()
    assert rec.name == "dies" and rec.dur_ns >= 0
    # the thread-local stack unwound: a new root span has no parent
    with obs.span("next", "t"):
        pass
    assert [r.parent_id for r in obs.captured()] == [None, None]


def test_threaded_spans_keep_independent_stacks():
    obs.enable()
    barrier = threading.Barrier(2)

    def work(tag: str) -> None:
        barrier.wait()
        with obs.span(f"{tag}/outer", "t"):
            with obs.span(f"{tag}/inner", "t"):
                pass

    threads = [threading.Thread(target=work, args=(t,), name=f"W{t}")
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = {r.name: r for r in obs.captured()}
    assert len(recs) == 4
    for tag in ("a", "b"):
        outer, inner = recs[f"{tag}/outer"], recs[f"{tag}/inner"]
        # nesting resolved per-thread: never across threads
        assert inner.parent_id == outer.span_id
        assert outer.parent_id is None
        assert inner.tid == outer.tid
    assert recs["a/outer"].tid != recs["b/outer"].tid
    assert recs["a/outer"].thread_name == "Wa"


def test_enable_disable_toggles_capture():
    obs.enable()
    with obs.span("while-on", "t"):
        pass
    obs.disable()
    with obs.span("while-off", "t"):
        pass
    names = [r.name for r in obs.captured()]
    assert names == ["while-on"]  # captured records stay readable


def test_ring_buffer_bounded():
    obs.enable(buffer_size=16)
    for k in range(64):
        with obs.span(f"s{k}", "t"):
            pass
    recs = obs.captured()
    assert len(recs) == 16
    assert recs[0].name == "s48" and recs[-1].name == "s63"  # newest kept


# ---- metrics registry ----

def test_counter_gauge_interning_and_labels():
    reg = obs.registry()
    c1 = reg.counter("x.total", model="m", bucket=8)
    c2 = reg.counter("x.total", bucket=8, model="m")  # order-insensitive
    assert c1 is c2
    c1.add(2)
    c2.add(0.5)
    assert reg.counter("x.total", model="m", bucket=8).value == 2.5
    assert reg.counter("x.total", model="other").value == 0  # distinct
    with pytest.raises(ValueError):
        c1.add(-1)
    g = reg.gauge("x.depth")
    assert g.value is None
    g.set(3)
    g.add(1)
    assert g.value == 4.0
    snap = reg.snapshot()
    assert snap["counters"]["x.total{bucket=8,model=m}"] == 2.5
    assert snap["gauges"]["x.depth"] == 4.0


def test_histogram_percentiles_match_numpy():
    rng = np.random.default_rng(7)
    values = rng.normal(size=500).tolist()
    h = obs.registry().histogram("lat", window=1024)
    for v in values:
        h.observe(v)
    p = h.percentiles(ndigits=None)
    p50, p95, p99 = np.percentile(np.asarray(values), [50, 95, 99])
    assert p["n"] == 500
    assert p["p50"] == pytest.approx(float(p50))
    assert p["p95"] == pytest.approx(float(p95))
    assert p["p99"] == pytest.approx(float(p99))
    assert h.count == 500 and h.sum == pytest.approx(sum(values))


def test_histogram_window_bounds_memory_but_not_count():
    h = obs.registry().histogram("w", window=8)
    for v in range(100):
        h.observe(v)
    assert h.count == 100  # lifetime count exact
    assert h.values() == list(range(92, 100))  # window keeps the newest
    assert h.percentiles()["n"] == 8


def test_empty_histogram_is_snapshot_safe():
    h = obs.registry().histogram("never")
    assert h.percentiles() is None and h.mean() is None
    snap = obs.registry().snapshot()["histograms"]["never"]
    assert snap["count"] == 0 and snap["percentiles"] is None
    json.dumps(snap)


# ---- Chrome-trace export ----

def test_chrome_trace_is_valid_trace_event_json():
    obs.enable()
    with obs.span("parent", "plan", {"rows": 4}):
        with obs.span("child", "plan"):
            pass
    obs.event("mark", "serve", {"model": "m"})
    payload = json.loads(json.dumps(obs.chrome_trace()))  # JSON-safe
    events = payload["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(complete) == 2 and len(instants) == 1 and len(meta) >= 1
    for e in complete:
        # the trace_event contract chrome://tracing / Perfetto require
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid"}
        assert isinstance(e["ts"], float) and e["dur"] >= 0
    by_name = {e["name"]: e for e in complete}
    parent, child = by_name["parent"], by_name["child"]
    # nesting: same lane, child interval inside the parent's
    assert child["tid"] == parent["tid"]
    assert child["ts"] >= parent["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"]
    assert child["args"]["parent_id"] == parent["args"]["span_id"]
    assert parent["args"]["rows"] == 4
    assert meta[0]["name"] == "thread_name"


def test_summarize_spans_aggregates_by_name():
    obs.enable()
    for _ in range(3):
        with obs.span("hot", "t"):
            pass
    with obs.span("cold", "t"):
        pass
    from mmlspark_tpu.obs.export import summarize_spans
    rows = {r["name"]: r for r in summarize_spans()}
    assert rows["hot"]["calls"] == 3 and rows["cold"]["calls"] == 1
    assert rows["hot"]["total_ms"] >= rows["hot"]["mean_ms"]


# ---- the acceptance contract: obs counters == the PR 1 seam counts ----

def _registry_crossings() -> dict:
    counters = obs.registry().snapshot()["counters"]
    shapes = obs.registry().series("plan.h2d_shapes")
    return {
        "uploads": counters.get("plan.h2d_uploads", 0),
        "fetches": counters.get("plan.d2h_fetches", 0),
        "upload_bytes": counters.get("plan.h2d_bytes", 0),
        "distinct_shapes": len(shapes),
    }


def parity_pipelines():
    """The tests/test_plan.py parity scenarios, rebuilt here: every fused
    shape the PR 1 suite pins, plus the host-fallback case that must
    count ZERO crossings."""
    return [
        ("crop_flip_unroll",
         [ImageTransformer().crop(2, 3, 16, 12).flip(-1),
          UnrollImage(scale=1.0, offset=0.0)],
         image_table()),
        ("resize_unroll",
         [ImageTransformer().resize(16, 12), UnrollImage()],
         image_table(h=29, w=23)),
        ("three_stage_model_tail_padding",
         [ImageTransformer().flip(0),
          AssembleFeatures(columns_to_featurize=["image"],
                           allow_images=True,
                           features_col="features").fit(
              image_table(n=10, h=12, w=10)),
          JaxModel(model=mlp_bundle(2 + 12 * 10 * 3),
                   input_col="features", output_col="scores",
                   minibatch_size=4, mesh_spec={"dp": 1})],
         image_table(n=10, h=12, w=10)),
        ("chained_models",
         [JaxModel(model=mlp_bundle(6, out_dim=5, seed=1), input_col="x",
                   output_col="h", minibatch_size=4),
          JaxModel(model=mlp_bundle(5, out_dim=3, seed=2), input_col="h",
                   output_col="scores", minibatch_size=4)],
         DataTable({"x": list(np.random.default_rng(3).normal(
             size=(9, 6)).astype(np.float32))})),
        ("ragged_host_fallback",
         [ImageTransformer().flip(1), UnrollImage()],
         DataTable({"image": [
             make_image(f"p{k}",
                        np.random.default_rng(5).integers(
                            0, 255, (10 + k, 8, 3)))
             for k in range(5)]})),
    ]


@pytest.mark.parametrize("name,stages,table",
                         parity_pipelines(),
                         ids=[p[0] for p in parity_pipelines()])
def test_obs_counters_equal_seam_counts_for_parity_pipelines(
        name, stages, table):
    """For every PR 1 parity pipeline the registry's crossing counters
    must EXACTLY equal what the independent seam-patching counter
    observes: crossings, bytes, and the distinct-upload-shape recompile
    surface. (The ragged case pins the zero: a host fallback records no
    phantom crossings.)"""
    obs.enable()
    with plan.count_crossings() as c:
        PipelineModel(stages).transform(table)
    got = _registry_crossings()
    assert got["uploads"] == c.uploads
    assert got["fetches"] == c.fetches
    assert got["upload_bytes"] == c.upload_bytes
    assert got["distinct_shapes"] == len(c.upload_shapes)
    if name == "ragged_host_fallback":
        assert got["uploads"] == 0 and got["upload_bytes"] == 0


def test_obs_compile_counter_counts_segment_builds():
    obs.enable()
    table = image_table(n=6)
    pm = PipelineModel([ImageTransformer().flip(1), UnrollImage()])
    pm.transform(table)
    first = obs.registry().value("plan.segment_compiles")
    assert first == 1
    pm.transform(table)  # cache hit: no new compile
    assert obs.registry().value("plan.segment_compiles") == first
    assert obs.compiled_programs(pm) == 1


# ---- serve burst: one substrate across the PR 4 observables ----

def test_serve_burst_obs_counters_match_pr4_observables():
    """One serve burst: the registry's crossing/shape counters, the
    obs-owned compile-cache hook, and the re-backed ServerStats snapshot
    must all agree with the independently counted values the PR 4 tests
    assert."""
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    buckets, n_req = (1, 8, 32), 48
    bundle = get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)
    jm = JaxModel(model=bundle, input_col="image", output_col="scores")
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 255, (n_req, 32 * 32 * 3)).astype(np.uint8)

    server = ModelServer(ServeConfig(buckets=buckets, max_queue=n_req,
                                     deadline_ms=None))
    try:
        server.add_model("cnn", jm,
                         example=DataTable({"image": [rows[0]]}))
        obs.enable()  # after warmup: count the burst only
        with plan.count_crossings() as c:
            handles = [server.submit("cnn",
                                     DataTable({"image": [rows[i]]}))
                       for i in range(n_req)]
            outs = [h.result(timeout=300) for h in handles]
        snap = server.stats("cnn").snapshot()
        programs = server.compiled_programs("cnn")
        entry = server._entry("cnn")
        obs_programs = obs.compiled_programs(entry.batcher.cache_host)
    finally:
        server.close()

    assert all(len(o) == 1 and "scores" in o for o in outs)
    got = _registry_crossings()
    # crossings + bytes + recompile surface: registry == seam counter
    assert got["uploads"] == c.uploads
    assert got["fetches"] == c.fetches
    assert got["upload_bytes"] == c.upload_bytes
    assert got["distinct_shapes"] == len(c.upload_shapes)
    assert got["distinct_shapes"] <= len(buckets)
    # the compile hook is obs-owned and serve-delegated: same number
    assert programs == obs_programs
    if programs is not None:
        assert programs <= len(buckets)
    # re-backed ServerStats stays value-compatible under real traffic
    assert snap["completed"] == n_req
    assert snap["rows_dispatched"] == n_req
    assert snap["distinct_batch_shapes"] <= len(buckets)
    assert sum(snap["occupancy_by_bucket"].values()) == snap["batches"]
    # serve spans landed on the timeline alongside the plan spans
    cats = {r.cat for r in obs.captured() if isinstance(r, SpanRecord)}
    assert "serve" in cats and "plan" in cats


# ---- train: loader spans + input_stats as a registry view ----

def test_trainer_input_stats_published_as_registry_view():
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    obs.enable()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = rng.integers(0, 4, 64).astype(np.int64)
    cfg = TrainConfig(batch_size=16, epochs=1, prefetch_depth=2,
                      log_every=2)
    tr = Trainer(MLP(features=(8,), num_outputs=4), cfg)
    tr.fit_arrays(x, y)

    stats = tr.input_stats
    assert stats is not None and stats["batches"] == 4
    reg = obs.registry()
    # every input_stats key is a gauge in the shared registry with the
    # SAME value — Trainer.input_stats is a view over the substrate
    for key, val in stats.items():
        g = reg.gauge(f"train.input.{key}", loader="fit_arrays")
        assert g.value == val, (key, g.value, val)
    assert reg.value("train.steps") == 4
    names = {r.name for r in obs.captured() if isinstance(r, SpanRecord)}
    assert "train/step" in names
    assert "fit_arrays/commit" in names
    assert "fit_arrays/wait" in names


def test_decode_chunk_span_and_counters(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from mmlspark_tpu.data.readers import read_images

    img = np.zeros((8, 8, 3), np.uint8)
    for k in range(3):
        cv2.imwrite(str(tmp_path / f"im{k}.png"), img)
    obs.enable()
    out = read_images(str(tmp_path))
    assert len(out) == 3
    names = {r.name for r in obs.captured() if isinstance(r, SpanRecord)}
    assert "data/decode_chunk" in names
    assert obs.registry().value("data.images_decoded") == 3


# ---- request-scoped tracing (obs/context.py) ----


def test_mint_is_none_when_disabled_and_unique_when_enabled():
    assert obs.mint() is None  # the disabled path: one flag check
    obs.enable()
    ids = [obs.mint() for _ in range(100)]
    assert len(set(ids)) == 100 and all(isinstance(t, int) for t in ids)


def test_spans_inherit_bound_trace_across_threads():
    from mmlspark_tpu.obs import context
    obs.enable()
    t1 = obs.mint()

    def worker():
        # a DIFFERENT thread binding the same trace: its spans belong
        # to the same request — the batcher's thread-hop case
        with context.bind(t1):
            with obs.span("lane/work", "serve"):
                pass

    with context.bind(t1):
        with obs.span("caller/work", "serve"):
            pass
    assert context.current() is None  # binding restored on exit
    th = threading.Thread(target=worker)
    th.start()
    th.join()
    recs = [r for r in obs.captured() if isinstance(r, SpanRecord)]
    assert {r.name for r in recs} == {"caller/work", "lane/work"}
    assert all(r.trace == t1 for r in recs)
    assert len({r.tid for r in recs}) == 2  # genuinely two threads


def test_bind_nests_and_restores_previous_trace():
    from mmlspark_tpu.obs import context
    obs.enable()
    t1, t2 = obs.mint(), obs.mint()
    with context.bind(t1):
        assert context.current() == t1
        with context.bind(t2):
            assert context.current() == t2
        assert context.current() == t1
        with context.bind(None):  # explicit clear (worker reuse)
            assert context.current() is None
        assert context.current() == t1
    assert context.current() is None


def _journey(t, *, admit=1, complete=1):
    """Record one synthetic request journey for trace id ``t``."""
    from mmlspark_tpu.obs import context
    for _ in range(admit):
        with context.bind(t):
            with obs.span("serve/admit", "serve"):
                pass
    for name in ("serve/pack", "serve/dispatch", "serve/drain"):
        with obs.span(name, "serve", links=(t,)):
            pass
    for _ in range(complete):
        with context.bind(t):
            with obs.span("serve/complete", "serve"):
                pass


def test_request_traces_groups_by_trace_and_links():
    obs.enable()
    t1, t2 = obs.mint(), obs.mint()
    # two requests coalesced into ONE batch: shared pack/dispatch/drain
    from mmlspark_tpu.obs import context
    for t in (t1, t2):
        with context.bind(t):
            with obs.span("serve/admit", "serve"):
                pass
    for name in ("serve/pack", "serve/dispatch", "serve/drain"):
        with obs.span(name, "serve", links=(t1, t2)):
            pass
    for t in (t1, t2):
        with context.bind(t):
            with obs.span("serve/complete", "serve"):
                pass
    traces = obs.request_traces()
    assert set(traces) == {t1, t2}
    for t in (t1, t2):
        assert obs.check_journey(traces[t]) is None
        names = [s.name for s in traces[t]]
        assert names[0] == "serve/admit" and names[-1] == "serve/complete"
        # the SHARED batch spans appear in both traces
        assert "serve/pack" in names and "serve/drain" in names


def test_check_journey_flags_missing_and_duplicated_spans():
    obs.enable()
    t = obs.mint()
    from mmlspark_tpu.obs import context
    with context.bind(t):
        with obs.span("serve/admit", "serve"):
            pass
    # half a journey: no batch spans, no completion
    traces = obs.request_traces()
    why = obs.check_journey(traces[t])
    assert why is not None and "serve/pack" in why
    # a duplicated endpoint is flagged too
    t2 = obs.mint()
    _journey(t2, admit=2)
    why2 = obs.check_journey(obs.request_traces()[t2])
    assert why2 is not None and "serve/admit" in why2


def test_chrome_trace_emits_flow_events_binding_the_journey():
    obs.enable()
    t = obs.mint()
    _journey(t)
    payload = json.loads(json.dumps(obs.chrome_trace()))
    flows = [e for e in payload["traceEvents"]
             if e.get("ph") in ("s", "t", "f")]
    mine = sorted((e for e in flows if e["id"] == t),
                  key=lambda e: e["ts"])
    # one flow: a start, three steps (pack/dispatch/drain), a finish
    assert [e["ph"] for e in mine] == ["s", "t", "t", "t", "f"]
    assert all(e.get("bp") == "e" for e in mine)
    # the complete events carry the trace/links in args for debugging
    admits = [e for e in payload["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "serve/admit"]
    assert admits and admits[0]["args"]["trace"] == t
    packs = [e for e in payload["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "serve/pack"]
    assert packs and packs[0]["args"]["links"] == [t]


def test_single_touch_trace_emits_no_flow():
    obs.enable()
    t = obs.mint()
    from mmlspark_tpu.obs import context
    with context.bind(t):
        with obs.span("serve/admit", "serve"):
            pass
    flows = [e for e in obs.chrome_trace()["traceEvents"]
             if e.get("ph") in ("s", "t", "f")]
    assert flows == []  # an arrow needs two ends


# ---- trace retention (the request_traces eviction policy) ----


def test_sustained_trace_burst_cannot_grow_memory_unboundedly():
    """Regression (PR 9 satellite): completed traces used to be retained
    for grouping until someone called clear() — a server left tracing
    under sustained traffic grew request_traces() without bound. The
    retention policy drops the OLDEST traces past ``max_traces``,
    evicts their spans from the ring, and counts the drops."""
    from mmlspark_tpu.obs import runtime as rt
    obs.enable(max_traces=64)
    n_burst = 2048
    for _ in range(n_burst):
        _journey(obs.mint())
    live = rt.live_traces()
    assert len(live) <= 64, (
        f"{len(live)} live traces retained against a bound of 64")
    traces = obs.request_traces()
    assert len(traces) <= 64
    # the newest traces survive, the oldest are gone (drop-OLDEST)
    assert max(traces) == max(live)
    assert min(traces) > n_burst - 128
    # the dropped traces' spans actually left the ring (memory, not
    # just the grouping view)
    for r in rt.spans():
        tr = getattr(r, "trace", None)
        links = getattr(r, "links", None) or ()
        if tr is not None or links:
            assert (tr in live) or any(t in live for t in links)
    dropped = obs.registry().value("obs.traces_dropped")
    assert dropped is not None and dropped >= n_burst - 64
    assert rt.dropped_trace_count() == dropped


def test_trace_eviction_spares_non_request_records():
    from mmlspark_tpu.obs import runtime as rt
    obs.enable(max_traces=8)
    with obs.span("train/step", "train"):  # no trace id: never evicted
        pass
    for _ in range(64):
        _journey(obs.mint())
    names = [getattr(r, "name", "") for r in rt.spans()]
    assert "train/step" in names, (
        "trace eviction evicted a span that carries no trace id")


def test_evicted_trace_is_not_resurrected_by_late_spans():
    """Regression: a trace dropped while its request was still in
    flight was re-registered as the NEWEST trace when its tail span
    completed — request_traces() then reported a broken journey for a
    partial, tail-only trace (and a second eviction double-counted the
    drop)."""
    from mmlspark_tpu.obs import context, runtime as rt
    obs.enable(max_traces=8)
    victim = obs.mint()
    with context.bind(victim):
        with obs.span("serve/admit", "serve"):
            pass
    # push the victim out of retention while it is "in flight"
    for _ in range(64):
        _journey(obs.mint())
    assert victim not in rt.live_traces()
    dropped_before = rt.dropped_trace_count()
    # the late tail span completes AFTER eviction
    with context.bind(victim):
        with obs.span("serve/complete", "serve"):
            pass
    assert victim not in rt.live_traces(), "dropped trace resurrected"
    assert victim not in obs.request_traces(), (
        "tail-only partial trace grouped after eviction")
    # and the drop is never double-counted by later evictions
    for _ in range(64):
        _journey(obs.mint())
    drops = rt.dropped_trace_count() - dropped_before
    assert drops == 64, f"{drops} drops for 64 new traces"


def test_enable_without_max_traces_restores_default_bound():
    """Regression: ``enable(max_traces=4)`` used to leave the tiny bound
    sticky for every later ``enable()`` in the process — a 200-request
    burst after a re-bounded enable retained 4 traces. Omitting the
    kwarg restores the default, same as ``buffer_size`` does."""
    from mmlspark_tpu.obs import device as obs_device
    from mmlspark_tpu.obs import runtime as rt
    obs.enable(max_traces=4, device=True)
    assert rt._max_traces == 4 and obs_device.enabled()
    obs.enable()
    assert rt._max_traces == rt.DEFAULT_MAX_TRACES
    # the device pillar follows the same rule: omitted → back to the
    # env baseline (off here)
    assert not obs_device.enabled()
    for _ in range(32):
        _journey(obs.mint())
    assert len(obs.request_traces()) == 32
    # with MMLSPARK_TPU_OBS_DEVICE=1 the baseline is ON: a library's
    # plain enable() must not defeat the no-code-changes env path
    from mmlspark_tpu.core import config
    config.set("obs_device", True)
    try:
        obs.enable()
        assert obs_device.enabled(), (
            "plain enable() defeated the env device baseline")
        obs.enable(device=False)  # explicit off still wins
        assert not obs_device.enabled()
    finally:
        config.set("obs_device", False)


def test_request_traces_explicit_records_bypass_retention():
    """A caller-supplied record list is the caller's retention problem —
    the filter applies only to the runtime ring's view."""
    obs.enable(max_traces=4)
    ids = []
    for _ in range(16):
        t = obs.mint()
        ids.append(t)
        _journey(t)
    kept = obs.captured()
    # grouping the ring honors the bound…
    assert len(obs.request_traces()) <= 4
    # …but an explicit list groups everything it holds
    explicit = obs.request_traces(kept)
    assert set(explicit) <= set(ids)
    assert len(explicit) >= len(obs.request_traces())


# ---- SLO engine (obs/slo.py) ----


def _slo_stats(model="m"):
    from mmlspark_tpu.serve.stats import ServerStats
    return ServerStats(window=64, model=model)


def test_slo_tracker_burn_rates_from_counter_deltas():
    from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
    spec = SLOSpec(objective=0.9, window_s=10.0, long_window_s=40.0,
                   min_requests=5)
    stats = _slo_stats()
    tracker = SLOTracker(spec, stats, queued_fn=lambda: 3)
    s0 = tracker.sample(now=0.0)
    assert s0["burn_rate_short"] is None  # one sample: no delta yet
    assert s0["queue_depth"] == 3
    # 10s later: 20 terminal requests, 4 failed → 20% errors on a 10%
    # budget → burn 2.0
    for _ in range(16):
        stats.record_admitted()
        stats.record_done(e2e_ms=5.0, queue_ms=1.0)
    for _ in range(4):
        stats.record_admitted()
        stats.record_failed()
    s1 = tracker.sample(now=10.0)
    assert s1["burn_rate_short"] == pytest.approx(2.0)
    assert s1["window_short"]["terminal"] == 20
    assert s1["window_short"]["errors"] == 4
    # lifetime error rate (20%) is 2x the whole budget: remaining
    # clamps at zero rather than going negative
    assert s1["budget_remaining"] == 0.0
    # quiet window: deltas vs the 10s-old sample go to zero traffic
    s2 = tracker.sample(now=20.0)
    assert s2["burn_rate_short"] is None  # < min_requests in window
    assert s2["window_short"]["terminal"] == 0


def test_slo_tracker_ignores_thin_windows():
    from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
    spec = SLOSpec(objective=0.99, window_s=10.0, long_window_s=20.0,
                   min_requests=10)
    stats = _slo_stats()
    tracker = SLOTracker(spec, stats)
    tracker.sample(now=0.0)
    stats.record_admitted()
    stats.record_failed()  # 100% errors, but only ONE request
    s = tracker.sample(now=10.0)
    assert s["burn_rate_short"] is None  # no verdict below min_requests
    assert s["window_short"]["errors"] == 1


def test_slo_tracker_long_window_survives_frequent_polling():
    """A dashboard polling /slo + /healthz at high frequency must not
    evict the long window's base sample — the ring is bounded by time
    (with sub-resolution appends coalesced), not a fixed maxlen that
    would silently collapse burn_rate_long onto a recent window."""
    from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
    spec = SLOSpec(objective=0.9, window_s=10.0, long_window_s=40.0,
                   min_requests=5)
    stats = _slo_stats()
    tracker = SLOTracker(spec, stats)
    tracker.sample(now=0.0)
    # the incident happens early: 20 terminal requests, 4 failed
    for _ in range(16):
        stats.record_admitted()
        stats.record_done(e2e_ms=5.0, queue_ms=1.0)
    for _ in range(4):
        stats.record_admitted()
        stats.record_failed()
    # then 2500 polls over 5 s — far more than any fixed sample cap
    for i in range(2500):
        tracker.sample(now=5.0 + i * 0.002)
    s = tracker.sample(now=41.0)
    # the 40 s base is still the t=0 sample: the incident stays visible
    assert s["window_long"]["terminal"] == 20
    assert s["window_long"]["errors"] == 4
    assert s["burn_rate_long"] == pytest.approx(2.0)
    # and coalescing kept the ring bounded despite the poll rate
    assert len(tracker._samples) < 8200


def test_slo_tracker_sub_resolution_polling_from_cold_start():
    """An LB probing every 2 ms from process start — faster than the
    ring resolution (long_window_s/4096 ≈ 9.8 ms here) with no slower
    poll ever banking a base sample — must still converge to a burn
    verdict. Coalescing replaces the tail slot's reads but keeps its
    original timestamp, so the slot ages past the resolution step and
    base samples accumulate; rewriting the timestamp made the tail a
    sliding target that kept the engine verdict-less forever."""
    from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
    spec = SLOSpec(objective=0.9, window_s=10.0, long_window_s=40.0,
                   min_requests=5)
    stats = _slo_stats()
    tracker = SLOTracker(spec, stats)
    for i in range(1000):          # t = 0 .. 2 s, quiet
        tracker.sample(now=i * 0.002)
    for _ in range(16):
        stats.record_admitted()
        stats.record_done(e2e_ms=5.0, queue_ms=1.0)
    for _ in range(4):
        stats.record_admitted()
        stats.record_failed()
    s = None
    for i in range(1000, 5501):    # keep probing through t = 11 s
        s = tracker.sample(now=i * 0.002)
    # the 10 s short-window base (a slot near t = 1 s) predates the
    # incident: the burn is visible instead of None-forever
    assert s["window_short"]["terminal"] == 20
    assert s["window_short"]["errors"] == 4
    assert s["burn_rate_short"] == pytest.approx(2.0)


def test_slo_latency_objective_and_derived_gauges():
    from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
    spec = SLOSpec(objective=0.999, latency_ms=50.0,
                   latency_quantile="p99")
    stats = _slo_stats()
    stats.record_batch(bucket=8, occupancy=6, device_ms=4.0,
                       replica=0)
    stats.record_batch(bucket=8, occupancy=2, device_ms=4.0,
                       replica=0)
    stats.record_batch(bucket=8, occupancy=8, device_ms=4.0,
                       replica=1)
    for ms in (10.0, 20.0, 200.0):
        stats.record_admitted()
        stats.record_done(e2e_ms=ms, queue_ms=1.0)
    tracker = SLOTracker(spec, stats, queued_fn=lambda: 7)
    s = tracker.sample(now=0.0)
    assert s["latency_ok"] is False and s["latency_ms"] > 50.0
    # derived gauges landed in the model's own registry
    reg = stats.registry
    assert reg.gauge("serve.queue_depth", model="m").value == 7.0
    assert reg.gauge("serve.occupancy_mean_window",
                     model="m").value == pytest.approx(16 / 3, abs=1e-3)
    # replica skew from the replica_batches counters: 2 vs 1 → 0.5
    assert reg.gauge("serve.replica_skew", model="m").value \
        == pytest.approx(0.5)
    assert s["replica_skew"] == pytest.approx(0.5)


def test_slo_spec_validation_and_parse():
    from mmlspark_tpu.obs.slo import SLOSpec
    with pytest.raises(ValueError):
        SLOSpec(objective=1.0)
    with pytest.raises(ValueError):
        SLOSpec(latency_quantile="p90")
    with pytest.raises(ValueError):
        SLOSpec(window_s=60.0, long_window_s=30.0)
    with pytest.raises(ValueError):
        SLOSpec(min_requests=0)  # would divide by a zero-traffic window
    with pytest.raises(ValueError):
        SLOSpec(fast_burn=0.0)
    with pytest.raises(ValueError):
        SLOSpec(slow_burn=-1.0)
    assert SLOSpec.parse(None).objective == 0.999
    parsed = SLOSpec.parse({"objective": 0.95, "latency_ms": 100.0})
    assert parsed.objective == 0.95 and parsed.budget == \
        pytest.approx(0.05)
    assert SLOSpec.parse(parsed) is parsed
    with pytest.raises(TypeError):
        SLOSpec.parse("p99<100ms")


def test_slow_step_detector_flags_outliers_and_rebaselines():
    from mmlspark_tpu.obs.slo import SlowStepDetector
    obs.enable()
    det = SlowStepDetector(loop="t", factor=3.0, min_samples=4,
                           window=8)
    assert not any(det.observe(10.0) for _ in range(4))  # baseline
    assert det.observe(100.0) is True  # 10x the median
    assert det.observe(12.0) is False
    assert obs.registry().value("train.slow_steps", loop="t") == 1
    events = [r for r in obs.captured()
              if getattr(r, "name", "") == "train/slow_step"]
    assert len(events) == 1 and events[0].labels["step_ms"] == 100.0
    # regime change: consistently slower steps re-baseline via the
    # window median instead of flagging forever
    for _ in range(8):
        det.observe(100.0)
    assert det.observe(110.0) is False


def test_slow_step_detector_baseline_is_per_instance():
    """The train.step_ms{loop=...} histogram is interned process-wide,
    but a fresh detector (a new fit) must baseline against ITS OWN
    steps — not the previous fit's window, which would flag every step
    of a legitimately slower run."""
    from mmlspark_tpu.obs.slo import SlowStepDetector
    obs.enable()
    fast = SlowStepDetector(loop="t2", factor=3.0, min_samples=4,
                            window=8)
    for _ in range(8):
        fast.observe(0.5)
    slow = SlowStepDetector(loop="t2", factor=3.0, min_samples=4,
                            window=8)
    # 5.0 ms steps are 10x the previous fit's median, but this fit's
    # own baseline is 5.0 — nothing is slow
    assert not any(slow.observe(5.0) for _ in range(8))
    assert obs.registry().value("train.slow_steps", loop="t2") == 0


def test_trainer_publishes_step_histogram_and_slow_counter():
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    obs.enable()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = rng.integers(0, 4, 64).astype(np.int64)
    tr = Trainer(MLP(features=(8,), num_outputs=4),
                 TrainConfig(batch_size=16, epochs=1, prefetch_depth=2))
    tr.fit_arrays(x, y)
    h = obs.registry().histogram("train.step_ms", loop="fit_arrays")
    assert h.count == 4  # one observation per step
    assert obs.registry().value("train.slow_steps",
                                loop="fit_arrays") is not None


# ---- health state machine (obs/health.py) ----


def _status(burn_short=None, burn_long=None, latency_ok=None,
            admitted=0, rejected=0, terminal=0):
    return {
        "burn_rate_short": burn_short,
        "burn_rate_long": burn_long,
        "latency_ok": latency_ok,
        "latency_ms": 10.0,
        "slo": {"latency_ms": 5.0, "latency_quantile": "p99"},
        "window_short": {"admitted": admitted, "rejected": rejected,
                         "terminal": terminal},
    }


def test_health_classification_levels():
    from mmlspark_tpu.obs.health import (
        DEGRADED, OK, UNHEALTHY, HealthPolicy, classify,
    )
    pol = HealthPolicy(fast_burn=10.0, slow_burn=2.0, min_events=5)
    assert classify(_status(), pol) == (OK, "")
    lvl, why = classify(_status(burn_short=12.0), pol)
    assert lvl == UNHEALTHY and "burn" in why
    lvl, why = classify(_status(burn_long=3.0), pol)
    assert lvl == DEGRADED and "long-window" in why
    lvl, why = classify(_status(latency_ok=False, terminal=10), pol)
    assert lvl == DEGRADED and "latency" in why
    # a frozen e2e reservoir (violating percentiles, no fresh window
    # traffic) is NOT a live violation — otherwise one cold-compile
    # spike would hold DEGRADED forever after traffic stops
    assert classify(_status(latency_ok=False), pol) == (OK, "")
    # admission bouncing most arrivals is unhealthy even with no
    # completed-request errors (Overloaded is backpressure)
    lvl, why = classify(_status(admitted=4, rejected=8), pol)
    assert lvl == UNHEALTHY and "rejecting" in why
    # ... but not below the event floor
    assert classify(_status(admitted=1, rejected=2), pol) == (OK, "")


def test_health_monitor_hysteresis():
    from mmlspark_tpu.obs.health import (
        DEGRADED, OK, UNHEALTHY, HealthMonitor, HealthPolicy,
    )
    mon = HealthMonitor(HealthPolicy(fast_burn=10.0, slow_burn=2.0,
                                     recover_after=3))
    assert mon.update(_status()) == OK
    # worsening applies immediately
    assert mon.update(_status(burn_short=20.0)) == UNHEALTHY
    assert mon.reason
    # recovery needs recover_after consecutive better samples
    assert mon.update(_status()) == UNHEALTHY
    assert mon.update(_status()) == UNHEALTHY
    assert mon.update(_status()) == OK
    # a relapse mid-streak resets it
    assert mon.update(_status(burn_long=5.0)) == DEGRADED
    assert mon.update(_status()) == DEGRADED
    assert mon.update(_status(burn_long=5.0)) == DEGRADED
    assert mon.update(_status()) == DEGRADED
    assert mon.update(_status()) == DEGRADED
    assert mon.update(_status()) == OK


def test_health_recovers_after_latency_spike_traffic_stops():
    """A latency violation backed by window traffic degrades; once
    traffic stops the reservoir stays frozen at the bad percentiles,
    but the verdict expires with the window and hysteresis recovers."""
    from mmlspark_tpu.obs.health import (
        DEGRADED, OK, HealthMonitor, HealthPolicy,
    )
    mon = HealthMonitor(HealthPolicy(min_events=5, recover_after=3))
    assert mon.update(_status(latency_ok=False, terminal=10)) == DEGRADED
    # traffic stops: percentiles still violating, window empty
    assert mon.update(_status(latency_ok=False)) == DEGRADED
    assert mon.update(_status(latency_ok=False)) == DEGRADED
    assert mon.update(_status(latency_ok=False)) == OK


def test_worst_of_states():
    from mmlspark_tpu.obs.health import worst
    assert worst([]) == "ok"
    assert worst(["ok", "degraded", "ok"]) == "degraded"
    assert worst(["degraded", "unhealthy"]) == "unhealthy"


# ---- Prometheus text exposition ----


def test_prometheus_text_exposition_format():
    from mmlspark_tpu.obs.export import prometheus_text
    reg = obs.registry()
    reg.counter("serve.admitted", model="m").add(3)
    reg.gauge("serve.queue_depth", model="m").set(2)
    reg.gauge("never.set")  # unset gauge: skipped (no null in prom)
    h = reg.histogram("serve.e2e_ms", window=16, model="m")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    text = prometheus_text()
    lines = text.splitlines()
    assert "# TYPE serve_admitted counter" in lines
    assert 'serve_admitted{model="m"} 3' in lines
    assert "# TYPE serve_queue_depth gauge" in lines
    assert 'serve_queue_depth{model="m"} 2' in lines
    assert "# TYPE serve_e2e_ms summary" in lines
    assert 'serve_e2e_ms{model="m",quantile="0.5"} 2.5' in lines
    assert 'serve_e2e_ms_count{model="m"} 4' in lines
    assert 'serve_e2e_ms_sum{model="m"} 10' in lines
    assert not any("never_set" in ln for ln in lines)
    # names are sanitized to the prom grammar; output ends with newline
    assert all(" " in ln or ln.startswith("#") for ln in lines)
    assert text.endswith("\n")


def test_prometheus_text_survives_non_finite_values():
    """One NaN/Inf series must not 500 the whole scrape — the registry
    is the shared substrate and any client can record a bad ratio.
    Non-finite samples render as the Prometheus literals."""
    from mmlspark_tpu.obs.export import prometheus_text
    reg = obs.registry()
    reg.gauge("bad.ratio", model="m").set(float("nan"))
    reg.gauge("bad.pos", model="m").set(float("inf"))
    reg.gauge("bad.neg", model="m").set(float("-inf"))
    reg.counter("still.fine").add(2)
    lines = prometheus_text().splitlines()
    assert 'bad_ratio{model="m"} NaN' in lines
    assert 'bad_pos{model="m"} +Inf' in lines
    assert 'bad_neg{model="m"} -Inf' in lines
    assert "still_fine 2" in lines


def test_prometheus_text_merges_registries_and_escapes_labels():
    from mmlspark_tpu.obs.export import prometheus_text
    from mmlspark_tpu.obs.metrics import MetricsRegistry
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.counter("serve.admitted", model="a").add(1)
    r2.counter("serve.admitted", model='b"\\q').add(2)
    text = prometheus_text([r1, r2])
    # ONE TYPE header for the shared name, both series present
    assert text.count("# TYPE serve_admitted counter") == 1
    assert 'serve_admitted{model="a"} 1' in text
    assert 'serve_admitted{model="b\\"\\\\q"} 2' in text


def test_prometheus_help_lines_per_family():
    """# HELP rides next to every # TYPE header: curated text for the
    known metric families, the generic fallback (naming the original
    dotted spelling) for the rest — and ONE pair per name across
    merged registries (the fleet-merged path hands several per-host
    registries to one exposition)."""
    from mmlspark_tpu.obs.export import prometheus_text
    from mmlspark_tpu.obs.metrics import MetricsRegistry
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.gauge("serve.queue_depth", model="a").set(2)
    r2.gauge("serve.queue_depth", model="b").set(3)
    r1.counter("totally.custom_metric").add(1)
    lines = prometheus_text([r1, r2]).splitlines()
    # curated help, once, immediately before its TYPE header
    assert lines.count("# HELP serve_queue_depth Live admission-queue "
                       "depth (the replica autoscaling signal).") == 1
    i = lines.index("# TYPE serve_queue_depth gauge")
    assert lines[i - 1].startswith("# HELP serve_queue_depth ")
    # generic fallback keeps the original dotted name greppable
    fallback = [ln for ln in lines
                if ln.startswith("# HELP totally_custom_metric ")]
    assert len(fallback) == 1
    assert "totally.custom_metric" in fallback[0]
    # every TYPE header has a HELP partner
    types = [ln.split()[2] for ln in lines if ln.startswith("# TYPE")]
    helps = [ln.split()[2] for ln in lines if ln.startswith("# HELP")]
    assert types == helps


def test_prometheus_text_byte_stable():
    """The non-fleet path is byte-stable: two expositions of the same
    registry state are identical bytes (scrape diffing, content
    hashing, and the docs' determinism claim all rely on it)."""
    from mmlspark_tpu.obs.export import prometheus_text
    reg = obs.registry()
    reg.counter("serve.admitted", model="m").add(3)
    reg.gauge("serve.queue_depth", model="m").set(2)
    h = reg.histogram("serve.e2e_ms", window=16, model="m")
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    first = prometheus_text()
    second = prometheus_text()
    assert first == second
    assert first.encode("utf-8") == second.encode("utf-8")

"""tools/bench_check.py — the perf-regression sentinel: tolerance-band
classification, exit-0 on the repo's real BENCH trajectory, exit-2 with
a named report on an injected regression."""

import importlib.util
import json
import os
import sys

import pytest

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
_REPO = os.path.join(os.path.dirname(__file__), "..")


def _load():
    spec = importlib.util.spec_from_file_location(
        "mmlspark_tools_bench_check",
        os.path.join(_TOOLS, "bench_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_check = _load()


def test_classification_rules():
    assert bench_check.classify("serve_rows_per_s") == "throughput"
    assert bench_check.classify("train_images_per_s_per_chip") \
        == "throughput"
    assert bench_check.classify("tunnel_upload_mb_s") == "throughput"
    assert bench_check.classify("mxu_matmul_tf_s") == "throughput"
    assert bench_check.classify("serve_p99_ms") == "p99"
    assert bench_check.classify("serve_swap_p99_ms_during") == "p99"
    assert bench_check.classify("weight_bytes_ratio") == "exact"
    assert bench_check.classify("vs_baseline") is None
    assert bench_check.classify("bridge_batch_p50_ms") is None


def _rounds(*parsed):
    return [(n + 1, p) for n, p in enumerate(parsed)]


def test_throughput_and_p99_bands():
    prior = {"serve_rows_per_s": 100.0, "serve_p99_ms": 10.0}
    ok = bench_check.check_line(
        {"serve_rows_per_s": 91.0, "serve_p99_ms": 12.4},
        _rounds(prior))
    assert ok["verdict"] == "ok" and not ok["regressions"]
    bad = bench_check.check_line(
        {"serve_rows_per_s": 89.0, "serve_p99_ms": 20.0},
        _rounds(prior))
    assert bad["verdict"] == "regressed"
    assert sorted(r["key"] for r in bad["regressions"]) \
        == ["serve_p99_ms", "serve_rows_per_s"]
    p99 = [r for r in bad["regressions"]
           if r["key"] == "serve_p99_ms"][0]
    assert p99["class"] == "p99" and p99["ratio"] == 2.0


def test_load_wall_warm_gated_within_line_not_across_rounds():
    """The compile-cache load walls gate warm <= cold WITHIN one line
    (same box, same minute); absolute walls never gate across rounds
    (box weather), so a prior round with faster walls is irrelevant."""
    prior = {"serve_load_wall_cold_s": 0.1, "serve_load_wall_warm_s": 0.05}
    ok = bench_check.check_line(
        {"serve_load_wall_cold_s": 6.0, "serve_load_wall_warm_s": 0.4},
        _rounds(prior))
    assert ok["verdict"] == "ok" and not ok["regressions"]
    bad = bench_check.check_line(
        {"serve_load_wall_cold_s": 1.0, "serve_load_wall_warm_s": 1.5},
        _rounds(prior))
    assert bad["verdict"] == "regressed"
    row = bad["regressions"][0]
    assert row["key"] == "serve_load_wall_warm_s"
    assert row["class"] == "within-line" and row["best"] == 1.0
    # the within-line gate holds even with no archived rounds at all
    empty = bench_check.check_line(
        {"serve_load_wall_cold_s": 1.0, "serve_load_wall_warm_s": 1.5}, [])
    assert empty["verdict"] == "regressed"


def test_best_prior_round_is_per_metric():
    # throughput compares against the per-metric MAX across priors
    # (r2's 120), p99 against the per-metric MIN (r1's 8.0) — the best
    # prior is chosen per metric, not one chosen round
    r1 = {"serve_rows_per_s": 80.0, "serve_p99_ms": 8.0}
    r2 = {"serve_rows_per_s": 120.0, "serve_p99_ms": 14.0}
    rep = bench_check.check_line(
        {"serve_rows_per_s": 100.0, "serve_p99_ms": 9.9},
        _rounds(r1, r2))
    assert rep["verdict"] == "regressed"
    regs = {r["key"]: r for r in rep["regressions"]}
    assert list(regs) == ["serve_rows_per_s"]  # 100 < 0.9 * 120
    assert regs["serve_rows_per_s"]["best_round"] == 2
    p99_row = [r for r in rep["checked"]
               if r["key"] == "serve_p99_ms"][0]
    assert p99_row["best"] == 8.0 and p99_row["best_round"] == 1


def test_byte_ratios_exact():
    rep = bench_check.check_line(
        {"weight_bytes_ratio": 0.26},
        _rounds({"weight_bytes_ratio": 0.25}))
    assert rep["verdict"] == "regressed"
    assert rep["regressions"][0]["band"] == "== last"
    ok = bench_check.check_line(
        {"weight_bytes_ratio": 0.25},
        _rounds({"weight_bytes_ratio": 0.25}))
    assert ok["verdict"] == "ok"


def test_volatile_metrics_tracked_not_gated():
    rep = bench_check.check_line(
        {"inference_images_per_s_per_chip": 1.0},
        _rounds({"inference_images_per_s_per_chip": 100.0}))
    assert rep["verdict"] == "ok"
    assert rep["volatile"][0]["ratio"] == 0.01
    assert rep["volatile"][0]["gated"] is False


def test_new_and_non_numeric_keys_skipped():
    rep = bench_check.check_line(
        {"serve_rows_per_s": None, "brand_new_per_s": 5.0,
         "device": "TPU v5 lite"},
        _rounds({"serve_rows_per_s": 100.0}))
    assert rep["verdict"] == "ok"
    assert rep["new"] == ["brand_new_per_s"]


def test_real_trajectory_exits_zero(capsys):
    """The acceptance pin: the repo's own BENCH_r*.json trajectory must
    pass the sentinel (volatile host-I/O probes tracked, not gated)."""
    rc = bench_check.main(["--repo", _REPO])
    out = capsys.readouterr().out
    assert rc == 0
    line = json.loads(out.splitlines()[0])
    assert line["bench_check"] == "ok"
    assert line["checked"] > 0


def test_injected_2x_p99_regression_exits_two(tmp_path, capsys):
    """The acceptance pin: a fixture trajectory with a 2x p99 blowup in
    the current line exits 2 and NAMES the regression."""
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump({"n": 1, "parsed": {
            "serve_rows_per_s": 100.0, "serve_p99_ms": 10.0,
            "weight_bytes_ratio": 0.25}}, fh)
    with open(tmp_path / "current.json", "w") as fh:
        json.dump({"serve_rows_per_s": 102.0, "serve_p99_ms": 20.0,
                   "weight_bytes_ratio": 0.25}, fh)
    rc = bench_check.main(["--repo", str(tmp_path),
                           "--current", str(tmp_path / "current.json")])
    out = capsys.readouterr().out
    assert rc == 2
    line = json.loads(out.splitlines()[0])
    assert line["bench_check"] == "regressed"
    assert line["regressions"] == ["serve_p99_ms"]
    assert "REGRESSION serve_p99_ms [p99]: 20.0" in out


def test_current_round_record_accepted(tmp_path, capsys):
    # --current also accepts a full round record ({"parsed": {...}})
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump({"n": 1, "parsed": {"serve_rows_per_s": 100.0}}, fh)
    with open(tmp_path / "current.json", "w") as fh:
        json.dump({"n": 2, "parsed": {"serve_rows_per_s": 95.0}}, fh)
    rc = bench_check.main(["--repo", str(tmp_path),
                           "--current", str(tmp_path / "current.json")])
    capsys.readouterr()
    assert rc == 0


def test_no_rounds_exits_two(tmp_path, capsys):
    rc = bench_check.main(["--repo", str(tmp_path)])
    assert rc == 2
    assert "no BENCH_r*.json" in capsys.readouterr().err

"""Filesystem abstraction: local + memory backends, scheme routing, and the
object-store model-repository / reader flows (core/hadoop + HDFSRepo analog,
reference: downloader/src/main/scala/ModelDownloader.scala:39-104)."""

import numpy as np
import pytest

from mmlspark_tpu.core import fs
from mmlspark_tpu.data.downloader import (
    ModelDownloader, load_bundle_file, publish_model,
)
from mmlspark_tpu.data.readers import read_binary_files, stream_binary_files
from mmlspark_tpu.models.zoo import get_model


@pytest.fixture(autouse=True)
def _clean_memory_fs():
    fs._memory_fs.clear()
    yield
    fs._memory_fs.clear()


class TestSchemeRouting:
    def test_split_scheme(self):
        assert fs.split_scheme("memory://a/b") == ("memory", "a/b")
        assert fs.split_scheme("/tmp/x") == ("", "/tmp/x")
        assert fs.split_scheme("gs://bucket/k") == ("gs", "bucket/k")
        # single letters are drive letters, not schemes
        assert fs.split_scheme("C://oddball") == ("", "C://oddball")

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError, match="unknown filesystem scheme"):
            fs.get_fs("bogus://x")

    def test_join_scheme_aware(self):
        assert fs.join("memory://repo", "a", "b") == "memory://repo/a/b"
        assert fs.join("/tmp/d", "f").endswith("tmp/d/f")

    def test_fsspec_gated_with_clear_error(self):
        try:
            import fsspec  # noqa: F401
            pytest.skip("fsspec installed; gating not observable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="fsspec"):
            fs.get_fs("gs://bucket/obj")


class TestMemoryFS:
    def test_write_read_roundtrip(self):
        fs.write_bytes("memory://d/x.bin", b"abc123")
        assert fs.read_bytes("memory://d/x.bin") == b"abc123"
        assert fs.exists("memory://d/x.bin")
        assert fs.size("memory://d/x.bin") == 6
        fs.remove("memory://d/x.bin")
        assert not fs.exists("memory://d/x.bin")

    def test_text_mode(self):
        with fs.open_file("memory://t.txt", "w") as f:
            f.write("héllo")
        with fs.open_file("memory://t.txt", "r") as f:
            assert f.read() == "héllo"

    def test_missing_file_raises(self):
        with pytest.raises(FileNotFoundError):
            fs.read_bytes("memory://nope")

    def test_list_recursive_and_flat(self):
        for p in ("memory://r/a.bin", "memory://r/b.bin",
                  "memory://r/sub/c.bin"):
            fs.write_bytes(p, b"x")
        assert fs.list_files("memory://r") == [
            "memory://r/a.bin", "memory://r/b.bin"]
        assert fs.list_files("memory://r", recursive=True) == [
            "memory://r/a.bin", "memory://r/b.bin", "memory://r/sub/c.bin"]

    def test_local_fs_still_default(self, tmp_path):
        p = str(tmp_path / "f.bin")
        fs.write_bytes(p, b"local")
        assert fs.read_bytes(p) == b"local"


class TestObjectStoreRepository:
    def test_publish_download_load_via_memory_repo(self, tmp_path):
        """The HDFSRepo flow end-to-end against the object-store double:
        publish to memory://, download into a local hash-verified cache,
        load, score."""
        bundle = get_model("MLP", input_dim=5, num_outputs=2)
        entry = publish_model(bundle, "memory://zoo")
        assert entry.hash and entry.size > 0

        dl = ModelDownloader("memory://zoo", cache_dir=str(tmp_path / "c"))
        assert [m.name for m in dl.list_models()] == ["MLP"]
        path = dl.download_by_name("MLP")
        loaded = load_bundle_file(path)
        x = np.zeros((2, 5), np.float32)
        np.testing.assert_allclose(np.asarray(bundle.apply(x)),
                                   np.asarray(loaded.apply(x)), atol=1e-6)

    def test_corrupted_object_store_artifact_detected(self, tmp_path):
        bundle = get_model("MLP", input_dim=3)
        entry = publish_model(bundle, "memory://zoo2")
        blob = fs.read_bytes(fs.join("memory://zoo2", entry.uri))
        fs.write_bytes(fs.join("memory://zoo2", entry.uri),
                       blob[: len(blob) // 2])
        dl = ModelDownloader("memory://zoo2", cache_dir=str(tmp_path / "c"))
        with pytest.raises(IOError, match="sha256 mismatch"):
            dl.download_by_name("MLP")

    def test_bundle_save_load_direct_on_memory(self):
        bundle = get_model("MLP", input_dim=4, num_outputs=2)
        from mmlspark_tpu.data.downloader import save_bundle_file
        save_bundle_file(bundle, "memory://direct/m.model")
        loaded = load_bundle_file("memory://direct/m.model")
        assert loaded.input_spec == (4,)


class TestObjectStoreReaders:
    def test_read_binary_files_from_memory(self):
        fs.write_bytes("memory://data/a.bin", b"AA")
        fs.write_bytes("memory://data/b.bin", b"BBB")
        t = read_binary_files("memory://data")
        assert list(t["path"]) == ["memory://data/a.bin",
                                   "memory://data/b.bin"]
        assert [len(b) for b in t["bytes"]] == [2, 3]

    def test_stream_from_memory_with_zip(self):
        import io
        import zipfile
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as zf:
            zf.writestr("inner1.bin", b"one")
            zf.writestr("inner2.bin", b"two")
        fs.write_bytes("memory://arch/pack.zip", buf.getvalue())
        fs.write_bytes("memory://arch/plain.bin", b"plain")
        chunks = list(stream_binary_files("memory://arch", chunk_rows=2))
        rows = [(p, bytes(b)) for c in chunks
                for p, b in zip(c["path"], c["bytes"])]
        assert ("memory://arch/pack.zip/inner1.bin", b"one") in rows
        assert ("memory://arch/plain.bin", b"plain") in rows
        assert len(rows) == 3


def test_memory_root_listing_respects_recursive_flag():
    fs.write_bytes("memory://top.bin", b"t")
    fs.write_bytes("memory://deep/nested.bin", b"n")
    assert fs.list_files("memory://") == ["memory://top.bin"]
    assert fs.list_files("memory://", recursive=True) == [
        "memory://deep/nested.bin", "memory://top.bin"]


def test_missing_memory_prefix_raises_like_local():
    from mmlspark_tpu.data.readers import read_binary_files
    fs.write_bytes("memory://realdata/a.bin", b"x")
    with pytest.raises(FileNotFoundError):
        read_binary_files("memory://datq")  # typo'd prefix

"""serve/ladder.py — traffic-learned bucket ladders: the single strict
ladder validation (ServeConfig's typed refusal), the exact padded-work
DP (deterministic, budget-respecting, top rung pinned), the SLO-gated
re-fit policy, and the zero-drop bit-identical mid-burst rollout
through the hot-swap path."""

import threading

import numpy as np
import pytest

from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import get_model
from mmlspark_tpu.serve import (
    LadderAdvisor, ModelLoadError, ModelServer, ServeConfig,
    expected_padded_rows, fit_ladder, validate_ladder,
)


def _jm():
    bundle = get_model("ConvNet_CIFAR10", widths=(4, 8), dense_width=16)
    return JaxModel(model=bundle, input_col="image", output_col="scores")


# ---- validation (the ONE ladder gate) ----


def test_validate_ladder_accepts_and_normalizes():
    assert validate_ladder([1, 8, 32]) == (1, 8, 32)
    assert validate_ladder((7,)) == (7,)
    assert validate_ladder([np.int64(2), np.int64(4)]) == (2, 4)


@pytest.mark.parametrize("bad,needle", [
    ((), "empty"),
    ((0, 8), "not a positive row count"),
    ((-1,), "not a positive row count"),
    ((1, 8, 8), "duplicate rung 8"),
    ((8, 1), "strictly ascending"),
    (("x", 2), "not ints"),
])
def test_validate_ladder_refuses(bad, needle):
    with pytest.raises(ValueError, match=needle):
        validate_ladder(bad)


def test_serveconfig_misordered_ladder_is_typed_refusal():
    """A misordered/duplicate ladder used to be silently re-sorted; it
    is now a ModelLoadError at config time, before any model loads."""
    for bad in ((8, 1), (1, 1, 8), (0, 4), ()):
        with pytest.raises(ModelLoadError):
            ServeConfig(buckets=bad)
    assert ServeConfig(buckets=(1, 4, 16)).buckets == (1, 4, 16)


def test_serve_cli_rejects_bad_ladder(capsys):
    """tools/serve.py --buckets 8,1 exits 2 with the ladder diagnostic
    before touching the model path."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "mmlspark_tools_serve",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "serve.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["/nonexistent/model", "--buckets", "8,1"])
    assert rc == 2
    assert "ascending" in capsys.readouterr().err


def test_add_model_bad_ladder_override_names_the_model():
    server = ModelServer(ServeConfig(buckets=(1, 4), deadline_ms=None))
    try:
        with pytest.raises(ModelLoadError, match="'m'"):
            server.add_model("m", _jm(), buckets=(4, 2))
    finally:
        server.close()


# ---- cost + fit ----


def test_expected_padded_rows():
    assert expected_padded_rows({3: 2, 10: 1}, (4, 16)) == 2 * 4 + 16
    assert expected_padded_rows([1, 1, 4], (4,)) == 12
    with pytest.raises(ValueError, match="exceeds top rung"):
        expected_padded_rows({32: 1}, (4, 16))


def test_fit_ladder_deterministic_budget_and_top_rung(rng):
    sizes = rng.integers(1, 129, size=2000).tolist()
    a = fit_ladder(sizes, budget=4, max_bucket=128)
    b = fit_ladder(list(sizes), budget=4, max_bucket=128)
    assert a == b  # pure function of the histogram
    assert 1 <= len(a) <= 4
    assert a[-1] == 128  # admission contract pinned
    assert list(a) == sorted(set(a))
    # the fit never loses to the default ladder it replaces
    assert expected_padded_rows(sizes, a) \
        <= expected_padded_rows(sizes, (1, 8, 32, 128))


def test_fit_ladder_degenerate_traffic():
    assert fit_ladder([], budget=4, max_bucket=128) == (128,)
    assert fit_ladder({}, budget=2, max_bucket=16) == (16,)
    # single observed size: one rung there, plus the pinned top
    assert fit_ladder({24: 100}, budget=4, max_bucket=128) == (24, 128)
    assert fit_ladder({24: 100}, budget=1, max_bucket=128) == (128,)
    # traffic at the max bucket needs exactly one rung
    assert fit_ladder({128: 50}, budget=4, max_bucket=128) == (128,)
    # sizes the server would never admit are ignored, not fitted
    assert fit_ladder({500: 99, 4: 1}, budget=2, max_bucket=8) == (4, 8)
    with pytest.raises(ValueError, match="budget"):
        fit_ladder({4: 1}, budget=0, max_bucket=8)


def test_fit_ladder_heavy_tail_cuts_padded_work():
    hist = {1: 500, 2: 300, 24: 1000, 100: 5}
    fitted = fit_ladder(hist, budget=4, max_bucket=128)
    assert fitted == (1, 2, 24, 128)
    cur = expected_padded_rows(hist, (1, 8, 32, 128))
    new = expected_padded_rows(hist, fitted)
    assert new < cur


# ---- the re-fit policy ----


def test_advisor_gates():
    adv = LadderAdvisor(min_requests=100, min_improvement=0.05)
    hist = {24: 1000}
    cur = (1, 8, 32, 128)
    # burning error budget: never reshape the fleet
    assert adv.propose(hist, cur, slo_clean=False) is None
    # thin window: not enough evidence
    assert adv.propose({24: 10}, cur) is None
    # real traffic, real win
    assert adv.propose(hist, cur) == (24, 128)
    # already optimal: no churn
    assert adv.propose(hist, (24, 128)) is None
    # marginal win under the improvement floor: no churn
    strict = LadderAdvisor(min_requests=1, min_improvement=0.9)
    assert strict.propose(hist, cur) is None


# ---- rollout through the hot-swap path ----


def test_apply_ladder_refuses_shrinking_the_top_rung(rng):
    img = rng.integers(0, 255, (32 * 32 * 3,)).astype(np.uint8)
    server = ModelServer(ServeConfig(buckets=(1, 4), deadline_ms=None))
    try:
        server.add_model("m", _jm(),
                         example=DataTable({"image": [img]}))
        with pytest.raises(ValueError, match="top rung"):
            server.apply_ladder("m", (1, 2))
    finally:
        server.close()


def test_mid_burst_ladder_flip_drops_nothing_bit_identical(rng):
    """The acceptance gate: a ladder rollout mid-burst answers every
    in-flight and following request, every answer bit-identical to the
    offline transform, and the flip is journaled."""
    jm = _jm()
    imgs = [rng.integers(0, 255, (2, 32 * 32 * 3)).astype(np.uint8)
            for _ in range(24)]
    tables = [DataTable({"image": list(a)}) for a in imgs]
    offline = [np.stack(list(jm.transform(t)["scores"])) for t in tables]

    server = ModelServer(ServeConfig(buckets=(1, 4), deadline_ms=None,
                                     max_queue=64))
    try:
        server.add_model("m", jm,
                         example=DataTable({"image": [imgs[0][0]]}))
        results: list = [None] * len(tables)
        errors: list = []

        def worker(i):
            try:
                results[i] = server.submit(
                    "m", tables[i]).result(timeout=300)
            except BaseException as e:  # noqa: BLE001 — asserted below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(tables))]
        for t in threads[:12]:
            t.start()
        server.apply_ladder("m", (2, 4))  # flip mid-burst
        for t in threads[12:]:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert server._entry("m").batcher.config.buckets == (2, 4)
        ladder_decisions = server.lifecycle_decisions("ladder")
    finally:
        server.close()

    for i, out in enumerate(results):  # zero drops, zero wrong answers
        got = np.stack(list(out["scores"]))
        np.testing.assert_array_equal(got, offline[i])
    assert ladder_decisions and ladder_decisions[-1]["to_buckets"] \
        == [2, 4]


def test_ladder_tick_learns_from_traffic_and_journals(rng):
    """ladder_tick: the observed request-size histogram (6-row
    requests on a 1/8/32 ladder) re-fits to (6, 32) through the
    hot-swap path on an SLO-clean window; an unclean or thin window
    changes nothing."""
    img = rng.integers(0, 255, (32 * 32 * 3,)).astype(np.uint8)
    server = ModelServer(ServeConfig(buckets=(1, 8, 32),
                                     deadline_ms=None))
    try:
        server.add_model("m", _jm(),
                         example=DataTable({"image": [img]}))
        adv = LadderAdvisor(min_requests=32)
        # thin window: no decision
        assert server.ladder_tick("m", advisor=adv) is None
        stats = server.stats("m")
        for _ in range(64):
            stats.record_admitted(6)
        decision = server.ladder_tick("m")  # advisor persists on entry
        assert decision == {"action": "ladder", "model": "m",
                            "from_buckets": [1, 8, 32],
                            "to_buckets": [6, 32]}
        assert server._entry("m").batcher.config.buckets == (6, 32)
        assert server.lifecycle_decisions("ladder")
        # the flipped entry serves
        out = server.submit(
            "m", DataTable({"image": [img]})).result(timeout=300)
        assert len(out) == 1 and "scores" in out
    finally:
        server.close()

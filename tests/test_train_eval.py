"""Tests for the classical train/eval layer (SURVEY §2.4 parity).

Mirrors the reference's VerifyTrainClassifier benchmark-matrix approach
(train-classifier/src/test/scala/benchmarkMetrics.csv): each learner must
reach a golden minimum accuracy on deterministic synthetic datasets; plus
VerifyComputeModelStatistics / VerifyComputePerInstanceStatistics /
VerifyFindBestModel behaviors and persistence round-trips.
"""

import numpy as np
import pytest

from mmlspark_tpu.core.schema import SchemaConstants
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml import (
    ComputeModelStatistics, ComputePerInstanceStatistics,
    DecisionTreeClassifier, FindBestModel, GBTRegressor, LinearRegression,
    LogisticRegression, MLPClassifier, MLPRegressor, NaiveBayes,
    RandomForestClassifier, TrainClassifier, TrainRegressor,
)


def blobs(n=200, seed=0, k=2):
    """Deterministic well-separated gaussian blobs + a categorical column."""
    r = np.random.default_rng(seed)
    y = r.integers(0, k, size=n)
    x1 = r.normal(size=n) + 3.0 * y
    x2 = r.normal(size=n) - 2.0 * y
    cat = [["low", "mid", "high"][min(int(v // 1.5), 2)] for v in x1]
    return DataTable({"x1": x1, "x2": x2, "band": cat, "label": y})


def linear_data(n=300, seed=1):
    r = np.random.default_rng(seed)
    x1 = r.normal(size=n)
    x2 = r.normal(size=n)
    y = 3.0 * x1 - 2.0 * x2 + 0.5 + r.normal(scale=0.05, size=n)
    return DataTable({"x1": x1, "x2": x2, "target": y})


def accuracy_of(model, table):
    scored = model.transform(table)
    stats = ComputeModelStatistics().transform(scored)
    return stats.to_rows()[0]["accuracy"]


# golden minimum accuracies (benchmarkMetrics.csv analog)
CLASSIFIER_BENCHMARKS = [
    (LogisticRegression, {}, 0.95),
    (NaiveBayes, {}, 0.80),
    (MLPClassifier, {"layers": [16], "epochs": 300,
                     "learning_rate": 0.01}, 0.95),
    (DecisionTreeClassifier, {}, 0.95),
    (RandomForestClassifier, {}, 0.95),
]


class TestTrainClassifier:
    @pytest.mark.parametrize("cls,kw,min_acc", CLASSIFIER_BENCHMARKS,
                             ids=lambda v: getattr(v, "__name__", str(v)))
    def test_benchmark_accuracy(self, cls, kw, min_acc):
        t = blobs(300)
        model = TrainClassifier(model=cls(**kw), label_col="label").fit(t)
        acc = accuracy_of(model, t)
        assert acc >= min_acc, f"{cls.__name__}: accuracy {acc} < {min_acc}"

    def test_score_metadata_stamped(self):
        t = blobs(100)
        model = TrainClassifier(label_col="label").fit(t)
        scored = model.transform(t)
        for col in (SchemaConstants.SCORES_COLUMN,
                    SchemaConstants.SCORED_LABELS_COLUMN,
                    SchemaConstants.SCORED_PROBABILITIES_COLUMN):
            assert col in scored.columns
            meta = scored.column_meta(col)
            assert meta[SchemaConstants.K_SCORE_VALUE_KIND] == \
                SchemaConstants.CLASSIFICATION_KIND
        # scored labels live in original label space
        assert set(np.unique(scored[SchemaConstants.SCORED_LABELS_COLUMN])) \
            <= {0, 1}

    def test_string_labels(self):
        t = blobs(150)
        t = t.with_column("label", ["yes" if v else "no" for v in t["label"]])
        model = TrainClassifier(label_col="label").fit(t)
        scored = model.transform(t)
        assert set(scored[SchemaConstants.SCORED_LABELS_COLUMN]) <= \
            {"yes", "no"}
        stats = ComputeModelStatistics().transform(scored)
        assert stats.to_rows()[0]["accuracy"] >= 0.9

    def test_multiclass(self):
        t = blobs(400, k=3)
        model = TrainClassifier(
            model=LogisticRegression(epochs=150), label_col="label").fit(t)
        scored = model.transform(t)
        stats = ComputeModelStatistics().transform(scored).to_rows()[0]
        assert stats["accuracy"] >= 0.9
        assert "macro_precision" in stats and "micro_recall" in stats

    def test_missing_labels_dropped(self):
        t = blobs(100)
        labels = t["label"].astype(np.float64)
        labels[:10] = np.nan
        t = t.with_column("label", labels)
        model = TrainClassifier(label_col="label").fit(t)
        assert accuracy_of(model, t.take(np.arange(10, 100))) > 0.8

    def test_roundtrip(self, tmp_path):
        t = blobs(120)
        model = TrainClassifier(label_col="label").fit(t)
        p = str(tmp_path / "clf")
        model.save(p)
        loaded = PipelineStage.load(p)
        a = model.transform(t)[SchemaConstants.SCORED_LABELS_COLUMN]
        b = loaded.transform(t)[SchemaConstants.SCORED_LABELS_COLUMN]
        np.testing.assert_array_equal(a, b)


class TestTrainRegressor:
    @pytest.mark.parametrize("cls,kw,max_rmse", [
        (LinearRegression, {}, 0.1),
        (MLPRegressor, {"layers": [32], "epochs": 400,
                        "learning_rate": 0.01}, 1.0),
        (GBTRegressor, {}, 1.0),
    ], ids=lambda v: getattr(v, "__name__", str(v)))
    def test_benchmark_rmse(self, cls, kw, max_rmse):
        t = linear_data()
        model = TrainRegressor(model=cls(**kw), label_col="target").fit(t)
        scored = model.transform(t)
        stats = ComputeModelStatistics().transform(scored).to_rows()[0]
        assert stats["evaluation_type"] == "Regression"
        assert stats["root_mean_squared_error"] <= max_rmse
        assert stats["R^2"] >= 0.9

    def test_wrong_learner_kind_raises(self):
        with pytest.raises(ValueError, match="not a regressor"):
            TrainRegressor(model=LogisticRegression(),
                           label_col="target").fit(linear_data(20))
        with pytest.raises(ValueError, match="not a classifier"):
            TrainClassifier(model=LinearRegression(),
                            label_col="label").fit(blobs(20))

    def test_roundtrip(self, tmp_path):
        t = linear_data(100)
        model = TrainRegressor(label_col="target").fit(t)
        p = str(tmp_path / "reg")
        model.save(p)
        loaded = PipelineStage.load(p)
        np.testing.assert_allclose(
            loaded.transform(t)[SchemaConstants.SCORES_COLUMN],
            model.transform(t)[SchemaConstants.SCORES_COLUMN])


class TestComputeModelStatistics:
    def test_explicit_columns_no_metadata(self):
        t = DataTable({"y": np.array([1.0, 2.0, 3.0]),
                       "pred": np.array([1.1, 1.9, 3.2])})
        stats = ComputeModelStatistics(
            evaluation_metric="regression", label_col="y",
            scores_col="pred").transform(t).to_rows()[0]
        assert stats["mean_squared_error"] == pytest.approx(
            np.mean([0.01, 0.01, 0.04]), rel=1e-6)

    def test_binary_metrics_exact(self):
        # hand-computable confusion: y=[0,0,1,1], pred=[0,1,1,1]
        t = DataTable({"y": np.array([0, 0, 1, 1]),
                       "p": np.array([0, 1, 1, 1])})
        ev = ComputeModelStatistics(
            evaluation_metric="classification", label_col="y",
            scored_labels_col="p")
        stats = ev.transform(t).to_rows()[0]
        assert stats["accuracy"] == pytest.approx(0.75)
        assert stats["precision"] == pytest.approx(2 / 3)
        assert stats["recall"] == pytest.approx(1.0)
        np.testing.assert_array_equal(ev.confusion_matrix_,
                                      [[1, 1], [0, 2]])

    def test_auc_perfect_separation(self):
        t = blobs(200)
        model = TrainClassifier(label_col="label").fit(t)
        ev = ComputeModelStatistics()
        stats = ev.transform(model.transform(t)).to_rows()[0]
        assert stats["AUC"] >= 0.99
        assert ev.roc_.shape[1] == 2

    def test_unseen_label_values_excluded_not_wrapped(self):
        from mmlspark_tpu.core.schema import set_categorical_levels
        # "maybe" is outside the model's stamped levels → code -1; it must
        # be excluded, not wrapped into the positive class via negative index
        t = DataTable({"y": ["no", "yes", "maybe", "no"],
                       "p": ["no", "yes", "yes", "yes"]})
        t = set_categorical_levels(t, "p", ["no", "yes"])
        ev = ComputeModelStatistics(
            evaluation_metric="classification", label_col="y",
            scored_labels_col="p")
        stats = ev.transform(t).to_rows()[0]
        # scorable rows: (no,no) (yes,yes) (no,yes) → accuracy 2/3
        assert stats["accuracy"] == pytest.approx(2 / 3)
        assert ev.confusion_matrix_.sum() == 3

    def test_no_metadata_raises(self):
        t = DataTable({"a": np.array([1.0])})
        with pytest.raises(ValueError, match="no score metadata"):
            ComputeModelStatistics().transform(t)


class TestComputePerInstanceStatistics:
    def test_regression_losses(self):
        t = DataTable({"y": np.array([1.0, 2.0]),
                       "pred": np.array([1.5, 1.0])})
        out = ComputePerInstanceStatistics(
            label_col="y", scores_col="pred").transform(t)
        np.testing.assert_allclose(out["L1_loss"], [0.5, 1.0])
        np.testing.assert_allclose(out["L2_loss"], [0.25, 1.0])

    def test_missing_columns_actionable_error(self):
        # no metadata + no params → actionable message, not KeyError(None)
        t = DataTable({"a": np.array([1.0, 2.0])})
        with pytest.raises(ValueError, match="label and scores"):
            ComputePerInstanceStatistics().transform(t)

    def test_classification_log_loss(self):
        t = blobs(100)
        model = TrainClassifier(label_col="label").fit(t)
        out = ComputePerInstanceStatistics().transform(model.transform(t))
        assert "log_loss" in out.columns
        assert np.all(out["log_loss"] >= 0)
        assert np.mean(out["log_loss"]) < 0.5


class TestFindBestModel:
    def test_selects_better_model(self):
        t = blobs(250)
        good = TrainClassifier(model=LogisticRegression(epochs=120),
                               label_col="label").fit(t)
        bad = TrainClassifier(model=LogisticRegression(epochs=0),
                              label_col="label").fit(t)
        best = FindBestModel(models=[bad, good],
                             evaluation_metric="accuracy").fit(t)
        assert best.best_model.uid == good.uid
        assert best.best_metric >= 0.9
        assert len(best.all_model_metrics_) == 2
        # BestModel is itself a transformer
        scored = best.transform(t)
        assert SchemaConstants.SCORED_LABELS_COLUMN in scored.columns

    def test_regression_metric_lower_is_better(self):
        t = linear_data(150)
        good = TrainRegressor(label_col="target").fit(t)
        bad = TrainRegressor(model=MLPRegressor(epochs=0),
                             label_col="target").fit(t)
        best = FindBestModel(models=[bad, good],
                             evaluation_metric="rmse").fit(t)
        assert best.best_model.uid == good.uid

"""Worker script for the multi-host SCORING e2e test.

The reference's *primary* parallelism is data-parallel inference across
Spark executors (reference: cntk-model/src/main/scala/CNTKModel.scala:
248-256). The TPU-native topology: each host process joins the
``jax.distributed`` world, reads ONLY its own shard of the input, and
scores it on its LOCAL device mesh (``JaxModel._mesh`` — scoring needs no
cross-host collectives, exactly like executor-side inference). This
worker scores its shard twice — through ``JaxModel.transform`` and
through the Arrow offload bridge with overlap workers — and writes both
score matrices for the launcher-driven test to merge and compare against
a single-host run.
"""

import multihost_env  # noqa: F401  (env setup BEFORE jax import)

import jax

multihost_env.pin_platform()

import numpy as np


N_ROWS = 96


def global_table(lo: int, hi: int):
    from mmlspark_tpu.core.schema import make_image
    from mmlspark_tpu.data.table import DataTable

    r = np.random.default_rng(7)
    imgs = r.integers(0, 255, size=(N_ROWS, 32, 32, 3)).astype(np.uint8)
    rows = [make_image(f"img{i}", imgs[i]) for i in range(lo, hi)]
    return DataTable({"image": rows})


def scoring_model():
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model

    # deterministic init (seed 0): every process and the test build the
    # SAME params, so outputs are directly comparable
    bundle = get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)
    return JaxModel(model=bundle, input_col="image", output_col="scores",
                    minibatch_size=16)


def main() -> None:
    from mmlspark_tpu.utils.env import distributed_init
    distributed_init()
    pid = jax.process_index()
    nproc = jax.process_count()

    lo, hi = pid * N_ROWS // nproc, (pid + 1) * N_ROWS // nproc
    table = global_table(lo, hi)
    jm = scoring_model()

    # path 1: direct transform on the local-device DP mesh
    scores = jm.transform(table).column_matrix("scores")

    # path 2: the Arrow offload bridge (wire format + overlap workers)
    import pyarrow as pa

    from mmlspark_tpu.bridge import ArrowBatchBridge
    from mmlspark_tpu.bridge.offload import stream_table
    from mmlspark_tpu.data.table import DataTable

    bridge = ArrowBatchBridge(jm, workers=2)
    rbs = list(bridge.process(stream_table(table, 16)))
    merged = DataTable.from_arrow(pa.Table.from_batches(rbs))
    bridge_scores = merged.column_matrix("scores")

    multihost_env.write_result(pid, {
        "pid": pid, "nproc": nproc, "lo": lo, "hi": hi,
        "n_local_devices": jax.local_device_count(),
        "scores": np.asarray(scores, np.float64).tolist(),
        "bridge_scores": np.asarray(bridge_scores, np.float64).tolist(),
    }, prefix="score_out")


if __name__ == "__main__":
    main()

"""Proof of the Spark offload bridge at the engine boundary.

Two tiers:

1. **Executor-contract tests** (always run): drive ``make_map_in_arrow_fn``
   exactly the way Spark's Python worker does — one call per partition with
   an iterator of Arrow RecordBatches, consuming an iterator of
   RecordBatches that must keep a stable schema, preserve row order, and
   propagate mid-stream failures (reference executor-side scoring loop:
   cntk-model/src/main/scala/CNTKModel.scala:248-256).
2. **Real PySpark test** (skipped when pyspark is not installed): a local
   SparkSession runs ``df.mapInArrow`` end-to-end via
   ``bridge.spark.spark_transform`` and must match ``JaxModel.transform``.
"""

import numpy as np
import pyarrow as pa
import pytest

from mmlspark_tpu.bridge.offload import make_map_in_arrow_fn, stream_table
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import get_model


def make_model(minibatch=16):
    bundle = get_model("MLP", input_dim=6, num_outputs=3)
    return JaxModel(model=bundle, input_col="vec", output_col="scores",
                    minibatch_size=minibatch)


def vec_table(n=50, seed=0):
    r = np.random.default_rng(seed)
    return DataTable({
        "id": np.arange(n, dtype=np.int64),
        "vec": [r.normal(size=6).astype(np.float32) for _ in range(n)],
    })


class TestExecutorContract:
    """The exact mapInArrow worker protocol, engine-free."""

    def test_partition_roundtrip_matches_direct_transform(self):
        jm = make_model()
        t = vec_table(50)
        fn = make_map_in_arrow_fn(jm)
        # Spark calls fn once per partition with a RecordBatch iterator
        out_batches = list(fn(stream_table(t, rows_per_batch=7)))
        assert all(isinstance(b, pa.RecordBatch) for b in out_batches)
        merged = DataTable.from_arrow(pa.Table.from_batches(out_batches))
        direct = jm.transform(t)
        # row order and ids preserved
        np.testing.assert_array_equal(merged["id"], direct["id"])
        np.testing.assert_allclose(
            np.stack(list(merged["scores"])),
            np.stack(list(direct["scores"])), rtol=1e-5, atol=1e-6)

    def test_output_schema_is_stable_across_batches(self):
        # Spark hard-fails if two output batches disagree on schema
        jm = make_model(minibatch=8)
        fn = make_map_in_arrow_fn(jm)
        out = list(fn(stream_table(vec_table(40), rows_per_batch=9)))
        schemas = {b.schema for b in out}
        assert len(schemas) == 1, [str(s) for s in schemas]

    def test_one_call_per_partition_isolation(self):
        # separate partitions → separate fn calls; outputs must not bleed
        jm = make_model()
        fn = make_map_in_arrow_fn(jm)
        t = vec_table(30, seed=1)
        parts = [t.take(np.arange(0, 10)), t.take(np.arange(10, 30))]
        outs = []
        for p in parts:
            outs.append(DataTable.from_arrow(pa.Table.from_batches(
                list(fn(stream_table(p, 4))))))
        assert [len(o) for o in outs] == [10, 20]
        np.testing.assert_array_equal(
            np.concatenate([o["id"] for o in outs]), t["id"])

    def test_empty_partition_yields_no_batches(self):
        jm = make_model()
        fn = make_map_in_arrow_fn(jm)
        assert list(fn(iter([]))) == []

    def test_midstream_failure_propagates_not_truncates(self):
        jm = make_model()
        fn = make_map_in_arrow_fn(jm)

        def failing_source():
            yield from stream_table(vec_table(16), 8)
            raise RuntimeError("executor input died mid-partition")

        with pytest.raises(RuntimeError, match="died mid-partition"):
            list(fn(failing_source()))

    def test_scoring_failure_propagates(self):
        jm = make_model()
        fn = make_map_in_arrow_fn(jm)
        bad = DataTable({"id": np.arange(4),
                         "vec": [np.zeros(5, np.float32)] * 4})  # wrong dim
        with pytest.raises(ValueError, match="model expects"):
            list(fn(stream_table(bad, 2)))


class TestStubEngine:
    """Drive the REAL ``bridge/spark.py`` wrapper code through a stand-in
    engine (tests/spark_stub.py): ``limit``/``toPandas`` for the schema
    probe and ``mapInArrow`` with Spark's exact per-partition
    RecordBatch-iterator convention. This is the CI coverage for the
    one-call wrapper; TestRealPySpark stays the engine-level proof."""

    def test_spark_transform_matches_direct_through_stub(self, monkeypatch):
        import spark_stub
        spark_stub.install(monkeypatch)
        from mmlspark_tpu.bridge.spark import spark_transform
        jm = make_model(minibatch=8)
        t = vec_table(48, seed=3)
        df = spark_stub.StubDataFrame.from_pandas(t.to_pandas(),
                                                  num_partitions=3)
        scored = spark_transform(df, jm)
        merged = DataTable.from_arrow(scored.to_arrow())
        direct = jm.transform(t)
        np.testing.assert_array_equal(merged["id"], direct["id"])
        np.testing.assert_allclose(
            np.stack([np.asarray(v) for v in merged["scores"]]),
            np.stack(list(direct["scores"])), rtol=1e-5, atol=1e-6)
        # the wrapper must have inferred the exact scored-output schema
        # from the driver-side probe and passed it to mapInArrow
        assert df.applied_schema.arrow_schema == direct.to_arrow().schema

    def test_empty_dataframe_schema_probe_raises(self, monkeypatch):
        import pandas as pd
        import spark_stub
        spark_stub.install(monkeypatch)
        from mmlspark_tpu.bridge.spark import output_spark_schema
        jm = make_model()
        empty = spark_stub.StubDataFrame.from_pandas(
            pd.DataFrame({"id": np.array([], np.int64), "vec": []}))
        with pytest.raises(ValueError, match="empty DataFrame"):
            output_spark_schema(empty, jm)

    def test_missing_pyspark_yields_clear_import_error(self):
        # without the stub (or real pyspark) installed the wrapper must
        # fail with the install hint, not an opaque ModuleNotFoundError
        try:
            import pyspark  # noqa: F401
            pytest.skip("real pyspark present")
        except ImportError:
            pass
        from mmlspark_tpu.bridge.spark import spark_transform
        with pytest.raises(ImportError, match="mmlspark-tpu\\[spark\\]"):
            spark_transform(object(), make_model())

    def test_scoring_failure_propagates_through_stub_job(self, monkeypatch):
        import spark_stub
        spark_stub.install(monkeypatch)
        from mmlspark_tpu.bridge.spark import spark_transform
        jm = make_model()
        t = vec_table(12)
        bad = t.with_column("vec", [np.zeros(5, np.float32)] * 12)
        df = spark_stub.StubDataFrame.from_pandas(bad.to_pandas())
        with pytest.raises(ValueError, match="model expects"):
            spark_transform(df, jm)


class TestRealPySpark:
    """End-to-end through a local SparkSession (runs where pyspark exists)."""

    @pytest.fixture(scope="class")
    def spark(self):
        pyspark = pytest.importorskip("pyspark")
        from pyspark.sql import SparkSession
        spark = (SparkSession.builder.master("local[2]")
                 .appName("mmlspark_tpu_bridge_test")
                 .config("spark.sql.execution.arrow.pyspark.enabled", "true")
                 .getOrCreate())
        yield spark
        spark.stop()

    def test_spark_transform_matches_direct(self, spark):
        from mmlspark_tpu.bridge.spark import spark_transform
        jm = make_model()
        t = vec_table(64)
        df = spark.createDataFrame(t.to_pandas())
        scored = spark_transform(df, jm).toPandas().sort_values("id")
        direct = jm.transform(t)
        np.testing.assert_allclose(
            np.stack([np.asarray(v) for v in scored["scores"]]),
            np.stack(list(direct["scores"])), rtol=1e-4, atol=1e-5)

    def test_spark_failure_propagates_through_job(self, spark):
        from mmlspark_tpu.bridge.spark import spark_transform
        jm = make_model()
        t = vec_table(8)
        bad = t.with_column("vec", [np.zeros(5, np.float32)] * 8)
        df = spark.createDataFrame(bad.to_pandas())
        with pytest.raises(Exception) as ei:
            spark_transform(df, jm)
        assert "model expects" in str(ei.value) or "Py4J" in \
            type(ei.value).__name__


class TestPinnedArrowContract:
    """Version-pinned Arrow-convention contract for ``mapInArrow``.

    pyspark cannot be installed in this environment (no egress; see the
    README's "Spark integration status" section), so the exact conventions
    Spark 3.5's ``DataFrame.mapInArrow`` imposes on the UDF are pinned
    HERE, against the pyspark 3.5 source of truth
    (python/pyspark/sql/pandas/{map_ops,types}.py):

    1. the UDF receives ``Iterator[pyarrow.RecordBatch]`` and must yield
       ``pyarrow.RecordBatch`` objects,
    2. every yielded batch's schema must EQUAL the schema declared to
       ``mapInArrow`` (Spark validates per batch; a drifting schema is a
       job failure),
    3. only Spark-convertible Arrow types may appear (from_arrow_type,
       types.py): ints/floats/bool/string/binary/date/timestamp/decimal/
       list/struct — notably NO unsigned ints wider than the signed range
       mapping, no null-typed columns,
    4. Python-worker calls are per-partition and independent (no shared
       mutable state between partitions).

    If a future pyspark changes these conventions, this is the one test to
    update — and the stub engine (tests/spark_stub.py) mirrors the same
    rules.
    """

    # Arrow type predicates Spark 3.5 from_arrow_type accepts (pinned)
    _SPARK35_OK = (
        pa.types.is_boolean, pa.types.is_int8, pa.types.is_int16,
        pa.types.is_int32, pa.types.is_int64, pa.types.is_uint8,
        pa.types.is_float32, pa.types.is_float64, pa.types.is_string,
        pa.types.is_binary, pa.types.is_date32, pa.types.is_timestamp,
        pa.types.is_decimal, pa.types.is_list, pa.types.is_struct,
    )

    def _assert_spark_convertible(self, typ):
        if pa.types.is_list(typ):
            return self._assert_spark_convertible(typ.value_type)
        if pa.types.is_struct(typ):
            for f in typ:
                self._assert_spark_convertible(f.type)
            return
        assert any(ok(typ) for ok in self._SPARK35_OK), \
            f"Arrow type {typ} is not Spark-3.5 convertible"

    def test_yielded_batches_keep_declared_schema_and_types(self):
        """Contract points 1-3 on the real scoring path, with an image
        table (the struct wire format) AND a vector table."""
        jm = make_model()
        t = vec_table(37)
        fn = make_map_in_arrow_fn(jm)
        # the schema a caller would declare (spark_transform's probe path)
        probe_schema = jm.transform(t.take(np.arange(4))).to_arrow().schema
        outs = list(fn(stream_table(t, 10)))
        assert outs and all(isinstance(rb, pa.RecordBatch) for rb in outs)
        for rb in outs:
            assert rb.schema.equals(probe_schema), \
                f"batch schema drifted:\n{rb.schema}\nvs\n{probe_schema}"
            for field in rb.schema:
                self._assert_spark_convertible(field.type)

    def test_image_struct_schema_is_spark_convertible(self):
        from mmlspark_tpu.core.schema import make_image

        r = np.random.default_rng(0)
        rows = [make_image(f"i{i}", r.integers(0, 255, (8, 8, 3)))
                for i in range(6)]
        t = DataTable({"image": rows})
        arrow = t.to_arrow()
        for field in arrow.schema:
            self._assert_spark_convertible(field.type)
        # the ImageSchema field set is part of the wire contract
        img = arrow.schema.field("image").type
        assert {f.name for f in img} == {
            "path", "height", "width", "channels", "mode", "data"}

    def test_partitions_share_no_state(self):
        """Contract point 4: scoring partition B must not disturb an
        in-flight iterator over partition A's results."""
        jm = make_model()
        fn = make_map_in_arrow_fn(jm)
        t = vec_table(24)
        it_a = fn(stream_table(t.take(np.arange(12)), 6))
        first_a = next(it_a)
        outs_b = list(fn(stream_table(t.take(np.arange(12, 24)), 6)))
        rest_a = list(it_a)
        got = pa.Table.from_batches([first_a] + rest_a + outs_b)
        ref = jm.transform(t).to_arrow()
        np.testing.assert_allclose(
            np.stack(got.column("scores").to_pylist()),
            np.stack(ref.column("scores").to_pylist()), rtol=1e-6)

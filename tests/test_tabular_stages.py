"""Tests for the tabular data-prep stages (SURVEY §2.3 parity).

Mirrors the reference suites VerifyValueIndexer / VerifyCleanMissingData /
VerifyDataConversion / VerifyPartitionSample / VerifySummarizeData /
EnsembleByKeySuite plus round-trip persistence per RoundTripTestBase.
"""

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Pipeline
from mmlspark_tpu.core.schema import SchemaConstants, get_categorical_levels
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.stages import (
    Cacher, CheckpointData, ClassBalancer, CleanMissingData, DataConversion,
    DropColumns, EnsembleByKey, IndexToValue, MultiColumnAdapter,
    PartitionSample, RenameColumns, Repartition, SelectColumns, SummarizeData,
    Timer, ValueIndexer,
)

from conftest import make_tabular


def roundtrip(stage, tmp_path):
    p = str(tmp_path / f"rt_{type(stage).__name__}")
    stage.save(p)
    return PipelineStage.load(p)


# ---- ValueIndexer / IndexToValue ----

class TestValueIndexer:
    def test_string_levels_sorted(self):
        t = DataTable({"c": ["b", "a", "c", "a", None, "b"]})
        model = ValueIndexer(input_col="c", output_col="idx").fit(t)
        assert model.levels == [None, "a", "b", "c"]
        out = model.transform(t)
        np.testing.assert_array_equal(out["idx"], [2, 1, 3, 1, 0, 2])
        assert get_categorical_levels(out, "idx") == [None, "a", "b", "c"]

    def test_int_levels(self):
        t = DataTable({"c": np.array([5, 3, 5, 1])})
        model = ValueIndexer(input_col="c", output_col="idx").fit(t)
        assert model.levels == [1, 3, 5]
        np.testing.assert_array_equal(
            model.transform(t)["idx"], [2, 1, 2, 0])

    def test_unseen_maps_to_minus_one(self):
        t = DataTable({"c": ["a", "b"]})
        model = ValueIndexer(input_col="c", output_col="idx").fit(t)
        out = model.transform(DataTable({"c": ["b", "zz"]}))
        np.testing.assert_array_equal(out["idx"], [1, -1])

    def test_inverse(self):
        t = DataTable({"c": ["x", "y", "x", "z"]})
        model = ValueIndexer(input_col="c", output_col="idx").fit(t)
        out = model.transform(t)
        back = IndexToValue(input_col="idx", output_col="orig").transform(out)
        assert list(back["orig"]) == ["x", "y", "x", "z"]

    def test_index_without_levels_raises(self):
        t = DataTable({"idx": np.array([0, 1])})
        with pytest.raises(ValueError, match="categorical levels"):
            IndexToValue(input_col="idx", output_col="o").transform(t)

    def test_roundtrip(self, tmp_path):
        t = DataTable({"c": ["b", "a"]})
        model = ValueIndexer(input_col="c", output_col="idx").fit(t)
        loaded = roundtrip(model, tmp_path)
        np.testing.assert_array_equal(
            loaded.transform(t)["idx"], model.transform(t)["idx"])

    def test_float32_nan_treated_as_null(self):
        t = DataTable({"c": np.array([2.0, np.nan, 1.0], dtype=np.float32)})
        model = ValueIndexer(input_col="c", output_col="idx").fit(t)
        assert model.levels == [None, 1.0, 2.0]
        np.testing.assert_array_equal(model.transform(t)["idx"], [2, 0, 1])


# ---- CleanMissingData ----

class TestCleanMissingData:
    def table(self):
        return DataTable({
            "a": np.array([1.0, np.nan, 3.0, np.nan]),
            "b": [10.0, 20.0, None, 40.0],
        })

    def test_mean(self):
        model = CleanMissingData(
            input_cols=["a", "b"], output_cols=["a", "b"]).fit(self.table())
        out = model.transform(self.table())
        np.testing.assert_allclose(out["a"], [1.0, 2.0, 3.0, 2.0])
        assert [float(v) for v in out["b"]] == [10.0, 20.0, pytest.approx(70 / 3), 40.0]

    def test_median(self):
        model = CleanMissingData(
            input_cols=["a"], output_cols=["a2"],
            cleaning_mode="Median").fit(self.table())
        out = model.transform(self.table())
        np.testing.assert_allclose(out["a2"], [1.0, 2.0, 3.0, 2.0])
        # original column untouched
        assert np.isnan(out["a"][1])

    def test_custom(self):
        model = CleanMissingData(
            input_cols=["a"], output_cols=["a"],
            cleaning_mode="Custom", custom_value=-1).fit(self.table())
        np.testing.assert_allclose(
            model.transform(self.table())["a"], [1.0, -1.0, 3.0, -1.0])

    def test_non_numeric_raises(self):
        t = DataTable({"s": ["x", None]})
        with pytest.raises(TypeError):
            CleanMissingData(input_cols=["s"], output_cols=["s"]).fit(t)

    def test_roundtrip(self, tmp_path):
        model = CleanMissingData(
            input_cols=["a"], output_cols=["a"]).fit(self.table())
        loaded = roundtrip(model, tmp_path)
        np.testing.assert_allclose(
            loaded.transform(self.table())["a"],
            model.transform(self.table())["a"])


# ---- DataConversion ----

class TestDataConversion:
    def test_numeric_targets(self):
        t = DataTable({"x": np.array([1.7, 2.2]), "y": np.array([1, 0])})
        out = DataConversion(cols=["x"], convert_to="integer").transform(t)
        assert out["x"].dtype == np.int32
        np.testing.assert_array_equal(out["x"], [1, 2])
        out = DataConversion(cols=["y"], convert_to="boolean").transform(t)
        assert out["y"].dtype == np.bool_

    def test_string_and_back(self):
        t = DataTable({"x": np.array([1.5, 2.5])})
        s = DataConversion(cols=["x"], convert_to="string").transform(t)
        assert list(s["x"]) == ["1.5", "2.5"]
        back = DataConversion(cols=["x"], convert_to="double").transform(s)
        np.testing.assert_allclose(back["x"], [1.5, 2.5])

    def test_date(self):
        t = DataTable({"d": ["2017-09-01 12:00:00", "2017-09-02 00:30:00"]})
        out = DataConversion(cols=["d"], convert_to="date").transform(t)
        assert out["d"][0].year == 2017 and out["d"][0].hour == 12
        nums = DataConversion(cols=["d"], convert_to="long").transform(out)
        assert nums["d"].dtype == np.int64

    def test_int_target_with_missing_raises(self):
        t = DataTable({"x": [1.0, None]})
        with pytest.raises(ValueError, match="missing"):
            DataConversion(cols=["x"], convert_to="integer").transform(t)

    def test_clear_categorical_strips_is_categorical(self):
        t = DataTable({"c": ["b", "a"]})
        cat = DataConversion(cols=["c"], convert_to="toCategorical").transform(t)
        clear = DataConversion(cols=["c"],
                               convert_to="clearCategorical").transform(cat)
        assert SchemaConstants.K_IS_CATEGORICAL not in clear.column_meta("c")

    def test_to_categorical_round(self):
        t = DataTable({"c": ["b", "a", "b"]})
        cat = DataConversion(cols=["c"], convert_to="toCategorical").transform(t)
        assert get_categorical_levels(cat, "c") == ["a", "b"]
        np.testing.assert_array_equal(cat["c"], [1, 0, 1])
        clear = DataConversion(cols=["c"],
                               convert_to="clearCategorical").transform(cat)
        assert list(clear["c"]) == ["b", "a", "b"]
        assert get_categorical_levels(clear, "c") is None


# ---- PartitionSample ----

class TestPartitionSample:
    def test_head(self):
        t = make_tabular(50)
        out = PartitionSample(mode="Head", count=7).transform(t)
        assert len(out) == 7

    def test_random_percent_seeded(self):
        t = make_tabular(200)
        a = PartitionSample(mode="RandomSample", percent=0.25,
                            seed=3).transform(t)
        b = PartitionSample(mode="RandomSample", percent=0.25,
                            seed=3).transform(t)
        assert len(a) == 50
        np.testing.assert_array_equal(a["num"], b["num"])

    def test_random_absolute(self):
        t = make_tabular(30)
        out = PartitionSample(mode="RandomSample", rs_mode="Absolute",
                              count=10, seed=1).transform(t)
        assert len(out) == 10

    def test_assign_to_partition(self):
        t = make_tabular(100)
        out = PartitionSample(mode="AssignToPartition", num_parts=4,
                              seed=0).transform(t)
        assert set(np.unique(out["Partition"])) <= {0, 1, 2, 3}
        assert len(out) == 100


# ---- utility stages ----

class TestUtilityStages:
    def test_select_drop_rename(self):
        t = make_tabular(10)
        assert SelectColumns(cols=["num", "label"]).transform(t).columns == \
            ["num", "label"]
        assert "cat" not in DropColumns(cols=["cat"]).transform(t).columns
        out = RenameColumns(mapping={"num": "n2"}).transform(t)
        assert "n2" in out.columns and "num" not in out.columns

    def test_repartition_and_cache(self):
        t = make_tabular(10)
        assert Repartition(n=4).transform(t).num_partitions == 4
        assert Repartition(n=4, disable=True).transform(t).num_partitions \
            != 4
        assert len(Cacher().transform(t)) == 10

    def test_cacher_memoizes_and_snapshots(self):
        """Cacher has real cache semantics (reference Cacher.scala:12-38):
        repeated transforms of the same table return the identical
        memoized snapshot, and later in-place mutation of the input does
        not leak through the cache."""
        t = make_tabular(10)
        c = Cacher()
        out1 = c.transform(t)
        out2 = c.transform(t)
        assert out1 is out2 and out1 is not t
        first_col = t.columns[0]
        before = np.copy(out1[first_col])
        t[first_col][:] = -999  # mutate the input AFTER caching
        np.testing.assert_array_equal(out1[first_col], before)
        # a different table is a cache miss
        t2 = make_tabular(10)
        assert c.transform(t2) is not out1
        # disable passes through untouched
        assert Cacher(disable=True).transform(t) is t

    def test_cacher_deep_copies_object_columns(self):
        """Object columns (image dicts, row vectors) hold references — the
        snapshot must deep-copy them so in-place row mutation can't leak
        through the cache."""
        row = np.arange(4, dtype=np.float32)
        t = DataTable({"vec": [row, row * 2]})
        c = Cacher()
        out = c.transform(t)
        before = np.copy(out["vec"][0])
        t["vec"][0][:] = -1  # mutate the cached input's row in place
        np.testing.assert_array_equal(out["vec"][0], before)

    def test_checkpoint_data(self, tmp_path):
        pytest.importorskip("pyarrow")
        t = DataTable({"x": np.arange(5).astype(np.float64),
                       "s": ["a", "b", "c", "d", "e"]})
        path = str(tmp_path / "ck.parquet")
        out = CheckpointData(path=path).transform(t)
        np.testing.assert_allclose(out["x"], t["x"])
        assert list(out["s"]) == list(t["s"])

    def test_class_balancer(self):
        t = DataTable({"y": np.array([0, 0, 0, 1])})
        model = ClassBalancer(input_col="y", output_col="w").fit(t)
        out = model.transform(t)
        np.testing.assert_allclose(out["w"], [1.0, 1.0, 1.0, 3.0])

    def test_class_balancer_unseen_value_message(self):
        t = DataTable({"y": np.array([0, 1])})
        model = ClassBalancer(input_col="y", output_col="w").fit(t)
        with pytest.raises(ValueError, match="not seen"):
            model.transform(DataTable({"y": np.array([2])}))

    def test_class_balancer_int_keys_roundtrip(self, tmp_path):
        t = DataTable({"y": np.array([0, 0, 1])})
        model = ClassBalancer(input_col="y", output_col="w").fit(t)
        loaded = roundtrip(model, tmp_path)
        np.testing.assert_allclose(loaded.transform(t)["w"], [1.0, 1.0, 2.0])

    def test_timer_wraps_estimator(self):
        t = DataTable({"y": np.array([0, 1, 1])})
        timer = Timer(stage=ClassBalancer(input_col="y", output_col="w"))
        model = timer.fit(t)
        out = model.transform(t)
        np.testing.assert_allclose(out["w"], [2.0, 1.0, 1.0])

    def test_multi_column_adapter(self):
        t = DataTable({"c1": ["a", "b"], "c2": ["x", "x"]})
        adapter = MultiColumnAdapter(
            base_stage=ValueIndexer(),
            input_cols=["c1", "c2"], output_cols=["i1", "i2"])
        out = adapter.fit(t).transform(t)
        np.testing.assert_array_equal(out["i1"], [0, 1])
        np.testing.assert_array_equal(out["i2"], [0, 0])


# ---- SummarizeData ----

class TestSummarizeData:
    def test_full_summary(self):
        t = DataTable({
            "x": np.array([1.0, 2.0, 3.0, np.nan]),
            "s": ["a", "b", "a", None],
        })
        out = SummarizeData().transform(t)
        rows = {r["Feature"]: r for r in out.to_rows()}
        assert rows["x"]["count"] == 4
        assert rows["x"]["missing_value_count"] == 1
        assert rows["x"]["mean"] == pytest.approx(2.0)
        assert rows["x"]["quantile_0.5"] == pytest.approx(2.0)
        assert rows["s"]["missing_value_count"] == 1
        assert rows["s"]["mean"] is None
        # distinct counts exclude missing values in both branches
        assert rows["s"]["unique_value_count"] == 2
        assert rows["x"]["unique_value_count"] == 3

    def test_toggles(self):
        t = DataTable({"x": np.array([1.0, 2.0])})
        out = SummarizeData(basic=False, sample=False,
                            percentiles=False).transform(t)
        assert "mean" not in out.columns
        assert "count" in out.columns


# ---- EnsembleByKey ----

class TestEnsembleByKey:
    def test_scalar_collapse(self):
        t = DataTable({"k": ["a", "a", "b"],
                       "score": np.array([1.0, 3.0, 5.0])})
        out = EnsembleByKey(keys=["k"], cols=["score"]).transform(t)
        rows = {r["k"]: r["mean(score)"] for r in out.to_rows()}
        assert rows == {"a": 2.0, "b": 5.0}

    def test_nan_keys_form_one_group(self):
        t = DataTable({"k": np.array([np.nan, np.nan, 1.0]),
                       "s": np.array([1.0, 3.0, 5.0])})
        out = EnsembleByKey(keys=["k"], cols=["s"]).transform(t)
        assert len(out) == 2
        by_key = {r["k"] if r["k"] == r["k"] else None: r["mean(s)"]
                  for r in out.to_rows()}
        assert by_key[None] == 2.0 and by_key[1.0] == 5.0

    def test_vector_no_collapse(self):
        t = DataTable({
            "k": ["a", "a"],
            "v": [np.array([0.0, 2.0]), np.array([2.0, 4.0])],
        })
        out = EnsembleByKey(keys=["k"], cols=["v"], col_names=["mv"],
                            collapse_group=False).transform(t)
        assert len(out) == 2
        np.testing.assert_allclose(out["mv"][0], [1.0, 3.0])
        np.testing.assert_allclose(out["mv"][1], [1.0, 3.0])


# ---- pipeline integration ----

def test_tabular_pipeline_roundtrip(tmp_path):
    t = make_tabular(40)
    pipe = Pipeline([
        DataConversion(cols=["int"], convert_to="double"),
        ValueIndexer(input_col="cat", output_col="cat_idx"),
        DropColumns(cols=["text"]),
    ])
    model = pipe.fit(t)
    out = model.transform(t)
    assert "cat_idx" in out.columns and "text" not in out.columns
    loaded = roundtrip(model, tmp_path)
    out2 = loaded.transform(t)
    np.testing.assert_array_equal(out["cat_idx"], out2["cat_idx"])

"""Tests for text featurization and automatic mixed-type featurization
(SURVEY §2.3 featurize / text-featurizer parity; mirrors the reference's
TextFeaturizerSpec and featurize benchmark fixtures)."""

from datetime import datetime

import numpy as np
import pytest

from mmlspark_tpu.core.schema import SchemaConstants
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.stages import (
    AssembleFeatures, Featurize, HashingTF, IDF, NGram, StopWordsRemover,
    TextFeaturizer, Tokenizer, ValueIndexer,
)

from conftest import make_tabular


class TestTokenizer:
    def test_gaps(self):
        t = DataTable({"s": ["Hello World", "ONE  two  three"]})
        out = Tokenizer(input_col="s", output_col="t").transform(t)
        assert out["t"][0] == ["hello", "world"]
        assert out["t"][1] == ["one", "two", "three"]

    def test_token_match_mode(self):
        t = DataTable({"s": ["a1 b2 c3"]})
        out = Tokenizer(input_col="s", output_col="t", gaps=False,
                        pattern=r"[a-z]+\d").transform(t)
        assert out["t"][0] == ["a1", "b2", "c3"]

    def test_min_token_length_and_none(self):
        t = DataTable({"s": ["a bb ccc", None]})
        out = Tokenizer(input_col="s", output_col="t",
                        min_token_length=2).transform(t)
        assert out["t"][0] == ["bb", "ccc"]
        assert out["t"][1] == []

    def test_nan_is_missing_not_token(self):
        t = DataTable({"s": ["apple", np.nan, None]})
        out = Tokenizer(input_col="s", output_col="t").transform(t)
        assert out["t"][1] == [] and out["t"][2] == []


class TestStopWordsAndNGram:
    def test_stop_words_default(self):
        t = DataTable({"toks": [["the", "cat", "and", "dog"]]})
        out = StopWordsRemover(input_col="toks",
                               output_col="o").transform(t)
        assert out["o"][0] == ["cat", "dog"]

    def test_stop_words_custom_case(self):
        t = DataTable({"toks": [["Foo", "bar"]]})
        out = StopWordsRemover(input_col="toks", output_col="o",
                               stop_words=["foo"],
                               case_sensitive=True).transform(t)
        assert out["o"][0] == ["Foo", "bar"]

    def test_ngram(self):
        t = DataTable({"toks": [["a", "b", "c"]]})
        out = NGram(input_col="toks", output_col="o", n=2).transform(t)
        assert out["o"][0] == ["a b", "b c"]


class TestHashingTFIDF:
    def test_tf_counts(self):
        t = DataTable({"toks": [["x", "x", "y"], ["z"]]})
        out = HashingTF(input_col="toks", output_col="tf",
                        num_features=64).transform(t)
        mat = out.column_matrix("tf")
        assert mat.shape == (2, 64)
        assert mat[0].sum() == 3.0 and mat[0].max() == 2.0
        assert mat[1].sum() == 1.0
        assert out.column_meta("tf")[SchemaConstants.K_VECTOR_SIZE] == 64

    def test_binary(self):
        t = DataTable({"toks": [["x", "x"]]})
        out = HashingTF(input_col="toks", output_col="tf", num_features=8,
                        binary=True).transform(t)
        assert out.column_matrix("tf").max() == 1.0

    def test_idf_downweights_common_terms(self):
        t = DataTable({"toks": [["common", "rare"], ["common"],
                                ["common", "other"]]})
        tf = HashingTF(input_col="toks", output_col="tf",
                       num_features=128).transform(t)
        model = IDF(input_col="tf", output_col="tfidf").fit(tf)
        out = model.transform(tf)
        mat = out.column_matrix("tfidf")
        slot_common = np.argmax(tf.column_matrix("tf").sum(axis=0))
        # the common term (df=3) gets the lowest idf weight
        nz = model.idf[np.unique(np.nonzero(tf.column_matrix("tf"))[1])]
        assert model.idf[slot_common] == nz.min()
        assert mat.shape == (3, 128)


class TestTextFeaturizer:
    def test_end_to_end_and_roundtrip(self, tmp_path):
        t = DataTable({"text": ["the quick brown fox", "lazy dogs lie",
                                "quick quick slow"],
                       "label": np.array([0, 1, 0])})
        model = TextFeaturizer(input_col="text", output_col="feats",
                               num_features=256,
                               use_stop_words_remover=True).fit(t)
        out = model.transform(t)
        assert "__tokens" not in out.columns and "__tf" not in out.columns
        mat = out.column_matrix("feats")
        assert mat.shape == (3, 256)
        assert (mat != 0).any()
        p = str(tmp_path / "textfeat")
        model.save(p)
        out2 = PipelineStage.load(p).transform(t)
        np.testing.assert_allclose(out2.column_matrix("feats"), mat)

    def test_user_columns_with_intermediate_names_survive(self):
        t = DataTable({"text": ["a b", "c d"], "__tokens": ["keep", "me"]})
        model = TextFeaturizer(input_col="text", output_col="f",
                               num_features=32).fit(t)
        out = model.transform(t)
        assert list(out["__tokens"]) == ["keep", "me"]

    def test_ngram_path(self):
        t = DataTable({"text": ["a b c d"]})
        model = TextFeaturizer(input_col="text", output_col="f",
                               use_ngram=True, ngram_length=2,
                               use_idf=False, num_features=64).fit(t)
        mat = model.transform(t).column_matrix("f")
        assert mat.sum() == 3.0  # "a b", "b c", "c d"


class TestAssembleFeatures:
    def test_numeric_and_missing_drop(self):
        t = DataTable({"a": np.array([1.0, np.nan, 3.0]),
                       "b": np.array([2, 4, 6])})
        model = AssembleFeatures(columns_to_featurize=["a", "b"]).fit(t)
        out = model.transform(t)
        mat = out.column_matrix("features")
        assert mat.shape == (2, 2)  # NaN row dropped (na.drop analog)
        np.testing.assert_allclose(mat, [[1, 2], [3, 6]])

    def test_categoricals_first_one_hot(self):
        t = DataTable({"num": np.array([0.5, 1.5, 2.5]),
                       "c": ["a", "b", "c"]})
        t = ValueIndexer(input_col="c", output_col="c").fit(t).transform(t)
        model = AssembleFeatures(columns_to_featurize=["num", "c"]).fit(t)
        mat = model.transform(t).column_matrix("features")
        # 3 levels one-hot drop-last = 2 slots, placed BEFORE the numeric
        assert mat.shape == (3, 3)
        np.testing.assert_allclose(mat[:, :2], [[1, 0], [0, 1], [0, 0]])
        np.testing.assert_allclose(mat[:, 2], [0.5, 1.5, 2.5])

    def test_string_hash_slot_selection(self):
        t = DataTable({"s": ["apple banana", "banana cherry", "apple"]})
        model = AssembleFeatures(columns_to_featurize=["s"],
                                 number_of_features=1 << 18).fit(t)
        out = model.transform(t)
        mat = out.column_matrix("features")
        # 2^18 hash space collapses to the 3 observed vocabulary slots
        assert mat.shape == (3, 3)
        assert mat.sum() == 5.0
        # unseen words at transform time fall outside selected slots
        out2 = model.transform(DataTable({"s": ["durian"]}))
        assert out2.column_matrix("features").sum() == 0.0

    def test_single_level_categorical_contributes_nothing(self):
        t = DataTable({"c": ["a", "a", "a"],
                       "x": np.array([1.0, 2.0, 3.0])})
        t = ValueIndexer(input_col="c", output_col="c").fit(t).transform(t)
        model = AssembleFeatures(columns_to_featurize=["c", "x"]).fit(t)
        mat = model.transform(t).column_matrix("features")
        assert mat.shape == (3, 1)  # drop-last on k=1 gives zero slots
        np.testing.assert_allclose(mat[:, 0], [1.0, 2.0, 3.0])

    def test_missing_image_row_dropped(self):
        from mmlspark_tpu.core.schema import make_image
        img = make_image("p", np.ones((1, 2, 3), dtype=np.uint8))
        t = DataTable({"im": [img, None]})
        t = t.with_meta("im", **{SchemaConstants.K_IMAGE: True})
        model = AssembleFeatures(columns_to_featurize=["im"],
                                 allow_images=True).fit(t)
        mat = model.transform(t).column_matrix("features")
        assert mat.shape == (1, 8)

    def test_dates(self):
        t = DataTable({"d": [datetime(2017, 9, 1, 12, 30, 5),
                             datetime(2018, 1, 2)]})
        model = AssembleFeatures(columns_to_featurize=["d"]).fit(t)
        mat = model.transform(t).column_matrix("features")
        assert mat.shape == (2, 8)
        assert mat[0, 1] == 2017 and mat[1, 1] == 2018
        assert mat[0, 5] == 12 and mat[0, 6] == 30 and mat[0, 7] == 5

    def test_vector_column(self):
        t = DataTable({"v": [np.array([1.0, 2.0]), np.array([3.0, 4.0])],
                       "x": np.array([9.0, 10.0])})
        model = AssembleFeatures(columns_to_featurize=["v", "x"]).fit(t)
        mat = model.transform(t).column_matrix("features")
        np.testing.assert_allclose(mat, [[1, 2, 9], [3, 4, 10]])

    def test_image_gate(self):
        from mmlspark_tpu.core.schema import make_image
        img = make_image("p", np.zeros((1, 2, 3), dtype=np.uint8))
        t = DataTable({"im": [img]})
        t = t.with_meta("im", **{SchemaConstants.K_IMAGE: True})
        with pytest.raises(ValueError, match="allow_images"):
            AssembleFeatures(columns_to_featurize=["im"]).fit(t)
        model = AssembleFeatures(columns_to_featurize=["im"],
                                 allow_images=True).fit(t)
        mat = model.transform(t).column_matrix("features")
        assert mat.shape == (1, 8)  # h, w, 6 pixels
        assert mat[0, 0] == 1 and mat[0, 1] == 2


class TestFeaturize:
    def test_mixed_table(self, tmp_path):
        t = make_tabular(60)
        t = ValueIndexer(input_col="cat", output_col="cat").fit(t).transform(t)
        model = Featurize(
            feature_columns={"features": ["num", "int", "cat", "text"]},
            number_of_features=1 << 18).fit(t)
        out = model.transform(t)
        mat = out.column_matrix("features")
        assert mat.shape[0] == 60
        assert out.column_meta("features")[SchemaConstants.K_VECTOR_SIZE] \
            == mat.shape[1]
        # round-trip
        p = str(tmp_path / "featurize")
        model.save(p)
        mat2 = PipelineStage.load(p).transform(t).column_matrix("features")
        np.testing.assert_allclose(mat2, mat)

    def test_multiple_outputs(self):
        t = DataTable({"a": np.arange(4).astype(float),
                       "b": np.arange(4).astype(float) * 2})
        model = Featurize(feature_columns={"fa": ["a"], "fb": ["b"]}).fit(t)
        out = model.transform(t)
        assert out.column_matrix("fa").shape == (4, 1)
        assert out.column_matrix("fb").shape == (4, 1)


class TestWord2Vec:
    """Word2Vec skip-gram embeddings (notebook-202 analog; reference spec:
    core/ml/src/test/scala/Word2VecSpec.scala)."""

    @staticmethod
    def topic_corpus(n=300, seed=0):
        # two disjoint topic clusters: co-occurrence must pull each topic's
        # words together in embedding space
        r = np.random.default_rng(seed)
        space = ["rocket", "orbit", "launch", "satellite", "astronaut"]
        ocean = ["whale", "coral", "tide", "reef", "dolphin"]
        rows = []
        for _ in range(n):
            topic = space if r.random() < 0.5 else ocean
            rows.append([topic[i] for i in r.integers(0, 5, size=8)])
        return DataTable({"tokens": rows})

    def test_synonyms_respect_topics(self):
        from mmlspark_tpu.stages.word2vec import Word2Vec
        t = self.topic_corpus()
        model = Word2Vec(vector_size=16, epochs=8, min_count=2,
                         window=3, seed=1).fit(t)
        assert len(model.vocab) == 10
        syns = [w for w, _ in model.find_synonyms("rocket", 4)]
        space = {"orbit", "launch", "satellite", "astronaut"}
        assert len(set(syns) & space) >= 3, syns

    def test_transform_averages_vectors(self):
        from mmlspark_tpu.stages.word2vec import Word2Vec
        t = self.topic_corpus(100)
        model = Word2Vec(vector_size=8, epochs=2).fit(t)
        out = model.transform(DataTable({"tokens": [
            ["rocket", "orbit"], ["unknownword"], None]}))
        vecs = list(out["features"])
        v = np.asarray(model.vectors)
        idx = {w: i for i, w in enumerate(model.vocab)}
        np.testing.assert_allclose(
            vecs[0], (v[idx["rocket"]] + v[idx["orbit"]]) / 2, rtol=1e-5)
        np.testing.assert_array_equal(vecs[1], np.zeros(8))  # OOV → zeros
        np.testing.assert_array_equal(vecs[2], np.zeros(8))  # missing row

    def test_save_load_roundtrip(self, tmp_path):
        from mmlspark_tpu.core.stage import PipelineStage
        from mmlspark_tpu.stages.word2vec import Word2Vec
        t = self.topic_corpus(80)
        model = Word2Vec(vector_size=8, epochs=2).fit(t)
        model.save(str(tmp_path / "w2v"))
        loaded = PipelineStage.load(str(tmp_path / "w2v"))
        a = np.stack(list(model.transform(t)["features"]))
        b = np.stack(list(loaded.transform(t)["features"]))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_min_count_filters_and_empty_vocab_raises(self):
        from mmlspark_tpu.stages.word2vec import Word2Vec
        t = DataTable({"tokens": [["a", "b"], ["a", "c"]]})
        m = Word2Vec(vector_size=4, min_count=2, epochs=1).fit(t)
        assert m.vocab == ["a"]
        with pytest.raises(ValueError, match="min_count"):
            Word2Vec(min_count=5).fit(t)


def test_word2vec_param_domains():
    from mmlspark_tpu.core.params import ParamValidationError
    from mmlspark_tpu.stages.word2vec import Word2Vec
    for bad in (dict(epochs=0), dict(batch_size=0), dict(negatives=0),
                dict(vector_size=0), dict(window=0), dict(max_vocab=0),
                dict(max_vocab=-3)):
        with pytest.raises(ParamValidationError):
            Word2Vec(**bad)


def test_word2vec_max_vocab_truncates_to_most_frequent():
    from mmlspark_tpu.stages.word2vec import Word2Vec
    t = DataTable({"tokens": [["a", "a", "a", "b", "b", "c"]] * 4})
    m = Word2Vec(vector_size=4, min_count=1, epochs=1, max_vocab=2).fit(t)
    assert m.vocab == ["a", "b"]


def test_word2vec_model_copy_with_new_vocab_reindexes():
    # review finding r3: copy(vocab=..., vectors=...) must not serve the
    # old word→row map against the new vectors
    from mmlspark_tpu.stages.word2vec import Word2VecModel
    v1 = np.eye(3, 4, dtype=np.float32)
    m1 = Word2VecModel(vocab=["a", "b", "c"], vectors=v1)
    t = DataTable({"tokens": [["a"]]})
    np.testing.assert_allclose(m1.transform(t)["features"][0], v1[0])
    m2 = m1.copy(vocab=["z", "a"], vectors=np.asarray(
        [[9, 9, 9, 9], [1, 2, 3, 4]], np.float32))
    np.testing.assert_allclose(m2.transform(t)["features"][0],
                               [1, 2, 3, 4])
    syn = m2.find_synonyms("z", 1)
    assert syn[0][0] == "a"

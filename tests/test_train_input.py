"""The asynchronous prefetching train-input pipeline (train/input.py).

Two families of guarantees:

* **Numerics**: prefetch on/off produce BIT-identical loss histories and
  final params for a fixed seed — the loader moves *when* batches cross
  the link, never what crosses — for fit_arrays and fit_stream including
  the padded-tail and unequal-chunk cases, and the uint8-to-device
  convention matches host-side normalization to float tolerance.
* **Lifecycle**: the bounded queue commits ahead of consumption, producer
  and commit errors surface at the point of consumption, and shutdown is
  clean on mid-epoch exceptions — no leaked threads, no deadlock.
"""

import itertools
import time

import numpy as np
import pytest

import jax

from conftest import assert_no_leaked_threads, thread_names

from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.train import DeviceLoader, TrainConfig, Trainer
from mmlspark_tpu.train.input import THREAD_PREFIX, input_stats


def _assert_no_leaked_threads(timeout=5.0):
    assert_no_leaked_threads(THREAD_PREFIX, timeout=timeout)


def _params_bitwise_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for u, v in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


def _xy(n=40, seed=3):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return x, y


def _cfg(depth, **kw):
    base = dict(batch_size=16, epochs=2, learning_rate=1e-2, log_every=1,
                prefetch_depth=depth, donate_state=False)
    base.update(kw)
    return TrainConfig(**base)


class TestBitIdentity:
    def test_fit_arrays_prefetch_matches_sync_with_padded_tail(self):
        # 40 rows / batch 16 → the tail batch is zero-padded + masked
        x, y = _xy(40)
        trainers = {}
        for depth in (2, 0):
            tr = Trainer(MLP(features=(16,), num_outputs=2), _cfg(depth))
            tr.fit_arrays(x, y)
            trainers[depth] = tr
        assert trainers[2].history == trainers[0].history
        assert len(trainers[2].history) == 6  # 3 batches × 2 epochs
        _params_bitwise_equal(trainers[2].params, trainers[0].params)
        assert trainers[2].input_stats["prefetch_depth"] == 2
        assert trainers[2].input_stats["batches"] == 6
        assert trainers[0].input_stats["committed_ahead_max"] == 0
        _assert_no_leaked_threads()

    def test_fit_stream_prefetch_matches_sync_unequal_chunks(self):
        x, y = _xy(40)
        sizes = [5, 11, 3, 13, 7, 1]  # 40 rows in ragged chunks

        def source():
            off = 0
            for n in sizes:
                yield x[off:off + n], y[off:off + n]
                off += n

        trainers = {}
        for depth in (3, 0):
            tr = Trainer(MLP(features=(16,), num_outputs=2), _cfg(depth))
            tr.fit_stream(source)
            trainers[depth] = tr
        assert trainers[3].history == trainers[0].history
        _params_bitwise_equal(trainers[3].params, trainers[0].params)
        assert trainers[3].input_stats["batches"] == 6
        _assert_no_leaked_threads()

    def test_deep_prefetch_matches_depth_one(self):
        # depth only bounds the queue; any depth > 0 is the same walk
        x, y = _xy(40)
        a = Trainer(MLP(features=(16,), num_outputs=2), _cfg(1))
        b = Trainer(MLP(features=(16,), num_outputs=2), _cfg(8))
        a.fit_arrays(x, y)
        b.fit_arrays(x, y)
        assert a.history == b.history
        _params_bitwise_equal(a.params, b.params)

    def test_uint8_ships_thin_and_normalizes_on_device(self):
        # uint8 batches cast to f32 and scale by cfg.input_scale INSIDE
        # the jitted step — equivalent to host-side /255 normalization to
        # float tolerance (a*(1/255) vs a/255 differ in last-ulp rounding)
        r = np.random.default_rng(7)
        xu = r.integers(0, 255, size=(48, 12)).astype(np.uint8)
        y = (xu.astype(np.float32).sum(axis=1) > 6 * 255).astype(np.int64)
        xf = xu.astype(np.float32) / 255.0

        tru = Trainer(MLP(features=(16,), num_outputs=2), _cfg(2))
        tru.fit_arrays(xu, y)
        trf = Trainer(MLP(features=(16,), num_outputs=2), _cfg(2))
        trf.fit_arrays(xf, y)
        np.testing.assert_allclose(tru.history, trf.history,
                                   rtol=1e-5, atol=1e-6)
        for u, v in zip(jax.tree_util.tree_leaves(tru.params),
                        jax.tree_util.tree_leaves(trf.params)):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                       rtol=1e-4, atol=1e-6)


class TestLoaderLifecycle:
    def test_commits_ahead_of_slow_consumer(self):
        ld = DeviceLoader(iter(range(10)), lambda v: v, depth=2,
                          name="t-ahead")
        got = []
        with ld:
            for v in ld:
                time.sleep(0.02)  # slow consumer: producer fills the queue
                got.append(v)
        assert got == list(range(10))
        assert ld.committed == ld.consumed == 10
        assert ld.max_ahead >= 2
        _assert_no_leaked_threads()

    def test_depth_zero_is_synchronous_no_thread(self):
        before = thread_names(THREAD_PREFIX)
        ld = DeviceLoader(iter(range(5)), lambda v: v * 2, depth=0,
                          name="t-sync")
        assert thread_names(THREAD_PREFIX) == before  # no worker spawned
        assert list(ld) == [0, 2, 4, 6, 8]
        assert ld.committed == ld.consumed == 5
        assert ld.max_ahead == 0

    def test_consumer_exception_shuts_down_cleanly(self):
        # producer is blocked on a full queue when the consumer bails —
        # close() must unblock it and join the thread (no deadlock)
        with pytest.raises(RuntimeError, match="boom"):
            with DeviceLoader(itertools.count(), lambda v: v, depth=2,
                              name="t-bail") as ld:
                for v in ld:
                    if v == 3:
                        raise RuntimeError("boom")
        _assert_no_leaked_threads()

    def test_source_exception_propagates_at_consumption(self):
        def src():
            yield 1
            yield 2
            raise ValueError("decode failed")

        ld = DeviceLoader(src(), lambda v: v, depth=2, name="t-srcfail")
        got = []
        with pytest.raises(ValueError, match="decode failed"):
            with ld:
                for v in ld:
                    got.append(v)
        assert got == [1, 2]
        _assert_no_leaked_threads()

    def test_commit_exception_propagates(self):
        def commit(v):
            if v == 2:
                raise TypeError("cannot commit")
            return v

        with pytest.raises(TypeError, match="cannot commit"):
            with DeviceLoader(iter(range(5)), commit, depth=2,
                              name="t-commitfail") as ld:
                list(ld)
        _assert_no_leaked_threads()

    def test_close_is_idempotent(self):
        ld = DeviceLoader(iter(range(100)), lambda v: v, depth=2,
                          name="t-idem")
        next(ld)
        ld.close()
        ld.close()
        _assert_no_leaked_threads()

    def test_sync_mode_closes_source(self):
        closed = []

        def src():
            try:
                yield from range(10)
            finally:
                closed.append(True)

        ld = DeviceLoader(src(), lambda v: v, depth=0, name="t-synccl")
        next(ld)
        ld.close()
        assert closed == [True]

    def test_input_stats_shape(self):
        ld = DeviceLoader(iter(range(4)), lambda v: v, depth=2,
                          name="t-stats")
        with ld:
            list(ld)
        s = input_stats(ld, 1.0)
        assert s["batches"] == 4
        assert 0.0 <= s["input_bound_fraction"] <= 1.0
        assert set(s) == {"prefetch_depth", "batches", "committed_ahead_max",
                          "input_wait_s", "step_s", "input_bound_fraction",
                          "assemble_s", "commit_s", "wire_mb"}

    def test_wire_bytes_track_the_committed_payload(self):
        # the wire-format observable of the thin-wire A/B: uint8 items
        # ship ¼ the bytes of the same-shaped f32 items
        items_u8 = [np.zeros((4, 8), np.uint8) for _ in range(3)]
        items_f32 = [np.zeros((4, 8), np.float32) for _ in range(3)]
        for items, expect in ((items_u8, 3 * 32), (items_f32, 3 * 128)):
            ld = DeviceLoader(iter(items), lambda v: v, depth=0,
                              name="t-wire")
            list(ld)
            assert ld.wire_bytes == expect


class TestTrainerShutdown:
    def test_fit_stream_source_error_mid_epoch_no_leak(self):
        x, y = _xy(40)

        def source():
            yield x[:16], y[:16]
            yield x[16:32], y[16:32]
            raise OSError("shard went away")

        tr = Trainer(MLP(features=(16,), num_outputs=2),
                     _cfg(2, epochs=1))
        with pytest.raises(OSError, match="shard went away"):
            tr.fit_stream(source())
        _assert_no_leaked_threads()

    def test_fit_stream_empty_still_raises(self):
        tr = Trainer(MLP(features=(16,), num_outputs=2), _cfg(2, epochs=1))
        with pytest.raises(ValueError, match="yielded no data"):
            tr.fit_stream(iter([]))
        _assert_no_leaked_threads()

    def test_step_error_mid_fit_no_leak(self):
        # consumer-side failure: labels out of range make the masked step
        # raise at dispatch on some backends; emulate determinism by
        # breaking the trainer's step fn instead
        x, y = _xy(40)
        tr = Trainer(MLP(features=(16,), num_outputs=2), _cfg(2))
        calls = {"n": 0}
        real_step = tr.step_masked

        def exploding_step(state, bx, by, bw):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("device OOM")
            return real_step(state, bx, by, bw)

        tr.step_masked = exploding_step
        with pytest.raises(RuntimeError, match="device OOM"):
            tr.fit_arrays(x, y)
        _assert_no_leaked_threads()

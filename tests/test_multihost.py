"""Real multi-host training: N jax.distributed processes, one global mesh.

The reference never wired its multi-node path (the MPI hostfile launcher is
an unused stub, cntk-train/src/main/scala/CommandBuilders.scala:95-117).
Here the framework's OWN pod launcher (``mmlspark_tpu.tools.launch``)
starts the worker processes — each holding 2 virtual CPU devices and ONLY
its shard of the dataset; ``Trainer.fit_arrays`` assembles global batches
from the local shards (``jax.make_array_from_process_local_data``) and XLA
all-reduces gradients across the world. Asserts: convergence, bit-identical
params across processes, loss parity with a single-process run, unequal
shards/streams handled, and the failure path — a worker hard-killed
mid-training is detected by the launcher and the job resumes from the last
checkpoint to the same final state as an uninterrupted run.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.multihost]


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")
FAIL_WORKER = os.path.join(REPO, "tests", "multihost_failure_worker.py")


def _launch(worker: str, nproc: int, out_dir: str, extra_env=None,
            grace: float = 30.0) -> int:
    """Run a worker set through the real pod launcher (the deploy path)."""
    from mmlspark_tpu.tools.launch import launch_local
    env = {"MULTIHOST_OUT_DIR": out_dir}
    env.update(extra_env or {})
    return launch_local([sys.executable, worker], nproc,
                        cpu_devices=2, grace_seconds=grace, extra_env=env)


def _read_outs(out_dir: str, nproc: int, prefix: str = "out"):
    outs = []
    for pid in range(nproc):
        with open(os.path.join(out_dir, f"{prefix}_{pid}.json")) as f:
            outs.append(json.load(f))
    return sorted(outs, key=lambda o: o["pid"])


@pytest.fixture(scope="module")
def multihost_result(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("mh2"))
    rc = _launch(WORKER, 2, out_dir)
    assert rc == 0, f"2-process launch failed with rc={rc}"
    return _read_outs(out_dir, 2)


def test_both_processes_trained_full_schedule(multihost_result):
    r0, r1 = multihost_result
    # 120 global rows, bs 40 → 3 steps/epoch × 4 epochs
    assert r0["steps"] == r1["steps"] == 12
    assert r0["losses"][-1] < r0["losses"][0]


def test_params_agree_across_processes(multihost_result):
    r0, r1 = multihost_result
    assert r0["checksum"] == pytest.approx(r1["checksum"], rel=0, abs=0.0), \
        "post-training params diverged across hosts"


def test_loss_parity_with_single_process(multihost_result):
    """A single process fed the identically-composed global batches must
    reproduce the 2-process loss trajectory (proves the multi-host input
    path feeds exactly the intended data, not a resharded approximation)."""
    import jax

    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.parallel.mesh import MeshSpec, batch_sharding, make_mesh
    from mmlspark_tpu.train import TrainConfig, Trainer
    from mmlspark_tpu.train.loop import _batches

    r = np.random.default_rng(0)
    x = r.normal(size=(120, 8)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    shards = [(x[:60], y[:60]), (x[60:], y[60:])]

    mesh = make_mesh(MeshSpec(dp=4), None)
    cfg = TrainConfig(batch_size=40, epochs=4, learning_rate=5e-3,
                      log_every=1, donate_state=False)
    tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
    tr.state = tr.init_state((8,))
    data = batch_sharding(mesh)

    losses = []
    for epoch in range(cfg.epochs):
        walks = [_batches(sx, sy, 20, cfg.seed + epoch) for sx, sy in shards]
        for locals_ in zip(*walks):
            # global batch = process-order concatenation of local slices
            bx = np.concatenate([b[0] for b in locals_])
            by = np.concatenate([b[1] for b in locals_])
            bw = np.concatenate([b[2] for b in locals_])
            tr.state, m = tr.step_masked(
                tr.state, jax.device_put(bx, data),
                jax.device_put(by, data), jax.device_put(bw, data))
            losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, multihost_result[0]["losses"],
                               rtol=1e-4, atol=1e-5)


def test_unequal_stream_shards_do_not_deadlock(multihost_result):
    """fit_stream liveness sync: process 0 streams 3 chunks, process 1
    streams 5 — the run must complete (filler batches on the short side)
    with identical params on both processes."""
    r0, r1 = multihost_result
    # 2 epochs × max-process batch count: p1 has 5 chunks × 8 rows / 4-row
    # local batches = 10 local batches per epoch → 20 global steps
    assert r0["stream_steps"] == r1["stream_steps"] == 20
    assert r0["stream_checksum"] == pytest.approx(r1["stream_checksum"],
                                                  rel=0, abs=0.0)


def test_four_process_unequal_shards(tmp_path):
    """4 launcher-started processes (8 global devices) with deliberately
    UNEQUAL fit_arrays shards (40/30/30/20 rows): the zero-weight shard
    padding must keep every process on the same batch walk and produce
    bit-identical params everywhere."""
    out_dir = str(tmp_path)
    rc = _launch(WORKER, 4, out_dir)
    assert rc == 0, f"4-process launch failed with rc={rc}"
    outs = _read_outs(out_dir, 4)
    # shards pad to 40 rows/process → 160 global rows, bs 40 → 4 steps ×
    # 4 epochs
    assert [o["steps"] for o in outs] == [16] * 4
    sums = {o["checksum"] for o in outs}
    assert len(sums) == 1, f"params diverged across 4 hosts: {sums}"
    assert outs[0]["losses"][-1] < outs[0]["losses"][0]
    stream_sums = {o["stream_checksum"] for o in outs}
    assert len(stream_sums) == 1


def test_worker_death_detected_and_resume_matches_uninterrupted(tmp_path):
    """The failure e2e (SURVEY §5): kill worker 1 mid-fit_stream; the
    launcher must surface the failure (terminating the survivor, no hang),
    and re-running the same command must resume from the last checkpoint
    and reach the same final params as a never-interrupted run."""
    FAIL_EXIT_CODE = 17  # multihost_failure_worker.FAIL_EXIT_CODE

    # 1) uninterrupted baseline
    base_dir = str(tmp_path / "base_out")
    os.makedirs(base_dir)
    rc = _launch(FAIL_WORKER, 2, base_dir,
                 {"MULTIHOST_CKPT_DIR": str(tmp_path / "ckpt_base")})
    assert rc == 0
    base = _read_outs(base_dir, 2, prefix="fail_out")

    # 2) run that dies: rank 1 hard-exits after 3 chunks (mid-stream)
    ckpt = str(tmp_path / "ckpt_fail")
    fail_dir = str(tmp_path / "fail_out")
    os.makedirs(fail_dir)
    t0 = time.time()
    rc = _launch(FAIL_WORKER, 2, fail_dir,
                 {"MULTIHOST_CKPT_DIR": ckpt,
                  "MULTIHOST_FAIL_AT_STEP": "3",
                  "MULTIHOST_FAIL_RANK": "1"}, grace=20.0)
    elapsed = time.time() - t0
    assert rc == FAIL_EXIT_CODE, \
        f"launcher must report the dead worker's exit code, got {rc}"
    # the survivor was terminated, not left hung in a collective forever
    assert elapsed < 240, f"failure detection took {elapsed:.0f}s"
    # some checkpoints landed before the death
    from mmlspark_tpu.train.checkpoint import TrainCheckpointer
    saved = TrainCheckpointer(ckpt).latest_step()
    assert saved is not None and saved >= 1

    # 3) restart the SAME command: resumes from the last checkpoint and
    # completes the schedule
    resume_dir = str(tmp_path / "resume_out")
    os.makedirs(resume_dir)
    rc = _launch(FAIL_WORKER, 2, resume_dir, {"MULTIHOST_CKPT_DIR": ckpt})
    assert rc == 0, "restart after failure did not complete"
    resumed = _read_outs(resume_dir, 2, prefix="fail_out")

    assert resumed[0]["steps"] == base[0]["steps"]
    assert resumed[0]["checksum"] == pytest.approx(resumed[1]["checksum"],
                                                   rel=0, abs=0.0)
    # deterministic schedule + resume replay ⇒ same final params as the
    # uninterrupted job
    assert resumed[0]["checksum"] == pytest.approx(base[0]["checksum"],
                                                   rel=1e-6)


def test_elastic_supervisor_rescales_world_and_resumes_bit_compatibly(
        tmp_path):
    """The elastic training service over a REAL multi-process world
    (SURVEY §5 extended to topology change): generation 0 trains on
    world=2 jax.distributed workers (2 virtual devices each, global mesh
    dp=2×fsdp=2); rank 1 hard-dies with the preemption exit code
    mid-stream. The supervisor must detect the loss (terminating the
    survivor, no hang), archive the recovery snapshot, and re-scale to
    world=1 (2 devices, dp=1×fsdp=2 — the survivors' mesh), where the
    restore targets re-shard the checkpoint onto the new topology and
    the deterministic elastic walk (train/service.elastic_stream) keeps
    the global batch composition identical. Bit-compat pin: an
    UNINTERRUPTED run at the surviving topology from the same snapshot
    reproduces the elastic run's loss tail and final params exactly."""
    import numpy as _np

    from mmlspark_tpu.train.service import (
        PREEMPT_EXIT_CODE, RecoveryPolicy, ServiceConfig, Topology,
        TrainSupervisor,
    )

    worker_cmd = (sys.executable,
                  os.path.join(REPO, "tools", "train_service.py"),
                  "worker")
    svc = str(tmp_path / "svc")
    sup = TrainSupervisor(ServiceConfig(
        cmd=worker_cmd, service_dir=svc,
        checkpoint_dir=str(tmp_path / "ckpt"),
        topologies=(Topology(world=2, devices=2),
                    Topology(world=1, devices=2)),
        policy=RecoveryPolicy(max_restarts=0),
        grace_seconds=30.0,
        # die AFTER the first liveness block: the multi-process producer
        # eagerly pulls 1 (signature sync) + liveness_sync_every=8 chunks
        # before the first step dispatches, so a smaller die point would
        # preempt before any step ran or checkpoint landed
        extra_env={"MMLSPARK_TPU_SERVICE_DIE_AT_STEP": "12",
                   "MMLSPARK_TPU_SERVICE_DIE_GEN": "0",
                   "MMLSPARK_TPU_SERVICE_DIE_RANK": "1"}))
    report = sup.run()
    assert report.ok, report.reason
    assert report.rescales == 1 and report.evictions == 1
    g0, g1 = report.generations
    assert g0.signal.rank == 1 and g0.signal.code == PREEMPT_EXIT_CODE
    assert (g1.topology.world, g1.topology.devices) == (1, 2)
    with open(os.path.join(svc, "result_gen1_rank0.json")) as f:
        elastic = json.load(f)
    assert elastic["world"] == 1 and elastic["devices"] == 2
    assert elastic["resumed"] >= 1

    # uninterrupted continuation at the surviving topology from the
    # recovery snapshot (no kill): same supervisor machinery, one rung
    svc2 = str(tmp_path / "svc_control")
    control_sup = TrainSupervisor(ServiceConfig(
        cmd=worker_cmd, service_dir=svc2,
        checkpoint_dir=report.snapshots[0],
        topologies=(Topology(world=1, devices=2),),
        grace_seconds=30.0))
    assert control_sup.run().ok
    with open(os.path.join(svc2, "result_gen0_rank0.json")) as f:
        control = json.load(f)

    assert elastic["steps"] == control["steps"]
    assert elastic["history"] == control["history"], (
        "elastic loss tail diverged from the uninterrupted continuation "
        "at the surviving topology")
    ep = _np.load(elastic["params_npz"])
    cp = _np.load(control["params_npz"])
    assert sorted(ep.files) == sorted(cp.files)
    for key in ep.files:
        assert _np.array_equal(ep[key], cp[key]), (
            f"final params differ at {key}")


SCORE_WORKER = os.path.join(REPO, "tests", "multihost_scoring_worker.py")


def test_multihost_scoring_matches_single_host(tmp_path):
    """Multi-host DP scoring e2e (the reference's *primary* parallelism,
    executor-side inference, CNTKModel.scala:248-256): two launcher-started
    processes each score only their file shard on their LOCAL device mesh;
    the rank-order merge must equal a single-host run of the full table —
    order-preserved, for both JaxModel.transform and the Arrow bridge."""
    out_dir = str(tmp_path)
    rc = _launch(SCORE_WORKER, 2, out_dir)
    assert rc == 0, f"scoring launch failed with rc={rc}"
    outs = _read_outs(out_dir, 2, prefix="score_out")
    assert [o["n_local_devices"] for o in outs] == [2, 2]
    # shards tile the table exactly, in rank order
    assert [(o["lo"], o["hi"]) for o in outs] == [(0, 48), (48, 96)]
    merged = np.concatenate([np.asarray(o["scores"]) for o in outs])
    merged_bridge = np.concatenate(
        [np.asarray(o["bridge_scores"]) for o in outs])

    # single-host reference on this process's own mesh
    import multihost_scoring_worker as sw
    table = sw.global_table(0, sw.N_ROWS)
    ref = sw.scoring_model().transform(table).column_matrix("scores")
    assert merged.shape == ref.shape == (96, 10)
    np.testing.assert_allclose(merged, np.asarray(ref, np.float64),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(merged_bridge, np.asarray(ref, np.float64),
                               rtol=1e-5, atol=1e-5)

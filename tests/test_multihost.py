"""Real multi-host training: 2 jax.distributed processes, one global mesh.

The reference never wired its multi-node path (the MPI hostfile launcher is
an unused stub, cntk-train/src/main/scala/CommandBuilders.scala:95-117).
Here two OS processes each hold 2 virtual CPU devices and ONLY HALF the
dataset; ``Trainer.fit_arrays`` assembles global batches from the local
shards (``jax.make_array_from_process_local_data``) and XLA all-reduces
gradients across the 4-device world. Asserts: both processes converge, the
trained params agree bit-for-bit across processes, and the loss trajectory
matches a single-process run fed the identically-composed global batches.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def multihost_result():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(port), str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in (0, 1)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return sorted(outs, key=lambda o: o["pid"])


def test_both_processes_trained_full_schedule(multihost_result):
    r0, r1 = multihost_result
    # 120 global rows, bs 40 → 3 steps/epoch × 4 epochs
    assert r0["steps"] == r1["steps"] == 12
    assert r0["losses"][-1] < r0["losses"][0]


def test_params_agree_across_processes(multihost_result):
    r0, r1 = multihost_result
    assert r0["checksum"] == pytest.approx(r1["checksum"], rel=0, abs=0.0), \
        "post-training params diverged across hosts"


def test_loss_parity_with_single_process(multihost_result):
    """A single process fed the identically-composed global batches must
    reproduce the 2-process loss trajectory (proves the multi-host input
    path feeds exactly the intended data, not a resharded approximation)."""
    import jax

    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.parallel.mesh import MeshSpec, batch_sharding, make_mesh
    from mmlspark_tpu.train import TrainConfig, Trainer
    from mmlspark_tpu.train.loop import _batches

    r = np.random.default_rng(0)
    x = r.normal(size=(120, 8)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    shards = [(x[:60], y[:60]), (x[60:], y[60:])]

    mesh = make_mesh(MeshSpec(dp=4), None)
    cfg = TrainConfig(batch_size=40, epochs=4, learning_rate=5e-3,
                      log_every=1, donate_state=False)
    tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
    tr.state = tr.init_state((8,))
    data = batch_sharding(mesh)

    losses = []
    for epoch in range(cfg.epochs):
        walks = [_batches(sx, sy, 20, cfg.seed + epoch) for sx, sy in shards]
        for locals_ in zip(*walks):
            # global batch = process-order concatenation of local slices
            bx = np.concatenate([b[0] for b in locals_])
            by = np.concatenate([b[1] for b in locals_])
            bw = np.concatenate([b[2] for b in locals_])
            tr.state, m = tr.step_masked(
                tr.state, jax.device_put(bx, data),
                jax.device_put(by, data), jax.device_put(bw, data))
            losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, multihost_result[0]["losses"],
                               rtol=1e-4, atol=1e-5)


def test_unequal_stream_shards_do_not_deadlock(multihost_result):
    """fit_stream liveness sync: process 0 streams 3 chunks, process 1
    streams 5 — the run must complete (filler batches on the short side)
    with identical params on both processes."""
    r0, r1 = multihost_result
    # 2 epochs × max-process batch count: p1 has 5 chunks × 8 rows / 4-row
    # local batches = 10 local batches per epoch → 20 global steps
    assert r0["stream_steps"] == r1["stream_steps"] == 20
    assert r0["stream_checksum"] == pytest.approx(r1["stream_checksum"],
                                                  rel=0, abs=0.0)

"""Image ingest tests: readers (globs, zip, sampling), native decode,
ImageTransformer ops, UnrollImage, ImageSetAugmenter, ImageFeaturizer,
ModelDownloader."""

import os
import zipfile

import numpy as np
import pytest

from mmlspark_tpu.core.schema import is_image_column, make_image
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.downloader import (
    ModelDownloader, ModelSchema, load_bundle_file, publish_model,
)
from mmlspark_tpu.data.readers import (
    decode_image, read_binary_files, read_images,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.image_featurizer import ImageFeaturizer
from mmlspark_tpu.models.zoo import get_model
from mmlspark_tpu.native import imgops
from mmlspark_tpu.stages.image import (
    ImageSetAugmenter, ImageTransformer, UnrollImage,
)


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    """Directory of jpg/png files + a zip archive + a junk file."""
    import cv2
    root = tmp_path_factory.mktemp("imgs")
    r = np.random.default_rng(0)
    for i in range(4):
        img = r.integers(0, 255, (24 + i, 36, 3)).astype(np.uint8)
        cv2.imwrite(str(root / f"im{i}.jpg"), img)
    cv2.imwrite(str(root / "p.png"),
                r.integers(0, 255, (20, 20, 3)).astype(np.uint8))
    (root / "notes.txt").write_text("not an image")
    sub = root / "sub"
    sub.mkdir()
    cv2.imwrite(str(sub / "deep.png"),
                r.integers(0, 255, (16, 16, 3)).astype(np.uint8))
    with zipfile.ZipFile(root / "arch.zip", "w") as zf:
        ok, buf = cv2.imencode(".jpg",
                               r.integers(0, 255, (12, 12, 3)).astype(np.uint8))
        zf.writestr("zipped1.jpg", buf.tobytes())
        zf.writestr("zipped2.jpg", buf.tobytes())
        zf.writestr("readme.md", "skip me")
    return str(root)


def rand_images(n=6, h=28, w=28, seed=0):
    r = np.random.default_rng(seed)
    return DataTable({"image": [
        make_image(f"i{k}", r.integers(0, 255, (h, w, 3))) for k in range(n)
    ]})


# ---- native ops ----

def test_native_available():
    assert imgops.available()


def test_native_unroll_matches_numpy():
    r = np.random.default_rng(1)
    img = r.integers(0, 255, (9, 7, 3)).astype(np.uint8)
    got = imgops.unroll(img, to_rgb=True, scale=1 / 255.0, offset=-0.5)
    want = (np.transpose(img[:, :, ::-1], (2, 0, 1)).astype(np.float32)
            / 255.0 - 0.5)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_native_unroll_batch():
    r = np.random.default_rng(2)
    batch = r.integers(0, 255, (5, 8, 8, 3)).astype(np.uint8)
    got = imgops.unroll_batch(batch, scale=2.0)
    want = np.transpose(batch, (0, 3, 1, 2)).astype(np.float32) * 2.0
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_native_decode_jpeg_png_match_cv2():
    import cv2
    r = np.random.default_rng(3)
    img = r.integers(0, 255, (30, 40, 3)).astype(np.uint8)
    _, png = cv2.imencode(".png", img)
    assert np.array_equal(imgops.decode(png.tobytes()), img)
    _, jpg = cv2.imencode(".jpg", img)
    ours = imgops.decode(jpg.tobytes())
    ref = cv2.imdecode(jpg, cv2.IMREAD_COLOR)
    assert np.abs(ours.astype(int) - ref.astype(int)).max() <= 1


# ---- readers ----

def test_read_binary_files(image_dir):
    t = read_binary_files(image_dir)
    names = [os.path.basename(p) for p in t["path"]]
    assert "notes.txt" in names  # binary reader takes everything
    assert any(n.endswith(".zip") or "zipped" in n for n in names)


def test_read_images_flat(image_dir):
    t = read_images(image_dir, inspect_zip=False)
    assert is_image_column(t, "image")
    assert len(t) == 5  # 4 jpg + 1 png; txt and zip skipped; sub/ skipped


def test_read_images_recursive_and_zip(image_dir):
    t = read_images(image_dir, recursive=True, inspect_zip=True)
    # 4 jpg + 1 png + 1 deep.png + 2 zip entries (readme.md filtered)
    assert len(t) == 8
    paths = [v["path"] for v in t["image"]]
    assert any("arch.zip/zipped1.jpg" in p for p in paths)


def test_read_images_sampling_deterministic(image_dir):
    a = read_images(image_dir, recursive=True, sample_ratio=0.5, seed=7)
    b = read_images(image_dir, recursive=True, sample_ratio=0.5, seed=7)
    assert [v["path"] for v in a["image"]] == [v["path"] for v in b["image"]]
    assert len(a) < 8
    c = read_images(image_dir, recursive=True, sample_ratio=0.5, seed=8)
    assert [v["path"] for v in c["image"]] != [v["path"] for v in a["image"]]


def test_read_images_sharding(image_dir):
    t0 = read_images(image_dir, recursive=True, shard_index=0, num_shards=2)
    t1 = read_images(image_dir, recursive=True, shard_index=1, num_shards=2)
    p0 = {v["path"] for v in t0["image"]}
    p1 = {v["path"] for v in t1["image"]}
    assert not (p0 & p1)
    assert len(p0) + len(p1) == 8


def test_read_images_bad_path():
    with pytest.raises(FileNotFoundError):
        read_images("/definitely/not/here")
    with pytest.raises(ValueError):
        read_binary_files(".", sample_ratio=2.0)


def test_decode_garbage_returns_none():
    assert decode_image(b"this is not an image") is None


# ---- ImageTransformer ----

def test_transformer_resize_crop_flip():
    t = rand_images(3, 20, 30)
    it = (ImageTransformer().resize(10, 12).crop(2, 2, 6, 8).flip(1))
    out = it.transform(t)
    img = out["image"][0]
    assert (img["height"], img["width"]) == (6, 8)
    # flip of a flip is identity
    it2 = ImageTransformer().flip(1)
    once = it2.transform(t)["image"][0]["data"]
    twice = it2.transform(it2.transform(t))["image"][0]["data"]
    np.testing.assert_array_equal(twice, t["image"][0]["data"])


def test_transformer_color_and_blur():
    t = rand_images(2)
    out = ImageTransformer().color_format("gray").transform(t)
    assert out["image"][0]["channels"] == 1
    out2 = ImageTransformer().blur(3, 3).transform(t)
    assert out2["image"][0]["data"].shape == (28, 28, 3)
    out3 = ImageTransformer().threshold(127, 255).transform(t)
    vals = np.unique(out3["image"][0]["data"])
    assert set(vals.tolist()) <= {0, 255}
    out4 = ImageTransformer().gaussian_kernel(5, 1.0).transform(t)
    assert out4["image"][0]["data"].shape == (28, 28, 3)


def test_transformer_decode_if_binary():
    import cv2
    r = np.random.default_rng(5)
    img = r.integers(0, 255, (14, 14, 3)).astype(np.uint8)
    _, jpg = cv2.imencode(".png", img)
    t = DataTable({"image": [jpg.tobytes()]})
    out = ImageTransformer().resize(7, 7).transform(t)
    assert out["image"][0]["height"] == 7


def test_transformer_bad_op_and_crop():
    t = rand_images(1, 10, 10)
    bad = ImageTransformer(ops=[{"op": "nope"}])
    with pytest.raises(ValueError):
        bad.transform(t)
    with pytest.raises(ValueError):
        ImageTransformer().crop(8, 8, 10, 10).transform(t)


def test_transformer_save_load(tmp_path):
    it = ImageTransformer().resize(8, 9).flip(1)
    p = str(tmp_path / "it")
    it.save(p)
    loaded = PipelineStage.load(p)
    t = rand_images(2)
    a = it.transform(t)["image"][0]["data"]
    b = loaded.transform(t)["image"][0]["data"]
    np.testing.assert_array_equal(a, b)


# ---- UnrollImage / Augmenter ----

def test_unroll_stage():
    t = rand_images(3, 8, 8)
    out = UnrollImage(scale=1 / 255.0).transform(t)
    v = out["features"][0]
    assert v.shape == (3 * 8 * 8,) and v.dtype == np.float32
    assert v.max() <= 1.0


def test_augmenter_doubles_rows():
    t = rand_images(4)
    out = ImageSetAugmenter().transform(t)
    assert len(out) == 8
    out2 = ImageSetAugmenter(flip_up_down=True).transform(t)
    assert len(out2) == 12
    # flipped copy really is flipped
    orig = t["image"][0]["data"]
    flipped = out["image"][4]["data"]
    np.testing.assert_array_equal(flipped, orig[:, ::-1])


# ---- ImageFeaturizer ----

def test_image_featurizer_cut_layers():
    bundle = get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=24)
    t = rand_images(5, 40, 40)  # wrong size on purpose; featurizer resizes
    f = ImageFeaturizer(cut_output_layers=1, minibatch_size=4)
    f.set(model=bundle)
    out = f.transform(t)
    feats = np.stack(list(out["features"]))
    assert feats.shape == (5, 24)
    # cut=0 keeps the classifier head
    f2 = ImageFeaturizer(cut_output_layers=0, minibatch_size=4)
    f2.set(model=bundle)
    logits = np.stack(list(f2.transform(t)["features"]))
    assert logits.shape == (5, 10)
    with pytest.raises(ValueError):
        f3 = ImageFeaturizer(cut_output_layers=5)
        f3.set(model=bundle)
        f3.transform(t)


# ---- ModelDownloader ----

def test_downloader_roundtrip(tmp_path):
    repo = str(tmp_path / "repo")
    cache = str(tmp_path / "cache")
    bundle = get_model("MLP", input_dim=6, num_outputs=3)
    entry = publish_model(bundle, repo)
    assert entry.hash and entry.size > 0

    dl = ModelDownloader(repo, cache_dir=cache)
    assert [m.name for m in dl.list_models()] == ["MLP"]
    path = dl.download_by_name("MLP")
    loaded = load_bundle_file(path)
    assert loaded.input_spec == (6,)
    x = np.zeros((2, 6), np.float32)
    np.testing.assert_allclose(np.asarray(bundle.apply(x)),
                               np.asarray(loaded.apply(x)), atol=1e-6)
    # cache hit: second download returns same path without refetch
    assert dl.download_by_name("MLP") == path


def test_downloader_hash_mismatch(tmp_path):
    repo = str(tmp_path / "repo")
    bundle = get_model("MLP", input_dim=4)
    entry = publish_model(bundle, repo)
    # corrupt the repo file
    with open(os.path.join(repo, entry.uri), "ab") as f:
        f.write(b"tamper")
    dl = ModelDownloader(repo, cache_dir=str(tmp_path / "cache"))
    with pytest.raises(IOError):
        dl.download_by_name("MLP")
    with pytest.raises(KeyError):
        dl.download_by_name("missing")


# ---- round-3 regression tests (VERDICT r2 weak items) ----

def test_hashless_cache_entry_is_verified(tmp_path):
    """Empty manifest hash: a corrupted cache entry must never be served
    (VERDICT r2 weak item 3 — sidecar self-hash restores the guarantee)."""
    repo = str(tmp_path / "repo")
    cache = str(tmp_path / "cache")
    bundle = get_model("MLP", input_dim=4)
    publish_model(bundle, repo)
    # strip the hash from the manifest (hashless deployment)
    import json
    mpath = os.path.join(repo, "MANIFEST.json")
    with open(mpath) as f:
        entries = json.load(f)
    for e in entries:
        e["hash"] = ""
    with open(mpath, "w") as f:
        json.dump(entries, f)

    dl = ModelDownloader(repo, cache_dir=cache)
    path = dl.download_by_name("MLP")
    assert os.path.exists(path + ".sha256")
    good = open(path, "rb").read()
    # second hit serves the verified cache
    assert dl.download_by_name("MLP") == path

    # truncate the cached file: next download must detect + refetch
    with open(path, "wb") as f:
        f.write(good[: len(good) // 2])
    path2 = dl.download_by_name("MLP")
    assert open(path2, "rb").read() == good
    load_bundle_file(path2)  # loads cleanly

    # sidecar missing entirely → refuse the cache, refetch
    os.remove(path + ".sha256")
    with open(path, "wb") as f:
        f.write(b"garbage")
    path3 = dl.download_by_name("MLP")
    assert open(path3, "rb").read() == good


def test_unroll_batch_fast_path_matches_per_row():
    t = rand_images(5)
    u = UnrollImage(input_col="image", output_col="f", scale=1 / 255.0,
                    offset=-0.5, to_rgb=True)
    out = u.transform(t)["f"]
    for i, v in enumerate(t["image"]):
        want = imgops.unroll(np.asarray(v["data"]), to_rgb=True,
                             scale=1 / 255.0, offset=-0.5).reshape(-1)
        np.testing.assert_allclose(out[i], want, atol=1e-6)


def test_unroll_mixed_shapes_and_none_rows():
    r = np.random.default_rng(3)
    rows = [make_image("a", r.integers(0, 255, (8, 8, 3))),
            None,
            make_image("b", r.integers(0, 255, (6, 10, 3)))]
    t = DataTable({"image": rows})
    out = UnrollImage(input_col="image", output_col="f").transform(t)["f"]
    assert out[1] is None
    assert out[0].shape == (3 * 8 * 8,)
    assert out[2].shape == (3 * 6 * 10,)


def test_image_transformer_threaded_matches_sequential():
    from mmlspark_tpu.core import config as cfg
    t = rand_images(8)
    tr = ImageTransformer().resize(12, 14).flip(1)
    cfg.set("image_threads", 1)
    try:
        seq = tr.transform(t)["image"]
    finally:
        cfg.reset("image_threads")
    par = tr.transform(t)["image"]  # default: thread pool
    for a, b in zip(seq, par):
        np.testing.assert_array_equal(a["data"], b["data"])


def test_unroll_uniform_grayscale_fast_path():
    r = np.random.default_rng(4)
    rows = [make_image("g", r.integers(0, 255, (9, 7))) for _ in range(3)]
    t = DataTable({"image": rows})
    out = UnrollImage(input_col="image", output_col="f").transform(t)["f"]
    assert all(v.shape == (9 * 7,) for v in out)
    # single-row column too
    t1 = DataTable({"image": rows[:1]})
    out1 = UnrollImage(input_col="image", output_col="f").transform(t1)["f"]
    np.testing.assert_allclose(out1[0], out[0])

"""Worker script for the multi-process multi-host training tests.

Launched via ``mmlspark_tpu.tools.launch`` (the pod-launcher analog of the
reference's never-wired multi-node MPI stub,
cntk-train/src/main/scala/CommandBuilders.scala:95-117): coordinator /
world-size / rank arrive through the ``MMLSPARK_TPU_*`` env vars the
launcher sets, and ``distributed_init()`` reads them back. Each process
joins the ``jax.distributed`` world, feeds ONLY its own shard of the
dataset through ``Trainer.fit_arrays``, and writes the loss trajectory +
a params checksum into ``$MULTIHOST_OUT_DIR/out_<pid>.json``.
"""

import multihost_env  # noqa: F401  (env setup BEFORE jax import)

import jax

multihost_env.pin_platform()

import numpy as np


def main() -> None:
    from mmlspark_tpu.utils.env import distributed_init
    distributed_init()  # env-driven (launcher wiring)
    pid = jax.process_index()
    nproc = jax.process_count()

    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    from mmlspark_tpu.train import TrainConfig, Trainer

    # deterministic dataset; THIS process holds only its contiguous shard.
    # With nproc=2 the split is equal (60/60); with nproc=4 the shards are
    # deliberately unequal (40/30/30/20) to exercise the zero-weight
    # shard-padding path
    r = np.random.default_rng(0)
    x = r.normal(size=(120, 8)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    if nproc == 2:
        bounds = [0, 60, 120]
    else:
        bounds = np.concatenate([[0], np.cumsum([40, 30, 30, 20])]).tolist()
    lo, hi = bounds[pid], bounds[pid + 1]
    x_local, y_local = x[lo:hi], y[lo:hi]

    mesh = make_mesh(MeshSpec(dp=-1))  # global mesh over all processes
    cfg = TrainConfig(batch_size=40, epochs=4, learning_rate=5e-3,
                      log_every=1, donate_state=False)
    tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
    tr.fit_arrays(x_local, y_local)

    # params are fully replicated after training; checksum must agree
    # across processes (the all-reduce proof)
    checksum = multihost_env.params_checksum(tr.params)

    # ---- streamed training with UNEQUAL per-process batch counts ----
    # process 0 streams 3 chunks, later processes stream 5; the liveness
    # sync must feed zero-weight filler on the short side instead of
    # deadlocking
    def source():
        n_chunks = 3 if pid == 0 else 5
        for c in range(n_chunks):
            r2 = np.random.default_rng(100 + 10 * pid + c)
            xs = r2.normal(size=(8, 8)).astype(np.float32)
            ys = ((xs[:, 0] > 0) ^ (xs[:, 1] > 0)).astype(np.int64)
            yield xs, ys

    cfg2 = TrainConfig(batch_size=8, epochs=2, learning_rate=5e-3,
                       log_every=1, donate_state=False)
    tr2 = Trainer(MLP(features=(16,), num_outputs=2), cfg2, mesh=mesh)
    tr2.fit_stream(source, input_spec=(8,))

    multihost_env.write_result(pid, {
        "pid": pid, "nproc": nproc, "losses": tr.history,
        "steps": int(tr.state["step"]),
        "checksum": checksum,
        "stream_steps": int(tr2.state["step"]),
        "stream_checksum": multihost_env.params_checksum(tr2.params)})


if __name__ == "__main__":
    main()

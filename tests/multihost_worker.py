"""Worker script for the 2-process multi-host training test.

Each process joins the jax.distributed world (2 virtual CPU devices per
process → a 4-device global mesh), feeds ONLY its own shard of the dataset
through ``Trainer.fit_arrays``, and prints the loss trajectory + a params
checksum as one JSON line. Run by tests/test_multihost.py; out-does the
reference's never-wired multi-node MPI stub
(cntk-train/src/main/scala/CommandBuilders.scala:95-117).
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])
    from mmlspark_tpu.utils.env import distributed_init
    distributed_init(coordinator_address=f"localhost:{port}",
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and jax.device_count() == 4

    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    from mmlspark_tpu.train import TrainConfig, Trainer

    # deterministic dataset; THIS process holds only rows [pid*60, pid*60+60)
    r = np.random.default_rng(0)
    x = r.normal(size=(120, 8)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    x_local, y_local = x[pid * 60:(pid + 1) * 60], y[pid * 60:(pid + 1) * 60]

    mesh = make_mesh(MeshSpec(dp=-1))  # global 4-device mesh
    cfg = TrainConfig(batch_size=40, epochs=4, learning_rate=5e-3,
                      log_every=1, donate_state=False)
    tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
    tr.fit_arrays(x_local, y_local)

    # params are fully replicated after training; checksum must agree
    # across processes (the all-reduce proof)
    leaves = jax.tree_util.tree_leaves(tr.params)
    checksum = float(sum(float(np.asarray(l).sum()) for l in leaves))

    # ---- streamed training with UNEQUAL per-process batch counts ----
    # process 0 streams 3 chunks, process 1 streams 5; the liveness sync
    # must feed zero-weight filler on the short side instead of deadlocking
    def source():
        n_chunks = 3 if pid == 0 else 5
        for c in range(n_chunks):
            r2 = np.random.default_rng(100 + 10 * pid + c)
            xs = r2.normal(size=(8, 8)).astype(np.float32)
            ys = ((xs[:, 0] > 0) ^ (xs[:, 1] > 0)).astype(np.int64)
            yield xs, ys

    cfg2 = TrainConfig(batch_size=8, epochs=2, learning_rate=5e-3,
                       log_every=1, donate_state=False)
    tr2 = Trainer(MLP(features=(16,), num_outputs=2), cfg2, mesh=mesh)
    tr2.fit_stream(source, input_spec=(8,))
    leaves2 = jax.tree_util.tree_leaves(tr2.params)
    checksum2 = float(sum(float(np.asarray(l).sum()) for l in leaves2))

    print(json.dumps({"pid": pid, "losses": tr.history,
                      "steps": int(tr.state["step"]),
                      "checksum": checksum,
                      "stream_steps": int(tr2.state["step"]),
                      "stream_checksum": checksum2}), flush=True)


if __name__ == "__main__":
    main()

"""Serving semantics: the online model server must be *boring* —

* served outputs are bit-identical to offline ``PipelineModel.transform``
  for the same rows, regardless of how requests were packed into buckets;
* a burst of mixed-size requests compiles at most ``len(buckets)``
  programs (asserted via the jit compile-cache counter hook);
* overload and deadline paths return typed errors (``Overloaded``,
  ``DeadlineExceeded``) — never a partial result;
* shutdown drains: every admitted request is answered, and no batcher
  thread survives ``close()``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.retry import RetryPolicy
from mmlspark_tpu.core.schema import make_image
from mmlspark_tpu.core.stage import LambdaTransformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import MLP, get_model
from mmlspark_tpu.serve import (
    THREAD_PREFIX, BadRequest, Client, DeadlineExceeded, ModelLoadError,
    ModelNotFound, ModelServer, Overloaded, ServeConfig, ServerClosed,
)
from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage


def mlp_bundle(in_dim=6, out_dim=4, seed=0):
    module = MLP(features=(8,), num_outputs=out_dim)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, in_dim), np.float32))["params"]
    return ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(in_dim,),
        output_names=("features", "logits"))


def vector_table(rows):
    return DataTable({"x": list(rows)})


def image_pipeline(seed=0):
    """The canonical fused chain: resize → unroll → score (3 device
    stages, ONE compiled program through the planner)."""
    stages = [
        ImageTransformer().resize(32, 32),
        UnrollImage(input_col="image", output_col="image_vec"),
        JaxModel(model=get_model("ConvNet_CIFAR10", widths=(8, 16),
                                 dense_width=32, seed=seed),
                 input_col="image_vec", output_col="scores"),
    ]
    return PipelineModel(stages)


def image_table(n, hw=40, seed=0):
    r = np.random.default_rng(seed)
    return DataTable({"image": [
        make_image(f"p{k}", r.integers(0, 255, (hw, hw, 3)))
        for k in range(n)]})


def sleepy_model(delay_s, out_col="out"):
    """Host-path model whose transform takes a known wall time."""
    def fn(table):
        time.sleep(delay_s)
        return table.with_column(
            out_col, np.asarray(table["x"], dtype=object))
    return LambdaTransformer(fn=fn)


# ---- parity: served == offline, regardless of packing ----


class TestParity:
    def test_single_stage_bit_identical_across_packings(self):
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(0)
        rows = rng.normal(size=(40, 6)).astype(np.float32)
        offline = jm.transform(vector_table(rows))

        with ModelServer(ServeConfig(buckets=(1, 4, 16),
                                     max_queue=128)) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]))
            # mixed request sizes force every packing shape
            sizes = [1, 2, 3, 5, 1, 4, 7, 1, 16, 2, 3, 5]
            handles, spans = [], []
            off = 0
            for n in sizes:
                if off + n > len(rows):
                    off = 0
                handles.append(server.submit(
                    "mlp", vector_table(rows[off:off + n])))
                spans.append((off, n))
                off += n
            for h, (off, n) in zip(handles, spans):
                out = h.result(timeout=60)
                assert len(out) == n
                for k in range(n):
                    assert np.array_equal(
                        np.asarray(out["scores"][k]),
                        np.asarray(offline["scores"][off + k]))

    def test_fused_pipeline_bit_identical_across_packings(self):
        pm = image_pipeline()
        table = image_table(24)
        offline = pm.transform(table)
        with ModelServer(ServeConfig(buckets=(1, 4, 16),
                                     max_queue=64)) as server:
            server.add_model("pipe", pm, example=table.take(np.arange(1)))
            handles = [
                server.submit("pipe", table.take(np.arange(i, i + n)))
                for i, n in [(0, 1), (1, 3), (4, 5), (9, 1), (10, 7),
                             (17, 2), (19, 5)]]
            outs = [h.result(timeout=120) for h in handles]
        row = 0
        for out in outs:
            for k in range(len(out)):
                assert np.array_equal(np.asarray(out["scores"][k]),
                                      np.asarray(offline["scores"][row]))
                row += 1
        assert row == 24

    def test_host_only_model_serves_through_fallback(self):
        # a pure-host transformer serves through the same batcher (no
        # async dispatch, same semantics)
        model = sleepy_model(0.0)
        rows = np.arange(6, dtype=np.float64)
        with ModelServer(ServeConfig(buckets=(1, 4),
                                     max_queue=16)) as server:
            server.add_model("host", model)
            out = server.predict("host", vector_table(rows[:3]),
                                 timeout=30)
            assert list(out["out"]) == list(rows[:3])


# ---- the bucket ladder bounds compilation ----


class TestCompileBound:
    def test_warmup_compiles_exactly_the_ladder(self):
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        buckets = (1, 4, 16)
        with ModelServer(ServeConfig(buckets=buckets)) as server:
            server.add_model("mlp", jm, example=vector_table(
                np.zeros((1, 6), np.float32)))
            programs = server.compiled_programs("mlp")
            # one program per *distinct dp-rounded* bucket shape: under
            # the 8-virtual-device test mesh buckets 1 and 4 both round
            # to one 8-row shard shape, so the count can be below
            # len(buckets) — never above it
            assert programs is None or 1 <= programs <= len(buckets)

    def test_mixed_size_burst_compiles_at_most_len_buckets(self):
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(1)
        rows = rng.normal(size=(64, 6)).astype(np.float32)
        buckets = (1, 4, 16)
        with ModelServer(ServeConfig(buckets=buckets,
                                     max_queue=256)) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]))
            sizes = [1, 2, 3, 4, 5, 8, 13, 16, 1, 6, 11, 2, 9, 16, 7, 1]
            handles = [server.submit("mlp", vector_table(
                rows[:n])) for n in sizes]
            for h in handles:
                h.result(timeout=60)
            programs = server.compiled_programs("mlp")
            snap = server.stats("mlp").snapshot()
        # the compile-counter hook: the jitted composite's own cache
        assert programs is None or programs <= len(buckets), programs
        # and the seam-counted observable: distinct dispatched shapes
        assert snap["distinct_batch_shapes"] <= len(buckets)


# ---- admission control and deadlines ----


class TestAdmission:
    def test_queue_full_returns_typed_overloaded(self):
        model = sleepy_model(0.15)
        with ModelServer(ServeConfig(buckets=(1,), max_queue=2,
                                     warmup=False)) as server:
            server.add_model("slow", model)
            accepted, rejected = [], 0
            for i in range(8):
                try:
                    accepted.append(server.submit(
                        "slow", vector_table(np.arange(1.0))))
                except Overloaded as e:
                    rejected += 1
                    assert e.model == "slow" and e.max_queue == 2
            assert rejected >= 1, "queue never filled"
            for h in accepted:
                assert len(h.result(timeout=30)) == 1
            snap = server.stats("slow").snapshot()
            assert snap["rejected_overload"] == rejected
            assert snap["completed"] == len(accepted)

    def test_deadline_expiry_in_queue_is_cancelled_before_dispatch(self):
        model = sleepy_model(0.3)
        with ModelServer(ServeConfig(buckets=(1,), max_queue=8,
                                     warmup=False)) as server:
            server.add_model("slow", model)
            first = server.submit("slow", vector_table(np.arange(1.0)))
            # wait until the first request is actually dispatched, so the
            # second provably sits in the queue past its deadline
            deadline = time.monotonic() + 5
            while first._dispatched_at is None:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            doomed = server.submit("slow", vector_table(np.arange(1.0)),
                                   deadline_ms=50)
            # don't await `doomed` yet: the BATCHER must observe the
            # expiry at pack time and cancel before dispatch
            assert len(first.result(timeout=30)) == 1
            wait_until = time.monotonic() + 5
            while not doomed.done:
                assert time.monotonic() < wait_until
                time.sleep(0.005)
            with pytest.raises(DeadlineExceeded) as exc:
                doomed.result(timeout=1)
            assert exc.value.where == "queued"
            snap = server.stats("slow").snapshot()
            assert snap["expired_deadline"] == 1
            assert doomed._dispatched_at is None  # cancelled pre-dispatch

    def test_inflight_deadline_returns_timeout_never_partial(self):
        model = sleepy_model(0.3)
        with ModelServer(ServeConfig(buckets=(1,), max_queue=8,
                                     warmup=False)) as server:
            server.add_model("slow", model)
            h = server.submit("slow", vector_table(np.arange(1.0)),
                              deadline_ms=100)
            with pytest.raises(DeadlineExceeded) as exc:
                h.result()
            assert exc.value.where in ("queued", "in-flight")
            # the batch completes later; its result must be discarded —
            # re-asking can only re-raise, never hand back data
            time.sleep(0.4)
            with pytest.raises(DeadlineExceeded):
                h.result()
            snap = server.stats("slow").snapshot()
            assert snap["timed_out"] >= 1

    def test_row_count_changing_model_fails_batch_never_misattributes(
            self):
        # a model that drops rows breaks the per-request split: offsets
        # would shift and neighbors would silently get each other's rows.
        # The whole batch must fail with a typed error instead
        def drop_first(table):
            import numpy as _np
            keep = _np.arange(1, len(table)) if len(table) > 1 \
                else _np.arange(len(table))
            return table.take(keep).with_column(
                "out", np.asarray(table["x"][len(table) - len(keep):],
                                  dtype=object))
        model = LambdaTransformer(fn=drop_first)
        with ModelServer(ServeConfig(buckets=(4,), max_queue=8,
                                     warmup=False)) as server:
            server.add_model("dropper", model)
            handles = [server.submit("dropper",
                                     vector_table(np.arange(2.0)))
                       for _ in range(2)]
            for h in handles:
                with pytest.raises(BadRequest, match="row count"):
                    h.result(timeout=30)
            assert server.stats("dropper").snapshot()["failed"] == 2

    def test_client_timeout_is_terminal_not_a_hang(self):
        # a give-up is final: repeat result() calls re-raise immediately
        # instead of blocking forever on an event the discarded
        # resolution will never set (and timed_out counts the transition
        # once, not every retry)
        model = sleepy_model(0.3)
        with ModelServer(ServeConfig(buckets=(1,), max_queue=8,
                                     warmup=False)) as server:
            server.add_model("slow", model)
            h = server.submit("slow", vector_table(np.arange(1.0)))
            with pytest.raises(TimeoutError):
                h.result(timeout=0.05)
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                h.result()  # no timeout arg: must NOT wait forever
            assert time.monotonic() - t0 < 1.0
            time.sleep(0.4)  # batch completes; result stays discarded
            with pytest.raises(TimeoutError):
                h.result()
            assert server.stats("slow").snapshot()["timed_out"] == 1

    @pytest.mark.parametrize("bad_rows", [
        lambda rng: DataTable({"wrong": [rng.normal(
            size=6).astype(np.float32)]}),     # wrong column name
        lambda rng: DataTable({"x": [rng.normal(
            size=100).astype(np.float32)]}),   # same column, wrong width
    ], ids=["wrong-column", "wrong-shape"])
    def test_mismatched_request_fails_alone(self, bad_rows):
        # a request with the wrong columns OR the wrong per-row layout is
        # never packed with (and can never fail) well-formed neighbors
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(7)
        rows = rng.normal(size=(4, 6)).astype(np.float32)
        with ModelServer(ServeConfig(buckets=(1, 8), max_queue=16,
                                     warmup=False)) as server:
            server.add_model("mlp", jm)
            good1 = server.submit("mlp", vector_table(rows[:2]))
            bad = server.submit("mlp", bad_rows(rng))
            good2 = server.submit("mlp", vector_table(rows[3:]))
            assert len(good1.result(timeout=30)) == 2
            assert len(good2.result(timeout=30)) == 1
            with pytest.raises(Exception) as exc:
                bad.result(timeout=30)
            assert not isinstance(exc.value, (DeadlineExceeded,
                                              TimeoutError))

    def test_bad_requests_are_typed(self):
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        with ModelServer(ServeConfig(buckets=(1, 4),
                                     warmup=False)) as server:
            server.add_model("mlp", jm)
            with pytest.raises(BadRequest):  # empty
                server.submit("mlp", DataTable({"x": []}))
            with pytest.raises(BadRequest):  # larger than the top bucket
                server.submit("mlp", vector_table(
                    np.zeros((5, 6), np.float32)))
            with pytest.raises(ModelNotFound):
                server.submit("nope", vector_table(
                    np.zeros((1, 6), np.float32)))


# ---- lifecycle ----


class TestLifecycle:
    def test_drain_on_shutdown_answers_all_admitted(self):
        model = sleepy_model(0.02)
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=64,
                                         warmup=False))
        server.add_model("slow", model)
        handles = [server.submit("slow", vector_table(np.arange(1.0)))
                   for _ in range(10)]
        server.close(drain=True)  # blocks until the worker drained
        for h in handles:
            assert len(h.result(timeout=1)) == 1
        snap = server.stats("slow").snapshot()
        assert snap["completed"] == 10
        with pytest.raises(ServerClosed):
            server.submit("slow", vector_table(np.arange(1.0)))

    def test_abort_close_fails_queued_with_server_closed(self):
        model = sleepy_model(0.2)
        server = ModelServer(ServeConfig(buckets=(1,), max_queue=16,
                                         warmup=False))
        server.add_model("slow", model)
        handles = [server.submit("slow", vector_table(np.arange(1.0)))
                   for _ in range(6)]
        server.close(drain=False)
        outcomes = []
        for h in handles:
            try:
                h.result(timeout=5)
                outcomes.append("ok")
            except ServerClosed:
                outcomes.append("closed")
        assert "closed" in outcomes  # queued work was failed, not served

    def test_no_leaked_threads_after_close(self, assert_no_leaked_threads):
        from conftest import thread_names
        assert_no_leaked_threads(THREAD_PREFIX, timeout=1.0)
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        server = ModelServer(ServeConfig(buckets=(1, 4)))
        server.add_model("mlp", jm,
                         example=vector_table(np.zeros((1, 6), np.float32)))
        server.predict("mlp", vector_table(np.zeros((2, 6), np.float32)),
                       timeout=30)
        assert thread_names(THREAD_PREFIX) != []
        server.close()
        assert_no_leaked_threads(THREAD_PREFIX)


# ---- load-time validation (the analyzer gate) ----


class TestLoadValidation:
    def test_model_not_set_fails_load_fast(self):
        with ModelServer(ServeConfig(warmup=False)) as server:
            with pytest.raises(ModelLoadError) as exc:
                server.add_model("broken", JaxModel(
                    input_col="x", output_col="scores"))
            assert "model-not-set" in str(exc.value)
            assert server.models() == []

    def test_schema_size_mismatch_fails_load_fast(self):
        from mmlspark_tpu.analysis import ColumnInfo, TableSchema
        jm = JaxModel(model=mlp_bundle(in_dim=6), input_col="x",
                      output_col="scores")
        schema = TableSchema({"x": ColumnInfo.vector(5, "float32")})
        with ModelServer(ServeConfig(warmup=False)) as server:
            with pytest.raises(ModelLoadError) as exc:
                server.add_model("mlp", jm, schema=schema)
            assert "input-size-mismatch" in str(exc.value)


# ---- the HTTP front end ----


@pytest.fixture()
def http_mlp_server():
    from mmlspark_tpu.serve.http import start_http_server
    server = ModelServer(ServeConfig(buckets=(1, 4, 16), max_queue=64))
    server.add_model("mlp", mlp_bundle())  # bundle wrap: input → scores
    httpd = start_http_server(server, host="127.0.0.1", port=0)
    yield server, f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()
    server.close()


def _post_json(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestHTTP:
    def test_json_predict_matches_offline(self, http_mlp_server):
        server, base = http_mlp_server
        rng = np.random.default_rng(3)
        x = rng.normal(size=(3, 6)).astype(np.float32)
        status, body = _post_json(
            f"{base}/v1/models/mlp:predict",
            {"rows": [{"input": r.tolist()} for r in x],
             "columns": ["scores"]})
        assert status == 200 and len(body["rows"]) == 3
        jm = JaxModel(model=mlp_bundle(), input_col="input",
                      output_col="scores")
        ref = jm.transform(DataTable({"input": list(x)}))
        for k in range(3):
            assert np.allclose(body["rows"][k]["scores"],
                               np.asarray(ref["scores"][k]), atol=1e-6)

    def test_health_models_and_stats_endpoints(self, http_mlp_server):
        _server, base = http_mlp_server
        with urllib.request.urlopen(f"{base}/healthz") as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(f"{base}/v1/models") as r:
            assert json.loads(r.read())["models"] == ["mlp"]
        with urllib.request.urlopen(f"{base}/v1/stats") as r:
            stats = json.loads(r.read())
        assert "mlp" in stats and "admitted" in stats["mlp"]

    def test_unknown_model_is_404_and_bad_body_is_400(self,
                                                      http_mlp_server):
        _server, base = http_mlp_server
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post_json(f"{base}/v1/models/nope:predict",
                       {"rows": [{"input": [0.0] * 6}]})
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post_json(f"{base}/v1/models/mlp:predict", {"rows": []})
        assert exc.value.code == 400

    def test_arrow_round_trip(self, http_mlp_server):
        pa = pytest.importorskip("pyarrow")
        import io
        _server, base = http_mlp_server
        rng = np.random.default_rng(4)
        x = rng.normal(size=(2, 6)).astype(np.float32)
        arrow = DataTable({"input": list(x)}).to_arrow()
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, arrow.schema) as writer:
            writer.write_table(arrow)
        ctype = "application/vnd.apache.arrow.stream"
        req = urllib.request.Request(
            f"{base}/v1/models/mlp:predict", data=sink.getvalue(),
            headers={"Content-Type": ctype, "Accept": ctype})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            out = DataTable.from_arrow(
                pa.ipc.open_stream(io.BytesIO(resp.read())).read_all()
                .combine_chunks().to_batches()[0])
        assert "scores" in out and len(out) == 2


class TestRetryAfterHeader:
    """errors.py tells clients to "retry with backoff"; the HTTP front
    must give them something to act on — the Retry-After header, on
    both backpressure paths (429 Overloaded, drain-time 503)."""

    def test_429_overloaded_carries_retry_after(self):
        from mmlspark_tpu.serve.http import start_http_server
        server = ModelServer(ServeConfig(buckets=(1,), max_queue=1,
                                         max_inflight=1, warmup=False,
                                         retry_after_s=2.5))
        httpd = start_http_server(server, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            server.add_model("slow", sleepy_model(1.0))
            # saturate the pipeline: lane in-flight + scheduler-held +
            # the 1-deep queue = 3 accepted; while the first batch
            # sleeps, the queue slot stays occupied and the HTTP submit
            # must see 429
            handles = []
            deadline = time.monotonic() + 5
            while len(handles) < 3 and time.monotonic() < deadline:
                try:
                    handles.append(server.submit(
                        "slow", vector_table(np.arange(1.0))))
                except Overloaded:
                    time.sleep(0.01)
            assert len(handles) == 3, "pipeline never saturated"
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post_json(f"{base}/v1/models/slow:predict",
                           {"rows": [{"x": 0.0}]})
            assert exc.value.code == 429
            # whole seconds, rounded UP from retry_after_s=2.5
            assert exc.value.headers["Retry-After"] == "3"
            for h in handles:
                h.result(timeout=30)
        finally:
            httpd.shutdown()
            httpd.server_close()
            server.close()

    def test_drain_time_healthz_503_carries_retry_after(
            self, http_mlp_server):
        server, base = http_mlp_server
        with urllib.request.urlopen(f"{base}/healthz") as r:
            assert r.status == 200
            assert r.headers.get("Retry-After") is None  # ready: none
        server.close(drain=True)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/healthz")
        assert exc.value.code == 503
        assert exc.value.headers["Retry-After"] == "1"  # the default
        body = json.loads(exc.value.read())
        assert body["draining"] is True


class _Resolved:
    def __init__(self, table):
        self._table = table

    def result(self, timeout=None):
        return self._table


class _ScriptedServer:
    """Submit/predict fail `failures` times with `exc`, then succeed —
    the deterministic client-retry surface (no timing, no threads)."""

    def __init__(self, failures, exc):
        self.failures = failures
        self.exc = exc
        self.calls = 0

    def predict(self, model, rows, deadline_ms=None, timeout=None):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc
        return rows

    def submit(self, model, rows, deadline_ms=None):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc
        return _Resolved(rows)


class TestClientRetry:
    """Client.predict/predict_async retry= (core/retry.py): transient
    serving faults only — never DeadlineExceeded/BadRequest."""

    FAST = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0,
                       retry_on=(Overloaded,))

    def test_retried_to_success(self):
        from mmlspark_tpu.serve.errors import LaneFailed
        for exc in (Overloaded("m", 8, 8), LaneFailed("m", 0, "died")):
            stub = _ScriptedServer(2, exc)
            out = Client(stub).predict("m", vector_table(np.arange(1.0)),
                                       retry=True)
            assert stub.calls == 3 and len(out) == 1

    def test_budget_exhausted_raises_the_real_error(self):
        stub = _ScriptedServer(5, Overloaded("m", 8, 8))
        with pytest.raises(Overloaded):
            Client(stub).predict("m", vector_table(np.arange(1.0)),
                                 retry=self.FAST)
        assert stub.calls == 3  # max_attempts, then the typed error

    def test_non_retryable_passthrough(self):
        for exc in (BadRequest("nope"),
                    DeadlineExceeded("m", 100.0, "queued"),
                    ModelNotFound("m", [])):
            stub = _ScriptedServer(5, exc)
            with pytest.raises(type(exc)):
                Client(stub).predict("m", vector_table(np.arange(1.0)),
                                     retry=True)
            assert stub.calls == 1, f"{type(exc).__name__} was retried"

    def test_never_retry_wins_over_a_broad_caller_policy(self):
        from mmlspark_tpu.serve.errors import ServeError
        broad = RetryPolicy(max_attempts=5, base_delay_s=0.0, jitter=0.0,
                            retry_on=(ServeError,))
        stub = _ScriptedServer(5, DeadlineExceeded("m", 100.0, "queued"))
        with pytest.raises(DeadlineExceeded):
            Client(stub).predict("m", vector_table(np.arange(1.0)),
                                 retry=broad)
        assert stub.calls == 1
        # ...while genuinely transient faults DO use the broad budget
        stub = _ScriptedServer(4, Overloaded("m", 8, 8))
        out = Client(stub).predict("m", vector_table(np.arange(1.0)),
                                   retry=broad)
        assert stub.calls == 5 and len(out) == 1

    def test_predict_async_retries_submission_only(self):
        stub = _ScriptedServer(2, Overloaded("m", 8, 8))
        handle = Client(stub).predict_async(
            "m", vector_table(np.arange(1.0)), retry=True)
        assert stub.calls == 3
        assert len(handle.result()) == 1

    def test_default_off_and_client_wide_default(self):
        stub = _ScriptedServer(1, Overloaded("m", 8, 8))
        with pytest.raises(Overloaded):
            Client(stub).predict("m", vector_table(np.arange(1.0)))
        stub = _ScriptedServer(1, Overloaded("m", 8, 8))
        client = Client(stub, retry=self.FAST)  # client-wide default
        out = client.predict("m", vector_table(np.arange(1.0)))
        assert stub.calls == 2 and len(out) == 1

    def test_retry_against_a_real_overloaded_server(self):
        """End-to-end: a 1-deep queue under a slow model rejects, the
        retrying client eventually lands every request."""
        model = sleepy_model(0.05)
        with ModelServer(ServeConfig(buckets=(1,), max_queue=1,
                                     warmup=False)) as server:
            server.add_model("slow", model)
            client = Client(server, retry=RetryPolicy(
                max_attempts=8, base_delay_s=0.05, max_delay_s=0.4,
                jitter=0.0, retry_on=(Overloaded,)))
            outs = []
            for _ in range(4):
                outs.append(client.predict(
                    "slow", vector_table(np.arange(1.0)), timeout=30))
            assert all(len(o) == 1 for o in outs)


class TestHealthAndSLOSurfaces:
    def test_healthz_is_ready_and_drain_aware(self, http_mlp_server):
        server, base = http_mlp_server
        with urllib.request.urlopen(f"{base}/healthz") as r:
            body = json.loads(r.read())
        assert r.status == 200
        assert body["status"] == "ok" and body["ready"] is True
        assert body["draining"] is False and body["models"] == ["mlp"]
        assert body["model_health"]["mlp"]["state"] == "ok"
        # draining: readiness drops to 503 while the body keeps
        # answering
        server.close(drain=True)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/healthz")
        assert exc.value.code == 503
        drained = json.loads(exc.value.read())
        assert drained["status"] == "draining"
        assert drained["ready"] is False and drained["draining"] is True
        # liveness is a separate surface: /livez stays 200 through the
        # drain, so a restart probe never kills a draining server
        with urllib.request.urlopen(f"{base}/livez") as r:
            assert r.status == 200
            assert json.loads(r.read()) == {"alive": True}

    def test_slo_endpoint_reports_burn_and_budget(self, http_mlp_server):
        server, base = http_mlp_server
        rng = np.random.default_rng(5)
        for _ in range(4):
            server.predict("mlp", DataTable({"input": list(
                rng.normal(size=(2, 6)).astype(np.float32))}))
        with urllib.request.urlopen(f"{base}/slo") as r:
            body = json.loads(r.read())
        slo = body["mlp"]
        assert slo["slo"]["objective"] == 0.999
        assert slo["budget_remaining"] == 1.0  # nothing failed
        assert slo["counters"]["completed"] == 4
        assert slo["health"]["state"] == "ok"
        assert slo["queue_depth"] == 0
        # a second poll is a second burn sample over real deltas: the
        # quiet window has no verdict, never a crash
        with urllib.request.urlopen(f"{base}/slo") as r:
            again = json.loads(r.read())
        assert again["mlp"]["burn_rate_short"] is None

    def test_unhealthy_model_fails_readiness(self):
        """Burn past the fast-burn threshold -> /healthz goes 503 with
        the unhealthy verdict (the state machine is wired to the real
        counters, not a synthetic status)."""
        from mmlspark_tpu.obs.slo import SLOSpec
        from mmlspark_tpu.serve.http import start_http_server
        # 50% objective, tiny short window, verdicts from 4 requests
        # up; long_window_s stays generous so the tracker's 2x-long
        # ring pruning can never drop the baseline sample on a slow box
        spec = SLOSpec(objective=0.5, window_s=0.05, long_window_s=10.0,
                       min_requests=4, fast_burn=1.5)
        server = ModelServer(ServeConfig(buckets=(1, 4), max_queue=64,
                                         slo=spec))
        httpd = start_http_server(server, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            server.add_model("mlp", mlp_bundle())
            with urllib.request.urlopen(f"{base}/healthz") as r:
                assert json.loads(r.read())["ready"] is True
            # every request fails: the bundle wants 6-wide vectors
            bad = vector_table(np.zeros((1, 3), np.float32))
            for _ in range(8):
                with pytest.raises(Exception):
                    server.predict("mlp", bad, timeout=30)
            time.sleep(0.06)  # let the short window age past window_s
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{base}/healthz")
            assert exc.value.code == 503
            body = json.loads(exc.value.read())
            assert body["model_health"]["mlp"]["state"] == "unhealthy"
            assert "burn" in body["model_health"]["mlp"]["reason"]
            # an alive-but-burning server must NOT fail liveness: a
            # restart would only amplify the incident
            with urllib.request.urlopen(f"{base}/livez") as r:
                assert r.status == 200
        finally:
            httpd.shutdown()
            httpd.server_close()
            server.close()

    def test_metrics_prometheus_content_negotiation(self,
                                                    http_mlp_server):
        server, base = http_mlp_server
        rng = np.random.default_rng(6)
        server.predict("mlp", DataTable({"input": list(
            rng.normal(size=(3, 6)).astype(np.float32))}))
        req = urllib.request.Request(
            f"{base}/metrics",
            headers={"Accept": "text/plain;version=0.0.4"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode("utf-8")
        assert "# TYPE serve_admitted counter" in text
        assert 'serve_admitted{model="mlp"} 1' in text
        assert 'serve_rows_dispatched{model="mlp"} 3' in text
        assert "# TYPE serve_e2e_ms summary" in text
        # the default stays the JSON snapshot, byte-compatible shape
        with urllib.request.urlopen(f"{base}/metrics") as r:
            body = json.loads(r.read())
        assert "metrics" in body and "models" in body
        assert body["models"]["mlp"]["admitted"] == 1
        assert body["models"]["mlp"]["rows_dispatched"] == 3


class TestObsEndpointsUnderTraffic:
    def test_metrics_and_trace_consistent_during_drain(self):
        """Satellite pin: /metrics and /trace polled from other threads
        while requests are in flight AND while drain-on-close runs must
        always answer (200, valid JSON, monotonic counters) and must
        never block the drain."""
        from mmlspark_tpu import obs
        from mmlspark_tpu.serve.http import start_http_server
        polls: list[tuple] = []
        stop = threading.Event()
        server = httpd = poller = None
        try:
            # everything that leaks on failure (global tracer flag,
            # batcher/HTTP threads) is created inside the try so a bind
            # error can't poison later tests in the session
            obs.enable()
            server = ModelServer(ServeConfig(
                buckets=(1, 4), max_queue=64,
                deadline_ms=None, warmup=False))
            httpd = start_http_server(server, host="127.0.0.1", port=0)
            base = f"http://127.0.0.1:{httpd.server_address[1]}"

            def poll_loop():
                while not stop.is_set():
                    for path in ("/metrics", "/trace", "/healthz",
                                 "/livez", "/slo"):
                        try:
                            with urllib.request.urlopen(
                                    base + path, timeout=10) as r:
                                polls.append((path, r.status,
                                              json.loads(r.read())))
                        except urllib.error.HTTPError as e:
                            # only the drain-aware readiness flip is
                            # legal
                            polls.append((path, e.code,
                                          json.loads(e.read())))
                    time.sleep(0.005)

            poller = threading.Thread(target=poll_loop, daemon=True)
            server.add_model("m", sleepy_model(0.03))
            rng = np.random.default_rng(7)
            rows = rng.normal(size=(16, 4)).astype(np.float32)
            handles = [server.submit("m", vector_table(rows[i:i + 1]))
                       for i in range(16)]
            poller.start()
            t0 = time.monotonic()
            server.close(drain=True)  # drains ~16 x 30 ms of work
            drain_s = time.monotonic() - t0
            for h in handles:  # every admitted request was answered
                assert len(h.result(timeout=1)) == 1
        finally:
            stop.set()
            if poller is not None and poller.ident is not None:
                poller.join(timeout=10)
            if httpd is not None:
                httpd.shutdown()
                httpd.server_close()
            if server is not None:
                server.close()
            obs.disable()
            obs.clear()
        assert drain_s < 20.0, f"drain took {drain_s:.1f}s — an obs " \
            "poll blocked the drain"
        metrics = [p for p in polls if p[0] == "/metrics"]
        traces = [p for p in polls if p[0] == "/trace"]
        healths = [p for p in polls if p[0] == "/healthz"]
        assert metrics and traces and healths, polls
        # every poll answered with valid JSON; /metrics and /trace and
        # /slo never fail, /healthz only ever flips to the typed 503
        for path, status, _body in polls:
            assert status == 200 or (path == "/healthz"
                                     and status == 503), (path, status)
        # counter consistency across concurrent snapshots: admitted and
        # completed are monotonic, and completed never exceeds admitted
        seen_admitted = seen_completed = 0
        for _path, _status, body in metrics:
            snap = body["models"].get("m")
            if snap is None:
                continue
            assert snap["completed"] <= snap["admitted"] == 16
            assert snap["admitted"] >= seen_admitted
            assert snap["completed"] >= seen_completed
            seen_admitted = snap["admitted"]
            seen_completed = snap["completed"]
        # the trace bodies are well-formed Chrome traces throughout
        for _path, _status, body in traces:
            assert isinstance(body["traceEvents"], list)


class TestStatsPreTraffic:
    def test_snapshot_safe_before_any_traffic(self):
        """Regression (obs satellite): a freshly created ServerStats —
        e.g. /v1/stats polled right after a model loads, before the first
        request — must snapshot cleanly: empty percentile windows report
        None, never an empty-array percentile or a zero division."""
        from mmlspark_tpu.serve.stats import ServerStats

        snap = ServerStats(model="pre-traffic").snapshot()
        assert snap["admitted"] == 0 and snap["completed"] == 0
        assert snap["batches"] == 0 and snap["rows_dispatched"] == 0
        assert snap["batch_occupancy_mean"] is None
        assert snap["e2e_ms"] is None
        assert snap["queue_wait_ms"] is None
        assert snap["device_ms"] is None
        assert snap["occupancy_by_bucket"] == {}
        assert snap["distinct_batch_shapes"] == 0
        import json
        json.dumps(snap)  # JSON-safe as served by the HTTP front end

    def test_snapshot_values_backed_by_obs_primitives(self):
        """ServerStats is re-backed by the shared obs metrics — the
        snapshot must stay value-compatible with the pre-obs class."""
        from mmlspark_tpu.serve.stats import ServerStats

        stats = ServerStats(window=8, model="m")
        for k in range(3):
            stats.record_admitted()
        stats.record_done(e2e_ms=10.0, queue_ms=2.0)
        stats.record_batch(bucket=8, occupancy=5, device_ms=4.0,
                           shapes=((8, 6),))
        stats.record_rejected()
        snap = stats.snapshot()
        assert snap["admitted"] == 3 and snap["completed"] == 1
        assert snap["rejected_overload"] == 1
        assert snap["rows_dispatched"] == 5 and snap["rows_padded"] == 3
        assert snap["occupancy_by_bucket"] == {8: 1}
        assert snap["batch_occupancy_mean"] == 5.0
        assert snap["e2e_ms"]["p50"] == 10.0 and snap["e2e_ms"]["n"] == 1
        assert snap["distinct_batch_shapes"] == 1
        # the per-instance registry exposes the same series for /metrics
        reg_snap = stats.registry.snapshot()
        assert reg_snap["counters"]["serve.admitted{model=m}"] == 3


# ---- sharded serving (serve.mesh): DP replicas, tp/pp segments, lockstep ----


from mmlspark_tpu.core.stage import (  # noqa: E402
    ArrayMeta, DeviceOp, DeviceStage, HasInputCol, HasOutputCol,
    Transformer,
)
from mmlspark_tpu.serve import ServeMeshSpec  # noqa: E402


class PipelinedTanh(Transformer, DeviceStage, HasInputCol, HasOutputCol):
    """Test-only pp-served model: L tanh blocks. The host ``transform``
    is the sequential reference; the mesh-aware device op runs the SAME
    blocks through ``parallel.pipeline.pipeline_apply`` on the segment's
    replica mesh (the pp serving tier), with the stacked layer axis
    placed over ``pp`` via the ``device_param_rules`` hook."""

    from mmlspark_tpu.core.params import Param
    layers = Param(default=None, is_complex=True,
                   doc="list of {'w','b'} numpy layer dicts")
    microbatches = Param(default=2, type_=int, doc="pipeline microbatches")

    def transform(self, table):
        x = table.column_matrix(self.input_col, dtype=np.float32)
        for layer in self.layers:
            x = np.tanh(x @ layer["w"] + layer["b"])
        return table.with_column(self.output_col, list(x))

    # -- DeviceStage --

    def device_cache_token(self):
        return (id(self.layers), self.microbatches, self.input_col,
                self.output_col)

    def _stacked(self):
        return {k: np.stack([np.asarray(layer[k], np.float32)
                             for layer in self.layers])
                for k in ("w", "b")}

    def _dim(self):
        return int(np.asarray(self.layers[0]["w"]).shape[0])

    def device_fn(self, meta):
        # mesh-less planning/shape probe: the sequential layer scan
        import jax
        import jax.numpy as jnp
        d = self._dim()
        if tuple(meta.shape) != (d,):
            return None

        def fwd(params, x):
            def body(h, layer):
                return jnp.tanh(h @ layer["w"] + layer["b"]), None
            h, _ = jax.lax.scan(body, x.astype(jnp.float32), params)
            return h

        return DeviceOp(fwd, ArrayMeta((d,), "float32"),
                        params=self._stacked())

    def device_fn_mesh(self, meta, mesh):
        if mesh.shape.get("pp", 1) == 1:
            return self.device_fn(meta)
        d = self._dim()
        if tuple(meta.shape) != (d,):
            return None
        m = int(self.microbatches)

        def fwd(params, x):
            import jax.numpy as jnp

            from mmlspark_tpu.parallel.pipeline import pipeline_apply

            def block(layer, h):
                return jnp.tanh(h @ layer["w"] + layer["b"])

            return pipeline_apply(block, params, x.astype(jnp.float32),
                                  mesh, num_microbatches=m)

        return DeviceOp(fwd, ArrayMeta((d,), "float32"),
                        params=self._stacked())

    def device_param_rules(self, path, leaf):
        from jax.sharding import PartitionSpec as P
        return P("pp")  # stacked layer axis over the pipeline stages


class CollectiveLeak(Transformer, DeviceStage, HasInputCol, HasOutputCol):
    """A served segment smuggling a MANUAL collective — what the
    load-time sharded SPMD audit must reject on a dp-replica mesh."""

    def transform(self, table):
        return table.with_column(
            self.output_col,
            list(table.column_matrix(self.input_col, dtype=np.float32)))

    def device_cache_token(self):
        return (self.input_col, self.output_col)

    def device_fn(self, meta):
        import jax.numpy as jnp

        def fwd(params, x):
            return x.astype(jnp.float32)

        return DeviceOp(fwd, ArrayMeta(tuple(meta.shape), "float32"),
                        params=())

    def device_fn_mesh(self, meta, mesh):
        from jax.sharding import PartitionSpec as P

        from mmlspark_tpu.parallel.mesh import shard_map

        def fwd(params, x):
            import jax

            def body(v):
                return jax.lax.psum(v, "pp")

            return shard_map(body, mesh=mesh, in_specs=(P(),),
                             out_specs=P(), check_vma=False)(
                                 x.astype(np.float32))

        return DeviceOp(fwd, ArrayMeta(tuple(meta.shape), "float32"),
                        params=())


def _score_rows(outs, spans):
    """request outputs -> {source row index: [score arrays seen]}."""
    seen: dict[int, list] = {}
    for out, (off, n) in zip(outs, spans):
        for k in range(n):
            seen.setdefault(off + k, []).append(
                np.asarray(out["scores"][k]))
    return seen


class TestShardedServing:
    def _serve_packed(self, mesh, sizes, rows, buckets=(1, 4, 16)):
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        with ModelServer(ServeConfig(buckets=buckets, max_queue=128,
                                     mesh=mesh)) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]))
            handles, spans, off = [], [], 0
            for n in sizes:
                if off + n > len(rows):
                    off = 0
                handles.append(server.submit(
                    "mlp", vector_table(rows[off:off + n])))
                spans.append((off, n))
                off += n
            outs = [h.result(timeout=120) for h in handles]
            snap = server.stats("mlp").snapshot()
            programs = server.compiled_programs("mlp")
        return outs, spans, snap, programs

    def test_dp_outputs_bit_identical_across_replica_counts_and_packings(
            self):
        """The acceptance pin: dp=N serving is bit-identical to
        single-chip (dp=1) serving for every packing and request
        interleaving, with compiled programs on the ladder per model."""
        rng = np.random.default_rng(11)
        rows = rng.normal(size=(40, 6)).astype(np.float32)
        sizes = [1, 2, 3, 5, 1, 4, 7, 1, 16, 2, 3, 5]
        reference: dict[int, np.ndarray] = {}
        for mesh, order in (("dp=1", sizes),
                            ("dp=2", list(reversed(sizes))),
                            ("dp=4", sizes)):
            outs, spans, snap, programs = self._serve_packed(
                mesh, order, rows)
            assert programs is None or programs <= 3, (mesh, programs)
            assert snap["distinct_batch_shapes"] <= 3
            dp = int(mesh.split("=")[1])
            assert set(snap["replicas"]) <= set(range(dp))
            assert sum(v["batches"] for v in snap["replicas"].values()) \
                == snap["batches"]
            for idx, arrays in _score_rows(outs, spans).items():
                for arr in arrays:
                    ref = reference.setdefault(idx, arr)
                    assert np.array_equal(ref, arr), (
                        f"{mesh}: row {idx} diverged from dp=1 serving")

    def test_dp_fanout_spreads_load_and_labels_replica_stats(self):
        rng = np.random.default_rng(12)
        rows = rng.normal(size=(64, 6)).astype(np.float32)
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        with ModelServer(ServeConfig(buckets=(4,), max_queue=128,
                                     mesh="dp=4")) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]))
            handles = [server.submit("mlp", vector_table(rows[i:i + 4]))
                       for i in range(0, 64, 4)]
            for h in handles:
                h.result(timeout=120)
            snap = server.stats("mlp").snapshot()
            reg = server.stats("mlp").registry.snapshot()["counters"]
        assert snap["batches"] == 16
        assert len(snap["replicas"]) >= 2, (
            f"least-loaded scheduling never fanned out: "
            f"{snap['replicas']}")
        for idx, rep in snap["replicas"].items():
            assert rep["batches"] >= 1
            assert rep["device_ms"] is not None
            # the replica label is a first-class series in the registry
            assert reg[f"serve.replica_batches{{model=mlp,replica={idx}}}"] \
                == rep["batches"]

    def test_tp_segment_matches_offline_transform(self):
        """Model-parallel tier: a tp=2-sharded serve segment (params
        column-sharded, GSPMD resharding only) equals the offline
        transform within the plan parity tolerance."""
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(13)
        rows = rng.normal(size=(24, 6)).astype(np.float32)
        offline = jm.transform(vector_table(rows))
        with ModelServer(ServeConfig(buckets=(1, 8), max_queue=64,
                                     mesh="dp=1,tp=2")) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]))
            handles = [server.submit("mlp", vector_table(rows[i:i + 8]))
                       for i in range(0, 24, 8)]
            outs = [h.result(timeout=120) for h in handles]
            snap = server.snapshot()["mlp"]
        assert snap["mesh"] == "dp=1,tp=2"
        row = 0
        for out in outs:
            for k in range(len(out)):
                assert np.allclose(np.asarray(out["scores"][k]),
                                   np.asarray(offline["scores"][row]),
                                   atol=1e-5)
                row += 1
        assert row == 24

    def test_shard_params_override_reaches_the_replica_lanes(self):
        """add_model(shard_params=...) overrides every replica's param
        placement — the explicit-placement escape hatch for models the
        generic rules misplace."""
        from mmlspark_tpu.parallel import mesh as mesh_lib
        calls = []

        def override(mesh, params):
            calls.append(dict(mesh.shape))
            return mesh_lib.param_shardings(mesh, params)

        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(17)
        rows = rng.normal(size=(8, 6)).astype(np.float32)
        offline = jm.transform(vector_table(rows))
        with ModelServer(ServeConfig(buckets=(8,), max_queue=16,
                                     mesh="dp=1,tp=2")) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]),
                             shard_params=override)
            out = server.predict("mlp", vector_table(rows), timeout=60)
        assert calls and all(c["tp"] == 2 for c in calls)
        for k in range(8):
            assert np.allclose(np.asarray(out["scores"][k]),
                               np.asarray(offline["scores"][k]),
                               atol=1e-5)

    def test_pp_segment_matches_offline_transform(self):
        """Pipeline-parallel tier: a pp=4 serve segment (stacked layers
        over the pp ring via pipeline_apply, under the same bucket
        ladder) equals the sequential host transform."""
        rng = np.random.default_rng(14)
        d, n_layers = 16, 8
        layers = [{"w": (rng.normal(size=(d, d)) / np.sqrt(d)
                         ).astype(np.float32),
                   "b": rng.normal(size=d).astype(np.float32) * 0.1}
                  for _ in range(n_layers)]
        stage = PipelinedTanh(layers=layers, microbatches=2,
                              input_col="x", output_col="y")
        rows = rng.normal(size=(16, d)).astype(np.float32)
        offline = stage.transform(vector_table(rows))
        with ModelServer(ServeConfig(buckets=(8,), max_queue=64,
                                     mesh="pp=4")) as server:
            server.add_model("pp", stage, example=vector_table(rows[:1]))
            handles = [server.submit("pp", vector_table(rows[i:i + 8]))
                       for i in range(0, 16, 8)]
            outs = [h.result(timeout=120) for h in handles]
            programs = server.compiled_programs("pp")
        assert programs is None or programs <= 1
        row = 0
        for out in outs:
            for k in range(len(out)):
                assert np.allclose(np.asarray(out["y"][k]),
                                   np.asarray(offline["y"][row]),
                                   atol=1e-5), f"row {row}"
                row += 1
        assert row == 16

    def test_mesh_that_does_not_divide_devices_is_typed_load_error(self):
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        for bad in ("dp=3", "dp=16", "dp=2,tp=3"):
            with ModelServer(ServeConfig(warmup=False)) as server:
                with pytest.raises(ModelLoadError, match="does not divide"):
                    server.add_model("mlp", jm, mesh=bad)
                assert server.models() == []

    def test_mesh_spec_parse_round_trip_and_errors(self):
        spec = ServeMeshSpec.parse("dp=4,tp=2")
        assert (spec.dp, spec.tp, spec.pp) == (4, 2, 1)
        assert spec.chips == 8 and spec.describe() == "dp=4,tp=2"
        assert ServeMeshSpec.parse({"dp": 2}).describe() == "dp=2"
        assert ServeMeshSpec.parse("dp=1,lockstep").lockstep is True
        for bad in ("dp", "dp=x", "sp=2"):
            with pytest.raises(ValueError):
                ServeMeshSpec.parse(bad)

    def test_lockstep_rejects_dp_fanout(self):
        """Lockstep serializes dispatch behind the drain fence, so a
        dp>1 fan-out could never be used — typed load error, no device
        work."""
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        with ModelServer(ServeConfig(warmup=False)) as server:
            with pytest.raises(ModelLoadError, match="lockstep"):
                server.add_model("mlp", jm, mesh="dp=2,lockstep")
            assert server.models() == []

    def test_compat_key_is_deterministic_and_keys_every_column(self):
        """The batch-compatibility key is a pure function of layout (the
        lockstep signature hashes it): ragged columns key by their full
        cell-by-cell layout WITHOUT dropping the other columns, so
        requests whose ragged columns agree but whose entry columns
        differ never coalesce."""
        from mmlspark_tpu.serve.batcher import _compat_key
        ragged = [np.zeros(3, np.float32), np.zeros(5, np.float32)]

        def key(width):
            return _compat_key(DataTable(
                {"x": [np.zeros(width, np.float32)] * 2,
                 "tags": list(ragged)}))

        assert key(4) == key(4)          # deterministic across tables
        assert key(4) != key(8)          # ragged col can't mask 'x'
        uniform = _compat_key(DataTable(
            {"x": [np.zeros(4, np.float32)] * 2,
             "tags": [np.zeros(3, np.float32)] * 2}))
        assert key(4) != uniform         # never packs with well-formed

    def test_sharded_audit_rejects_manual_collective_segment(self):
        from mmlspark_tpu.analysis import ColumnInfo, TableSchema
        stage = CollectiveLeak(input_col="x", output_col="y")
        schema = TableSchema({"x": ColumnInfo.vector(8, "float32")})
        with ModelServer(ServeConfig(warmup=False)) as server:
            with pytest.raises(ModelLoadError, match="SPMD"):
                server.add_model("leak", stage, schema=schema, mesh="dp=2")
            assert server.models() == []

    def test_lockstep_fences_and_agrees_every_dispatch(self):
        """Collective-lockstep serving: every dispatched batch passes the
        drain fence + signature agreement, in order (the multi-host
        discipline, exercised single-process on the dryrun mesh)."""
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(15)
        rows = rng.normal(size=(24, 6)).astype(np.float32)
        offline = jm.transform(vector_table(rows))
        with ModelServer(ServeConfig(buckets=(8,), max_queue=64,
                                     mesh="tp=2,lockstep")) as server:
            server.add_model("mlp", jm, example=vector_table(rows[:1]))
            handles = [server.submit("mlp", vector_table(rows[i:i + 8]))
                       for i in range(0, 24, 8)]
            outs = [h.result(timeout=120) for h in handles]
            coord = server._entry("mlp").batcher._lockstep
            snap = server.stats("mlp").snapshot()
        assert coord is not None and coord.steps == snap["batches"]
        assert coord.fingerprint != 0
        row = 0
        for out in outs:
            for k in range(len(out)):
                assert np.allclose(np.asarray(out["scores"][k]),
                                   np.asarray(offline["scores"][row]),
                                   atol=1e-5)
                row += 1

    def test_replica_spans_render_one_timeline_lane_per_replica(self):
        from mmlspark_tpu import obs
        from mmlspark_tpu.obs.export import REPLICA_TID_BASE, chrome_trace
        jm = JaxModel(model=mlp_bundle(), input_col="x",
                      output_col="scores")
        rng = np.random.default_rng(16)
        rows = rng.normal(size=(32, 6)).astype(np.float32)
        obs.enable()
        try:
            obs.clear()
            with ModelServer(ServeConfig(buckets=(4,), max_queue=64,
                                         mesh="dp=2")) as server:
                server.add_model("mlp", jm,
                                 example=vector_table(rows[:1]))
                handles = [server.submit("mlp",
                                         vector_table(rows[i:i + 4]))
                           for i in range(0, 32, 4)]
                for h in handles:
                    h.result(timeout=120)
                used = sorted(server.stats("mlp").snapshot()["replicas"])
            trace = chrome_trace()
        finally:
            obs.disable()
            obs.clear()
        lanes = {e["args"]["name"]: e["tid"] for e in trace["traceEvents"]
                 if e.get("ph") == "M"}
        # one synthetic lane per (model, replica), above the tid base so
        # real worker-thread lanes can never collide with it
        for idx in used:
            name = f"serve-replica-{idx} [mlp]"
            assert name in lanes and lanes[name] >= REPLICA_TID_BASE, lanes
        # replica spans actually moved onto the synthetic lanes
        replica_tids = {e["tid"] for e in trace["traceEvents"]
                        if e.get("ph") == "X"
                        and e["args"].get("replica") is not None}
        assert replica_tids == {lanes[f"serve-replica-{i} [mlp]"]
                                for i in used}

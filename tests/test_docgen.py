"""Doc generation (L7 codegen analog) — every stage documented, output fresh.

The reference's build fails if codegen can't wrap a stage; here CI fails if
a stage lacks a doc page or the committed generated artifacts are stale
(reference: codegen/src/main/scala/CodeGen.scala:44-83)."""

import os

from mmlspark_tpu.core.registry import all_stages

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _generate():
    from mmlspark_tpu.tools.docgen import generate
    return generate()


def test_every_stage_has_a_doc_page():
    stages = all_stages()
    assert len(stages) >= 50
    for path, cls in stages.items():
        page = os.path.join(REPO, "docs", "api", f"{cls.__name__}.md")
        assert os.path.exists(page), \
            f"{path} has no doc page; run python tools/docgen.py"


def test_generated_artifacts_are_fresh():
    """Committed docs + generated smoke tests must match a regeneration."""
    for rel, content in _generate().items():
        dest = os.path.join(REPO, rel)
        assert os.path.exists(dest), f"{rel} missing; run tools/docgen.py"
        with open(dest) as f:
            on_disk = f.read()
        assert on_disk == content, \
            f"{rel} is stale; run python tools/docgen.py"


def test_every_stage_docstring_cites_or_describes():
    # every stage page carries a non-trivial description (docstring-driven)
    for path, cls in all_stages().items():
        assert (cls.__doc__ or "").strip(), f"{path} lacks a docstring"


def test_index_lists_every_stage():
    with open(os.path.join(REPO, "docs", "api", "index.md")) as f:
        idx = f.read()
    for path, cls in all_stages().items():
        assert f"[{cls.__name__}]({cls.__name__}.md)" in idx, \
            f"{cls.__name__} missing from docs/api/index.md"

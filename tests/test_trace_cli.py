"""tools/trace.py CLI: the render path must consume exactly what the
exporter writes (valid Chrome-trace JSON with per-thread AND per-replica
lanes, request flows included), and missing/malformed trace input must be
a typed one-line error with exit code 2 — never an unhandled traceback."""

import importlib.util
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from mmlspark_tpu import obs
from mmlspark_tpu.obs import context as obs_context
from mmlspark_tpu.obs.export import REPLICA_TID_BASE

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _load_trace_cli():
    """Import tools/trace.py under a private name (plain ``import
    trace`` would shadow the stdlib module for the whole test process)."""
    spec = importlib.util.spec_from_file_location(
        "mmlspark_tools_trace", os.path.join(_TOOLS, "trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_cli = _load_trace_cli()


@pytest.fixture(autouse=True)
def obs_isolated():
    obs.disable()
    obs.clear()
    obs.registry().reset()
    yield
    obs.disable()
    obs.clear()
    obs.registry().reset()


def _write_capture(path: str) -> int:
    """Record a small capture with a request flow and replica-labeled
    spans (two lanes), write it, return the trace id."""
    obs.enable()
    t = obs.mint()
    with obs_context.bind(t):
        with obs.span("serve/admit", "serve", {"model": "m"}):
            pass
    for replica in (0, 1):
        with obs.span("serve/dispatch", "serve",
                      {"model": "m", "replica": replica}, (t,)):
            pass
    with obs_context.bind(t):
        with obs.span("serve/complete", "serve", {"model": "m"}):
            pass
    obs.write_chrome_trace(path)
    return t


class TestRender:
    def test_render_validates_and_summarizes_written_trace(
            self, tmp_path, capsys):
        path = str(tmp_path / "trace.json")
        t = _write_capture(path)
        # the emitted JSON loads and carries per-thread AND per-replica
        # lanes (thread_name metadata), exactly what Perfetto groups by
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
        events = payload["traceEvents"]
        lanes = {e["args"]["name"]: e["tid"] for e in events
                 if e.get("ph") == "M"}
        replica_lanes = {n for n in lanes if n.startswith("serve-replica-")}
        assert replica_lanes == {"serve-replica-0 [m]",
                                 "serve-replica-1 [m]"}
        assert all(lanes[n] >= REPLICA_TID_BASE for n in replica_lanes)
        thread_lanes = set(lanes) - replica_lanes
        assert thread_lanes  # the recording thread's own lane
        assert len({lanes[n] for n in lanes}) == len(lanes)  # distinct
        # the request flow survived serialization
        flow = [e for e in events if e.get("ph") in ("s", "t", "f")]
        assert [e["ph"] for e in flow] == ["s", "t", "t", "f"]
        assert all(e["id"] == t for e in flow)

        # render succeeds and aggregates the span names
        rc = trace_cli.main(["render", path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serve/dispatch" in out and "serve/admit" in out
        assert "1 request flow(s)" in out

    def test_render_missing_file_is_typed_exit_2(self, tmp_path, capsys):
        rc = trace_cli.main(["render", str(tmp_path / "nope.json")])
        err = capsys.readouterr().err
        assert rc == 2
        assert err.startswith("trace:") and "cannot read" in err

    def test_render_malformed_json_is_typed_exit_2(self, tmp_path,
                                                   capsys):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json", encoding="utf-8")
        rc = trace_cli.main(["render", str(bad)])
        err = capsys.readouterr().err
        assert rc == 2
        assert "not valid JSON" in err

    def test_render_non_trace_json_is_typed_exit_2(self, tmp_path,
                                                   capsys):
        for doc in ("[1, 2, 3]", '{"spans": []}'):
            f = tmp_path / "doc.json"
            f.write_text(doc, encoding="utf-8")
            rc = trace_cli.main(["render", str(f)])
            err = capsys.readouterr().err
            assert rc == 2
            assert "traceEvents" in err

    def test_render_malformed_event_is_typed_exit_2(self, tmp_path,
                                                    capsys):
        f = tmp_path / "evil.json"
        f.write_text(json.dumps({"traceEvents": [
            {"ph": "X", "dur": 1.0},  # no name
        ]}), encoding="utf-8")
        rc = trace_cli.main(["render", str(f)])
        err = capsys.readouterr().err
        assert rc == 2
        assert err.startswith("trace:")


class TestPostmortem:
    def test_postmortem_missing_and_malformed_are_typed_exit_2(
            self, tmp_path, capsys):
        rc = trace_cli.main(["postmortem", str(tmp_path / "nope.json")])
        assert rc == 2
        assert "cannot read" in capsys.readouterr().err
        f = tmp_path / "doc.json"
        f.write_text('{"spans": []}', encoding="utf-8")
        rc = trace_cli.main(["postmortem", str(f)])
        assert rc == 2
        assert "flight-recorder dump" in capsys.readouterr().err

    def test_postmortem_tolerates_mangled_dump_content(self, tmp_path,
                                                       capsys):
        """A truncated or hand-edited dump that still passes the shape
        validation must render what it can — never a raw traceback
        (the render discipline the subcommand documents)."""
        f = tmp_path / "mangled.json"
        f.write_text(json.dumps({
            "flight": 1,
            "reason": "hang",
            "ring": [
                {"name": "train/step", "start_ns": "not-a-number",
                 "dur_ns": None, "thread_name": "MainThread"},
                {"name": "plan/h2d", "ts_ns": 5, "dur_ns": "9"},
            ],
            "threads": {"123": "not-a-dict"},
            "metric_deltas": {"train.steps": "NaNish", "obs.x": 3},
            "heartbeats": {"serve/m#0": 1, "train/fit": {"busy": True}},
        }), encoding="utf-8")
        rc = trace_cli.main(["postmortem", str(f)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "train/step" in out
        assert "train.steps" in out
        assert "serve/m#0" in out


class TestRenderFleet:
    """Fleet-merged traces (obs/fleet.py): multi-pid traceEvents with
    process-group metadata render with per-host lane counts and the
    stitched cross-process flow count; a mixed-clock trace (a process
    without the stamp pair) is the typed exit-2 diagnostic."""

    def _fleet_payload(self, unaligned=()):
        def span(pid, tid, name, ts, dur):
            return {"name": name, "cat": "train", "ph": "X", "ts": ts,
                    "dur": dur, "pid": pid, "tid": tid, "args": {}}
        events = [
            span(11, 1, "train/step", 0.0, 50.0),
            span(11, 1, "train/liveness_sync", 100.0, 5.0),
            span(11, 2, "plan/dispatch", 10.0, 5.0),
            span(22, 1, "train/liveness_sync", 101.0, 5.0),
            # the stitched fence flow crossing both pids
            {"name": "fleet-fence", "cat": "fleet.fence", "ph": "s",
             "id": 7, "bp": "e", "ts": 102.5, "pid": 11, "tid": 1},
            {"name": "fleet-fence", "cat": "fleet.fence", "ph": "f",
             "id": 7, "bp": "e", "ts": 103.5, "pid": 22, "tid": 1},
            {"name": "process_name", "ph": "M", "pid": 11,
             "args": {"name": "hostA pid=11"}},
            {"name": "process_name", "ph": "M", "pid": 22,
             "args": {"name": "hostB pid=22"}},
        ]
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "fleetMeta": {
                    "fleet": 1,
                    "hosts": {"hostA": [11], "hostB": [22]},
                    "processes": [{"process": "proc_hostA_11"},
                                  {"process": "proc_hostB_22"}],
                    "stitched_flows": 1,
                    "unaligned": list(unaligned)}}

    def test_render_fleet_trace_reports_hosts_lanes_flows(
            self, tmp_path, capsys):
        path = str(tmp_path / "fleet_trace.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self._fleet_payload(), fh)
        rc = trace_cli.main(["render", path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fleet trace: 2 host(s), 2 process(es)" in out
        assert "hostA: 2 lane(s)" in out and "hostB: 1 lane(s)" in out
        assert "1 stitched cross-process flow(s)" in out
        assert "train/step" in out  # the span table still aggregates
        # fence-stitch arrows are barrier structure, not requests: a
        # capture with zero request traces reports zero request flows
        assert "request flow(s)" not in out

    def test_render_mixed_clock_fleet_trace_typed_exit_2(
            self, tmp_path, capsys):
        path = str(tmp_path / "mixed.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self._fleet_payload(
                unaligned=["proc_hostB_22"]), fh)
        rc = trace_cli.main(["render", path])
        err = capsys.readouterr().err
        assert rc == 2
        assert err.startswith("trace:")
        assert "mixed-clock" in err and "proc_hostB_22" in err
        assert "stamp pair" in err

    def test_render_real_fleet_export_round_trips(self, tmp_path,
                                                  capsys):
        """What obs/fleet.py actually writes renders exit-0 — the CLI
        contract is pinned against the real exporter, not a hand-built
        fixture."""
        import time as _time

        from mmlspark_tpu.obs import fleet as obs_fleet

        d = str(tmp_path / "fleet")
        obs.enable()
        with obs.span("train/step", "train"):
            _time.sleep(0.001)
        exp = obs_fleet.enable(d, interval_s=30.0)
        exp.snapshot("manual")
        try:
            view = obs_fleet.FleetCollector(d).collect()
            path = view.write_chrome_trace(
                str(tmp_path / "real_fleet.json"))
        finally:
            obs_fleet.disable()
        rc = trace_cli.main(["render", path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fleet trace: 1 host(s), 1 process(es)" in out

"""On-device train preprocessing (train/preprocess.py + ops/pallas).

Four contract families:

* **Kernel parity** — the Pallas fused crop→resize→normalize kernel is
  ≤ 1 ULP from its pure-XLA reference (bit-identical under jit), runs in
  interpreter mode on this CPU backend (the kernel body executes, not a
  shadow path), and the numpy host oracle tracks both to FMA tolerance.
* **Spec semantics** — validation, static geometry replay (the
  analyzer's ``infer_schema`` face), deterministic per-step PRNG folds.
* **End-to-end wire-form parity** — thin uint8 batches vs
  host-preprocessed float batches produce equal loss histories for
  fit_arrays AND fit_stream; prefetch on/off stays bit-identical; a
  changed spec refuses to resume.
* **Analyzer/byte accounting** — ``audit_train_preprocess`` predictions
  equal the bytes observed at the ``core/plan.train_commit`` seam.
"""

import numpy as np
import pytest

import jax

from mmlspark_tpu.models.zoo import ConvNetCifar
from mmlspark_tpu.ops.pallas.resize import (
    fused_resize_norm, fused_resize_norm_host, fused_resize_norm_reference,
)
from mmlspark_tpu.train import (
    DevicePreprocess, TrainConfig, Trainer, envelope_batch, host_preprocess,
)
from mmlspark_tpu.train import preprocess as pp_lib


def _images(n=6, h=24, w=20, c=3, seed=0):
    r = np.random.default_rng(seed)
    return r.integers(0, 256, (n, h, w, c)).astype(np.uint8)


class TestFusedKernel:
    CROP, OUT = (20, 16), (8, 8)

    def _offsets(self, n, seed=1):
        r = np.random.default_rng(seed)
        return (r.integers(0, 5, n).astype(np.int32),
                r.integers(0, 5, n).astype(np.int32))

    def _run(self, impl, x, oy, ox, jit=True):
        fn = lambda a, b, c: fused_resize_norm(  # noqa: E731
            a, b, c, self.CROP, self.OUT, 1 / 255.0, impl=impl)
        if jit:
            fn = jax.jit(fn)
        return np.asarray(fn(x, oy, ox))

    def test_pallas_within_1_ulp_of_reference(self):
        # the acceptance pin, in the context the train step uses (the
        # ops trace into one jitted program): <= 1 ULP — in fact XLA
        # lowers both to the identical arithmetic, so bit-equal too
        x = _images()
        oy, ox = self._offsets(len(x))
        ref = self._run("xla", x, oy, ox)
        ker = self._run("pallas", x, oy, ox)
        np.testing.assert_array_max_ulp(ref, ker, maxulp=1)
        np.testing.assert_array_equal(ref, ker)

    def test_eager_drift_bounded_by_fma_contraction(self):
        # un-jitted, the vmapped reference gets FMA-contracted
        # differently than the interpreted kernel: 2 ULP bound
        x = _images()
        oy, ox = self._offsets(len(x))
        np.testing.assert_array_max_ulp(
            self._run("xla", x, oy, ox, jit=False),
            self._run("pallas", x, oy, ox, jit=False), maxulp=2)

    def test_host_oracle_tracks_to_fma_tolerance(self):
        x = _images()
        oy, ox = self._offsets(len(x))
        ref = np.asarray(fused_resize_norm_reference(
            x, oy, ox, self.CROP, self.OUT, 1 / 255.0))
        host = fused_resize_norm_host(x, oy, ox, self.CROP, self.OUT,
                                      1 / 255.0)
        # XLA contracts the 4-tap blend into FMAs; numpy cannot — one
        # extra rounding per tap bounds the drift at 2 ULP
        np.testing.assert_array_max_ulp(ref, host, maxulp=2)

    def test_identity_geometry_equals_plain_cast(self):
        x = _images(4, 8, 8)
        z = np.zeros(4, np.int32)
        out = np.asarray(fused_resize_norm(
            x, z, z, (8, 8), (8, 8), 1 / 255.0, impl="xla"))
        np.testing.assert_array_equal(
            out, x.astype(np.float32) * np.float32(1 / 255.0))

    def test_vmem_overflow_falls_back_to_reference(self):
        from mmlspark_tpu.ops.pallas.resize import _fits_vmem
        assert not _fits_vmem(4096, 4096, 224, 224, 3)
        assert _fits_vmem(96, 96, 32, 32, 3)  # the CIFAR-scale case
        # a forced-pallas call on an oversized block still computes (the
        # reference path), and matches the explicit reference exactly
        assert not _fits_vmem(512, 512, 32, 32, 3)
        big = _images(1, 512, 512)
        z = np.zeros(1, np.int32)
        a = np.asarray(fused_resize_norm(big, z, z, (512, 512), (32, 32),
                                         1.0, impl="pallas"))
        b = np.asarray(fused_resize_norm(big, z, z, (512, 512), (32, 32),
                                         1.0, impl="xla"))
        np.testing.assert_array_equal(a, b)

    def test_bad_inputs_raise(self):
        x = _images(2, 8, 8)
        z = np.zeros(2, np.int32)
        with pytest.raises(ValueError, match="unknown fused_resize_norm"):
            fused_resize_norm(x, z, z, (8, 8), (4, 4), 1.0, impl="cuda")
        with pytest.raises(ValueError, match="larger than the source"):
            fused_resize_norm(x, z, z, (16, 8), (4, 4), 1.0)


class TestDevicePreprocessSpec:
    def test_parse_dict_and_identity(self):
        spec = DevicePreprocess.parse(
            {"resize": [32, 32], "flip_lr": True, "crop_pad": 4})
        assert spec.resize == (32, 32) and spec.flip_lr
        assert DevicePreprocess.parse(spec) is spec
        assert DevicePreprocess.parse(None) is None
        with pytest.raises(TypeError, match="DevicePreprocess"):
            DevicePreprocess.parse("resize=32")

    def test_validation_rejects_bad_fields(self):
        with pytest.raises(ValueError, match="impl"):
            DevicePreprocess(impl="tpu")
        with pytest.raises(ValueError, match="resize"):
            DevicePreprocess(resize=(0, 32))
        with pytest.raises(ValueError, match="contrast"):
            DevicePreprocess(contrast=(1.2, 0.8))
        with pytest.raises(ValueError, match="crop_pad"):
            DevicePreprocess(crop_pad=-1)
        with pytest.raises(ValueError, match="zero"):
            DevicePreprocess(std=(0.5, 0.0, 0.5))

    def test_out_shape_replays_geometry(self):
        spec = DevicePreprocess(src_crop=(28, 28), resize=(16, 16),
                                crop_pad=2)
        assert spec.out_shape((32, 32, 3)) == (16, 16, 3)
        assert DevicePreprocess().out_shape((9, 7, 1)) == (9, 7, 1)
        with pytest.raises(ValueError, match="src_crop"):
            DevicePreprocess(src_crop=(40, 40)).out_shape((32, 32, 3))
        with pytest.raises(ValueError, match="crop_pad"):
            DevicePreprocess(crop_pad=9).out_shape((8, 8, 3))
        with pytest.raises(ValueError, match="channels"):
            DevicePreprocess(mean=(0.5, 0.5)).out_shape((8, 8, 3))
        with pytest.raises(ValueError, match="image geometry"):
            DevicePreprocess().out_shape((8, 8))

    def test_fingerprint_tracks_every_field(self):
        a = DevicePreprocess(flip_lr=True)
        b = DevicePreprocess(flip_lr=True, brightness=0.1)
        assert a.fingerprint() == DevicePreprocess(
            flip_lr=True).fingerprint()
        assert a.fingerprint() != b.fingerprint()

    def test_apply_keys_fold_per_step(self):
        # same step → identical pixels; different step → different draws
        spec = DevicePreprocess(crop_pad=2, flip_lr=True, brightness=0.2)
        x = _images(8, 8, 8).astype(np.float32) / 255.0
        key0 = jax.random.fold_in(jax.random.PRNGKey(0), 0)
        key1 = jax.random.fold_in(jax.random.PRNGKey(0), 1)
        a = np.asarray(pp_lib.apply(spec, key0, x, 1.0))
        b = np.asarray(pp_lib.apply(spec, key0, x, 1.0))
        c = np.asarray(pp_lib.apply(spec, key1, x, 1.0))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_apply_standardizes_after_augment(self):
        spec = DevicePreprocess(mean=(0.5,), std=(0.25,))
        x = _images(4, 6, 6, 1)
        out = np.asarray(pp_lib.apply(
            spec, jax.random.PRNGKey(0), x, 1 / 255.0))
        want = (x.astype(np.float32) * np.float32(1 / 255.0) - 0.5) / 0.25
        np.testing.assert_allclose(out, want, rtol=1e-6, atol=1e-6)

    def test_host_preprocess_matches_device_geometry(self):
        # host resize+normalize vs the device fused pass: same grids,
        # FMA-tolerance agreement
        spec = DevicePreprocess(resize=(16, 12))
        x = _images(5, 40, 36)
        host = host_preprocess(spec, x, 1 / 255.0)
        z = np.zeros(5, np.int32)
        dev = np.asarray(fused_resize_norm(
            x, z, z, (40, 36), (16, 12), 1 / 255.0, impl="xla"))
        np.testing.assert_array_max_ulp(host, dev, maxulp=2)
        with pytest.raises(ValueError, match="src_crop"):
            host_preprocess(DevicePreprocess(src_crop=(8, 8)), x, 1.0)


class TestEnvelopeBatch:
    def test_pad_and_center_small_images(self):
        imgs = [np.full((4, 4, 3), 7, np.uint8)]
        out = envelope_batch(imgs, (8, 8))
        assert out.shape == (1, 8, 8, 3)
        assert (out[0, 2:6, 2:6] == 7).all()
        assert out.sum() == 7 * 4 * 4 * 3  # zero padding elsewhere

    def test_center_crop_large_images(self):
        img = np.arange(10 * 10).reshape(10, 10, 1).astype(np.uint8)
        out = envelope_batch([img], (6, 6))
        np.testing.assert_array_equal(out[0], img[2:8, 2:8])

    def test_ragged_batch_and_grayscale(self):
        imgs = [np.zeros((12, 4), np.uint8),       # HW grayscale
                np.ones((4, 12, 3), np.uint8)]
        out = envelope_batch(imgs, (8, 8))
        assert out.shape == (2, 8, 8, 3)
        assert envelope_batch([], (8, 8)).shape == (0, 8, 8, 3)

    def test_non_uint8_input_refused(self):
        # normalized floats silently truncate to all-zero uint8 — the
        # envelope refuses them loudly instead
        with pytest.raises(TypeError, match="uint8 wire form"):
            envelope_batch([np.random.default_rng(0).random((4, 4, 3))
                            .astype(np.float32)], (8, 8))

    def test_grids_stay_float32(self):
        # the shared-constants contract: every weight array is f32, so
        # the numpy oracle blends in the same precision the device
        # paths canonicalize to
        from mmlspark_tpu.ops.pallas.resize import _grids
        for g in _grids(20, 16, 8, 8)[4:]:
            assert g.dtype == np.float32


def _cfg(spec, depth=2, **kw):
    return TrainConfig(batch_size=16, epochs=1, optimizer="momentum",
                       learning_rate=0.01, log_every=1,
                       prefetch_depth=depth, preprocess=spec, seed=0,
                       **kw)


def _module():
    return ConvNetCifar(num_classes=4, widths=(4,), dense_width=8)


class TestEndToEndParity:
    """Thin uint8 vs host-preprocessed f32: the two wire forms of the
    same spec train identically (stochastic draws fold from the global
    step, so both runs augment the same pixels the same way)."""

    N, SIDE = 64, 16

    def _data(self, side=None):
        r = np.random.default_rng(3)
        x = r.integers(0, 256, (self.N, side or self.SIDE,
                                side or self.SIDE, 3)).astype(np.uint8)
        y = r.integers(0, 4, self.N).astype(np.int64)
        return x, y

    def test_fit_arrays_resize_geometry_parity(self):
        # REAL geometry on the wire: 24x24 source → 16x16 on device vs
        # host bilinear baseline; augment still on device in both runs
        spec = DevicePreprocess(resize=(16, 16), crop_pad=2,
                                flip_lr=True, brightness=0.1)
        x, y = self._data(side=24)
        tr_thin = Trainer(_module(), _cfg(spec))
        tr_thin.fit_arrays(x, y)
        tr_host = Trainer(_module(), _cfg(spec))
        tr_host.fit_arrays(host_preprocess(spec, x, 1 / 255.0), y)
        np.testing.assert_allclose(tr_thin.history, tr_host.history,
                                   rtol=0, atol=1e-5)

    def test_fit_stream_parity_and_prefetch_bit_identity(self):
        spec = DevicePreprocess(crop_pad=2, flip_lr=True,
                                brightness=0.1, contrast=(0.9, 1.1))
        x, y = self._data()

        def chunks(data):
            def source():
                for s in range(0, self.N, 20):  # ragged vs batch_size
                    yield data[s:s + 20], y[s:s + 20]
            return source

        tr_thin = Trainer(_module(), _cfg(spec))
        tr_thin.fit_stream(chunks(x))
        tr_host = Trainer(_module(), _cfg(spec))
        tr_host.fit_stream(chunks(host_preprocess(spec, x, 1 / 255.0)))
        np.testing.assert_allclose(tr_thin.history, tr_host.history,
                                   rtol=0, atol=1e-5)
        # prefetch off: bit-identical walk (preprocess lives in-step, so
        # the loader still only moves WHEN bytes cross, never what)
        tr_sync = Trainer(_module(), _cfg(spec, depth=0))
        tr_sync.fit_stream(chunks(x))
        assert tr_sync.history == tr_thin.history
        for a, b in zip(jax.tree_util.tree_leaves(tr_sync.params),
                        jax.tree_util.tree_leaves(tr_thin.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_src_crop_random_geometry_trains(self):
        # the fused random-crop-window path end to end (no host twin —
        # the draw lives in the step); shapes and finiteness are the pin
        spec = DevicePreprocess(src_crop=(12, 12), resize=(16, 16),
                                flip_lr=True)
        x, y = self._data(side=20)
        tr = Trainer(_module(), _cfg(spec))
        tr.fit_arrays(x, y)
        assert len(tr.history) == self.N // 16
        assert all(np.isfinite(v) for v in tr.history)

    def test_changed_spec_refuses_to_resume(self, tmp_path):
        spec = DevicePreprocess(flip_lr=True)
        x, y = self._data()
        cfg = _cfg(spec, checkpoint_dir=str(tmp_path), checkpoint_every=2)
        Trainer(_module(), cfg).fit_arrays(x, y)
        changed = _cfg(DevicePreprocess(flip_lr=True, brightness=0.2),
                       checkpoint_dir=str(tmp_path))
        with pytest.raises(ValueError, match="fingerprint mismatch"):
            Trainer(_module(), changed).fit_arrays(x, y)


class TestAnalyzerAndBytes:
    def test_audit_validates_geometry(self):
        from mmlspark_tpu.analysis import (
            SchemaError, audit_train_preprocess,
        )
        spec = DevicePreprocess(resize=(16, 16))
        audit = audit_train_preprocess(spec, (32, 32, 3), 16)
        assert audit.out_shape == (16, 16, 3)
        assert audit.thin_bytes == 16 * 32 * 32 * 3
        assert audit.host_bytes == 16 * 16 * 16 * 3 * 4
        assert "uint8" in audit.describe()
        with pytest.raises(SchemaError, match="src_crop"):
            audit_train_preprocess(
                DevicePreprocess(src_crop=(64, 64)), (32, 32, 3), 16)
        with pytest.raises(SchemaError, match="needs a spec"):
            audit_train_preprocess(None, (32, 32, 3), 16)

    def test_predicted_thin_bytes_equal_observed_seam_bytes(self):
        from mmlspark_tpu.analysis import audit_train_preprocess
        from mmlspark_tpu.core import plan

        spec = DevicePreprocess(crop_pad=2, flip_lr=True)
        r = np.random.default_rng(0)
        x = r.integers(0, 256, (32, 16, 16, 3)).astype(np.uint8)
        y = r.integers(0, 4, 32).astype(np.int64)
        audit = audit_train_preprocess(spec, x.shape[1:], 16)
        tr = Trainer(_module(), _cfg(spec))
        with plan.count_crossings() as c:
            tr.fit_arrays(x, y)
        aux = 2 * 16 * (8 + 4)  # per-step labels (int64) + mask (f32)
        assert c.upload_bytes - aux == 2 * audit.thin_bytes


def test_loader_wire_bytes_decompose_the_ab():
    # the loader-side observable: uint8 wire ≈ ¼ the f32 wire for the
    # same schedule (labels/mask identical across the A/B)
    spec = DevicePreprocess(flip_lr=True)
    r = np.random.default_rng(5)
    x = r.integers(0, 256, (64, 16, 16, 3)).astype(np.uint8)
    y = r.integers(0, 4, 64).astype(np.int64)
    tr_thin = Trainer(_module(), _cfg(spec))
    tr_thin.fit_arrays(x, y)
    tr_host = Trainer(_module(), _cfg(spec))
    tr_host.fit_arrays(host_preprocess(spec, x, 1 / 255.0), y)
    thin = tr_thin.input_stats["wire_mb"]
    host = tr_host.input_stats["wire_mb"]
    assert thin < host < 4.2 * thin
